"""The override/tagging pass: CPU physical plan -> mixed CPU/TPU plan.

Reference analog:
  * GpuOverrides.apply (GpuOverrides.scala:2516-2546) — wrap, tag, explain,
    convert;
  * RapidsMeta (RapidsMeta.scala:70-693) — the wrapper tree accumulating
    "cannot replace because ..." reasons, converting only fully-replaceable
    subtrees;
  * TypeChecks (TypeChecks.scala:453) — per-rule allowed-type matrices;
  * the rule registries (GpuOverrides.scala:661-2492).

Differences by design: there is no separate "partitioning"/"scan" rule space
yet (exchange and file scans register here as exec rules when those layers
land). Expression supportability is decided by the STATIC per-rule type
matrix (plugin/typechecks.py, the TypeChecks.scala analog): the checker
walks the plan without lowering anything and every fallback carries a
reason naming the rule, parameter, and offending type. The old abstract
lowering probe (eval.tpu_supports) survives as a conf-gated debug
cross-check (spark.rapids.tpu.sql.matrix.probeCrossCheck.enabled) and as
the value-level tag hook of the few rules whose support depends on
literal values (regex patterns, UDF traces).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Type

from .. import types as T
from ..conf import (
    ENABLE_CAST_FLOAT_TO_TIMESTAMP,
    ENABLE_CAST_STRING_TO_FLOAT,
    ENABLE_CAST_STRING_TO_INTEGER,
    ENABLE_CAST_STRING_TO_TIMESTAMP,
    EXPLAIN,
    MATRIX_PROBE_CROSS_CHECK,
    RapidsConf,
    SQL_ENABLED,
    TEST_ALLOWED_NONTPU,
    TEST_CONF,
)
from ..cpu import plan as C
from ..exec import aggregate as XA
from ..exec import basic as XB
from ..exec.base import TpuExec
from ..exec.transitions import (
    ColumnarToRowExec,
    RowToColumnarExec,
)
from ..expr import aggregates as A
from ..expr import expressions as E
from ..expr.eval import tpu_supports
from ..types import StructType


# ---------------------------------------------------------------------------
# Expression rules (reference: GpuOverrides.scala:661-2124, 144 rules)
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ExprRule:
    name: str
    description: str


EXPRESSION_RULES: Dict[Type[E.Expression], ExprRule] = {}


def _expr_rule(cls: Type[E.Expression], name: str, desc: str) -> None:
    EXPRESSION_RULES[cls] = ExprRule(name, desc)


for _cls, _name, _desc in [
    (E.Literal, "Literal", "holds a static value"),
    (E.UnresolvedAttribute, "AttributeReference", "references an input column"),
    (E.BoundReference, "BoundReference", "bound input column"),
    (E.Alias, "Alias", "gives a column a name"),
    (E.Add, "Add", "addition"),
    (E.Subtract, "Subtract", "subtraction"),
    (E.Multiply, "Multiply", "multiplication"),
    (E.Divide, "Divide", "division"),
    (E.IntegralDivide, "IntegralDivide", "division with integer result"),
    (E.Remainder, "Remainder", "remainder (%)"),
    (E.Pmod, "Pmod", "positive modulo"),
    (E.UnaryMinus, "UnaryMinus", "negation"),
    (E.UnaryPositive, "UnaryPositive", "identity +"),
    (E.Abs, "Abs", "absolute value"),
    (E.EqualTo, "EqualTo", "equality"),
    (E.EqualNullSafe, "EqualNullSafe", "null-safe equality (<=>)"),
    (E.LessThan, "LessThan", "< comparison"),
    (E.LessThanOrEqual, "LessThanOrEqual", "<= comparison"),
    (E.GreaterThan, "GreaterThan", "> comparison"),
    (E.GreaterThanOrEqual, "GreaterThanOrEqual", ">= comparison"),
    (E.In, "In", "IN list membership"),
    (E.And, "And", "logical AND (3-valued)"),
    (E.Or, "Or", "logical OR (3-valued)"),
    (E.Not, "Not", "logical NOT"),
    (E.IsNull, "IsNull", "null check"),
    (E.IsNotNull, "IsNotNull", "non-null check"),
    (E.IsNan, "IsNan", "NaN check"),
    (E.Coalesce, "Coalesce", "first non-null"),
    (E.NaNvl, "NaNvl", "NaN replacement"),
    (E.If, "If", "if/then/else"),
    (E.CaseWhen, "CaseWhen", "CASE WHEN"),
    (E.Cast, "Cast", "type cast"),
    (E.Sqrt, "Sqrt", "square root"),
    (E.Exp, "Exp", "e^x"),
    (E.Log, "Log", "natural log"),
    (E.Log10, "Log10", "log base 10"),
    (E.Log2, "Log2", "log base 2"),
    (E.Log1p, "Log1p", "log(1+x)"),
    (E.Expm1, "Expm1", "e^x - 1"),
    (E.Sin, "Sin", "sine"),
    (E.Cos, "Cos", "cosine"),
    (E.Tan, "Tan", "tangent"),
    (E.Asin, "Asin", "arcsine"),
    (E.Acos, "Acos", "arccosine"),
    (E.Atan, "Atan", "arctangent"),
    (E.Sinh, "Sinh", "hyperbolic sine"),
    (E.Cosh, "Cosh", "hyperbolic cosine"),
    (E.Tanh, "Tanh", "hyperbolic tangent"),
    (E.Cbrt, "Cbrt", "cube root"),
    (E.ToDegrees, "ToDegrees", "radians to degrees"),
    (E.ToRadians, "ToRadians", "degrees to radians"),
    (E.Floor, "Floor", "floor"),
    (E.Ceil, "Ceil", "ceiling"),
    (E.Round, "Round", "HALF_UP rounding"),
    (E.Rint, "Rint", "round to even"),
    (E.Pow, "Pow", "power"),
    (E.Atan2, "Atan2", "two-argument arctangent"),
    (E.Signum, "Signum", "sign"),
    (E.BitwiseAnd, "BitwiseAnd", "bitwise AND"),
    (E.BitwiseOr, "BitwiseOr", "bitwise OR"),
    (E.BitwiseXor, "BitwiseXor", "bitwise XOR"),
    (E.BitwiseNot, "BitwiseNot", "bitwise NOT"),
    (E.ShiftLeft, "ShiftLeft", "shift left"),
    (E.ShiftRight, "ShiftRight", "shift right"),
    (E.ShiftRightUnsigned, "ShiftRightUnsigned", "unsigned shift right"),
    (E.Length, "Length", "string character length"),
    (E.Upper, "Upper", "uppercase conversion"),
    (E.Lower, "Lower", "lowercase conversion"),
    (E.InitCap, "InitCap", "capitalize each word"),
    (E.Substring, "Substring", "substring by character position"),
    (E.Concat, "Concat", "string concatenation"),
    (E.StringTrim, "StringTrim", "trim both ends"),
    (E.StringTrimLeft, "StringTrimLeft", "trim leading chars"),
    (E.StringTrimRight, "StringTrimRight", "trim trailing chars"),
    (E.StartsWith, "StartsWith", "prefix test"),
    (E.EndsWith, "EndsWith", "suffix test"),
    (E.Contains, "Contains", "substring containment test"),
    (E.Like, "Like", "SQL LIKE pattern match"),
    (E.RLike, "RLike", "regex match via compiled byte DFA"),
    (E.RegExpReplace, "RegExpReplace",
     "regex replace (literal-equivalent patterns)"),
    (E.StringLocate, "StringLocate", "substring position (1-based)"),
    (E.StringReplace, "StringReplace", "replace all occurrences"),
    (E.StringLPad, "StringLPad", "left-pad to length"),
    (E.StringRPad, "StringRPad", "right-pad to length"),
    (E.SubstringIndex, "SubstringIndex", "substring before/after delimiter"),
    (E.StringSplitPart, "StringSplit", "split on delimiter + index"),
    (E.Year, "Year", "year of date/timestamp"),
    (E.Quarter, "Quarter", "quarter of year"),
    (E.Month, "Month", "month of date/timestamp"),
    (E.DayOfMonth, "DayOfMonth", "day of month"),
    (E.DayOfYear, "DayOfYear", "day of year"),
    (E.DayOfWeek, "DayOfWeek", "day of week (1=Sunday)"),
    (E.WeekDay, "WeekDay", "day of week (0=Monday)"),
    (E.Hour, "Hour", "hour of timestamp (UTC)"),
    (E.Minute, "Minute", "minute of timestamp (UTC)"),
    (E.Second, "Second", "second of timestamp (UTC)"),
    (E.DateAdd, "DateAdd", "add days to date"),
    (E.DateSub, "DateSub", "subtract days from date"),
    (E.DateDiff, "DateDiff", "days between dates"),
    (E.LastDay, "LastDay", "last day of month"),
    (E.UnixTimestamp, "UnixTimestamp", "seconds since epoch"),
    (E.ToUnixTimestamp, "ToUnixTimestamp", "seconds since epoch"),
    (E.FromUnixTime, "FromUnixTime", "format seconds since epoch"),
    (E.TimeAdd, "TimeAdd", "timestamp + interval"),
    (E.TruncDate, "TruncDate", "truncate date to unit"),
    (A.AggregateExpression, "AggregateExpression", "aggregate holder"),
    (A.Count, "Count", "count aggregate"),
    (A.Sum, "Sum", "sum aggregate"),
    (A.Min, "Min", "min aggregate"),
    (A.Max, "Max", "max aggregate"),
    (A.Average, "Average", "average aggregate"),
    (A.First, "First", "first value aggregate"),
    (A.Last, "Last", "last value aggregate"),
]:
    _expr_rule(_cls, _name, _desc)

from ..expr import windows as _W  # noqa: E402

for _cls, _name, _desc in [
    (_W.WindowExpression, "WindowExpression", "function over a window spec"),
    (_W.RowNumber, "RowNumber", "row number within partition"),
    (_W.Rank, "Rank", "rank with gaps"),
    (_W.DenseRank, "DenseRank", "rank without gaps"),
    (_W.Lead, "Lead", "value of a following row"),
    (_W.Lag, "Lag", "value of a preceding row"),
]:
    _expr_rule(_cls, _name, _desc)

# nondeterministic / metadata family (reference:
# GpuRandomExpressions.scala:31, GpuMonotonicallyIncreasingID.scala,
# GpuSparkPartitionID.scala, GpuInputFileBlock.scala, HashFunctions.scala:43)
for _cls, _name, _desc in [
    (E.Rand, "Rand", "uniform random in [0,1), deterministic per seed"),
    (E.MonotonicallyIncreasingID, "MonotonicallyIncreasingID",
     "unique id: (partition << 33) + row"),
    (E.SparkPartitionID, "SparkPartitionID", "current partition index"),
    (E.InputFileName, "InputFileName", "path of the file being scanned"),
    (E.Murmur3Hash, "Murmur3Hash", "Spark murmur3_32 hash of columns"),
    # reference: RapidsUDF.java — a user columnar function traced into
    # the fused projection; supportability is value-level (the trace),
    # so its matrix tag hook IS the probe
    (E.NativeUDF, "NativeUDF", "user JAX/Pallas columnar UDF"),
]:
    _expr_rule(_cls, _name, _desc)


def _check_type(dt: T.DataType, conf: RapidsConf) -> Optional[str]:
    """Allowed-type matrix (reference: isSupportedType GpuOverrides.scala:531)."""
    from .typechecks import decimal_reason

    if isinstance(dt, (T.ArrayType, T.StructType)):
        return f"type {dt.simpleString} is not supported on TPU"
    if isinstance(dt, T.DecimalType):
        return decimal_reason(dt, conf)
    return None


_CONTEXT_EXPR_REASON = (
    "nondeterministic/metadata expressions (rand, "
    "monotonically_increasing_id, spark_partition_id, "
    "input_file_name, hash over strings) only run on TPU "
    "inside a projection"
)


def check_expression(
    expr: E.Expression, schema: StructType, conf: RapidsConf,
    allow_context: bool = False, context: Optional[str] = None,
) -> List[str]:
    """All the reasons this expression can't lower; empty = supported.

    The verdict comes from the STATIC type matrix (plugin/typechecks.py):
    nothing is traced. ``allow_context``: True only where the exec
    evaluates partition-context expressions at its boundary (the project;
    reference: Spark pins nondeterministic expressions into their own
    Project) — anywhere else rand()/ids/input_file_name must tag the
    plan off. ``context`` defaults to the project context."""
    from . import typechecks as TC

    if context is None:
        context = TC.PROJECT
    reasons: List[str] = []
    if (E.has_context_expr(expr) or _has_string_hash(expr, schema)) \
            and not allow_context:
        reasons.append(_CONTEXT_EXPR_REASON)
    if not reasons:
        try:
            bound = E.bind_references(expr, schema)
        except (ValueError, KeyError) as e:
            reasons.append(str(e))
        else:
            reasons.extend(TC.check_expr(bound, conf, context))
            try:
                err = _check_type(bound.dtype, conf)
                if err:
                    reasons.append(err)
            except Exception:  # noqa: BLE001
                pass  # already reported by the matrix walk
    if conf.get(MATRIX_PROBE_CROSS_CHECK):
        try:
            legacy = _probe_check_expression(
                expr, schema, conf, allow_context)
        except Exception as e:  # noqa: BLE001 — probe crash = probe fallback
            legacy = [f"lowering probe raised: {e}"]
        if bool(legacy) != bool(reasons):
            TC.note_cross_check_disagreement(
                f"{type(expr).__name__}: matrix="
                f"{'FALLBACK' if reasons else 'ON_TPU'}"
                f"({'; '.join(reasons) or '-'}) probe="
                f"{'FALLBACK' if legacy else 'ON_TPU'}"
                f"({'; '.join(legacy) or '-'})")
            if legacy and not reasons:
                # conservative: a probe-detected lowering gap falls back
                # even when the matrix disagrees (then fix the matrix)
                reasons.extend(legacy)
    return reasons


def _probe_check_expression(
    expr: E.Expression, schema: StructType, conf: RapidsConf,
    allow_context: bool = False,
) -> List[str]:
    """The LEGACY verdict: abstractly trace the real lowering
    (eval.tpu_supports). Kept verbatim as the probeCrossCheck debug path;
    the matrix above is the primary tagging mechanism."""
    reasons: List[str] = []

    def visit(node: E.Expression):
        if type(node) not in EXPRESSION_RULES:
            reasons.append(
                f"expression {type(node).__name__} is not supported on TPU"
            )
        for c in node.children:
            visit(c)

    visit(expr)
    if reasons:
        return reasons
    # context expressions (rand / ids / input_file_name, and hash() over
    # strings, which needs the exec's host-synced byte bound) evaluate at
    # the project's boundary, not in eval.py — probe them as typed
    # placeholders there, reject them everywhere else
    probe_expr = expr
    if E.has_context_expr(expr) or _has_string_hash(expr, schema):
        if not allow_context:
            return [_CONTEXT_EXPR_REASON]

        def _placeholder(node):
            if isinstance(node, E.NONDETERMINISTIC_CONTEXT_EXPRS) or (
                isinstance(node, E.Murmur3Hash)
                and _has_string_hash(node, schema)
            ):
                zero = {T.DOUBLE: 0.0, T.LONG: 0, T.INT: 0,
                        T.STRING: ""}.get(node.dtype, 0)
                return E.Literal(zero, node.dtype)
            return node

        probe_expr = expr.transform(_placeholder)
    if not isinstance(expr, (A.AggregateExpression, A.AggregateFunction)):
        ok, why = tpu_supports(probe_expr, schema)
        if not ok:
            reasons.append(why or "lowering probe failed")
        else:
            try:
                bound = E.bind_references(expr, schema)
                err = _check_type(bound.dtype, conf)
                if err:
                    reasons.append(err)
                reasons.extend(_gated_cast_reasons(bound, conf))
            except (TypeError, ValueError, KeyError) as e:
                reasons.append(str(e))
    return reasons


def _gated_cast_reasons(bound: E.Expression, conf: RapidsConf) -> List[str]:
    """Conf-gated cast pairs (reference: RapidsConf.scala:487-533 — risky
    cast kernels exist but tag the plan for fallback unless enabled)."""
    reasons: List[str] = []

    def visit(node: E.Expression):
        if (isinstance(node, E.Cast) and node.child.dtype.is_floating
                and isinstance(node.to, T.TimestampType)
                and not conf.get(ENABLE_CAST_FLOAT_TO_TIMESTAMP)):
            reasons.append(
                "casting float to timestamp is disabled; set "
                "spark.rapids.tpu.sql.castFloatToTimestamp.enabled=true")
        if isinstance(node, E.Cast) and isinstance(
            node.child.dtype, T.StringType
        ):
            to = node.to
            if to.name in ("tinyint", "smallint", "int", "bigint") and not conf.get(
                ENABLE_CAST_STRING_TO_INTEGER
            ):
                reasons.append(
                    "casting string to integral types is disabled; set "
                    "spark.rapids.tpu.sql.castStringToInteger.enabled=true")
            if to.is_floating and not conf.get(ENABLE_CAST_STRING_TO_FLOAT):
                reasons.append(
                    "casting string to float is disabled; set "
                    "spark.rapids.tpu.sql.castStringToFloat.enabled=true")
            if isinstance(to, T.TimestampType) and not conf.get(
                ENABLE_CAST_STRING_TO_TIMESTAMP
            ):
                reasons.append(
                    "casting string to timestamp is disabled; set "
                    "spark.rapids.tpu.sql.castStringToTimestamp.enabled=true")
        for c in node.children:
            visit(c)

    visit(bound)
    return reasons


def check_aggregate(
    ae: A.AggregateExpression, schema: StructType, conf: RapidsConf,
    context: Optional[str] = None,
) -> List[str]:
    """Matrix verdict for one aggregate: the function's own cell in the
    aggregation (or window) context, plus its input expression checked as
    the projection it evaluates in."""
    from . import typechecks as TC

    context = context or TC.AGGREGATION
    reasons: List[str] = []
    f = ae.func
    if type(f) not in EXPRESSION_RULES:
        reasons.append(f"aggregate {type(f).__name__} is not supported on TPU")
        return reasons
    if f.input is not None:
        try:
            bound_f = E.bind_references(f, schema)
        except (ValueError, KeyError) as e:
            return [str(e)]
        reasons.extend(TC.check_node(bound_f, conf, context))
        if not reasons:
            reasons.extend(check_expression(f.child, schema, conf))
    else:
        reasons.extend(TC.check_node(f, conf, context))
    return reasons


# ---------------------------------------------------------------------------
# Exec rules (reference: commonExecs GpuOverrides.scala:2243-2492)
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class ExecRule:
    name: str
    description: str
    tag: Callable[["PlanMeta"], None]
    convert: Callable[[C.CpuExec, RapidsConf, List[TpuExec]], TpuExec]


EXEC_RULES: Dict[Type[C.CpuExec], ExecRule] = {}


def _exec_rule(cls, name, desc, tag, convert):
    EXEC_RULES[cls] = ExecRule(name, desc, tag, convert)


def _tag_output_types(meta: "PlanMeta") -> None:
    for f in meta.wrapped.output_schema.fields:
        err = _check_type(f.dataType, meta.conf)
        if err:
            meta.will_not_work(f"column {f.name}: {err}")


def _tag_scan(meta: "PlanMeta") -> None:
    _tag_output_types(meta)


def _convert_scan(cpu: C.CpuScanExec, conf, children):
    from ..columnar.batch import batch_from_rows

    parts = []
    for i in range(cpu.num_partitions):
        rows = list(cpu.execute_rows_partition(i))
        parts.append([batch_from_rows(rows, cpu.output_schema)] if rows else [])
    return XB.InMemoryScanExec(conf, parts, cpu.output_schema)


def _tag_file_scan(meta: "PlanMeta") -> None:
    cpu: C.CpuFileScanExec = meta.wrapped  # type: ignore[assignment]
    from ..conf import CSV_ENABLED, ORC_ENABLED, PARQUET_ENABLED

    gate = {
        "parquet": (PARQUET_ENABLED, "spark.rapids.tpu.sql.format.parquet.enabled"),
        "csv": (CSV_ENABLED, "spark.rapids.tpu.sql.format.csv.enabled"),
        "orc": (ORC_ENABLED, "spark.rapids.tpu.sql.format.orc.enabled"),
    }.get(cpu.fmt)
    if gate is not None and not meta.conf.get(gate[0]):
        meta.will_not_work(
            f"{cpu.fmt} scan is disabled by {gate[1]}")
    _tag_output_types(meta)


def _convert_file_scan(cpu: "C.CpuFileScanExec", conf, children):
    from ..exec.scan import TpuFileSourceScanExec

    return TpuFileSourceScanExec(conf, cpu.scanner, cpu.fmt)


def _has_string_hash(e: E.Expression, schema: StructType) -> bool:
    """hash() with a string input (expr may be unbound: bind to type)."""
    if isinstance(e, E.Murmur3Hash):
        for c in e.exprs:
            try:
                b = E.bind_references(c, schema)
            except (ValueError, KeyError):
                return True  # unresolvable: treat as context, tag later
            if T.is_string(b.dtype):
                return True
    return any(_has_string_hash(c, schema) for c in e.children)


def _tag_project(meta: "PlanMeta") -> None:
    cpu: C.CpuProjectExec = meta.wrapped  # type: ignore[assignment]
    schema = cpu.children[0].output_schema
    for e in cpu.exprs:
        for r in check_expression(e, schema, meta.conf, allow_context=True):
            meta.will_not_work(r)
    _tag_output_types(meta)


def _convert_project(cpu: C.CpuProjectExec, conf, children):
    return XB.TpuProjectExec(conf, cpu.exprs, children[0])


def _tag_filter(meta: "PlanMeta") -> None:
    cpu: C.CpuFilterExec = meta.wrapped  # type: ignore[assignment]
    schema = cpu.children[0].output_schema
    for r in check_expression(cpu.condition, schema, meta.conf):
        meta.will_not_work(r)


def _convert_filter(cpu: C.CpuFilterExec, conf, children):
    return XB.TpuFilterExec(conf, cpu.condition, children[0])


def _tag_range(meta: "PlanMeta") -> None:
    pass


def _convert_range(cpu: C.CpuRangeExec, conf, children):
    return XB.TpuRangeExec(conf, cpu.start, cpu.end, cpu.step, cpu.num_slices,
                           cpu.output_schema.fields[0].name)


def _tag_union(meta: "PlanMeta") -> None:
    _tag_output_types(meta)


def _convert_union(cpu: C.CpuUnionExec, conf, children):
    return XB.TpuUnionExec(conf, children)


def _tag_limit(meta: "PlanMeta") -> None:
    pass


def _convert_limit(cpu: C.CpuLocalLimitExec, conf, children):
    return XB.TpuLocalLimitExec(conf, cpu.limit, children[0])


def _convert_collect_limit(cpu: "C.CpuCollectLimitExec", conf, children):
    return XB.TpuCollectLimitExec(conf, cpu.limit, children[0])


def _tag_expand(meta: "PlanMeta") -> None:
    cpu: C.CpuExpandExec = meta.wrapped  # type: ignore[assignment]
    schema = cpu.children[0].output_schema
    for p in cpu.projections:
        for e in p:
            for r in check_expression(e, schema, meta.conf):
                meta.will_not_work(r)


def _convert_expand(cpu: C.CpuExpandExec, conf, children):
    return XB.TpuExpandExec(
        conf, cpu.projections, [f.name for f in cpu.output_schema.fields],
        children[0],
    )


def _tag_aggregate(meta: "PlanMeta") -> None:
    cpu: C.CpuHashAggregateExec = meta.wrapped  # type: ignore[assignment]
    schema = cpu.children[0].output_schema
    for g in cpu.group_exprs:
        for r in check_expression(g, schema, meta.conf):
            meta.will_not_work(r)
    for ae in cpu.agg_exprs:
        for r in check_aggregate(ae, schema, meta.conf):
            meta.will_not_work(r)
    _tag_output_types(meta)


def _shuffle_partitions(conf, child) -> int:
    from ..conf import SHUFFLE_PARTITIONS

    n = conf.get(SHUFFLE_PARTITIONS)
    return n if n > 0 else child.num_partitions


def _mesh_eligible(conf, *schemas) -> bool:
    """True when the exchange-bounded stage can lower to ONE shard_map
    program over the device mesh (exec/mesh.py). Strings cross the
    collective as a second byte plane (parallel/collective.py), matching
    the reference's type-agnostic UCX transport
    (RapidsShuffleClient.scala:35-98); other non-fixed types (binary)
    stay on the single-host exchange."""
    from ..exec.mesh import mesh_available

    return mesh_available(conf) and all(
        T.is_fixed_width(f.dataType) or isinstance(f.dataType, T.StringType)
        for s in schemas for f in s.fields)


def _convert_aggregate(cpu: C.CpuHashAggregateExec, conf, children):
    child = children[0]
    if child.num_partitions == 1:
        return XA.TpuHashAggregateExec(
            conf, cpu.group_exprs, cpu.agg_exprs, child, A.COMPLETE)
    # mesh path: the whole partial->exchange->final stage as one shard_map
    # program over ICI (the accelerated-shuffle analog the planner selects,
    # RapidsShuffleInternalManager.scala:58-150). String AGGREGATE inputs
    # (min/max over char columns) stay on the exchange path: their string
    # buffer columns have no shard_map lowering yet.
    def _string_agg_input() -> bool:
        for ae in cpu.agg_exprs:
            f = ae.func
            if f.input is None:
                continue
            try:
                b = E.bind_references(f.child, child.output_schema)
            except (ValueError, KeyError):
                return True
            if isinstance(b.dtype, (T.StringType, T.BinaryType)):
                return True
        return False

    if cpu.group_exprs and _mesh_eligible(conf, child.output_schema) \
            and not _string_agg_input():
        try:
            bound_keys = [
                E.bind_references(g, child.output_schema)
                for g in cpu.group_exprs
            ]
        except (ValueError, KeyError):
            bound_keys = None
        # string group keys need the staged source column's byte bound, so
        # they must be DIRECT column references; computed string keys
        # (concat, substring, ...) stay on the single-host exchange
        if bound_keys is not None and all(
            T.is_fixed_width(b.dtype)
            or (T.is_string(b.dtype) and isinstance(b, E.BoundReference))
            for b in bound_keys
        ):
            from ..exec.mesh import TpuMeshAggregateExec

            return TpuMeshAggregateExec(
                conf, cpu.group_exprs, cpu.agg_exprs, child)
    # partial per partition -> key-hash exchange -> final merge per reduce
    # partition (reference: GpuHashAggregateExec partial/final split +
    # GpuShuffleExchangeExec; group keys are partition-disjoint after the
    # hash exchange so FINAL merges stay partition-local)
    from ..exec.exchange import TpuShuffleExchangeExec
    from ..shuffle.partition import HashPartitioning, SinglePartitioning

    partial = XA.TpuHashAggregateExec(
        conf, cpu.group_exprs, cpu.agg_exprs, child, A.PARTIAL)
    nk = len(cpu.group_exprs)
    if nk == 0:
        part = SinglePartitioning()
    else:
        part = HashPartitioning(
            list(range(nk)), _shuffle_partitions(conf, child))
    exchanged = TpuShuffleExchangeExec(conf, partial, part)
    final_child: TpuExec = exchanged
    from ..conf import AQE_ENABLED

    if conf.get(AQE_ENABLED) and nk > 0:
        # lazy AQE: the coalesce plan needs map-side stats, which only
        # exist at execute time — wrap in a thunk exec that re-plans on
        # first touch (reference: AQE re-optimizes between query stages)
        from ..exec.exchange import TpuLazyAQEReadExec

        final_child = TpuLazyAQEReadExec(conf, exchanged)
    return XA.TpuHashAggregateExec(
        conf, cpu.group_exprs, cpu.agg_exprs, final_child, A.FINAL)


def _sortable(dt: T.DataType) -> bool:
    return T.is_fixed_width(dt) or isinstance(dt, (T.StringType, T.BinaryType))


def _tag_sort(meta: "PlanMeta") -> None:
    cpu: C.CpuSortExec = meta.wrapped  # type: ignore[assignment]
    schema = cpu.children[0].output_schema
    for e in cpu.sort_exprs:
        for r in check_expression(e, schema, meta.conf):
            meta.will_not_work(r)
        try:
            b = E.bind_references(e, schema)
            if not _sortable(b.dtype):
                meta.will_not_work(
                    f"sort key type {b.dtype.simpleString} is not sortable on TPU")
        except (ValueError, KeyError) as ex:
            meta.will_not_work(str(ex))
    _tag_output_types(meta)


def _convert_sort(cpu: C.CpuSortExec, conf, children):
    from ..exec.sort import TpuSortExec

    child = children[0]
    if child.num_partitions == 1:
        return TpuSortExec(conf, cpu.sort_exprs, cpu.orders, child)
    # global sort over a partitioned child: range-exchange so partitions are
    # key-ordered, then sort each locally (reference: GpuSortExec global
    # path = GpuRangePartitioning + local sort)
    from ..exec.exchange import TpuShuffleExchangeExec
    from ..ops.sort import SortOrder
    from ..shuffle.partition import RangePartitioning, SinglePartitioning

    schema = child.output_schema
    bound = []
    try:
        bound = [E.bind_references(e, schema) for e in cpu.sort_exprs]
    except (ValueError, KeyError):
        bound = []
    P = _shuffle_partitions(conf, child)
    if (
        bound and all(isinstance(b, E.BoundReference) for b in bound)
        and _mesh_eligible(conf, schema)
    ):
        # mesh path: local sort -> sampled range all_to_all -> merge sort
        # as one shard_map program
        from ..exec.mesh import TpuMeshSortExec

        return TpuMeshSortExec(
            conf, [b.ordinal for b in bound], cpu.orders, child)
    if bound and all(isinstance(b, E.BoundReference) for b in bound) and P > 1:
        part = RangePartitioning(
            [b.ordinal for b in bound],
            [SortOrder(a, nf) for a, nf in cpu.orders],
            P,
        )
    else:
        part = SinglePartitioning()
    exchanged = TpuShuffleExchangeExec(conf, child, part)
    return TpuSortExec(
        conf, cpu.sort_exprs, cpu.orders, exchanged, global_sort=False)


def _tag_join(meta: "PlanMeta") -> None:
    cpu: C.CpuJoinExec = meta.wrapped  # type: ignore[assignment]
    ls = cpu.children[0].output_schema
    rs = cpu.children[1].output_schema
    if not cpu.left_keys:
        if cpu.join_type != "inner":
            meta.will_not_work(
                f"non-equi {cpu.join_type} joins are not supported on TPU")
    for k, schema in [(k, ls) for k in cpu.left_keys] + [
        (k, rs) for k in cpu.right_keys
    ]:
        for r in check_expression(k, schema, meta.conf):
            meta.will_not_work(r)
        try:
            b = E.bind_references(k, schema)
            if not _sortable(b.dtype):
                meta.will_not_work(
                    f"join key type {b.dtype.simpleString} not supported on TPU")
        except (ValueError, KeyError) as ex:
            meta.will_not_work(str(ex))
    if cpu.condition is not None:
        if cpu.join_type != "inner":
            meta.will_not_work(
                "residual join conditions only run on TPU for inner joins")
        comb = StructType(tuple(ls.fields) + tuple(rs.fields))
        for r in check_expression(cpu.condition, comb, meta.conf):
            meta.will_not_work(r)
    _tag_output_types(meta)


def _convert_join(cpu: C.CpuJoinExec, conf, children):
    from ..exec.join import (
        TpuBroadcastNestedLoopJoinExec,
        TpuCartesianProductExec,
        TpuShuffledHashJoinExec,
    )

    if not cpu.left_keys:
        # build side flows through a broadcast exchange (reference:
        # GpuBroadcastExchangeExec feeding GpuBroadcastNestedLoopJoinExec;
        # no condition = GpuCartesianProductExec.scala:304)
        from ..exec.exchange import TpuBroadcastExchangeExec

        bcast = TpuBroadcastExchangeExec(conf, children[1])
        if cpu.condition is None:
            return TpuCartesianProductExec(conf, children[0], bcast)
        return TpuBroadcastNestedLoopJoinExec(
            conf, children[0], bcast, cpu.condition)
    left, right = children
    # size-thresholded broadcast hash join (reference: the shim
    # GpuBroadcastHashJoinExec selected when Spark stats fall under
    # autoBroadcastJoinThreshold): the small side broadcasts and the big
    # side's partitions probe in place — no exchanges at all
    from ..conf import AUTO_BROADCAST_JOIN_THRESHOLD

    thresh = conf.get(AUTO_BROADCAST_JOIN_THRESHOLD)
    if (
        thresh >= 0 and cpu.condition is None
        and (left.num_partitions > 1 or right.num_partitions > 1)
    ):
        from ..exec.exchange import TpuBroadcastExchangeExec

        lsz = cpu.children[0].estimated_size_bytes()
        rsz = cpu.children[1].estimated_size_bytes()
        # the exec builds from the RIGHT side (left for right joins)
        if cpu.join_type in ("inner", "left", "semi", "anti") \
                and rsz is not None and rsz <= thresh:
            return TpuShuffledHashJoinExec(
                conf, left, TpuBroadcastExchangeExec(conf, right),
                cpu.left_keys, cpu.right_keys, cpu.join_type, None)
        if cpu.join_type == "right" and lsz is not None and lsz <= thresh:
            return TpuShuffledHashJoinExec(
                conf, TpuBroadcastExchangeExec(conf, left), right,
                cpu.left_keys, cpu.right_keys, cpu.join_type, None)
    if left.num_partitions > 1 or right.num_partitions > 1:
        # co-partition both sides by the join keys through hash exchanges
        # (reference: GpuShuffledHashJoinExec requires HashPartitioning
        # children); non-column keys fall back to a single partition
        from ..exec.exchange import TpuShuffleExchangeExec
        from ..shuffle.partition import HashPartitioning, SinglePartitioning

        lb = rb = None
        try:
            lb = [E.bind_references(k, left.output_schema)
                  for k in cpu.left_keys]
            rb = [E.bind_references(k, right.output_schema)
                  for k in cpu.right_keys]
        except (ValueError, KeyError):
            pass
        P = max(_shuffle_partitions(conf, left),
                _shuffle_partitions(conf, right))
        plain = (
            lb is not None and rb is not None
            and all(isinstance(b, E.BoundReference) for b in lb)
            and all(isinstance(b, E.BoundReference) for b in rb)
            # mismatched key dtypes hash differently (Spark casts first);
            # keep those single-partition until the planner inserts casts
            and all(l.dtype == r.dtype for l, r in zip(lb, rb))
        )
        if (
            plain and cpu.join_type == "inner" and cpu.condition is None
            and _mesh_eligible(conf, left.output_schema, right.output_schema)
        ):
            # mesh path: hash-exchange both sides + local join, one
            # shard_map program
            from ..exec.mesh import TpuMeshHashJoinExec

            return TpuMeshHashJoinExec(
                conf, left, right,
                [b.ordinal for b in lb], [b.ordinal for b in rb])
        if plain and P > 1:
            lpart = HashPartitioning([b.ordinal for b in lb], P)
            rpart = HashPartitioning([b.ordinal for b in rb], P)
            partitioned = True
        else:
            lpart = SinglePartitioning()
            rpart = SinglePartitioning()
            partitioned = False
        left = TpuShuffleExchangeExec(conf, left, lpart)
        right = TpuShuffleExchangeExec(conf, right, rpart)
        from ..conf import AQE_ENABLED

        if partitioned and conf.get(AQE_ENABLED) and cpu.join_type != "full":
            # skew-split the probe side + coalesce small pairs, specs
            # index-aligned across both exchanges (full outer excluded:
            # its unmatched-build pass would emit once per probe slice)
            from ..exec.exchange import lazy_aqe_join_pair

            left, right = lazy_aqe_join_pair(
                conf, left, right, probe_left=cpu.join_type != "right")
        return TpuShuffledHashJoinExec(
            conf, left, right, cpu.left_keys, cpu.right_keys,
            cpu.join_type, cpu.condition, partitioned=partitioned,
        )
    return TpuShuffledHashJoinExec(
        conf, children[0], children[1], cpu.left_keys, cpu.right_keys,
        cpu.join_type, cpu.condition,
    )


def _tag_window(meta: "PlanMeta") -> None:
    from ..expr import windows as W

    cpu: C.CpuWindowExec = meta.wrapped  # type: ignore[assignment]
    schema = cpu.children[0].output_schema
    spec = cpu.spec
    for k in list(spec.partition_by) + list(spec.order_by):
        for r in check_expression(k, schema, meta.conf):
            meta.will_not_work(r)
        try:
            b = E.bind_references(k, schema)
            if not _sortable(b.dtype):
                meta.will_not_work(
                    f"window key type {b.dtype.simpleString} not supported on TPU")
        except (ValueError, KeyError) as ex:
            meta.will_not_work(str(ex))
    frame = spec.resolved_frame()
    branged = False
    if not (frame.is_running or frame.is_whole_partition
            or frame.is_bounded_rows):
        if frame.is_bounded_range:
            # literal RANGE frames need ONE numeric/date/timestamp ORDER
            # BY key for the value search (GpuWindowExpression.scala:168
            # imposes the same single-orderable-key shape)
            branged = True
            if len(spec.order_by) != 1:
                meta.will_not_work(
                    "literal RANGE frames need exactly one ORDER BY key")
            else:
                try:
                    b = E.bind_references(spec.order_by[0], schema)
                    if not (b.dtype.is_numeric or isinstance(
                            b.dtype, (T.DateType, T.TimestampType))):
                        meta.will_not_work(
                            f"RANGE frame order key type "
                            f"{b.dtype.simpleString} not supported on TPU")
                except (ValueError, KeyError) as ex:
                    meta.will_not_work(str(ex))
        else:
            meta.will_not_work(
                "only UNBOUNDED PRECEDING..CURRENT ROW, whole-partition, "
                "literal ROWS, or literal RANGE window frames run on TPU")
    from . import typechecks as TC

    for we in cpu.window_exprs:
        f = we.func
        if branged and isinstance(f, (A.Min, A.Max)):
            # arbitrary-range min/max needs a log2(cap)-level sparse
            # table (HBM-heavy); not lowered yet
            meta.will_not_work(
                "min/max over a literal RANGE frame not supported on TPU")
        if isinstance(f, (W.RowNumber, W.Rank, W.DenseRank)):
            continue
        if isinstance(f, (W.Lead, W.Lag)):
            for r in check_expression(f.child, schema, meta.conf):
                meta.will_not_work(r)
            continue
        if isinstance(f, (A.Count, A.Sum, A.Min, A.Max, A.Average)):
            # the function's WINDOW-context matrix cell (reference: the
            # window column of TypeChecks; float agg gated per
            # GpuOverrides.scala:1725, strings off — the window kernels
            # have no string frame path)
            if f.input is not None:
                try:
                    bound_f = E.bind_references(f, schema)
                except (ValueError, KeyError) as ex:
                    meta.will_not_work(str(ex))
                    continue
                for r in TC.check_node(bound_f, meta.conf, TC.WINDOW):
                    meta.will_not_work(r)
                for r in check_expression(f.child, schema, meta.conf):
                    meta.will_not_work(r)
            continue
        meta.will_not_work(
            f"window function {type(f).__name__} is not supported on TPU")
    _tag_output_types(meta)


def _convert_window(cpu: C.CpuWindowExec, conf, children):
    from ..exec.window import TpuWindowExec

    child = children[0]
    # mesh path (round 6): hash-exchange rows by the PARTITION keys, then
    # the per-shard window body — window partitions are independent, so
    # the exchange preserves exact semantics. Gated to direct fixed-width
    # partition-key references over an all-fixed-width child (strings
    # keep the single-partition gather path).
    spec = cpu.window_exprs[0].spec if cpu.window_exprs else None
    if (
        spec is not None and spec.partition_by
        and child.num_partitions > 1
        and _mesh_eligible(conf, child.output_schema)
        and all(T.is_fixed_width(f.dataType)
                for f in child.output_schema.fields)
    ):
        try:
            bound = [E.bind_references(k, child.output_schema)
                     for k in spec.partition_by]
        except (ValueError, KeyError):
            bound = None
        if bound is not None and all(
            isinstance(b, E.BoundReference) and T.is_fixed_width(b.dtype)
            for b in bound
        ):
            from ..exec.mesh import TpuMeshWindowExec

            return TpuMeshWindowExec(conf, cpu.window_exprs, child)
    return TpuWindowExec(conf, cpu.window_exprs, children[0])


_exec_rule(C.CpuScanExec, "ScanExec", "in-memory data source", _tag_scan, _convert_scan)
_exec_rule(C.CpuFileScanExec, "FileSourceScanExec", "parquet/csv/orc file scan",
           _tag_file_scan, _convert_file_scan)
_exec_rule(C.CpuRangeExec, "RangeExec", "range of longs", _tag_range, _convert_range)
_exec_rule(C.CpuProjectExec, "ProjectExec", "column projection", _tag_project, _convert_project)
_exec_rule(C.CpuFilterExec, "FilterExec", "row filter", _tag_filter, _convert_filter)
_exec_rule(C.CpuUnionExec, "UnionExec", "union all", _tag_union, _convert_union)
_exec_rule(C.CpuLocalLimitExec, "LocalLimitExec", "row limit", _tag_limit, _convert_limit)
_exec_rule(C.CpuCollectLimitExec, "CollectLimitExec", "global row limit",
           _tag_limit, _convert_collect_limit)
_exec_rule(C.CpuExpandExec, "ExpandExec", "expand projections", _tag_expand, _convert_expand)
_exec_rule(C.CpuGenerateExec, "GenerateExec", "explode generator rows",
           _tag_expand, _convert_expand)
_exec_rule(C.CpuHashAggregateExec, "HashAggregateExec", "hash aggregation",
           _tag_aggregate, _convert_aggregate)
_exec_rule(C.CpuSortExec, "SortExec", "sort", _tag_sort, _convert_sort)
_exec_rule(C.CpuJoinExec, "JoinExec", "equi/nested-loop join",
           _tag_join, _convert_join)
_exec_rule(C.CpuWindowExec, "WindowExec", "window functions",
           _tag_window, _convert_window)


# ---------------------------------------------------------------------------
# Meta / tagging (reference: RapidsMeta.scala)
# ---------------------------------------------------------------------------
class PlanMeta:
    def __init__(self, cpu_exec: C.CpuExec, conf: RapidsConf,
                 parent: Optional["PlanMeta"] = None):
        self.wrapped = cpu_exec
        self.conf = conf
        self.parent = parent
        self.child_metas = [PlanMeta(c, conf, self) for c in cpu_exec.children]
        self.reasons: List[str] = []
        self.rule = EXEC_RULES.get(type(cpu_exec))

    def will_not_work(self, reason: str) -> None:
        if reason not in self.reasons:
            self.reasons.append(reason)

    def tag_for_tpu(self) -> None:
        if self.rule is None:
            self.will_not_work(
                f"no TPU replacement rule for {self.wrapped.node_name}"
            )
        else:
            self.rule.tag(self)
        for c in self.child_metas:
            c.tag_for_tpu()

    @property
    def can_replace(self) -> bool:
        return not self.reasons

    def convert_if_needed(self):
        """Returns (exec, is_tpu) inserting transitions at boundaries
        (reference: RapidsMeta.convertIfNeeded :623)."""
        converted = [c.convert_if_needed() for c in self.child_metas]
        if self.can_replace and self.rule is not None:
            tpu_children = [
                ex if is_tpu else RowToColumnarExec(self.conf, ex)
                for ex, is_tpu in converted
            ]
            return self.rule.convert(self.wrapped, self.conf, tpu_children), True
        cpu_children = [
            ColumnarToRowExec(self.conf, ex) if is_tpu else ex
            for ex, is_tpu in converted
        ]
        self.wrapped.children = cpu_children
        return self.wrapped, False

    # -- reporting ---------------------------------------------------------
    def explain_lines(self, indent: int = 0) -> List[str]:
        """The willNotWorkOnTpu report (reference: RapidsMeta.explain):
        one line per exec, plus — for fallen-back execs — one nested
        ``!Expression`` line per expression-level matrix reason, so the
        operator AND the offending expression/parameter/type are both
        named without reading code."""
        name = self.rule.name if self.rule else self.wrapped.node_name
        pad = "  " * indent
        if self.can_replace:
            lines = [f"{pad}*Exec <{name}> will run on TPU"]
        else:
            why = "; ".join(self.reasons)
            lines = [f"{pad}!Exec <{name}> cannot run on TPU because {why}"]
            known = {r.name for r in EXPRESSION_RULES.values()}
            for r in self.reasons:
                rule, sep, rest = r.partition(": ")
                if sep and rule in known:
                    lines.append(
                        f"{pad}  !Expression <{rule}> cannot run on TPU "
                        f"because {rest}")
        for c in self.child_metas:
            lines.extend(c.explain_lines(indent + 1))
        return lines

    def fallback_name_sets(self) -> List[Tuple[str, ...]]:
        """Per fallen-back node, every name it answers to: the wrapped CPU
        class name and the Spark-style rule name (reference:
        assert_gpu_fallback_collect matches Spark class names)."""
        out: List[Tuple[str, ...]] = []
        if not self.can_replace:
            names = [self.wrapped.node_name]
            if self.rule is not None and self.rule.name not in names:
                names.append(self.rule.name)
            out.append(tuple(names))
        for c in self.child_metas:
            out.extend(c.fallback_name_sets())
        return out

    def fallback_nodes(self) -> List[str]:
        return [n for names in self.fallback_name_sets() for n in names]


def explain_plan(meta: PlanMeta, conf: RapidsConf) -> str:
    mode = conf.get(EXPLAIN)
    if mode == "NONE":
        return ""
    lines = meta.explain_lines()
    if mode == "NOT_ON_TPU":
        lines = [l for l in lines if "!Exec" in l]
    return "\n".join(lines)


class TpuOverrides:
    """The ColumnarRule analog (reference: Plugin.scala:40-47 +
    GpuOverrides.apply)."""

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self.last_explain = ""
        self.last_meta: Optional[PlanMeta] = None

    def apply(self, plan: C.CpuExec):
        """CPU plan -> (executable plan, is_tpu_topmost)."""
        if not self.conf.get(SQL_ENABLED):
            return plan, False
        from ..exec.base import planning_mode

        with planning_mode():  # adaptive reads must not run stages here
            meta = PlanMeta(plan, self.conf)
            meta.tag_for_tpu()
            self.last_meta = meta
            self.last_explain = explain_plan(meta, self.conf)
            if self.conf.get(TEST_CONF):
                allowed = {
                    s.strip()
                    for s in self.conf.get(TEST_ALLOWED_NONTPU).split(",")
                    if s.strip()
                }
                bad = [names[0] for names in meta.fallback_name_sets()
                       if not any(n in allowed for n in names)]
                if bad:
                    raise AssertionError(
                        "Part of the plan is not columnar "
                        f"(fell back to CPU): {bad}\n"
                        + "\n".join(meta.explain_lines())
                    )
            return meta.convert_if_needed()
