"""Static per-operator type-support matrices.

Reference analog: TypeChecks.scala (453 LoC) — every GPU rule declares,
per parameter and per expression context, exactly which input types it
accepts, and ONE checker walks the plan producing reasoned verdicts
(``willNotWorkOnGpu``). The same tables generate docs/supported_ops.md so
the documentation can never drift from the tagging behavior.

This module is that subsystem for the TPU engine:

  * :class:`TypeSig` — a set of supported type tags, plus conditional
    support (conf gates, literal-only parameters, footnotes).
  * :class:`ExprChecks` — per-context (project / aggregation / window /
    lambda) parameter and output signatures for one expression rule,
    with an optional value-level ``tag`` hook for the few rules whose
    supportability depends on literal VALUES (regex patterns, trunc
    units, UDF trace) rather than types.
  * :class:`CastChecks` — the full from-type x to-type cast matrix with
    its conf-gated pairs.
  * :func:`check_expr` — the single checker the override pass calls:
    walks a bound expression tree without lowering anything and returns
    every reason the tree cannot run on TPU, each reason naming the
    rule, the parameter, and the offending type (e.g. ``Min: input
    string is not supported in the window context``).

The matrix is the PRIMARY tagging mechanism; the legacy abstract-trace
probe (expr/eval.tpu_supports) survives only as a conf-gated
cross-check (spark.rapids.tpu.sql.matrix.probeCrossCheck.enabled) and
as the value-level ``tag`` hook of the rules that need it.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..conf import (
    DECIMAL_ENABLED,
    ENABLE_CAST_FLOAT_TO_TIMESTAMP,
    ENABLE_CAST_STRING_TO_FLOAT,
    ENABLE_CAST_STRING_TO_INTEGER,
    ENABLE_CAST_STRING_TO_TIMESTAMP,
    IMPROVED_FLOAT_OPS,
    RapidsConf,
)
from ..expr import aggregates as A
from ..expr import expressions as E
from ..expr import windows as W

# ---------------------------------------------------------------------------
# Expression contexts (reference: the ExprContext column of TypeChecks —
# project / aggregation / window / lambda cells can differ per rule)
# ---------------------------------------------------------------------------
PROJECT = "project"
AGGREGATION = "aggregation"
WINDOW = "window"
LAMBDA = "lambda"

CONTEXTS = (PROJECT, AGGREGATION, WINDOW, LAMBDA)

# Canonical type-tag order (doc columns). ``decimal`` covers every
# DecimalType(p<=18); array/struct are not representable on the engine at
# all and never appear as tags.
TYPE_TAGS = (
    "boolean", "tinyint", "smallint", "int", "bigint", "float", "double",
    "decimal", "string", "binary", "date", "timestamp", "null",
)


def tag_of(dt: T.DataType) -> str:
    """Doc/matrix tag of a concrete type ('array<...>' etc. for the
    unrepresentable ones, which never match any TypeSig)."""
    if isinstance(dt, T.DecimalType):
        return "decimal"
    return dt.simpleString


class TypeSig:
    """An immutable set of supported type tags with conditional support.

    ``lit_only``  tags supported only when the argument is a literal.
    ``notes``     tag -> footnote rendered as PS (partial support) in docs.
    ``gates``     tag -> (ConfEntry, message): supported only when the
                  boolean conf is enabled; the message is the fallback
                  reason (and the doc footnote) while it is off.
    """

    __slots__ = ("tags", "lit_only", "notes", "gates")

    def __init__(self, tags, lit_only=(), notes=None, gates=None):
        self.tags = frozenset(tags)
        self.lit_only = frozenset(lit_only)
        self.notes: Dict[str, str] = dict(notes or {})
        self.gates: Dict[str, tuple] = dict(gates or {})

    # -- construction -----------------------------------------------------
    @staticmethod
    def of(*tags: str) -> "TypeSig":
        return TypeSig(tags)

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(
            self.tags | other.tags,
            self.lit_only | other.lit_only,
            {**self.notes, **other.notes},
            {**self.gates, **other.gates},
        )

    def with_note(self, tags, note: str) -> "TypeSig":
        tags = (tags,) if isinstance(tags, str) else tags
        notes = dict(self.notes)
        for t in tags:
            notes[t] = note
        return TypeSig(self.tags, self.lit_only, notes, self.gates)

    def with_lit_only(self, *tags: str) -> "TypeSig":
        add = tags or tuple(self.tags)
        return TypeSig(self.tags | set(add), self.lit_only | set(add),
                       self.notes, self.gates)

    def with_gate(self, tags, entry, message: str) -> "TypeSig":
        tags = (tags,) if isinstance(tags, str) else tags
        gates = dict(self.gates)
        for t in tags:
            gates[t] = (entry, message)
        return TypeSig(self.tags, self.lit_only, gates=gates,
                       notes=self.notes)

    # -- checking ---------------------------------------------------------
    def check(self, dt: T.DataType, conf: RapidsConf,
              is_literal: bool = False) -> Optional[str]:
        """None when ``dt`` is supported here; otherwise the detail text
        the caller prefixes with rule/parameter/context."""
        if isinstance(dt, T.NullType) and is_literal:
            return None  # a null literal is valid anywhere a value is
        t = tag_of(dt)
        if t not in self.tags:
            return f"{dt.simpleString} is not supported"
        if t in self.lit_only and not is_literal:
            return f"{dt.simpleString} is only supported as a literal"
        if t == "decimal":
            err = decimal_reason(dt, conf)
            if err:
                return err
        gate = self.gates.get(t)
        if gate is not None and not conf.get(gate[0]):
            return gate[1]
        return None

    # -- doc cells --------------------------------------------------------
    def cell(self, tag: str) -> str:
        """'S' full support, 'PS' partial (noted/gated/lit-only), '' none."""
        if tag not in self.tags:
            return ""
        if tag in self.notes or tag in self.gates or tag in self.lit_only:
            return "PS"
        return "S"

    def cell_note(self, tag: str) -> Optional[str]:
        if tag not in self.tags:
            return None
        parts = []
        if tag in self.lit_only:
            parts.append("literal only")
        if tag in self.notes:
            parts.append(self.notes[tag])
        if tag in self.gates:
            entry, _ = self.gates[tag]
            parts.append(f"requires {entry.key}=true")
        return "; ".join(parts) if parts else None


def decimal_reason(dt: T.DecimalType, conf: RapidsConf) -> Optional[str]:
    """The engine-wide DECIMAL64 gate (reference: isSupportedType
    GpuOverrides.scala:531 + the decimalType.enabled conf)."""
    if not conf.get(DECIMAL_ENABLED):
        return ("decimal support is disabled "
                "(spark.rapids.tpu.sql.decimalType.enabled)")
    if dt.precision > T.DecimalType.MAX_PRECISION:
        return f"decimal precision {dt.precision} > 18 not supported"
    return None


# Shared signatures (reference: the TypeSig companions in TypeChecks.scala)
none = TypeSig.of()
BOOLEAN = TypeSig.of("boolean")
integral = TypeSig.of("tinyint", "smallint", "int", "bigint")
fp = TypeSig.of("float", "double")
decimal128 = TypeSig.of("decimal")  # DECIMAL64 really; tag name is 'decimal'
numeric = integral + fp + decimal128
datetime = TypeSig.of("date", "timestamp")
STRING = TypeSig.of("string")
BINARY = TypeSig.of("binary")
NULL = TypeSig.of("null")
orderable = numeric + BOOLEAN + datetime + STRING
commonTypes = numeric + BOOLEAN + datetime + STRING
allTypes = commonTypes + BINARY + NULL

_FLOAT_AGG_MSG = (
    "floating-point sum/average can differ from CPU results; set "
    "spark.rapids.tpu.sql.variableFloatAgg.enabled=true to enable"
)
_FLOAT_WINDOW_AGG_MSG = (
    "floating-point window sum/average can differ from CPU results; set "
    "spark.rapids.tpu.sql.variableFloatAgg.enabled=true to enable"
)


# ---------------------------------------------------------------------------
# Per-rule checks
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ParamCheck:
    name: str
    sig: TypeSig
    lit_required: bool = False


@dataclasses.dataclass(frozen=True)
class ContextCheck:
    """Signatures of one rule in one expression context."""

    params: Tuple[ParamCheck, ...]
    output: TypeSig
    #: variadic tail: children beyond ``params`` check against this
    repeat: Optional[ParamCheck] = None


class ExprChecks:
    """All declared contexts of one expression rule + the optional
    value-level tag hook (reference: tagExprForGpu)."""

    __slots__ = ("contexts", "tag")

    def __init__(self, contexts: Dict[str, ContextCheck],
                 tag: Optional[Callable] = None):
        self.contexts = contexts
        self.tag = tag

    # -- constructors ------------------------------------------------------
    @staticmethod
    def project_only(output: TypeSig, params: Sequence[Tuple] = (),
                     repeat: Optional[Tuple] = None,
                     tag: Optional[Callable] = None) -> "ExprChecks":
        pcs = tuple(ParamCheck(*p) for p in params)
        rep = ParamCheck(*repeat) if repeat is not None else None
        return ExprChecks(
            {PROJECT: ContextCheck(pcs, output, rep)}, tag=tag)

    @staticmethod
    def unary(output: TypeSig, input_sig: TypeSig, name: str = "input",
              tag: Optional[Callable] = None) -> "ExprChecks":
        return ExprChecks.project_only(output, [(name, input_sig)], tag=tag)

    @staticmethod
    def binary(output: TypeSig, lhs: TypeSig, rhs: TypeSig,
               names: Tuple[str, str] = ("lhs", "rhs"),
               tag: Optional[Callable] = None) -> "ExprChecks":
        return ExprChecks.project_only(
            output, [(names[0], lhs), (names[1], rhs)], tag=tag)

    @staticmethod
    def math_unary() -> "ExprChecks":
        return ExprChecks.unary(fp + NULL, numeric + BOOLEAN)

    @staticmethod
    def aggregate(input_sig: TypeSig, output: TypeSig,
                  window_input: Optional[TypeSig] = None,
                  tag: Optional[Callable] = None) -> "ExprChecks":
        """An aggregate function: aggregation context always, window
        context when ``window_input`` is given (its own, usually
        narrower, input sig — e.g. Min/Max support strings in
        aggregation but not over window frames)."""
        ctxs = {
            AGGREGATION: ContextCheck(
                (ParamCheck("input", input_sig),), output),
        }
        if window_input is not None:
            ctxs[WINDOW] = ContextCheck(
                (ParamCheck("input", window_input),), output)
        return ExprChecks(ctxs, tag=tag)

    @staticmethod
    def window_only(output: TypeSig, params: Sequence[Tuple] = ()) -> "ExprChecks":
        pcs = tuple(ParamCheck(*p) for p in params)
        return ExprChecks({WINDOW: ContextCheck(pcs, output)})

    @staticmethod
    def passthrough() -> "ExprChecks":
        """Structural nodes (Alias, references, holders): every engine
        type, no checks of their own. One shared ContextCheck so docgen
        collapses the contexts into a single 'all' row."""
        cc = ContextCheck((), allTypes)
        return ExprChecks(
            {c: cc for c in (PROJECT, AGGREGATION, WINDOW)})


# ---------------------------------------------------------------------------
# Cast matrix (reference: CastChecks in TypeChecks.scala — a full
# from-type x to-type grid with the conf-gated pairs)
# ---------------------------------------------------------------------------
_CAST_STRING_TO_INT_MSG = (
    "casting string to integral types is disabled; set "
    "spark.rapids.tpu.sql.castStringToInteger.enabled=true")
_CAST_STRING_TO_FLOAT_MSG = (
    "casting string to float is disabled; set "
    "spark.rapids.tpu.sql.castStringToFloat.enabled=true")
_CAST_STRING_TO_TS_MSG = (
    "casting string to timestamp is disabled; set "
    "spark.rapids.tpu.sql.castStringToTimestamp.enabled=true")
_CAST_FLOAT_TO_TS_MSG = (
    "casting float to timestamp is disabled; set "
    "spark.rapids.tpu.sql.castFloatToTimestamp.enabled=true")


class CastChecks:
    """from-tag -> TypeSig of castable to-types. Derived from the actual
    device kernels (eval.py _cast_data/_decimal_cast, eval_strings
    lower_string_cast/lower_cast_to_string) so the matrix states exactly
    what lowers."""

    def __init__(self):
        b = "boolean"
        ints = ("tinyint", "smallint", "int", "bigint")
        m: Dict[str, TypeSig] = {}
        m["boolean"] = (TypeSig.of(b, *ints) + fp + STRING
                        + TypeSig.of("timestamp") + decimal128)
        for i in ints:
            m[i] = (TypeSig.of(b, *ints) + fp + STRING
                    + TypeSig.of("timestamp")
                    + decimal128.with_note(
                        "decimal",
                        "values beyond the target precision null out"))
        f = (TypeSig.of(b, *ints) + fp
             + TypeSig.of("timestamp").with_gate(
                 "timestamp", ENABLE_CAST_FLOAT_TO_TIMESTAMP,
                 _CAST_FLOAT_TO_TS_MSG))
        m["float"] = f
        m["double"] = f
        m["decimal"] = (TypeSig.of(b, *ints) + fp
                        + decimal128.with_note(
                            "decimal",
                            "rescale must fit DECIMAL64 headroom"))
        m["string"] = (STRING + TypeSig.of("date")
                       + TypeSig.of(b)
                       + TypeSig.of(*ints).with_gate(
                           ints, ENABLE_CAST_STRING_TO_INTEGER,
                           _CAST_STRING_TO_INT_MSG)
                       + fp.with_gate(
                           ("float", "double"), ENABLE_CAST_STRING_TO_FLOAT,
                           _CAST_STRING_TO_FLOAT_MSG)
                       + TypeSig.of("timestamp").with_gate(
                           "timestamp", ENABLE_CAST_STRING_TO_TIMESTAMP,
                           _CAST_STRING_TO_TS_MSG))
        m["date"] = TypeSig.of("date", "timestamp") + STRING
        m["timestamp"] = (TypeSig.of(b, *ints) + fp
                          + TypeSig.of("date", "timestamp") + STRING)
        m["binary"] = none
        m["null"] = allTypes
        self.matrix = m

    def reason(self, frm: T.DataType, to: T.DataType,
               conf: RapidsConf) -> Optional[str]:
        for dt in (frm, to):
            if isinstance(dt, T.DecimalType):
                err = decimal_reason(dt, conf)
                if err:
                    return err
        sig = self.matrix.get(tag_of(frm))
        if sig is None:
            return (f"cast from {frm.simpleString} is not supported on TPU")
        t = tag_of(to)
        if t not in sig.tags:
            return (f"cast {frm.simpleString} -> {to.simpleString} "
                    "is not supported on TPU")
        gate = sig.gates.get(t)
        if gate is not None and not conf.get(gate[0]):
            return gate[1]
        return None


CAST_CHECKS = CastChecks()


def _tag_cast(node: E.Cast, conf: RapidsConf) -> List[str]:
    r = CAST_CHECKS.reason(node.child.dtype, node.to, conf)
    return [f"Cast: {r}"] if r else []


# ---------------------------------------------------------------------------
# Value-level tag hooks (reference: tagExprForGpu overrides — the few
# rules whose support depends on literal VALUES, not types)
# ---------------------------------------------------------------------------
def _lit_value(e: E.Expression):
    return e.value if isinstance(e, E.Literal) else None


def _tag_comparable(node, conf) -> List[str]:
    """Binary comparison operands must promote to one comparison type
    (string-vs-string or one numeric/datetime lattice point)."""
    l, r = node.left.dtype, node.right.dtype
    if isinstance(l, T.NullType) or isinstance(r, T.NullType):
        return []
    ls, rs = (isinstance(x, T.StringType) for x in (l, r))
    if ls != rs:
        return [f"{type(node).__name__}: comparison between "
                f"{l.simpleString} and {r.simpleString} is not supported"]
    if not ls and l != r:
        try:
            T.promote(l, r)
        except TypeError as e:
            return [f"{type(node).__name__}: {e}"]
    return []


def _tag_binary_arith(node, conf) -> List[str]:
    """+,-,*,%,pmod operand pair must promote (decimal results must also
    fit DECIMAL64 — surfaced by the dtype computation itself)."""
    l, r = node.left.dtype, node.right.dtype
    if isinstance(l, T.NullType) or isinstance(r, T.NullType):
        return []
    if l != r:
        try:
            T.promote(l, r)
        except TypeError as e:
            return [f"{type(node).__name__}: {e}"]
    return []


def _tag_like(node: E.Like, conf) -> List[str]:
    pat = _lit_value(node.pattern)
    if pat is None:
        return []
    from ..expr.eval_strings import _parse_like

    try:
        toks = _parse_like(pat, node.escape)
    except ValueError as e:
        return [f"Like: {e}"]
    if "%" in toks and "_" in toks:
        return ["Like: patterns mixing % and _ are not supported on TPU"]
    return []


def _tag_rlike(node: E.RLike, conf) -> List[str]:
    pat = _lit_value(node.pattern)
    if pat is None:
        return []
    from ..ops import regex as RX

    try:
        RX.compile_search_dfa(pat)
    except Exception as e:  # noqa: BLE001 — any compile failure = fallback
        return [f"RLike: pattern not supported by the byte DFA: {e}"]
    return []


def _tag_regexp_replace(node: E.RegExpReplace, conf) -> List[str]:
    pat = _lit_value(node.pattern)
    repl = _lit_value(node.replacement)
    reasons = []
    if pat is not None:
        from ..ops import regex as RX

        literal = RX.regex_as_literal(pat)
        if literal is None or literal == "":
            reasons.append(
                "RegExpReplace: pattern is not literal-equivalent")
    if repl is not None and ("$" in repl or "\\" in repl):
        reasons.append(
            "RegExpReplace: replacement with group references")
    return reasons


def _tag_split_part(node: E.StringSplitPart, conf) -> List[str]:
    reasons = []
    d = _lit_value(node.delim)
    if d == "":
        reasons.append("StringSplit: split with empty delimiter")
    idx = _lit_value(node.index)
    if isinstance(idx, int) and idx < 0:
        reasons.append("StringSplit: split index must be >= 0")
    return reasons


def _tag_trunc_date(node: E.TruncDate, conf) -> List[str]:
    fmt = _lit_value(node.fmt)
    if fmt is None:
        return []
    if fmt.lower() not in (
            "year", "yyyy", "yy", "quarter", "month", "mon", "mm", "week"):
        return [f"TruncDate: unit {fmt!r} is not supported on TPU"]
    return []


def _tag_from_unixtime(node: E.FromUnixTime, conf) -> List[str]:
    fmt = _lit_value(node.format)
    if fmt is not None and fmt != "yyyy-MM-dd HH:mm:ss":
        return ["FromUnixTime: only the default 'yyyy-MM-dd HH:mm:ss' "
                "format is supported on TPU"]
    return []


def _tag_in_values(node: E.In, conf) -> List[str]:
    ok = (type(None), bool, int, float, str)
    bad = [v for v in node.values if not isinstance(v, ok)]
    if bad:
        return [f"In: value {bad[0]!r} is not a supported literal"]
    return []


def _tag_native_udf(node: E.NativeUDF, conf) -> List[str]:
    """A native UDF's columnar function is arbitrary user code: the only
    sound static check is the abstract trace itself (reference: a
    RapidsUDF throwing in evaluateColumnar falls back to the row path).
    This is the ONE rule where the lowering probe is the matrix."""
    from .. import types as TT
    from ..expr.eval import tpu_supports

    dts = [c.dtype for c in node.children_]
    schema = TT.StructType(tuple(
        TT.StructField(f"c{i}", dt, True) for i, dt in enumerate(dts)))
    probe = E.NativeUDF(
        node.columnar_fn, node.row_fn,
        tuple(E.BoundReference(i, dt, True) for i, dt in enumerate(dts)),
        node.return_type)
    ok, why = tpu_supports(probe, schema)
    if not ok:
        return [f"NativeUDF: {why or 'columnar trace failed'}"]
    return []


# ---------------------------------------------------------------------------
# The declarations: one ExprChecks per registered expression rule
# ---------------------------------------------------------------------------
_PROJECTION_ONLY_NOTE = "only inside a projection"

CHECKS: Dict[type, ExprChecks] = {}


def _c(cls, checks: ExprChecks) -> None:
    CHECKS[cls] = checks


# structural / leaves -------------------------------------------------------
_c(E.Literal, ExprChecks.passthrough())
_c(E.UnresolvedAttribute, ExprChecks.passthrough())
_c(E.BoundReference, ExprChecks.passthrough())
_c(E.Alias, ExprChecks.passthrough())

# arithmetic ----------------------------------------------------------------
_arith_out = numeric.with_note(
    "decimal", "result precision must fit DECIMAL64 (18 digits)")
for _cls in (E.Add, E.Subtract, E.Multiply):
    _c(_cls, ExprChecks.binary(_arith_out, numeric, numeric,
                               tag=_tag_binary_arith))
_c(E.Divide, ExprChecks.binary(
    fp + decimal128.with_note(
        "decimal", "quotient digits must fit DECIMAL64"),
    numeric, numeric, tag=_tag_binary_arith))
_c(E.IntegralDivide, ExprChecks.binary(
    TypeSig.of("bigint"), integral + fp, integral + fp,
    tag=_tag_binary_arith))
_no_dec_mod = integral + fp
_c(E.Remainder, ExprChecks.binary(_no_dec_mod, _no_dec_mod, _no_dec_mod,
                                  tag=_tag_binary_arith))
_c(E.Pmod, ExprChecks.binary(_no_dec_mod, _no_dec_mod, _no_dec_mod,
                             tag=_tag_binary_arith))
_c(E.UnaryMinus, ExprChecks.unary(numeric, numeric))
_c(E.UnaryPositive, ExprChecks.unary(numeric, numeric))
_c(E.Abs, ExprChecks.unary(numeric, numeric))

# comparisons ---------------------------------------------------------------
for _cls in (E.EqualTo, E.EqualNullSafe, E.LessThan, E.LessThanOrEqual,
             E.GreaterThan, E.GreaterThanOrEqual):
    _c(_cls, ExprChecks.binary(BOOLEAN, orderable, orderable,
                               tag=_tag_comparable))
_c(E.In, ExprChecks.unary(BOOLEAN, orderable, name="value",
                          tag=_tag_in_values))

# boolean logic -------------------------------------------------------------
_c(E.And, ExprChecks.binary(BOOLEAN, BOOLEAN, BOOLEAN))
_c(E.Or, ExprChecks.binary(BOOLEAN, BOOLEAN, BOOLEAN))
_c(E.Not, ExprChecks.unary(BOOLEAN, BOOLEAN))

# null / NaN ----------------------------------------------------------------
_c(E.IsNull, ExprChecks.unary(BOOLEAN, allTypes))
_c(E.IsNotNull, ExprChecks.unary(BOOLEAN, allTypes))
_c(E.IsNan, ExprChecks.unary(BOOLEAN, numeric + BOOLEAN))
_c(E.Coalesce, ExprChecks.project_only(
    commonTypes, repeat=("param", commonTypes)))
_c(E.NaNvl, ExprChecks.binary(fp, fp, fp))

# conditionals --------------------------------------------------------------
_cond_val = commonTypes
_c(E.If, ExprChecks.project_only(
    _cond_val, [("predicate", BOOLEAN), ("trueValue", _cond_val),
                ("falseValue", _cond_val)]))
_c(E.CaseWhen, ExprChecks.project_only(
    _cond_val, repeat=("branch", _cond_val + BOOLEAN)))

# cast ----------------------------------------------------------------------
_c(E.Cast, ExprChecks.unary(
    allTypes, commonTypes + NULL, tag=_tag_cast))

# math ----------------------------------------------------------------------
for _cls in (E.Sqrt, E.Exp, E.Log, E.Log10, E.Log2, E.Log1p, E.Expm1,
             E.Sin, E.Cos, E.Tan, E.Asin, E.Acos, E.Atan, E.Sinh, E.Cosh,
             E.Tanh, E.Cbrt, E.ToDegrees, E.ToRadians):
    _c(_cls, ExprChecks.math_unary())
_c(E.Floor, ExprChecks.unary(numeric, numeric))
_c(E.Ceil, ExprChecks.unary(numeric, numeric))
_c(E.Round, ExprChecks.unary(numeric, numeric))
_c(E.Rint, ExprChecks.unary(fp, numeric + BOOLEAN))
_c(E.Pow, ExprChecks.binary(fp, numeric + BOOLEAN, numeric + BOOLEAN))
_c(E.Atan2, ExprChecks.binary(fp, numeric + BOOLEAN, numeric + BOOLEAN))
_c(E.Signum, ExprChecks.unary(fp, numeric))

# bitwise -------------------------------------------------------------------
for _cls in (E.BitwiseAnd, E.BitwiseOr, E.BitwiseXor):
    _c(_cls, ExprChecks.binary(integral, integral, integral,
                               tag=_tag_binary_arith))
_c(E.BitwiseNot, ExprChecks.unary(integral, integral))
for _cls in (E.ShiftLeft, E.ShiftRight, E.ShiftRightUnsigned):
    _c(_cls, ExprChecks.binary(
        TypeSig.of("int", "bigint"), TypeSig.of("int", "bigint"),
        TypeSig.of("int"), names=("value", "amount")))

# strings -------------------------------------------------------------------
_c(E.Length, ExprChecks.unary(TypeSig.of("int"), STRING))
for _cls in (E.Upper, E.Lower, E.InitCap):
    _c(_cls, ExprChecks.unary(STRING, STRING))
_c(E.Substring, ExprChecks.project_only(
    STRING, [("str", STRING), ("pos", TypeSig.of("int"), True),
             ("len", TypeSig.of("int"), True)]))
_c(E.Concat, ExprChecks.project_only(STRING, repeat=("input", STRING)))
for _cls in (E.StringTrim, E.StringTrimLeft, E.StringTrimRight):
    _c(_cls, ExprChecks.unary(STRING, STRING, name="src"))
for _cls in (E.StartsWith, E.EndsWith, E.Contains):
    _c(_cls, ExprChecks.project_only(
        BOOLEAN, [("src", STRING), ("search", STRING, True)]))
_c(E.Like, ExprChecks.project_only(
    BOOLEAN, [("src", STRING), ("search", STRING, True)], tag=_tag_like))
_c(E.RLike, ExprChecks.project_only(
    BOOLEAN, [("str", STRING), ("regexp", STRING, True)], tag=_tag_rlike))
_c(E.RegExpReplace, ExprChecks.project_only(
    STRING, [("str", STRING), ("regex", STRING, True),
             ("rep", STRING, True)], tag=_tag_regexp_replace))
_c(E.StringLocate, ExprChecks.project_only(
    TypeSig.of("int"), [("substr", STRING, True), ("str", STRING),
                        ("start", TypeSig.of("int"), True)]))
_c(E.StringReplace, ExprChecks.project_only(
    STRING, [("src", STRING), ("search", STRING, True),
             ("replace", STRING, True)]))
for _cls in (E.StringLPad, E.StringRPad):
    _c(_cls, ExprChecks.project_only(
        STRING, [("str", STRING), ("len", TypeSig.of("int"), True),
                 ("pad", STRING, True)]))
_c(E.SubstringIndex, ExprChecks.project_only(
    STRING, [("str", STRING), ("delim", STRING, True),
             ("count", TypeSig.of("int"), True)]))
_c(E.StringSplitPart, ExprChecks.project_only(
    STRING, [("str", STRING), ("delimiter", STRING, True),
             ("index", TypeSig.of("int"), True)], tag=_tag_split_part))

# datetime ------------------------------------------------------------------
for _cls in (E.Year, E.Quarter, E.Month, E.DayOfMonth, E.DayOfYear,
             E.DayOfWeek, E.WeekDay):
    _c(_cls, ExprChecks.unary(TypeSig.of("int"), datetime))
for _cls in (E.Hour, E.Minute, E.Second):
    _c(_cls, ExprChecks.unary(TypeSig.of("int"), TypeSig.of("timestamp")))
_c(E.DateAdd, ExprChecks.project_only(
    TypeSig.of("date"),
    [("startDate", TypeSig.of("date")),
     ("days", TypeSig.of("tinyint", "smallint", "int"))]))
_c(E.DateSub, ExprChecks.project_only(
    TypeSig.of("date"),
    [("startDate", TypeSig.of("date")),
     ("days", TypeSig.of("tinyint", "smallint", "int"))]))
_c(E.DateDiff, ExprChecks.project_only(
    TypeSig.of("int"),
    [("lhs", TypeSig.of("date")), ("rhs", TypeSig.of("date"))]))
_c(E.LastDay, ExprChecks.unary(TypeSig.of("date"), TypeSig.of("date")))
_c(E.UnixTimestamp, ExprChecks.unary(TypeSig.of("bigint"), datetime))
_c(E.ToUnixTimestamp, ExprChecks.unary(TypeSig.of("bigint"), datetime))
_c(E.FromUnixTime, ExprChecks.project_only(
    STRING,
    [("sec", TypeSig.of("bigint")),
     ("format", STRING.with_note(
         "string", "only the default 'yyyy-MM-dd HH:mm:ss' format"), True)],
    tag=_tag_from_unixtime))
_c(E.TimeAdd, ExprChecks.unary(
    TypeSig.of("timestamp"), TypeSig.of("timestamp"), name="start"))
_c(E.TruncDate, ExprChecks.project_only(
    TypeSig.of("date"),
    [("date", TypeSig.of("date")),
     ("format", STRING.with_note(
         "string", "units: year/yyyy/yy/quarter/month/mon/mm/week"), True)],
    tag=_tag_trunc_date))

# nondeterministic / metadata (projection-context only — enforced by the
# override pass, which rejects them anywhere but a project boundary)
_c(E.Rand, ExprChecks.project_only(
    fp.with_note(("float", "double"), _PROJECTION_ONLY_NOTE)))
_c(E.MonotonicallyIncreasingID, ExprChecks.project_only(
    TypeSig.of("bigint").with_note("bigint", _PROJECTION_ONLY_NOTE)))
_c(E.SparkPartitionID, ExprChecks.project_only(
    TypeSig.of("int").with_note("int", _PROJECTION_ONLY_NOTE)))
_c(E.InputFileName, ExprChecks.project_only(
    STRING.with_note("string", _PROJECTION_ONLY_NOTE)))
# decimal excluded: Spark hashes decimals via their BigDecimal layout,
# which neither the TPU kernel nor the row oracle implements yet
_c(E.Murmur3Hash, ExprChecks.project_only(
    TypeSig.of("int"),
    repeat=("input", (integral + fp + BOOLEAN + datetime
                      + STRING.with_note(
                          "string",
                          "hash over strings only inside a projection")))))

# aggregates ----------------------------------------------------------------
_c(A.AggregateExpression, ExprChecks.passthrough())
_c(A.Count, ExprChecks.aggregate(
    numeric + BOOLEAN + datetime, TypeSig.of("bigint"),
    window_input=numeric + BOOLEAN + datetime))
_sum_in = (integral + BOOLEAN
           + fp.with_gate(("float", "double"), IMPROVED_FLOAT_OPS,
                          _FLOAT_AGG_MSG)
           + decimal128.with_note(
               "decimal", "sum buffer needs precision+10 <= 18"))
_sum_in_w = (integral + BOOLEAN
             + fp.with_gate(("float", "double"), IMPROVED_FLOAT_OPS,
                            _FLOAT_WINDOW_AGG_MSG)
             + decimal128.with_note(
                 "decimal", "sum buffer needs precision+10 <= 18"))
_c(A.Sum, ExprChecks.aggregate(
    _sum_in, numeric, window_input=_sum_in_w))
_c(A.Average, ExprChecks.aggregate(
    (integral + BOOLEAN
     + fp.with_gate(("float", "double"), IMPROVED_FLOAT_OPS, _FLOAT_AGG_MSG)
     + decimal128.with_note("decimal", "result needs precision+4 <= 18")),
    fp + decimal128,
    window_input=(integral + BOOLEAN
                  + fp.with_gate(("float", "double"), IMPROVED_FLOAT_OPS,
                                 _FLOAT_WINDOW_AGG_MSG)
                  + decimal128.with_note(
                      "decimal", "result needs precision+4 <= 18"))))
# Min/Max: STRING inputs lower in the AGGREGATION context (dictionary
# sorted-code order, or a rank-by-sort for plain columns) — the window
# kernels have no string frame path yet, so the window cell stays off.
# DIRECT column references only: the rank sort needs a static byte
# bound, which is exact for a column (synced max, or dict metadata) but
# unboundable for length-growing expressions (concat, pads) — a short
# bound would silently compare only a prefix.
def _tag_string_minmax(node, conf) -> List[str]:
    child = getattr(node, "child", None)
    if child is None or not isinstance(child.dtype,
                                       (T.StringType, T.BinaryType)):
        return []
    while isinstance(child, E.Alias):
        child = child.child
    if not isinstance(child, (E.BoundReference, E.UnresolvedAttribute)):
        return [f"{type(node).__name__}: string min/max supports only "
                "direct column references (a computed string has no "
                "static byte bound for the rank sort)"]
    return []


_minmax_in = (numeric + BOOLEAN + datetime
              + STRING.with_note(
                  "string",
                  "direct column references only; lexicographic; "
                  "dictionary-encoded columns reduce in sorted-code "
                  "order"))
_c(A.Min, ExprChecks.aggregate(
    _minmax_in, orderable, window_input=numeric + BOOLEAN + datetime,
    tag=_tag_string_minmax))
_c(A.Max, ExprChecks.aggregate(
    _minmax_in, orderable, window_input=numeric + BOOLEAN + datetime,
    tag=_tag_string_minmax))
_c(A.First, ExprChecks.aggregate(
    numeric + BOOLEAN + datetime, numeric + BOOLEAN + datetime))
_c(A.Last, ExprChecks.aggregate(
    numeric + BOOLEAN + datetime, numeric + BOOLEAN + datetime))

# window functions ----------------------------------------------------------
_c(W.WindowExpression, ExprChecks.passthrough())
_c(W.RowNumber, ExprChecks.window_only(TypeSig.of("int")))
_c(W.Rank, ExprChecks.window_only(TypeSig.of("int")))
_c(W.DenseRank, ExprChecks.window_only(TypeSig.of("int")))
_c(W.Lead, ExprChecks.window_only(
    commonTypes, [("input", commonTypes)]))
_c(W.Lag, ExprChecks.window_only(
    commonTypes, [("input", commonTypes)]))

# native UDFs: type-open, value-checked by tracing the user's columnar fn
_c(E.NativeUDF, ExprChecks.project_only(
    allTypes,
    repeat=("input", commonTypes.with_note(
        tuple(commonTypes.tags),
        "the registered columnar function must trace for these inputs")),
    tag=_tag_native_udf))


# ---------------------------------------------------------------------------
# The checker
# ---------------------------------------------------------------------------
def rule_name(cls: type) -> str:
    """Spark-style rule name (falls back to the class name for internal
    nodes that have no registered rule)."""
    from .overrides import EXPRESSION_RULES

    r = EXPRESSION_RULES.get(cls)
    return r.name if r is not None else cls.__name__


def _node_dtype(node: E.Expression) -> Tuple[Optional[T.DataType],
                                             Optional[str]]:
    try:
        return node.dtype, None
    except Exception as e:  # noqa: BLE001 — any dtype failure = fallback
        return None, str(e) or type(e).__name__


def check_node(node: E.Expression, conf: RapidsConf,
               context: str) -> List[str]:
    """All the reasons ONE bound node cannot run on TPU in ``context``
    (children are checked by their own calls)."""
    from .overrides import EXPRESSION_RULES

    name = rule_name(type(node))
    if type(node) not in EXPRESSION_RULES:
        return [f"expression {type(node).__name__} is not supported on TPU"]
    checks = CHECKS.get(type(node))
    if checks is None:
        return [f"{name} has no type matrix declared"]
    ctx = checks.contexts.get(context)
    if ctx is None and context in (AGGREGATION, WINDOW):
        # inside an aggregation/window the non-aggregate input expressions
        # evaluate in the surrounding projection pass, so rules without a
        # dedicated cell inherit their project declarations
        if isinstance(node, (A.AggregateFunction, W.WindowFunction)):
            return [f"{name}: is not supported in the {context} context"]
        ctx = checks.contexts.get(PROJECT)
    if ctx is None:
        return [f"{name}: is not supported in the {context} context"]

    reasons: List[str] = []
    children = node.children
    for i, child in enumerate(children):
        if i < len(ctx.params):
            pc = ctx.params[i]
        elif ctx.repeat is not None:
            pc = ctx.repeat
        else:
            continue
        cdt, err = _node_dtype(child)
        if err is not None:
            continue  # the child's own check reports it
        is_lit = isinstance(child, E.Literal)
        if pc.lit_required and not is_lit:
            reasons.append(
                f"{name}: {pc.name} must be a literal value")
            continue
        detail = pc.sig.check(cdt, conf, is_literal=is_lit)
        if detail is not None:
            reasons.append(
                f"{name}: {pc.name} {detail} in the {context} context")
    odt, err = _node_dtype(node)
    if err is not None:
        reasons.append(f"{name}: {err}")
    elif not reasons and not isinstance(node, E.Cast):
        # output cell (skipped when an input already failed — the result
        # type follows from the inputs; cast outputs are the cast grid's)
        detail = ctx.output.check(odt, conf,
                                  is_literal=isinstance(node, E.Literal))
        if detail is not None:
            reasons.append(
                f"{name}: produces {detail} in the {context} context")
    if checks.tag is not None:
        try:
            reasons.extend(checks.tag(node, conf))
        except (TypeError, ValueError) as e:
            reasons.append(f"{name}: {e}")
    return reasons


def check_expr(bound: E.Expression, conf: RapidsConf,
               context: str = PROJECT) -> List[str]:
    """Walk a BOUND expression tree; every reason it cannot lower, each
    naming the rule, parameter, and offending type. Empty = ON_TPU."""
    reasons: List[str] = []
    seen = set()

    def visit(node: E.Expression, ctx: str):
        if isinstance(node, (A.AggregateExpression,)):
            # the holder's function/inputs are checked by check_aggregate
            # in the aggregation context; seeing one anywhere else is a
            # planner bug surfaced as a reason, not a crash
            if ctx != AGGREGATION:
                reasons.append(
                    "AggregateExpression: aggregates are only supported "
                    "in the aggregation context")
            node_ctx = AGGREGATION
        else:
            node_ctx = ctx
        for r in check_node(node, conf, node_ctx):
            if r not in seen:
                seen.add(r)
                reasons.append(r)
        for c in node.children:
            visit(c, node_ctx)

    visit(bound, context)
    return reasons


# ---------------------------------------------------------------------------
# Cross-check bookkeeping (matrix verdict vs the legacy lowering probe,
# behind spark.rapids.tpu.sql.matrix.probeCrossCheck.enabled)
# ---------------------------------------------------------------------------
_CROSS_CHECK_LOG: List[str] = []
_CROSS_CHECK_MAX = 256


def note_cross_check_disagreement(msg: str) -> None:
    if len(_CROSS_CHECK_LOG) < _CROSS_CHECK_MAX:
        _CROSS_CHECK_LOG.append(msg)


def cross_check_log() -> List[str]:
    return list(_CROSS_CHECK_LOG)


def clear_cross_check_log() -> None:
    _CROSS_CHECK_LOG.clear()
