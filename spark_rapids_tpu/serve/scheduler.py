"""Concurrent multi-query serving: admission control + fair scheduling.

The arbitration layer between sessions that ROADMAP item 3 names: the
``TpuSemaphore`` caps how many threads hold the device, but nothing
decides whether a plan even FITS, queues work, or keeps one chatty
session from starving the rest. The :class:`QueryScheduler` closes that
gap with the two signals earlier rounds built but never connected:

  * the static plan analyzer's **peak-HBM forecast** (PR 4,
    plugin/plananalysis.py) — what the plan will demand;
  * the live **BufferCatalog watermark + derived budget** (PR 6,
    memory/catalog.py) — what the device can still give.

Admission compares the two and answers **admit / queue / reject**:

  * *admit* — the forecast fits the live headroom (budget − watermark −
    outstanding reservations); the forecast is RESERVED in the catalog
    until release so concurrent admits can't promise the same bytes
    twice. A fixed HBM budget therefore yields queueing, not OOMs.
  * *queue* — the forecast doesn't fit right now. The query waits in its
    session's FIFO; sessions drain round-robin (priority tiers first),
    so one heavy session can't starve the others. While queued, the
    submit thread has already done its host-side work (lowering,
    analysis, plan-cache fill) — and after admission it host-prefetches
    scans BEFORE taking the device semaphore, so host decode of query B
    overlaps device compute of query A (pipelined execution).
  * *reject* — the forecast exceeds the TOTAL budget (it can never fit)
    or the session's queue is at serve.maxQueueDepth; a named error,
    not a hang.

Progress guarantee: when nothing is admitted and nothing else waits, the
head ticket is admitted even if its forecast exceeds the headroom
("bypass") — residual catalog-tracked buffers (caches) must not wedge
the queue; the spiller then enforces the budget as it always has for a
single query. Reference analog: GpuSemaphore plus the admission/queueing
every production serving tier layers above it.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, List, Optional

from .. import events as _events
from .. import obs as _obs
from ..conf import RapidsConf, conf
from ..utils.locks import ordered_lock

SERVE_ENABLED = conf(
    "spark.rapids.tpu.serve.enabled", False,
    "Route query execution through the process-wide QueryScheduler "
    "(serve/scheduler.py): plans are admitted by checking the static "
    "analyzer's peak-HBM forecast against the live BufferCatalog "
    "watermark and derived budget (admit / queue / reject-with-reason), "
    "queued per session with round-robin across sessions, and the "
    "admitted query host-prefetches its scans before taking the device "
    "semaphore so host decode overlaps the running query's device "
    "compute. Off (the default) keeps the single-session direct path.")
SERVE_MAX_QUEUE_DEPTH = conf(
    "spark.rapids.tpu.serve.maxQueueDepth", 64,
    "Per-session queue cap: a submit that would queue deeper than this "
    "is rejected with a named error instead of growing the backlog "
    "without bound (load shedding).", check=lambda v: (
        None if v > 0 else "must be positive"))
SERVE_QUEUE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.serve.queueTimeoutMs", 0,
    "Give up on a queued query after this many milliseconds with a "
    "named error carrying its queue position and the admission reason; "
    "0 (the default) waits indefinitely.", conf_type=int,
    check=lambda v: None if v >= 0 else "must be >= 0")
SERVE_PRIORITY = conf(
    "spark.rapids.tpu.serve.priority", 0,
    "Scheduling priority of THIS session's queries (a per-session "
    "setting): higher-priority sessions' queues drain first; sessions "
    "at the same priority round-robin.", conf_type=int)
SERVE_ADMISSION_ENABLED = conf(
    "spark.rapids.tpu.serve.admission.enabled", True,
    "Forecast-based admission control. Off admits every submit "
    "immediately (fair queueing and pipelining still apply); on — the "
    "default — plans whose peak-HBM forecast exceeds the live headroom "
    "queue until reservations release, and plans that can never fit "
    "the total budget are rejected with a named reason.")


def _pretty_bytes(n: Optional[int]) -> str:
    if n is None:
        return "unbounded"
    if abs(n) >= 1 << 30:
        return f"{n / (1 << 30):.2f} GB"
    if abs(n) >= 1 << 20:
        return f"{n / (1 << 20):.1f} MB"
    return f"{n} B"


class ServeAdmissionRejected(RuntimeError):
    """The scheduler refused the query outright (reason in the message):
    forecast above the total budget, or queue depth at the cap."""


class ServeQueueTimeout(RuntimeError):
    """serve.queueTimeoutMs elapsed while the query waited for headroom."""


class Ticket:
    """One submitted query's trip through the scheduler."""

    __slots__ = ("session", "digest", "forecast", "priority", "seq",
                 "event", "enqueue_ns", "admit_ns", "reservation",
                 "verdict", "reason", "bypass", "forecast_source")

    def __init__(self, session: str, digest: str, forecast: Optional[int],
                 priority: int, seq: int,
                 forecast_source: str = "analyzer"):
        self.session = session
        self.digest = digest
        self.forecast = forecast
        #: where the forecast figure came from: "analyzer" (static HLO
        #: cost model) or "ledger" (a measured per-digest peak from the
        #: HBM ledger replaced the static guess — the measured-stats
        #: admission loop)
        self.forecast_source = forecast_source
        self.priority = priority
        self.seq = seq
        self.event = threading.Event()
        self.enqueue_ns = time.perf_counter_ns()
        self.admit_ns: Optional[int] = None
        self.reservation: Optional[int] = None
        self.verdict = ""
        self.reason = ""
        self.bypass = False


class QueryScheduler:
    """Process-wide fair scheduler with forecast-based admission.

    Usage (sql/session.py's serve path)::

        ticket = scheduler.acquire(session, priority, forecast, digest)
        try:
            ...host prefetch + drain (the semaphore caps device holders)
        finally:
            scheduler.release(ticket)
    """

    _instance: Optional["QueryScheduler"] = None
    _instance_lock = threading.Lock()

    def __init__(self, conf_: Optional[RapidsConf] = None):
        self.conf = conf_ or RapidsConf({})
        self._lock = ordered_lock("serve.scheduler")
        self._queues: Dict[str, Deque[Ticket]] = {}
        self._rr_order: List[str] = []  # round-robin rotation of sessions
        self._active: Dict[int, Ticket] = {}  # seq -> admitted ticket
        self._seq = 0
        # stats the stress test and /status read
        self.admitted = 0
        self.queued = 0
        self.rejected = 0
        self.timeouts = 0
        self.bypass_admissions = 0
        #: admitted queries that OOMed at runtime (a forecast MISS the
        #: static plane couldn't see) and were requeued once with their
        #: forecast inflated to the observed peak watermark
        self.oom_requeues = 0
        #: max simultaneously-admitted queries — proof the scheduler
        #: actually overlaps work (the pipelining claim is structural)
        self.peak_active = 0
        #: high-water mark of the summed admitted forecasts — the stress
        #: test's "zero admission-forecast violations" figure: with no
        #: bypass, it must never exceed the HBM budget
        self.peak_inflight_forecast = 0

    # -- singleton ---------------------------------------------------------
    @classmethod
    def get(cls, conf_: Optional[RapidsConf] = None) -> "QueryScheduler":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = QueryScheduler(conf_)
            return cls._instance

    @classmethod
    def instance(cls) -> Optional["QueryScheduler"]:
        """The live scheduler if any (the /status serve block peeks
        without creating one)."""
        return cls._instance

    @classmethod
    def reset(cls, conf_: Optional[RapidsConf] = None) -> "QueryScheduler":
        with cls._instance_lock:
            cls._instance = QueryScheduler(conf_)
            return cls._instance

    # -- internals (call under self._lock) ---------------------------------
    def _catalog(self):
        from ..memory.catalog import BufferCatalog

        return BufferCatalog.get()

    def _headroom(self) -> tuple:
        """(budget, free) from ONE locked catalog snapshot — separate
        property reads could mix two catalog states mid-update. The
        budget falls back to the scheduler conf's own derivation when
        the lazily-created catalog carries none (the same fallback the
        watchdog's pressure rule uses); (None, None) = no budget,
        admission has nothing to check."""
        budget, device, reserved = self._catalog().admission_state()
        if budget is None:
            from ..memory.catalog import derive_hbm_budget

            budget = derive_hbm_budget(self.conf)
        if budget is None:
            return None, None
        return budget, budget - device - reserved

    def _inflight_forecast(self) -> int:
        return sum(t.forecast or 0 for t in self._active.values())

    def _depth(self, session: Optional[str] = None) -> int:
        if session is not None:
            q = self._queues.get(session)
            return len(q) if q else 0
        return sum(len(q) for q in self._queues.values())

    def _admit_locked(self, t: Ticket, bypass: bool = False) -> None:
        t.reservation = self._catalog().reserve(
            t.forecast or 0, label=f"{t.session}:{t.digest}")
        t.admit_ns = time.perf_counter_ns()
        t.verdict = "admit"
        t.bypass = bypass
        self._active[t.seq] = t
        self.admitted += 1
        self.peak_active = max(self.peak_active, len(self._active))
        if bypass:
            self.bypass_admissions += 1
        self.peak_inflight_forecast = max(
            self.peak_inflight_forecast, self._inflight_forecast())
        if _obs.enabled():
            _obs.inc("tpu_serve_admissions", 1, verdict="admit")
        t.event.set()

    def _emit_admission(self, t: Ticket, verdict: str,
                        free: Optional[int]) -> None:
        if _events.enabled():
            _events.emit("admission", session=t.session, digest=t.digest,
                         verdict=verdict, forecast_bytes=t.forecast,
                         free_bytes=free, reason=t.reason,
                         forecast_source=t.forecast_source)

    def _emit_queue(self, t: Ticket, op: str, depth: int,
                    wait_ns: int = 0) -> None:
        if _events.enabled():
            _events.emit("queue", session=t.session, op=op, depth=depth,
                         wait_ns=wait_ns)
            if op in ("dequeue", "timeout") and wait_ns:
                # the queue wait as a span on the session's serve lane —
                # Perfetto then shows queued/running interleaving per
                # session next to the per-op tracks
                _events.emit("op_span", op=f"serve {t.session}",
                             section="queue_wait", start=t.enqueue_ns,
                             dur=wait_ns, lane="host")
        if _obs.enabled():
            _obs.inc("tpu_serve_queue", 1, op=op)
            _obs.set_gauge("tpu_serve_queue_depth", self._depth())
            if op == "dequeue":
                _obs.observe("tpu_serve_queue_wait_seconds", wait_ns / 1e9)

    def _pump_locked(self) -> None:
        """Admit every waiting head that fits, honoring priority tiers
        and round-robin within a tier; called whenever headroom may have
        grown (a release), a query timed out of the queue, or a new
        ticket enqueued.

        Anti-starvation barrier: backfilling past a head that does not
        fit is allowed only for tickets that ARRIVED EARLIER (lower seq)
        or carry strictly higher priority — a steady stream of small
        later queries can therefore never starve a large-forecast head:
        once it is the oldest skipped ticket, no younger same-or-lower
        priority work admits, the active set drains, and it fits (or the
        nothing-running bypass takes it)."""
        while True:
            heads = [
                (s, self._queues[s][0]) for s in self._rr_order
                if self._queues.get(s)
            ]
            if not heads:
                return
            # stable sort keeps rr rotation order within a priority tier
            heads.sort(key=lambda st: -st[1].priority)
            _, free = self._headroom()
            admitted_one = False
            blocked: Optional[Ticket] = None  # oldest skipped head
            for s, t in heads:
                fits = free is None or (t.forecast or 0) <= free
                bypass = not fits and not self._active
                if not (fits or bypass):
                    if blocked is None or t.seq < blocked.seq:
                        blocked = t
                    continue
                if blocked is not None and t.seq > blocked.seq \
                        and t.priority <= blocked.priority:
                    continue  # no queue-jumping past a starving head
                self._queues[s].popleft()
                # rotate: s goes to the back of its tier
                self._rr_order.remove(s)
                self._rr_order.append(s)
                wait = time.perf_counter_ns() - t.enqueue_ns
                if bypass:
                    t.reason = (
                        f"bypass: nothing running, admitting despite "
                        f"forecast {_pretty_bytes(t.forecast)} > "
                        f"{_pretty_bytes(free)} free (spill will enforce "
                        "the budget)")
                self._admit_locked(t, bypass=bypass)
                self._emit_queue(t, "dequeue", self._depth(s), wait)
                self._emit_admission(t, "admit", free)
                admitted_one = True
                break  # re-evaluate headroom + rr order from scratch
            if not admitted_one:
                return

    # -- API ---------------------------------------------------------------
    def acquire(self, session: str, priority: int,
                forecast: Optional[int], digest: str,
                conf_: Optional[RapidsConf] = None,
                forecast_source: str = "analyzer") -> Ticket:
        """Block until the query is admitted (or raise). The caller runs
        its host prefetch + drain after this returns and MUST pair it
        with :meth:`release` in a finally.

        ``conf_``: the SUBMITTING session's conf — queue timeout, depth
        cap, and the admission on/off switch are per-submit settings
        read from it (the process-wide singleton was created by
        whichever session touched it first; silently pinning every
        later session to that session's limits would be a trap). Omitted
        = the scheduler's own conf."""
        conf_ = conf_ or self.conf
        admission_on = conf_.get(SERVE_ADMISSION_ENABLED)
        max_depth = conf_.get(SERVE_MAX_QUEUE_DEPTH)
        timeout_ms = conf_.get(SERVE_QUEUE_TIMEOUT_MS)
        with self._lock:
            self._seq += 1
            t = Ticket(session, digest, forecast, priority, self._seq,
                       forecast_source=forecast_source)
            if session not in self._rr_order:
                self._rr_order.append(session)
                self._queues.setdefault(session, collections.deque())
            budget, free = self._headroom()
            if (admission_on and budget is not None
                    and forecast is not None and forecast > budget):
                t.reason = (
                    f"forecast {_pretty_bytes(forecast)} exceeds the "
                    f"total HBM budget {_pretty_bytes(budget)} — the "
                    "plan can never fit; shrink it or raise "
                    "spark.rapids.tpu.memory.hbm.budgetBytes")
                self.rejected += 1
                if _obs.enabled():
                    _obs.inc("tpu_serve_admissions", 1, verdict="reject")
                self._emit_admission(t, "reject", free)
                raise ServeAdmissionRejected(
                    f"session {session} plan {digest}: {t.reason}")
            if self._depth(session) >= max_depth:
                t.reason = (
                    f"session queue depth {self._depth(session)} at "
                    f"spark.rapids.tpu.serve.maxQueueDepth={max_depth}")
                self.rejected += 1
                if _obs.enabled():
                    _obs.inc("tpu_serve_admissions", 1, verdict="reject")
                self._emit_admission(t, "reject", free)
                raise ServeAdmissionRejected(
                    f"session {session} plan {digest}: {t.reason}")
            fits = (not admission_on or free is None
                    or (forecast or 0) <= free)
            waiting_elsewhere = self._depth() > 0
            if self._depth(session) == 0 and fits and not waiting_elsewhere:
                # fast path: nothing queued anywhere and it fits — admit
                # on the submit thread (round-robin is vacuous here)
                t.reason = ("admission off" if not admission_on else
                            "no HBM budget derived" if free is None else
                            f"forecast {_pretty_bytes(forecast)} <= "
                            f"{_pretty_bytes(free)} free")
                self._admit_locked(t)
                self._emit_admission(t, "admit", free)
                return t
            if self._depth(session) == 0 and not fits and not self._active:
                # progress guarantee: nothing running, nothing can shrink
                # the watermark — admit and let the spiller enforce
                t.reason = (
                    f"bypass: nothing running, admitting despite "
                    f"forecast {_pretty_bytes(forecast)} > "
                    f"{_pretty_bytes(free)} free (spill will enforce "
                    "the budget)")
                self._admit_locked(t, bypass=True)
                self._emit_admission(t, "admit", free)
                return t
            # queue: behind this session's FIFO / other sessions' turns
            t.verdict = "queue"
            t.reason = (
                f"queued: forecast {_pretty_bytes(forecast)} > "
                f"{_pretty_bytes(free)} free" if not fits else
                f"queued: behind {self._depth()} waiting quer"
                f"{'y' if self._depth() == 1 else 'ies'}")
            self._queues[session].append(t)
            self.queued += 1
            if _obs.enabled():
                _obs.inc("tpu_serve_admissions", 1, verdict="queue")
            self._emit_admission(t, "queue", free)
            self._emit_queue(t, "enqueue", self._depth(session))
            # a fitting ticket queued only for fairness may be admittable
            # right away once round-robin considers it
            self._pump_locked()
        if timeout_ms > 0:
            if not t.event.wait(timeout_ms / 1e3):
                # may have been admitted in the instant the wait gave up:
                # _try_timeout decides under the lock
                if self._try_timeout(t):
                    raise ServeQueueTimeout(
                        f"session {session} plan {digest} gave up after "
                        f"{timeout_ms}ms in the serving queue "
                        f"(spark.rapids.tpu.serve.queueTimeoutMs); "
                        f"last verdict: {t.reason}")
                t.event.wait()  # admitted concurrently; set is imminent
        else:
            t.event.wait()
        return t

    def note_oom_requeue(self, session: str, digest: str,
                         inflated_forecast: Optional[int],
                         forecast_source: str = "watermark") -> None:
        """Record one OOM-driven requeue (sql/session._collect_serve):
        the admitted query failed with a typed device-OOM despite the
        recovery plane, its reservation is already released, and it is
        being resubmitted ONCE with its forecast inflated to the
        observed peak watermark — forecast misses become queueing, not
        crashes. Surfaced in stats()/'/status', the admission event
        stream, and the oom_retry resilience events."""
        with self._lock:
            self.oom_requeues += 1
        if _obs.enabled():
            _obs.inc("tpu_serve_admissions", 1, verdict="requeue")
            _obs.note_oom_retry(f"serve {session}", "requeue")
        if _events.enabled():
            _events.emit(
                "admission", session=session, digest=digest,
                verdict="requeue", forecast_bytes=inflated_forecast,
                free_bytes=None,
                reason="admitted query OOMed at runtime; requeued once "
                       "with forecast inflated to the observed peak "
                       "watermark",
                forecast_source=forecast_source)
            _events.emit(
                "oom_retry", op=f"serve {session}", kind="requeue",
                attempt=1, depth=0, watermark=inflated_forecast,
                budget=None)

    def _try_timeout(self, t: Ticket) -> bool:
        """Remove a still-queued ticket (timeout); False if it was
        admitted concurrently (the caller proceeds with it)."""
        with self._lock:
            q = self._queues.get(t.session)
            if q is None or t not in q:
                return False
            q.remove(t)
            self.timeouts += 1
            wait = time.perf_counter_ns() - t.enqueue_ns
            self._emit_queue(t, "timeout", self._depth(t.session), wait)
            # the queue shape changed: a successor head that fits (or
            # the anti-starvation barrier the departed ticket held) may
            # now admit — without this pump it would idle until some
            # unrelated release
            self._pump_locked()
            return True

    def release(self, t: Ticket) -> None:
        """Return the ticket's reservation and wake whatever now fits."""
        with self._lock:
            if t.seq not in self._active:
                return
            del self._active[t.seq]
            if t.reservation is not None:
                self._catalog().release_reservation(t.reservation)
                t.reservation = None
            if _events.enabled() and t.admit_ns is not None:
                # the admitted run on the session's serve lane, next to
                # its queue_wait span
                _events.emit(
                    "op_span", op=f"serve {t.session}", section="run",
                    start=t.admit_ns,
                    dur=time.perf_counter_ns() - t.admit_ns, lane="host")
            self._pump_locked()

    # -- introspection (/status, tools/tpu_top.py, tests) ------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "admitted": self.admitted, "queued": self.queued,
                "rejected": self.rejected, "timeouts": self.timeouts,
                "bypass_admissions": self.bypass_admissions,
                "oom_requeues": self.oom_requeues,
                "peak_inflight_forecast": self.peak_inflight_forecast,
                "peak_active": self.peak_active,
                "active": len(self._active), "waiting": self._depth(),
            }

    def queue_status(self) -> List[dict]:
        """Waiting queries in drain order (priority tiers, then rr),
        each with its session, queue position, and admission reason —
        the /status + tpu_top payload."""
        now = time.perf_counter_ns()
        with self._lock:
            heads: List[dict] = []
            order = sorted(
                (s for s in self._rr_order if self._queues.get(s)),
                key=lambda s: -(self._queues[s][0].priority
                                if self._queues[s] else 0))
            pos = 0
            for s in order:
                for t in self._queues[s]:
                    heads.append({
                        "session": t.session, "digest": t.digest,
                        "position": pos, "priority": t.priority,
                        "forecast_bytes": t.forecast,
                        "reason": t.reason,
                        "waited_ms": (now - t.enqueue_ns) / 1e6,
                    })
                    pos += 1
            return heads

    def active_status(self) -> List[dict]:
        now = time.perf_counter_ns()
        with self._lock:
            return [{
                "session": t.session, "digest": t.digest,
                "forecast_bytes": t.forecast, "bypass": t.bypass,
                "running_ms": ((now - t.admit_ns) / 1e6
                               if t.admit_ns else None),
            } for t in sorted(self._active.values(),
                              key=lambda t: t.seq)]
