"""Persistent AOT program cache: compile once, serve everywhere.

Serving cold-start is the integral of compile seconds the cost plane
(xla_cost.py) measures: every fresh process re-traces and re-compiles
every program at the ``exec/base.cached_pipeline`` chokepoint, so a
restarted server pays the full compile bill before its first query
returns. This module is the disk half of that chokepoint — the analog of
the reference plugin's digest-keyed compiled-kernel cache shared across
executors, built on the TPU-native pair of mechanisms:

  * ``jax.export`` — the traced + lowered program serializes to a
    portable StableHLO artifact, so a warm process never re-runs the
    engine's Python tracing (for the big fused chains, seconds of
    expression lowering);
  * the JAX **persistent compilation cache** — ``install()`` points
    ``jax_compilation_cache_dir`` at ``<dir>/xla``, and the store path
    compiles the *exported* module (the exact module a warm process will
    compile), so the backend-compile of a deserialized program is a
    cache **read**, not a multi-second XLA run.

Entry anatomy: one ``<sha256>.aot`` file per program, named by the full
cache identity — (format version, compile site, pipeline-key repr
digest, backend, device kind + count, jax version, conf fingerprint) —
so flipping ANY component is a natural miss (a new jax version or a
different layout conf can never deserialize a stale executable). The
file holds a JSON header (the identity spelled out, the harvested
``program_cost`` payload, the ``hlo_summary`` payload, pickled mesh aux)
followed by the serialized artifact, written atomically
(write-then-rename) under a best-effort cross-process lockfile — the
single-flight pattern of ``serve/plan_cache.py`` extended from analyses
to programs (in-process single-flight is the pipeline-cache lock
itself; cross-process, a loser compiles for itself but skips the
duplicate write — a store must never block a query).

The cost plane survives caching: the harvested ``cost_analysis`` /
``hlo_summary`` payloads persist beside the executable and re-emit on a
deserialize hit flagged ``from_cache`` (with ``saved_ms`` naming the
original trace+compile bill avoided), so the roofline report, ``--diff``
gates, bench ``hbm_frac_xla``, and the live obs twins stay truthful for
a process that never compiled anything.

Negative paths never fail a query: a corrupt/truncated entry, a
``jax.export`` version mismatch, or an executable that rejects this
call's signature logs, deletes the poisoned entry, and falls through to
a plain compile. The ``aotcache`` fault channel (faults.py,
``read:<site>`` / ``write:<site>`` specs) drives both deterministically.

Zero-overhead contract (the events.py pattern): with the confs off —
the default — ``enabled()`` is one module-global boolean read on the
pipeline-cache SLOW path only, no directory is touched, no thread is
started, and ``cached_pipeline``'s fast path is byte-for-byte unchanged
(tests/test_program_cache.py pins this with a spy).
"""
from __future__ import annotations

import base64
import json
import os
import pickle
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import events as _events
from .. import faults as _faults
from .. import obs as _obs
from ..conf import RapidsConf, conf
from ..utils.locks import ordered_lock

AOT_CACHE_ENABLED = conf(
    "spark.rapids.tpu.aotCache.enabled", False,
    "Enable the persistent AOT program cache: every compile miss at the "
    "pipeline-cache chokepoint serializes its program (jax.export) to "
    "aotCache.dir keyed by (site, signature digest, backend, device "
    "kind, jax version, conf fingerprint), and a later process "
    "deserializes instead of tracing + compiling — near-zero cold-start "
    "compile seconds for a warmed cache directory (the harvested cost "
    "payloads re-emit flagged from_cache so the roofline report stays "
    "truthful). Setting aotCache.dir implies this key. Off by default — "
    "the off path is a single boolean read and touches no disk.")
AOT_CACHE_DIR = conf(
    "spark.rapids.tpu.aotCache.dir", "",
    "Directory for the persistent AOT program cache (one <digest>.aot "
    "entry per program + the JAX persistent compilation cache under "
    "<dir>/xla). Setting a directory turns the cache on; with "
    "aotCache.enabled true and no directory, entries land under "
    "~/.cache/spark-rapids-tpu/aot. Share a directory only between "
    "processes on identical hardware/jax/conf (mismatches are safe — "
    "they key apart — but never hit); see docs/tuning.md.")
AOT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.aotCache.maxBytes", 1 << 30,
    "Size cap for the AOT program-cache directory. After each store the "
    "directory is scanned and least-recently-USED entries (hits bump an "
    "entry's mtime) are evicted until under the cap. The JAX persistent "
    "compilation cache under <dir>/xla is bounded separately by jax "
    "itself.", conf_type=int,
    check=lambda v: None if v > 0 else "must be positive")

#: bump to invalidate every existing entry (header + filename component,
#: so old-format files simply stop being addressed AND are rejected if
#: hand-renamed into place)
FORMAT_VERSION = 1

#: conf prefixes excluded from the cache-key fingerprint: observability,
#: chaos and the cache's own knobs cannot change WHAT a program computes,
#: and including them would make a warm bench subprocess (different
#: eventLog.dir) miss on every entry. Everything else explicitly set —
#: layout, memory, strategy, analysis confs — keys the entry apart.
_FINGERPRINT_EXCLUDE = (
    "spark.rapids.tpu.aotCache.",
    "spark.rapids.tpu.eventLog.",
    "spark.rapids.tpu.metrics.",
    "spark.rapids.tpu.watchdog.",
    "spark.rapids.tpu.hlo.",
    "spark.rapids.tpu.roofline.",
    "spark.rapids.tpu.tools.",
    "spark.rapids.tpu.test.faults.",
)

#: lockfiles older than this are presumed abandoned (a crashed writer)
_LOCK_STALE_S = 120.0

#: persisted program_cost payload fields (the COST_FIELDS superset that
#: rides in the header and re-emits on a deserialize hit)
_COST_KEYS = ("flops", "bytes_accessed", "temp_bytes", "argument_bytes",
              "output_bytes", "out_bytes", "generated_code_bytes",
              "peak_hbm_gbps", "peak_tflops", "trace_ms", "compile_ms",
              "op")


def program_conf_fingerprint(conf_: RapidsConf) -> str:
    """sha256 of the explicitly-set conf values that can shape compiled
    programs (see _FINGERPRINT_EXCLUDE) — the disk twin of
    serve/plan_cache.conf_fingerprint, filtered so observability-only
    settings don't shatter the key space."""
    import hashlib

    items = tuple(sorted(
        (k, repr(v)) for k, v in conf_._values.items()
        if not any(k.startswith(p) for p in _FINGERPRINT_EXCLUDE)))
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# pytree serialization registration: the engine's column values (ColV /
# StrV / DictV) cross the jit boundary as custom pytree nodes, and
# jax.export refuses to serialize unregistered types. Registered once,
# lazily, at first install; programs carrying any OTHER custom node
# simply fall back to plain compilation (store() is best-effort).
# ---------------------------------------------------------------------------
_PYTREES_REGISTERED = False


def _register_pytree_serialization() -> None:
    global _PYTREES_REGISTERED
    if _PYTREES_REGISTERED:
        return
    _PYTREES_REGISTERED = True
    try:
        from jax import export as _export

        from ..expr.values import ColV, DictV, StrV

        _export.register_namedtuple_serialization(
            ColV, serialized_name="srtpu.ColV")
        _export.register_namedtuple_serialization(
            StrV, serialized_name="srtpu.StrV")
        _export.register_pytree_node_serialization(
            DictV, serialized_name="srtpu.DictV",
            serialize_auxdata=lambda aux: json.dumps(list(aux)).encode(),
            deserialize_auxdata=lambda b: tuple(json.loads(b.decode())))
    except Exception:
        # older jax without the registration API: string/dict programs
        # fall back to plain compilation, fixed-width ones still cache
        pass


# ---------------------------------------------------------------------------
# Stats: the /status + tpu_top + profiler-section feed (module-level so
# the engine's deep call sites need no handle)
# ---------------------------------------------------------------------------
class ProgramCacheStats:
    """Thread-safe counters for one installed cache."""

    def __init__(self):
        self._lock = ordered_lock("aot.stats")
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.corrupt = 0
        self.write_errors = 0
        self.deserialized = 0
        #: original trace+compile milliseconds the persisted payloads say
        #: the hits avoided (the compile-seconds-avoided estimate)
        self.saved_ms = 0.0
        #: trace+compile milliseconds warm programs actually paid
        #: (deserialize + cached backend compile)
        self.warm_ms = 0.0

    def bump(self, field: str, amount: float = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + amount)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hits": self.hits, "misses": self.misses,
                "puts": self.puts, "evictions": self.evictions,
                "corrupt": self.corrupt,
                "write_errors": self.write_errors,
                "deserialized": self.deserialized,
                "saved_ms": round(self.saved_ms, 3),
                "warm_ms": round(self.warm_ms, 3),
            }


class ProgramCache:
    """One disk-backed AOT program store (install() makes it active)."""

    def __init__(self, conf_: RapidsConf):
        import jax

        from .. import envinfo

        d = conf_.get(AOT_CACHE_DIR) or os.path.expanduser(
            "~/.cache/spark-rapids-tpu/aot")
        self.dir = os.path.abspath(d)
        self.max_bytes = conf_.get(AOT_CACHE_MAX_BYTES)
        env = envinfo.environment_info()
        # identity components — instance attributes so the key-flip tests
        # can construct a cache claiming different hardware
        self.backend = env.get("backend")
        self.device_kind = env.get("device_kind")
        self.device_count = env.get("device_count")
        self.jax_version = jax.__version__
        self.conf_fp = program_conf_fingerprint(conf_)
        self.stats = ProgramCacheStats()
        #: sites whose programs proved non-exportable this process (an
        #: unregistered pytree, a shard_map dialect export rejects):
        #: skip the export attempt instead of re-failing per key
        self._unexportable: set = set()
        os.makedirs(self.dir, exist_ok=True)
        os.makedirs(os.path.join(self.dir, "xla"), exist_ok=True)

    # -- keying ------------------------------------------------------------
    def entry_name(self, site: str, key: Any) -> Optional[str]:
        """Filename for one program's full cache identity, or None when
        the pipeline key's repr is not process-stable (a default object
        repr leaks an address — such a key could never hit across
        processes and must not pollute the directory)."""
        import hashlib

        key_repr = repr(key)
        if " at 0x" in key_repr:
            return None
        ident = repr((FORMAT_VERSION, site,
                      hashlib.sha256(key_repr.encode()).hexdigest(),
                      self.backend, self.device_kind, self.device_count,
                      self.jax_version, self.conf_fp))
        return hashlib.sha256(ident.encode()).hexdigest()[:40] + ".aot"

    def entry_path(self, site: str, key: Any) -> Optional[str]:
        name = self.entry_name(site, key)
        return None if name is None else os.path.join(self.dir, name)

    def header_identity(self, site: str) -> Dict[str, Any]:
        return {
            "version": FORMAT_VERSION, "site": site,
            "backend": self.backend, "device_kind": self.device_kind,
            "device_count": self.device_count,
            "jax_version": self.jax_version, "conf_fp": self.conf_fp,
        }

    # -- disk I/O ----------------------------------------------------------
    def _read_entry(self, path: str) -> Tuple[Dict[str, Any], bytes]:
        """Parse one entry file; raises on any corruption (caller turns
        that into delete + plain compile)."""
        with open(path, "rb") as f:
            raw = f.read()
        if len(raw) < 8:
            raise ValueError("truncated entry (no header length)")
        (hlen,) = struct.unpack(">Q", raw[:8])
        if hlen <= 0 or 8 + hlen > len(raw):
            raise ValueError("truncated entry (header)")
        header = json.loads(raw[8:8 + hlen].decode())
        blob = raw[8 + hlen:]
        if header.get("blob_len") != len(blob):
            raise ValueError(
                f"truncated entry (blob {len(blob)} != "
                f"{header.get('blob_len')})")
        return header, blob

    def _poison(self, path: str, site: str, detail: str) -> None:
        """A corrupt/mismatched entry: delete it (it can only ever fail
        again), count it, log it — and let the caller fall through to a
        plain compile."""
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        try:
            os.unlink(path)
        except OSError:
            pass
        self.stats.bump("corrupt")
        if _events.enabled():
            _events.emit("program_cache", op="corrupt", site=site,
                         key=os.path.basename(path)[:12], bytes=size,
                         detail=detail[:200])
        if _obs.enabled():
            _obs.inc("tpu_program_cache", 1, op="corrupt")

    def lookup(self, site: str, key: Any, build: Callable[[], Any],
               donate: Tuple[int, ...] = ()):
        """Disk probe for one pipeline-cache miss. Returns a callable
        (or the mesh ``(callable, aux...)`` tuple) serving the entry, or
        None — and on None the caller compiles exactly as before. Never
        raises. ``donate`` is the donate_argnums mask of the program
        being served: jax.export does NOT preserve donation across
        serialize/deserialize, so the hit side must re-declare it when
        compiling the deserialized call (the mask is part of the cache
        key the caller folded, so an entry is only ever served to
        callers with the same mask)."""
        path = self.entry_path(site, key)
        if path is None:
            return None
        kd = _digest_of(key)
        try:
            if _faults.enabled():
                _faults.check("aotcache", "read:" + site)
            if not os.path.exists(path):
                self.stats.bump("misses")
                if _events.enabled():
                    _events.emit("program_cache", op="miss", site=site,
                                 key=kd, bytes=0)
                if _obs.enabled():
                    _obs.inc("tpu_program_cache", 1, op="miss")
                return None
            t0 = time.perf_counter_ns()
            header, blob = self._read_entry(path)
            ident = self.header_identity(site)
            mismatched = [k for k, v in ident.items()
                          if header.get(k) != v]
            if mismatched:
                raise ValueError("identity mismatch on " +
                                 ",".join(mismatched))
            from jax import export as _export

            _register_pytree_serialization()
            exported = _export.deserialize(blob)
            # the mesh tuple path's aux decodes INSIDE the corruption
            # guard: a bit-flipped/stale aux pickle must poison the
            # entry and fall through, never raise out of lookup()
            aux_b64 = header.get("aux")
            aux = (tuple(pickle.loads(base64.b64decode(aux_b64)))
                   if aux_b64 is not None else None)
            deser_ns = time.perf_counter_ns() - t0
        except Exception as e:
            if os.path.exists(path):
                self._poison(path, site, f"{type(e).__name__}: {e}")
            return None
        try:
            os.utime(path)  # LRU touch: hits protect an entry
        except OSError:
            pass
        self.stats.bump("hits")
        self.stats.bump("saved_ms",
                        (header.get("cost") or {}).get("trace_ms", 0.0)
                        + (header.get("cost") or {}).get("compile_ms", 0.0))
        if _events.enabled():
            _events.emit("program_cache", op="hit", site=site, key=kd,
                         bytes=len(blob), ms=round(deser_ns / 1e6, 3))
        if _obs.enabled():
            _obs.inc("tpu_program_cache", 1, op="hit")
        probe = _LoadProbe(self, exported, header, site, key, kd, path,
                           build, deser_ns, donate)
        if aux is not None:
            return (probe,) + aux
        return probe

    def wrap_store(self, built: Any, site: str, key: Any,
                   donate: Tuple[int, ...] = ()):
        """Miss path: arrange for the freshly-built program to be
        exported + persisted at its first call. Falls back to the plain
        cost-plane wrap (xla_cost.wrap) whenever this program cannot
        participate — the cost plane must keep working either way.
        ``donate`` rides to the store probe so the compile of the
        exported module carries the same donate_argnums the traced
        program declared (export drops donation; see lookup)."""
        from .. import xla_cost as _xla_cost

        path = self.entry_path(site, key)
        aux: Tuple = ()
        fn = built
        if isinstance(built, tuple):
            if not built or not callable(built[0]):
                path = None
            else:
                fn, aux = built[0], tuple(built[1:])
        if (path is None or site in self._unexportable
                or not callable(fn) or not hasattr(fn, "lower")):
            return _xla_cost.wrap(built, site, key)
        try:
            aux_b64 = (base64.b64encode(pickle.dumps(aux)).decode()
                       if aux else None)
        except Exception:
            return _xla_cost.wrap(built, site, key)
        probe = _StoreProbe(self, fn, site, key, _digest_of(key), path,
                            aux_b64, donate)
        if aux:
            return (probe,) + aux
        return probe

    # -- store + eviction --------------------------------------------------
    def store(self, site: str, key_digest: str, path: str,
              header: Dict[str, Any], blob: bytes) -> None:
        """Atomic write-then-rename under a best-effort cross-process
        lockfile. A racing writer in another process makes this a no-op
        (it is writing the same bytes); any failure counts + logs and
        the query proceeds on the in-memory executable."""
        try:
            if _faults.enabled():
                _faults.check("aotcache", "write:" + site)
            lock = path + ".lock"
            fd = None
            try:
                try:
                    fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                except FileExistsError:
                    try:
                        fresh = (time.time() - os.path.getmtime(lock)
                                 < _LOCK_STALE_S)
                    except OSError:
                        fresh = False
                    if fresh:
                        return  # single-flight: the other process writes
                    try:
                        os.unlink(lock)  # stale lock from a dead writer
                    except OSError:
                        pass
                    fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                hdr = json.dumps(header, separators=(",", ":"),
                                 sort_keys=True).encode()
                tmp = path + f".tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(struct.pack(">Q", len(hdr)))
                    f.write(hdr)
                    f.write(blob)
                os.replace(tmp, path)
            finally:
                if fd is not None:
                    os.close(fd)
                    try:
                        os.unlink(lock)
                    except OSError:
                        pass
        except Exception as e:
            self.stats.bump("write_errors")
            if _events.enabled():
                _events.emit("program_cache", op="write_error", site=site,
                             key=key_digest, bytes=0,
                             detail=f"{type(e).__name__}: {e}"[:200])
            if _obs.enabled():
                _obs.inc("tpu_program_cache", 1, op="write_error")
            return
        self.stats.bump("puts")
        if _events.enabled():
            _events.emit("program_cache", op="put", site=site,
                         key=key_digest, bytes=len(blob))
        if _obs.enabled():
            _obs.inc("tpu_program_cache", 1, op="put")
        self._evict_if_needed()

    def _entries(self) -> List[Tuple[str, float, int]]:
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            if not n.endswith(".aot"):
                continue
            p = os.path.join(self.dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((p, st.st_mtime, st.st_size))
        return out

    def resident_bytes(self) -> int:
        return sum(sz for _, _, sz in self._entries())

    def _evict_if_needed(self) -> None:
        """Size-capped LRU over entry mtimes (hits os.utime their entry,
        so 'oldest mtime' = least recently used)."""
        entries = self._entries()
        total = sum(sz for _, _, sz in entries)
        if total > self.max_bytes:
            for p, _, sz in sorted(entries, key=lambda t: t[1]):
                if total <= self.max_bytes:
                    break
                try:
                    os.unlink(p)
                except OSError:
                    continue
                total -= sz
                self.stats.bump("evictions")
                if _events.enabled():
                    _events.emit("program_cache", op="evict", site="",
                                 key=os.path.basename(p)[:12], bytes=sz)
                if _obs.enabled():
                    _obs.inc("tpu_program_cache", 1, op="evict")
        if _obs.enabled():
            _obs.set_gauge("tpu_program_cache_resident_bytes", total)


def _digest_of(key: Any) -> str:
    """The 12-hex signature digest program_cost events carry — reused so
    the profiler can join program_cache and program_cost records."""
    from .. import xla_cost as _xla_cost

    return _xla_cost.digest_of(key)


# ---------------------------------------------------------------------------
# The probes. Both defer real work to the FIRST call (the only moment
# concrete arguments exist), exactly like xla_cost.CostProbe — and both
# are defensive by design: no failure in here may fail a query.
# ---------------------------------------------------------------------------
class _StoreProbe:
    """Miss-side shim: first call exports the jitted program, compiles
    the *exported* module (seeding the JAX persistent compilation cache
    with the very module a warm process will compile), harvests the cost
    plane from it, persists everything, then serves every call from the
    kept executable. Cold-path cost is the same one trace + one backend
    compile a plain jit would have paid lazily."""

    __slots__ = ("_cache", "_fn", "_site", "_key", "_digest", "_path",
                 "_aux_b64", "_donate", "_compiled", "_done", "_lock")

    def __init__(self, cache: ProgramCache, fn: Callable, site: str,
                 key: Any, digest: str, path: str,
                 aux_b64: Optional[str],
                 donate: Tuple[int, ...] = ()):
        self._cache = cache
        self._fn = fn
        self._site = site
        self._key = key
        self._digest = digest
        self._path = path
        self._aux_b64 = aux_b64
        self._donate = tuple(donate)
        self._compiled = None
        self._done = False
        self._lock = ordered_lock("aot.store_probe")

    def __call__(self, *args, **kwargs):
        if not self._done:
            with self._lock:
                if not self._done:
                    try:
                        self._export_compile_store(args, kwargs)
                    except Exception:
                        # program not exportable with this jax/backend:
                        # permanent per-site fallback to the plain path.
                        # Re-wrap in the cost plane so the site's
                        # program_cost harvest (one per compile miss)
                        # survives losing the cache.
                        from .. import xla_cost as _xla_cost

                        self._cache._unexportable.add(self._site)
                        self._compiled = None
                        self._fn = _xla_cost.wrap(
                            self._fn, self._site, self._key)
                    self._done = True
        c = self._compiled
        if c is not None:
            try:
                return c(*args, **kwargs)
            except (TypeError, ValueError):
                # signature the cache key under-captured: serve from the
                # plain jit path from now on (the CostProbe contract)
                self._compiled = None
        return self._fn(*args, **kwargs)

    def _export_compile_store(self, args, kwargs) -> None:
        import jax
        from jax import export as _export

        from .. import hlo as _hlo
        from .. import xla_cost as _xla_cost

        _register_pytree_serialization()
        t0 = time.perf_counter_ns()
        exported = _export.export(self._fn)(*args, **kwargs)
        blob = exported.serialize()
        t1 = time.perf_counter_ns()
        # donation does not survive export: exported.call is a plain
        # function, so the donate_argnums of the original jit must be
        # re-declared here or the persisted-path compile silently loses
        # the aliasing (and its temp-bytes savings)
        compiled = jax.jit(
            exported.call, donate_argnums=self._donate,
        ).lower(*args, **kwargs).compile()
        t2 = time.perf_counter_ns()
        cost = _xla_cost.harvest_compiled(compiled)
        hlo_rec = None
        if _xla_cost.harvesting():
            rec = _xla_cost.note_program_cost(
                self._site, self._digest, t1 - t0, t2 - t1, cost,
                op=_xla_cost.current_op())
            hlo_rec = _hlo.harvest_hlo(
                compiled, self._site, self._digest, op=rec.get("op"),
                xla_bytes=rec.get("bytes_accessed"))
        self._compiled = compiled
        header = self._cache.header_identity(self._site)
        cost_payload = {k: v for k, v in cost.items() if v is not None}
        cost_payload["trace_ms"] = round((t1 - t0) / 1e6, 3)
        cost_payload["compile_ms"] = round((t2 - t1) / 1e6, 3)
        op = _xla_cost.current_op()
        if op:
            cost_payload["op"] = op
        header["cost"] = cost_payload
        if hlo_rec is not None:
            header["hlo"] = {
                k: hlo_rec[k] for k in _hlo.SUMMARY_FIELDS}
            if hlo_rec.get("accounted_frac") is not None:
                header["hlo"]["accounted_frac"] = hlo_rec["accounted_frac"]
        header["aux"] = self._aux_b64
        if self._donate:
            header["donate"] = list(self._donate)
        header["blob_len"] = len(blob)
        header["created"] = round(time.time(), 3)
        self._cache.store(self._site, self._digest, self._path, header,
                          blob)


class _LoadProbe:
    """Hit-side shim: the entry deserialized at lookup time; the first
    call compiles the exported module (a JAX persistent-cache read when
    the store side seeded it), re-emits the persisted cost + HLO
    payloads flagged ``from_cache``, and serves every later call from
    the kept executable. Any failure deletes the entry and falls back
    to building + compiling the program exactly as a plain miss would
    have — a poisoned cache can cost time, never correctness."""

    __slots__ = ("_cache", "_exp", "_header", "_site", "_key", "_digest",
                 "_path", "_build", "_deser_ns", "_donate", "_compiled",
                 "_fallback", "_done", "_lock")

    def __init__(self, cache: ProgramCache, exported, header: dict,
                 site: str, key: Any, digest: str, path: str,
                 build: Callable[[], Any], deser_ns: int,
                 donate: Tuple[int, ...] = ()):
        self._cache = cache
        self._exp = exported
        self._header = header
        self._site = site
        self._key = key
        self._digest = digest
        self._path = path
        self._build = build
        self._deser_ns = deser_ns
        self._donate = tuple(donate)
        self._compiled = None
        self._fallback: Optional[Callable] = None
        self._done = False
        self._lock = ordered_lock("aot.load_probe")

    def __call__(self, *args, **kwargs):
        if not self._done:
            with self._lock:
                if not self._done:
                    try:
                        self._compile_deserialized(args, kwargs)
                    except Exception as e:
                        self._to_fallback(
                            f"{type(e).__name__}: {e}")
                    self._done = True
        c = self._compiled
        if c is not None:
            try:
                return c(*args, **kwargs)
            except (TypeError, ValueError) as e:
                # args the entry's signature won't take (key drift):
                # the real build handles them — and the entry is wrong
                # for this key, so it goes. Under the lock: a racing
                # caller must never observe _compiled cleared while
                # _fallback is still unset.
                with self._lock:
                    self._compiled = None
                    self._to_fallback(f"signature drift: {e}")
        fb = self._fallback
        if fb is None:
            # concurrent caller caught mid-transition (another thread
            # cleared _compiled and is building the fallback): wait on
            # the lock, then the fallback is guaranteed present
            with self._lock:
                self._to_fallback("concurrent fallback")
                fb = self._fallback
        return fb(*args, **kwargs)

    def _compile_deserialized(self, args, kwargs) -> None:
        import jax

        from .. import hlo as _hlo
        from .. import xla_cost as _xla_cost

        t0 = time.perf_counter_ns()
        # re-declare donation: serialize/deserialize strips the original
        # jit's donate_argnums, and a warm process that silently compiled
        # without them would dispatch correctly but lose the input-output
        # aliasing the donation analyzer certified
        compiled = jax.jit(self._exp.call, donate_argnums=self._donate).lower(
            *args, **kwargs).compile()
        t1 = time.perf_counter_ns()
        self._compiled = compiled
        self._cache.stats.bump("deserialized")
        self._cache.stats.bump(
            "warm_ms", (self._deser_ns + t1 - t0) / 1e6)
        if _events.enabled():
            _events.emit("program_cache", op="deserialize",
                         site=self._site, key=self._digest,
                         bytes=self._header.get("blob_len", 0),
                         ms=round((self._deser_ns + t1 - t0) / 1e6, 3))
        if _obs.enabled():
            _obs.inc("tpu_program_cache", 1, op="deserialize")
        if not _xla_cost.harvesting():
            return
        # re-emit the PERSISTED cost payload so the roofline report /
        # bench hbm_frac_xla / obs twins of a process that compiled
        # nothing stay truthful: XLA bytes/flops come from the original
        # harvest, trace/compile ms are THIS process's (near-zero)
        # deserialize + cached-compile cost, saved_ms names the bill
        # avoided, from_cache flags the provenance
        persisted = self._header.get("cost") or {}
        cost = {k: persisted.get(k) for k in _xla_cost.COST_FIELDS}
        for k in ("out_bytes", "generated_code_bytes", "peak_hbm_gbps",
                  "peak_tflops"):
            if persisted.get(k) is not None:
                cost[k] = persisted[k]
        cost["from_cache"] = True
        cost["saved_ms"] = round(
            (persisted.get("trace_ms") or 0.0)
            + (persisted.get("compile_ms") or 0.0), 3)
        _xla_cost.note_program_cost(
            self._site, self._digest, self._deser_ns, t1 - t0, cost,
            op=_xla_cost.current_op() or persisted.get("op"))
        if _obs.enabled():
            _obs.inc("tpu_program_cache_saved_seconds",
                     cost["saved_ms"] / 1e3)
        hlo_payload = self._header.get("hlo")
        if hlo_payload:
            _hlo.note_cached_summary(
                self._site, self._digest, dict(hlo_payload),
                op=_xla_cost.current_op() or persisted.get("op"))

    def _to_fallback(self, detail: str) -> None:
        """The negative path: poison the entry, pay the plain compile
        this process would have paid on a miss, keep serving. Caller
        must hold ``self._lock`` (first-call path holds it; the drift
        path takes it) — idempotent, so late racers are no-ops."""
        from ..exec import base as _base
        from .. import xla_cost as _xla_cost

        self._compiled = None
        if self._fallback is None:
            self._cache._poison(self._path, self._site, detail)
            _base.note_compile_miss(self._site)
            built = self._build()
            if isinstance(built, tuple):  # mesh aux rode the header;
                built = built[0]          # callers already hold it
            self._fallback = _xla_cost.wrap(built, self._site, self._key)


# ---------------------------------------------------------------------------
# Process-global active cache (the events/faults install pattern: the
# pipeline-cache chokepoint lives where no session handle exists).
# install() also hands the JAX persistent compilation cache its
# directory — that is what turns a warm process's backend compile of a
# deserialized module into a disk read.
# ---------------------------------------------------------------------------
_ENABLED = False
_ACTIVE: Optional[ProgramCache] = None
_INSTALL_LOCK = threading.Lock()
_PREV_JAX_CACHE: Optional[tuple] = None


def enabled() -> bool:
    """The hot-path guard — one module-global boolean read, consulted
    only on the pipeline-cache SLOW path (a fresh compile miss)."""
    return _ENABLED


def active() -> Optional[ProgramCache]:
    return _ACTIVE


def stats() -> Optional[Dict[str, Any]]:
    """Live counters for /status and tpu_top (None while off)."""
    pc = _ACTIVE
    return pc.stats.to_json() if pc is not None else None


def install(conf_: RapidsConf) -> Optional[ProgramCache]:
    """Install the cache when the confs ask for one (aotCache.dir
    implies aotCache.enabled, the eventLog pattern). Off — the default —
    installs NOTHING: no directory access, no jax config change, no
    threads. Idempotent for an identical (dir, identity) pair."""
    want = conf_.get(AOT_CACHE_ENABLED) or conf_.get(AOT_CACHE_DIR)
    if not want:
        return None
    global _ENABLED, _ACTIVE, _PREV_JAX_CACHE
    with _INSTALL_LOCK:
        cache = ProgramCache(conf_)
        cur = _ACTIVE
        if (cur is not None and cur.dir == cache.dir
                and cur.conf_fp == cache.conf_fp
                and cur.max_bytes == cache.max_bytes):
            return cur  # same identity: keep the live stats
        _register_pytree_serialization()
        import jax

        try:
            if _PREV_JAX_CACHE is None:
                _PREV_JAX_CACHE = (
                    jax.config.jax_compilation_cache_dir,
                    jax.config.jax_persistent_cache_min_entry_size_bytes,
                    jax.config.jax_persistent_cache_min_compile_time_secs)
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(cache.dir, "xla"))
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:
            # older jax without the persistent-cache knobs (the
            # snapshot reads degrade too, not just the updates): export
            # artifacts still skip the re-trace, the backend compile
            # just isn't disk-cached
            pass
        _ACTIVE = cache
        _ENABLED = True
        return cache


def uninstall() -> None:
    """Detach the cache and restore the pre-install jax compilation
    cache settings (tests pair install with this)."""
    global _ENABLED, _ACTIVE, _PREV_JAX_CACHE
    with _INSTALL_LOCK:
        _ACTIVE = None
        _ENABLED = False
        if _PREV_JAX_CACHE is not None:
            import jax

            d, sz, secs = _PREV_JAX_CACHE
            try:
                jax.config.update("jax_compilation_cache_dir", d)
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", sz)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", secs)
            except Exception:
                pass
            _PREV_JAX_CACHE = None
