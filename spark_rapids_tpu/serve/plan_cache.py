"""Process-shared plan cache keyed by plan digest.

N sessions submitting the same plan shape should pay the planning-side
work ONCE: the static analysis (the admission forecast the scheduler
checks) is computed on first submit and served from here afterwards, and
the first completed execution marks the digest "warm" — its XLA pipeline
programs sit in the process-global compile caches (exec/base.py et al.,
keyed structurally), so later sessions' submits dispatch without
compiling. The digest is the same sha1-of-tree_string the session stamps
into query_start events (sql/session.py), extended with a conf
fingerprint: two sessions submitting one plan under different layout/
memory settings must not share a forecast.

Reference analog: the driver-side plan de-duplication every serving
system grows (and the JVM plugin's own per-schema cudf JIT kernel
cache); thread-safe under concurrent sessions by construction — one
in-flight computation per key, later arrivals wait on it instead of
recomputing.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

from .. import obs as _obs
from ..utils.locks import ordered_lock

#: one analysis per distinct (digest, conf fingerprint) is plenty; the
#: cap only bounds a pathological digest churn (ragged ad-hoc plans)
_MAX_ENTRIES = 4096


class SharedPlanCache:
    """digest -> (analysis, warm flag) with single-flight computation."""

    _instance: Optional["SharedPlanCache"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = ordered_lock("serve.plan_cache")
        self._entries: Dict[tuple, Any] = {}
        self._inflight: Dict[tuple, threading.Event] = {}
        self._warm: Dict[tuple, bool] = {}
        self.hits = 0
        self.misses = 0

    @classmethod
    def get(cls) -> "SharedPlanCache":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = SharedPlanCache()
            return cls._instance

    @classmethod
    def reset(cls) -> "SharedPlanCache":
        with cls._instance_lock:
            cls._instance = SharedPlanCache()
            return cls._instance

    def analysis_for(self, key: tuple,
                     compute: Callable[[], Any]) -> Tuple[Any, bool]:
        """(analysis, was_hit). Single-flight: the first submitter of a
        key computes while later submitters of the SAME key wait on its
        event — never N analyses of one plan, and never a lock held
        across the (CPU-heavy) computation for unrelated keys."""
        while True:
            with self._lock:
                if key in self._entries:
                    self.hits += 1
                    if _obs.enabled():
                        _obs.inc("tpu_serve_plan_cache", 1, op="hit")
                    return self._entries[key], True
                ev = self._inflight.get(key)
                if ev is None:
                    ev = self._inflight[key] = threading.Event()
                    mine = True
                else:
                    mine = False
            if not mine:
                ev.wait()
                continue  # re-read: the computer published (or failed)
            try:
                value = compute()
            except BaseException:
                # a failed analysis must not wedge later submitters of
                # the same key into waiting forever — clear the flight
                # so the next one retries
                with self._lock:
                    self._inflight.pop(key, None)
                ev.set()
                raise
            with self._lock:
                if len(self._entries) > _MAX_ENTRIES:
                    self._entries.clear()
                    self._warm.clear()
                self._entries[key] = value
                self._inflight.pop(key, None)
                self.misses += 1
                if _obs.enabled():
                    _obs.inc("tpu_serve_plan_cache", 1, op="miss")
            ev.set()
            return value, False

    def mark_warm(self, key: tuple) -> None:
        """First completed execution of this digest: its pipeline
        programs are compiled in the process-global caches (surfaced as
        the ``warm`` count in :meth:`stats` / the serve bench lane)."""
        with self._lock:
            self._warm[key] = True

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "entries": len(self._entries),
                    "warm": sum(1 for v in self._warm.values() if v)}


def conf_fingerprint(conf_) -> tuple:
    """The part of a cache key that keeps sessions with different
    settings apart: the explicitly-set conf values (layout, memory and
    analysis behavior all hang off registered entries, and defaults are
    identical process-wide)."""
    return tuple(sorted(conf_._values.items()))
