"""Concurrent multi-query serving (ROADMAP item 3).

``scheduler.QueryScheduler`` — forecast-based admission control (the
static peak-HBM forecast vs the live catalog watermark/budget), a fair
per-session queue layered over the TpuSemaphore, and pipelined session
execution (admitted queries host-prefetch scans before taking the device
semaphore). ``plan_cache.SharedPlanCache`` — one static analysis / warm
compile set per plan digest across all sessions. Sessions route through
here when ``spark.rapids.tpu.serve.enabled`` is set (sql/session.py).
``program_cache.ProgramCache`` — the persistent AOT program store
(compile once, serve everywhere) riding the ``exec/base.cached_pipeline``
chokepoint; imported lazily by its consumers (NOT re-exported here:
exec/base imports this package, and pulling program_cache in at package
import would make that import order-sensitive).
"""
from .plan_cache import SharedPlanCache, conf_fingerprint
from .scheduler import (
    SERVE_ADMISSION_ENABLED,
    SERVE_ENABLED,
    SERVE_MAX_QUEUE_DEPTH,
    SERVE_PRIORITY,
    SERVE_QUEUE_TIMEOUT_MS,
    QueryScheduler,
    ServeAdmissionRejected,
    ServeQueueTimeout,
    Ticket,
)

__all__ = [
    "QueryScheduler",
    "SERVE_ADMISSION_ENABLED",
    "SERVE_ENABLED",
    "SERVE_MAX_QUEUE_DEPTH",
    "SERVE_PRIORITY",
    "SERVE_QUEUE_TIMEOUT_MS",
    "ServeAdmissionRejected",
    "ServeQueueTimeout",
    "SharedPlanCache",
    "Ticket",
    "conf_fingerprint",
]
