"""Physical operator (exec) layer.

Reference analog: the GpuExec hierarchy (GpuExec.scala:68,
basicPhysicalOperators.scala, aggregate.scala, GpuSortExec.scala, joins under
sql/rapids/execution/). Execs produce per-partition iterators of
ColumnarBatch; the partition is the data-parallel unit exactly as Spark's
RDD[ColumnarBatch] partitions are in the reference.
"""
from .base import Metric, TpuExec, batch_from_vals, vals_of_batch  # noqa: F401
from .basic import (  # noqa: F401
    TpuCoalesceBatchesExec,
    TpuExpandExec,
    TpuFilterExec,
    TpuLocalLimitExec,
    TpuProjectExec,
    TpuRangeExec,
    TpuUnionExec,
    InMemoryScanExec,
)
from .aggregate import TpuHashAggregateExec  # noqa: F401
from .join import (  # noqa: F401
    TpuBroadcastNestedLoopJoinExec,
    TpuShuffledHashJoinExec,
)
from .sort import TpuSortExec  # noqa: F401
from .window import TpuWindowExec  # noqa: F401
