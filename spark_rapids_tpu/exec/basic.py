"""Basic physical operators: scan-from-memory, project, filter, range,
union, limit, expand, coalesce-batches.

Reference analog: basicPhysicalOperators.scala (GpuProjectExec:48,
GpuFilter:113-129, GpuRangeExec:187, GpuUnionExec:315, GpuCoalesceExec:353),
limit.scala:51, GpuExpandExec.scala:67, GpuCoalesceBatches.scala.

TPU re-design notes:
  * Filter fuses condition evaluation AND row compaction into one jitted
    program — the cudf path launches a kernel per expression node plus a
    filter kernel; here XLA sees the whole thing.
  * Every pipeline is cached per (expression tree, input layout signature)
    so ragged batch sizes reuse executables via capacity bucketing.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch, DeviceColumn
from ..columnar.column import column_from_pylist
from ..conf import MAX_READER_BATCH_SIZE_ROWS, RapidsConf
from ..expr import expressions as E
from ..expr.eval import ColV, StrV, lower
from ..ops import filter_gather
from ..types import StructField, StructType
from ..columnar.column import choose_capacity
from .base import (
    NUM_OUTPUT_BATCHES,
    NUM_OUTPUT_ROWS,
    TpuExec,
    batch_from_vals,
    batch_signature,
    vals_of_batch,
)


def _output_schema_for(exprs: Sequence[E.Expression], child: StructType) -> StructType:
    fields = []
    for i, e in enumerate(exprs):
        name = e.name if isinstance(e, E.Alias) else (
            e.name if isinstance(e, E.UnresolvedAttribute) else f"col{i}"
        )
        bound = E.bind_references(e, child)
        fields.append(StructField(name, bound.dtype, bound.nullable))
    return StructType(tuple(fields))


class InMemoryScanExec(TpuExec):
    """Leaf over already-device-resident batches (test/data source seam).

    Under ``spark.rapids.tpu.sql.inMemoryScan.hostResident`` the cached
    representation lives on the HOST (the faithful Spark ``.cache()``
    semantics — the cache survives the query) and every execute uploads
    fresh device planes. Fresh uploads have exactly one reference — the
    executing query — so they are marked exclusive and every certified
    downstream site may donate them (plugin/donation.py). The default
    device-resident mode retains device batches across executes and
    therefore never marks them: donating a retained plane would delete
    the cache out from under the next query."""

    def __init__(self, conf: RapidsConf, partitions: Sequence[Sequence[ColumnarBatch]],
                 schema: StructType):
        super().__init__(conf)
        self._partitions = [list(p) for p in partitions]
        self._schema = schema
        from ..conf import SCAN_HOST_RESIDENT

        self._host_resident = bool(conf.get(SCAN_HOST_RESIDENT))
        self._host_planes: Optional[List[List[Optional[tuple]]]] = None

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return len(self._partitions)

    def _snapshot_to_host(self) -> List[List[Optional[tuple]]]:
        """One-time demotion of the cached batches to host numpy planes
        (one batched pull per batch through the sanctioned sync point).
        Dict-encoded batches stay device-resident — their dictionary
        pools are shared, so they could never donate anyway. Built into
        a local and assigned whole by the caller: concurrent partition
        executors may both compute it (idempotent — source batches are
        immutable), but neither ever observes a partial list."""
        from .base import host_pull

        out: List[List[Optional[tuple]]] = []
        for part in self._partitions:
            rows: List[Optional[tuple]] = []
            for b in part:
                if any(c.is_dict for c in b.columns):
                    rows.append(None)
                    continue
                planes = []
                for c in b.columns:
                    planes.append(tuple(
                        getattr(c, s, None)
                        for s in ("data", "validity", "offsets", "chars")))
                pulled = host_pull(
                    [a for ps in planes for a in ps if a is not None])
                it = iter(pulled)
                rows.append((b.num_rows, b.capacity, tuple(
                    tuple(next(it) if a is not None else None for a in ps)
                    for ps in planes)))
            out.append(rows)
        return out

    def _upload(self, b: ColumnarBatch, snap: tuple) -> ColumnarBatch:
        import jax.numpy as jnp

        from ..plugin import donation as _donation

        num_rows, _cap, planes = snap
        cols = []
        for c, (data, validity, offsets, chars) in zip(b.columns, planes):
            cols.append(DeviceColumn(
                c.dtype, num_rows,
                None if data is None else jnp.asarray(data),
                jnp.asarray(validity),
                None if offsets is None else jnp.asarray(offsets),
                None if chars is None else jnp.asarray(chars)))
        return _donation.mark_exclusive(
            ColumnarBatch(cols, self._schema, num_rows))

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        if self._host_resident:
            if self._host_planes is None:
                self._host_planes = self._snapshot_to_host()
            for b, snap in zip(self._partitions[index],
                               self._host_planes[index]):
                yield self.record_batch(
                    b if snap is None else self._upload(b, snap))
            return
        for b in self._partitions[index]:
            yield self.record_batch(b)

    def partition_rows(self):
        """Static per-partition row counts (batch num_rows are host ints)
        — the plananalysis mesh forecast's input for host-staged sources."""
        return [
            sum(int(b.num_rows) for b in p) for p in self._partitions
        ]

    @staticmethod
    def from_pydict(conf: RapidsConf, data, schema: StructType,
                    num_partitions: int = 1) -> "InMemoryScanExec":
        batch = ColumnarBatch.from_pydict(data, schema)
        if num_partitions == 1:
            return InMemoryScanExec(conf, [[batch]], schema)
        rows = batch.to_rows()
        chunks: List[List[ColumnarBatch]] = []
        n = len(rows)
        per = (n + num_partitions - 1) // num_partitions
        from ..columnar.batch import batch_from_rows

        for i in range(num_partitions):
            part = rows[i * per: (i + 1) * per]
            chunks.append([batch_from_rows(part, schema)] if part else [])
        return InMemoryScanExec(conf, chunks, schema)


_PROJECT_CACHE: dict = {}


def _project_pipeline(exprs: Tuple[E.Expression, ...], sig: tuple, cap: int,
                      nonnull: Tuple[bool, ...] = (),
                      donate: Tuple[int, ...] = ()):
    """Standalone projection program. ``nonnull``: the plan analyzer's
    validity-elision flags for the input columns — flagged columns swap
    their stored validity plane for the iota-derived liveness mask
    (ops/filter_gather.elide_validity); the compiled fn takes
    ``(cols, num_rows)`` either way so call sites stay uniform."""
    key = (exprs, sig, cap, nonnull)

    def build():
        def run(cols, num_rows):
            if nonnull and any(nonnull):
                live = filter_gather.live_of(num_rows, cap)
                cols = filter_gather.elide_validity(cols, live, nonnull)
            return [lower(e, cols, cap) for e in exprs]

        return jax.jit(run, donate_argnums=donate)

    from .base import cached_pipeline

    return cached_pipeline(_PROJECT_CACHE, key, "project", build,
                           donate=donate)


class TpuProjectExec(TpuExec):
    """reference: GpuProjectExec (basicPhysicalOperators.scala:48-61).

    Fusable: a project never dispatches alone if its neighbors fuse too.
    Partition-context expressions (rand / monotonically_increasing_id /
    spark_partition_id / input_file_name, plus hash() over strings, which
    needs a host-synced byte bound) evaluate at the exec boundary as
    appended input columns — the same treatment Spark gives
    nondeterministic expressions by pinning them in their own Project —
    and such a project does not fuse."""

    def __init__(self, conf: RapidsConf, exprs: Sequence[E.Expression], child: TpuExec):
        super().__init__(conf, [child])
        self.exprs = list(exprs)
        self._schema = _output_schema_for(self.exprs, child.output_schema)
        self._bound = tuple(
            E.bind_references(e, child.output_schema) for e in self.exprs
        )
        self._ctx_exprs = self._collect_ctx_exprs()

    def _collect_ctx_exprs(self):
        """Distinct context subexpressions, in first-appearance order.
        Equal nodes share one column — Spark semantics: two rand(5) calls
        draw the same per-row sequence (same seeded generator)."""
        out = []

        def walk(e):
            if isinstance(e, E.NONDETERMINISTIC_CONTEXT_EXPRS) or (
                isinstance(e, E.Murmur3Hash)
                and any(T.is_string(c.dtype) for c in e.exprs)
            ):
                if e not in out:
                    out.append(e)
                return
            for c in e.children:
                walk(c)

        for b in self._bound:
            walk(b)
        return tuple(out)

    @property
    def fusable(self):  # type: ignore[override]
        return not self._ctx_exprs

    @property
    def output_schema(self):
        return self._schema

    def describe(self):
        return f"TpuProjectExec [{', '.join(map(str, self.exprs))}]"

    def fusion_key(self):
        return ("project", self._bound)

    def lower_batch(self, cols, live, cap, side=()):
        return [lower(e, cols, cap) for e in self._bound], live

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        # per-batch timing/tracing happens inside run_fused_chain /
        # _execute_with_context (an outer wrapper here would also bill the
        # CONSUMER's time between yields to this exec)
        from .base import run_fused_chain

        if self._ctx_exprs:
            yield from self._execute_with_context(index)
        else:
            yield from run_fused_chain(self, index)

    # -- partition-context evaluation --------------------------------------
    def _source_file(self, index: int) -> str:
        """File path for input_file_name: walk single-child row-preserving
        execs down to a file scan (partition indices pass through 1:1)."""
        node: TpuExec = self.children[0]
        while True:
            scanner = getattr(node, "scanner", None)
            if scanner is not None and hasattr(scanner, "splits"):
                splits = scanner.splits()
                return splits[index].path if index < len(splits) else ""
            kids = node.children
            if len(kids) != 1 or not getattr(node, "fusable", False):
                return ""  # not a file scan source (Spark returns "")
            node = kids[0]

    def _ctx_columns(self, batch, index: int, row_base, cap: int, fpath: str):
        """Materialize one DeviceColumn per context expression."""
        import jax.numpy as jnp

        from ..expr.nondet import rand_double_jax
        from ..ops import hashing
        from ..ops.sort import max_string_len
        from .base import count_scalar
        from .scan import constant_string_column

        cols = []
        fields = []
        n = batch.num_rows_lazy
        idx64 = jnp.arange(cap, dtype=jnp.int64)
        for k, e in enumerate(self._ctx_exprs):
            if isinstance(e, E.SparkPartitionID):
                c = DeviceColumn(
                    T.INT, n, jnp.full(cap, index, jnp.int32),
                    jnp.ones(cap, jnp.bool_))
            elif isinstance(e, E.MonotonicallyIncreasingID):
                base = (jnp.int64(index) << 33) + count_scalar(
                    row_base).astype(jnp.int64)
                c = DeviceColumn(
                    T.LONG, n, base + idx64, jnp.ones(cap, jnp.bool_))
            elif isinstance(e, E.Rand):
                rows = count_scalar(row_base).astype(jnp.int64) + idx64
                c = DeviceColumn(
                    T.DOUBLE, n, rand_double_jax(e.seed, index, rows),
                    jnp.ones(cap, jnp.bool_))
            elif isinstance(e, E.InputFileName):
                nn = n if isinstance(n, int) else cap
                c = constant_string_column(fpath, nn, cap)
            else:  # Murmur3Hash with string children
                vals = [lower(x, vals_of_batch(batch), cap)
                        for x in e.exprs]
                smls = [
                    max(4, int(max_string_len(v)))
                    for v in vals if hasattr(v, "offsets")
                ]
                h = hashing.murmur3(
                    vals, [x.dtype for x in e.exprs], e.seed, smls)
                c = DeviceColumn(T.INT, n, h, jnp.ones(cap, jnp.bool_))
            cols.append(c)
            fields.append(StructField(f"_ctx{k}", c.dtype, False))
        return cols, fields

    def _execute_with_context(self, index: int) -> Iterator[ColumnarBatch]:
        from .base import count_scalar

        child = self.children[0]
        child_schema = child.output_schema
        nbase = len(child_schema.fields)
        subst = {e: i for i, e in enumerate(self._ctx_exprs)}

        def rewrite(node):
            i = subst.get(node)
            if i is not None:
                return E.BoundReference(
                    nbase + i, node.dtype, node.nullable)
            return node

        rewritten = tuple(b.transform(rewrite) for b in self._bound)
        fpath = self._source_file(index)
        row_base = 0
        for batch in child.execute_partition(index):
            with self.op_timed("ctx"):
                cap = batch.capacity
                extra_cols, extra_fields = self._ctx_columns(
                    batch, index, row_base, cap, fpath)
                ext = ColumnarBatch(
                    list(batch.columns) + extra_cols,
                    StructType(tuple(child_schema.fields) + tuple(extra_fields)),
                    batch.num_rows_lazy)
                from .base import _donation
                from .base import count_scalar as _cs

                don = _donation()
                # ext shares the child batch's planes; the appended ctx
                # columns are fresh by construction, so the dispatch may
                # donate exactly when the CHILD batch is donatable (the
                # loop reads only its scalar row count afterwards)
                nr_lazy = batch.num_rows_lazy
                mask = don.dispatch_mask("project", batch, self.conf)
                fn = _project_pipeline(
                    rewritten, batch_signature(ext), cap, donate=mask)
                if mask:
                    # no retry harness wraps this dispatch, so the
                    # snapshot leg of the guard is skipped: nothing
                    # re-reads the planes on failure
                    with don.guard("project", ext, op=self.node_name,
                                   snapshot=False,
                                   metric=self.metric("donatedBytes")):
                        vals = fn(vals_of_batch(ext), _cs(nr_lazy))
                else:
                    vals = fn(vals_of_batch(ext), _cs(nr_lazy))
                out = don.mark_exclusive(
                    batch_from_vals(vals, self._schema, nr_lazy))
            yield self.record_batch(out)
            nr = batch.num_rows_lazy
            row_base = (row_base + nr if isinstance(nr, int)
                        and isinstance(row_base, int)
                        else count_scalar(row_base) + count_scalar(nr))


class TpuFilterExec(TpuExec):
    """reference: GpuFilterExec/GpuFilter (basicPhysicalOperators.scala:113-172).

    Condition eval + row compaction lower into the fused stage; the surviving
    row count stays on device (cudf syncs for it — we don't have to)."""

    fusable = True
    sparsifies = True

    def __init__(self, conf: RapidsConf, condition: E.Expression, child: TpuExec):
        super().__init__(conf, [child])
        self.condition = condition
        self._bound = E.bind_references(condition, child.output_schema)

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def describe(self):
        return f"TpuFilterExec [{self.condition}]"

    def fusion_key(self):
        return ("filter", self._bound)

    def lower_batch(self, cols, live, cap, side=()):
        c = lower(self._bound, cols, cap)
        return cols, live & c.data & c.validity

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        from .base import run_fused_chain

        yield from run_fused_chain(self, index)


class TpuRangeExec(TpuExec):
    """reference: GpuRangeExec (basicPhysicalOperators.scala:187)."""

    def __init__(self, conf: RapidsConf, start: int, end: int, step: int = 1,
                 num_slices: int = 1, name: str = "id"):
        super().__init__(conf)
        if step == 0:
            raise ValueError("step must not be 0")
        self.start, self.end, self.step = start, end, step
        self.num_slices = num_slices
        self._schema = StructType((StructField(name, T.LONG, False),))

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return self.num_slices

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        total = max(0, -(-(self.end - self.start) // self.step))
        per = (total + self.num_slices - 1) // self.num_slices if total else 0
        lo = index * per
        hi = min(total, (index + 1) * per)
        max_rows = self.conf.get(MAX_READER_BATCH_SIZE_ROWS)
        pos = lo
        while pos < hi:
            n = min(max_rows, hi - pos)
            cap = choose_capacity(n, self.conf.shape_bucket_min)
            base = self.start + pos * self.step
            data = jnp.arange(cap, dtype=jnp.int64) * self.step + base
            live = jnp.arange(cap, dtype=jnp.int32) < n
            data = jnp.where(live, data, 0)
            col = DeviceColumn(T.LONG, n, data, live)
            yield self.record_batch(ColumnarBatch([col], self._schema, n))
            pos += n


class TpuUnionExec(TpuExec):
    """reference: GpuUnionExec (basicPhysicalOperators.scala:315)."""

    def __init__(self, conf: RapidsConf, children: Sequence[TpuExec]):
        super().__init__(conf, children)
        self._schema = children[0].output_schema

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return sum(c.num_partitions for c in self.children)

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        for c in self.children:
            if index < c.num_partitions:
                for b in c.execute_partition(index):
                    yield self.record_batch(b)
                return
            index -= c.num_partitions
        raise IndexError(index)


class TpuLocalLimitExec(TpuExec):
    """reference: GpuBaseLimitExec (limit.scala:51) — per-partition limit."""

    def __init__(self, conf: RapidsConf, limit: int, child: TpuExec):
        super().__init__(conf, [child])
        self.limit = limit

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        remaining = self.limit
        for batch in self.children[0].execute_partition(index):
            if remaining <= 0:
                return
            if batch.num_rows <= remaining:
                remaining -= batch.num_rows
                yield self.record_batch(batch)
                if remaining == 0:
                    return  # don't pull (compute) another child batch
            else:
                vals, count = filter_gather.slice_cols(
                    vals_of_batch(batch), 0, choose_capacity(remaining, self.conf.shape_bucket_min),
                    jnp.int32(min(remaining, batch.num_rows)),
                )
                out = batch_from_vals(vals, self.output_schema, remaining)
                remaining = 0
                yield self.record_batch(out)
                return


class TpuCollectLimitExec(TpuExec):
    """Global limit: one output partition draining children in order until
    ``limit`` rows (reference: GpuCollectLimitMeta limit.scala:126)."""

    def __init__(self, conf: RapidsConf, limit: int, child: TpuExec):
        super().__init__(conf, [child])
        self.limit = limit

    @property
    def output_schema(self):
        return self.children[0].output_schema

    @property
    def num_partitions(self):
        return 1

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        remaining = self.limit
        child = self.children[0]
        for p in range(child.num_partitions):
            for batch in child.execute_partition(p):
                if remaining <= 0:
                    return
                n = batch.num_rows
                if n <= remaining:
                    remaining -= n
                    yield self.record_batch(batch)
                    if remaining == 0:
                        return  # don't pull (compute) another child batch
                else:
                    vals, count = filter_gather.slice_cols(
                        vals_of_batch(batch), 0,
                        choose_capacity(remaining, self.conf.shape_bucket_min),
                        jnp.int32(remaining),
                    )
                    out = batch_from_vals(vals, self.output_schema, remaining)
                    remaining = 0
                    yield self.record_batch(out)
                    return


class TpuExpandExec(TpuExec):
    """reference: GpuExpandExec (GpuExpandExec.scala:67) — each input batch
    is projected once per projection group (rollup/cube lowering)."""

    def __init__(self, conf: RapidsConf, projections: Sequence[Sequence[E.Expression]],
                 output_names: Sequence[str], child: TpuExec):
        super().__init__(conf, [child])
        self.projections = [list(p) for p in projections]
        child_schema = child.output_schema
        first = [E.bind_references(e, child_schema) for e in self.projections[0]]
        self._schema = StructType(tuple(
            StructField(n, e.dtype, True) for n, e in zip(output_names, first)
        ))
        self._bound = [
            tuple(E.bind_references(e, child_schema) for e in p)
            for p in self.projections
        ]

    @property
    def output_schema(self):
        return self._schema

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        from ..plugin.plananalysis import entry_nonnull_flags
        from .base import count_scalar

        nonnull = entry_nonnull_flags(
            self.children[0].output_schema, self.conf)
        for batch in self.children[0].execute_partition(index):
            cap = batch.capacity
            sig = batch_signature(batch)
            vals_in = vals_of_batch(batch)
            for bound in self._bound:
                with self.op_timed():
                    fn = _project_pipeline(bound, sig, cap, nonnull)
                    vals = fn(vals_in, count_scalar(batch.num_rows))
                    out = batch_from_vals(vals, self._schema, batch.num_rows)
                yield self.record_batch(out)


class TpuCoalesceBatchesExec(TpuExec):
    """reference: GpuCoalesceBatches (GpuCoalesceBatches.scala:398-571) —
    concatenate small batches up to a target size before heavy operators."""

    def __init__(self, conf: RapidsConf, child: TpuExec,
                 target_rows: Optional[int] = None):
        super().__init__(conf, [child])
        self.target_rows = target_rows or conf.get(MAX_READER_BATCH_SIZE_ROWS)

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def _flush(self, pending: List[ColumnarBatch]) -> Optional[ColumnarBatch]:
        if not pending:
            return None
        # ONE multi-batch stitch engine-wide: the same helper re-joins
        # split-and-retry pieces (memory/retry.py), so the concat
        # invariants (dict materialization, char-cap bucketing,
        # zero-column row carry) cannot drift between the two paths
        from ..memory.retry import concat_batches

        return concat_batches(self.conf, pending)

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        pending: List[ColumnarBatch] = []
        rows = 0
        for batch in self.children[0].execute_partition(index):
            if batch.num_rows == 0:
                continue
            pending.append(batch)
            rows += batch.num_rows
            if rows >= self.target_rows:
                with self.op_timed():
                    out = self._flush(pending)
                pending, rows = [], 0
                if out is not None:
                    yield self.record_batch(out)
        with self.op_timed():
            out = self._flush(pending)
        if out is not None:
            yield self.record_batch(out)
