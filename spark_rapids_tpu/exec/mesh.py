"""Mesh-lowered SPMD stages: whole plan fragments as ONE shard_map program
over the device mesh, fed by a sharded scan.

Reference analog: the accelerated shuffle path the planner actually selects
(RapidsShuffleInternalManager.scala:58-150 + the UCX transport): there, a
PARTIAL aggregate, a device-cached shuffle write, an RDMA fetch, and a FINAL
aggregate are four separately-scheduled stages. Here the planner lowers the
whole exchange-bounded stage — partial aggregate -> all_to_all -> final
merge -> result projection, or local-sort -> sampled range exchange -> merge
sort, or hash-exchange both sides -> local join — into ONE jitted SPMD
computation over a jax.sharding.Mesh (parallel/distributed.py), with child
partition i living on mesh shard i % n. XLA schedules the ICI collectives
against compute; nothing touches the host between the child batches and the
stage output.

Fixed-width columns cross the mesh as data/validity planes; STRING columns
cross as offsets/chars/validity planes with the chars riding the
collective's byte-plane all_to_all (parallel/collective.py) — the same
type-agnostic contract as the reference's UCX transport
(RapidsShuffleClient.scala:35-98). Staging computes a static max byte
length per string column, so string GROUP KEYS must be direct column
references (computed string keys have no staged bound and stay on the
single-host exchange, as do binary columns).

Whole-plan SPMD (round 6): a fixed-width filter/project chain between the
stage and its source is ABSORBED into the shard_map program (the execs'
own ``lower_batch`` hooks run per shard, exactly the single-device fused
chain's seam), and a source exposing ``stage_mesh_planes`` (sharded scans:
io/mesh_stage.py — in-memory shard sources, round-robined parquet row
groups) feeds the program with per-shard committed device batches instead
of the host-gathered staging path. The post-PARTIAL aggregate exchange is
sliced to the group cardinality (``shuffle.mesh.aggExchangeCapacity`` +
overflow retry) and the sort exchange granule to ~2x the fair share
(``shuffle.mesh.exchangeBucketFactor``), so the all_to_all surface scales
with what actually crosses the wire, not n_shards x input capacity.
"""
from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import shard_map

from .. import types as T
from ..columnar import ColumnarBatch, DeviceColumn
from ..conf import RapidsConf
from ..expr import aggregates as A
from ..expr import expressions as E
from ..expr.eval import ColV, lower
from ..ops.sort import SortOrder
from ..parallel import distributed as D
from ..parallel.mesh import AXIS, get_mesh, row_sharding
from ..types import StructField, StructType
from ..utils.bucketing import bucket_rows
from . import aggregate as XA
from .base import TpuExec

P = jax.sharding.PartitionSpec


def _np_of(arr) -> np.ndarray:
    from .base import host_pull

    return np.asarray(host_pull(arr))


class StagedChild:
    """What a mesh stage consumes: flat global planes + counts + layout,
    the absorbed in-program chain steps, and the staging telemetry the
    plananalysis cross-check compares against its forecast."""

    __slots__ = ("cols", "counts", "cap", "layout", "smls", "steps",
                 "staged_bytes", "source")

    def __init__(self, cols, counts, cap, layout, smls, steps=(),
                 staged_bytes=(), source="host"):
        self.cols = cols
        self.counts = counts
        self.cap = cap
        self.layout = layout
        self.smls = smls
        self.steps = tuple(steps)
        self.staged_bytes = tuple(staged_bytes)
        self.source = source

    def steps_sig(self) -> tuple:
        return tuple(s.fusion_key() for s in self.steps)


class _MeshStage(TpuExec):
    """Base: stage child partitions onto the mesh, run one SPMD program,
    emit one output partition per shard."""

    def __init__(self, conf: RapidsConf, children: Sequence[TpuExec]):
        super().__init__(conf, children)
        self.mesh = get_mesh(conf=conf)
        self.n_shards = int(self.mesh.devices.size)
        self._outputs: Optional[List[Optional[ColumnarBatch]]] = None
        #: staging/execution actuals per materialized child, keyed like the
        #: plananalysis mesh forecast ("cap", "per_shard_rows",
        #: "staged_bytes", "source") + "per_chip_ns"/"programs" run-wide —
        #: the cross-check's measured side
        self.mesh_actuals: dict = {}

    @property
    def num_partitions(self) -> int:
        return self.n_shards

    def reset_for_rerun(self) -> None:
        """Drop materialized outputs so the stage re-stages and re-runs
        (the bench mesh lane times staging+execution per iteration; the
        compiled SPMD program stays cached)."""
        self._outputs = None

    # -- whole-plan absorption --------------------------------------------
    def _absorb_chain(self, child: TpuExec):
        """Peel fixed-width filter/project execs off ``child`` so they run
        INSIDE the shard_map program (their own ``lower_batch`` hooks —
        the same seam the single-device fused chain uses). Absorption is
        conservative: every schema the chain touches must be fixed-width
        (string/dict columns keep the host-fed path, whose staging knows
        their byte bounds) and each exec must be fusable (partition-
        context expressions pin their project at the exec boundary).
        Returns (base child, steps bottom-up)."""
        from ..conf import MESH_WHOLE_PLAN
        from .basic import TpuFilterExec, TpuProjectExec

        if not self.conf.get(MESH_WHOLE_PLAN):
            return child, ()
        steps: List[TpuExec] = []
        node = child
        while isinstance(node, (TpuFilterExec, TpuProjectExec)):
            if not getattr(node, "fusable", False):
                break
            below = node.children[0].output_schema
            if not all(T.is_fixed_width(f.dataType)
                       for f in node.output_schema.fields):
                break
            if not all(T.is_fixed_width(f.dataType) for f in below.fields):
                break
            steps.append(node)
            node = node.children[0]
        steps.reverse()
        return node, tuple(steps)

    @staticmethod
    def _apply_steps(steps, cols, live, cap):
        """Run absorbed chain steps per shard (trace-time). Returns
        (cols, live-mask) — filters sparsify via the mask (the distributed
        kernels take a mask as their row count), projects rewrite cols."""
        for st in steps:
            cols, live = st.lower_batch(cols, live, cap)
        return cols, live

    # -- staging -----------------------------------------------------------
    def _on_shard_staged(self, s: int, rows: int, nbytes: int,
                         secs: float) -> None:
        """Per-shard staging telemetry: the transfer event gains a shard
        lane (Perfetto shows one upload track per chip) and the live
        plane counts rows per device."""
        from .. import events as EV
        from .. import obs as _obs

        if EV.enabled():
            EV.emit("transfer", direction="h2d", bytes=nbytes,
                    site="mesh_stage", shard=s)
        if _obs.enabled():
            _obs.inc("tpu_mesh_staged_rows", rows, device=str(s))
            _obs.inc("tpu_transfer_bytes", nbytes, direction="h2d")

    def _stage_child(self, child: TpuExec) -> StagedChild:
        """Stage ``child`` onto the mesh: absorb the fixed-width chain,
        then either the child's own sharded-scan path (no host gather) or
        the generic host-gather staging."""
        base, steps = self._absorb_chain(child)
        fast = getattr(base, "stage_mesh_planes", None)
        if fast is not None:
            staged = fast(self.mesh, self.n_shards, self.conf,
                          on_shard=self._on_shard_staged)
            if staged is not None:
                return StagedChild(
                    list(staged.cols), staged.counts, staged.cap,
                    staged.layout, staged.smls, steps,
                    staged.staged_bytes, source="sharded_scan")
        cols, counts, cap, layout, smls, staged_bytes = \
            self._stage_host(base)
        return StagedChild(cols, counts, cap, layout, smls, steps,
                           staged_bytes, source="host")

    def _stage_host(self, child: TpuExec):
        """Materialize every child partition and lay rows onto the mesh:
        returns (flat global arrays, per-shard counts, per-shard cap,
        layout, str_max_lens). Child partition p maps to shard p % n.

        layout[i] is ("f",) for a fixed column or ("s", char_cap) for a
        string column (offsets/chars/validity planes); str_max_lens[i] is
        0 for fixed columns and the bucketed max byte length for string
        columns (a STATIC bound the sort / hash kernels need, computed
        host-side here — staging already touches every byte)."""
        schema = child.output_schema
        per_shard: List[List[ColumnarBatch]] = [[] for _ in range(self.n_shards)]
        for p in range(child.num_partitions):
            for b in child.execute_partition(p):
                per_shard[p % self.n_shards].append(b)
        counts = np.zeros(self.n_shards, np.int32)
        rows_per_shard = [
            sum(int(b.num_rows) for b in bs) for bs in per_shard
        ]
        cap = bucket_rows(max(max(rows_per_shard), 1),
                          self.conf.shape_bucket_min)
        fields = schema.fields
        ncols = len(fields)
        is_str = [T.is_string(f.dataType) for f in fields]
        # gather host views once (dict-encoded strings materialize: the
        # mesh planes splice raw offset/chars byte pools across shards)
        from .base import materialized_batch

        host: List[List[tuple]] = [[] for _ in range(self.n_shards)]
        for s, bs in enumerate(per_shard):
            for b in bs:
                b = materialized_batch(b)
                n = int(b.num_rows)
                row = []
                for c in b.columns:
                    if c.is_string:
                        row.append((
                            _np_of(c.offsets), _np_of(c.chars),
                            _np_of(c.validity), n))
                    else:
                        row.append((_np_of(c.data), _np_of(c.validity), n))
                host[s].append(row)
            counts[s] = sum(int(b.num_rows) for b in bs)
        # per string column: per-shard byte totals -> common char cap + sml
        layout: List[tuple] = []
        smls: List[int] = []
        for j in range(ncols):
            if not is_str[j]:
                layout.append(("f",))
                smls.append(0)
                continue
            max_bytes = 1
            max_len = 1
            for s in range(self.n_shards):
                tot = 0
                for row in host[s]:
                    offs, _, _, n = row[j]
                    tot += int(offs[n])
                    if n:
                        max_len = max(
                            max_len, int((offs[1:n + 1] - offs[:n]).max()))
                max_bytes = max(max_bytes, tot)
            ccap = bucket_rows(max_bytes, 128)
            layout.append(("s", ccap))
            smls.append(max(4, bucket_rows(max_len, 4)))
        # build global planes
        planes: List[np.ndarray] = []
        for j in range(ncols):
            if layout[j][0] == "f":
                d = np.zeros((self.n_shards, cap), fields[j].dataType.to_numpy())
                v = np.zeros((self.n_shards, cap), bool)
                for s in range(self.n_shards):
                    pos = 0
                    for row in host[s]:
                        data, valid, n = row[j]
                        d[s, pos:pos + n] = data[:n]
                        v[s, pos:pos + n] = valid[:n]
                        pos += n
                planes.extend([d, v])
            else:
                ccap = layout[j][1]
                o = np.zeros((self.n_shards, cap + 1), np.int32)
                ch = np.zeros((self.n_shards, ccap), np.uint8)
                v = np.zeros((self.n_shards, cap), bool)
                for s in range(self.n_shards):
                    pos = 0
                    bpos = 0
                    for row in host[s]:
                        offs, chars, valid, n = row[j]
                        nb = int(offs[n])
                        o[s, pos + 1: pos + n + 1] = bpos + offs[1: n + 1]
                        ch[s, bpos: bpos + nb] = chars[:nb]
                        v[s, pos:pos + n] = valid[:n]
                        pos += n
                        bpos += nb
                    o[s, pos + 1:] = bpos
                planes.extend([o, ch, v])
        sh = row_sharding(self.mesh)
        out = [jax.device_put(a.reshape(-1), sh) for a in planes]
        # host-staged planes are uniform by construction: every shard's
        # slice is the same 1/n_shards of each global plane
        per_shard_bytes = sum(a.nbytes for a in planes) // self.n_shards
        staged_bytes = (per_shard_bytes,) * self.n_shards
        for s, r in enumerate(rows_per_shard):
            # per-chip staging lane (a skewed shard shows up immediately)
            self._on_shard_staged(s, r, staged_bytes[s], 0.0)
        return out, counts, cap, tuple(layout), tuple(smls), staged_bytes

    @staticmethod
    def _cols_of_flat(colflat: Sequence[jax.Array], layout) -> List:
        """Per-shard flat planes -> ColV/StrV column list (inside
        shard_map: a string column is offsets/chars/validity planes)."""
        from ..expr.eval import StrV

        cols: List = []
        gi = 0
        for lay in layout:
            if lay[0] == "f":
                cols.append(ColV(colflat[gi], colflat[gi + 1]))
                gi += 2
            else:
                cols.append(
                    StrV(colflat[gi], colflat[gi + 1], colflat[gi + 2]))
                gi += 3
        return cols

    @staticmethod
    def _flatten_vals(outs) -> Tuple[List[jax.Array], Tuple[tuple, ...]]:
        """Column values -> flat planes + an output layout for _emit."""
        from ..expr.eval import StrV

        flat: List[jax.Array] = []
        layout: List[tuple] = []
        for o in outs:
            if isinstance(o, StrV):
                flat.extend([o.offsets, o.chars, o.validity])
                layout.append(("s",))
            else:
                flat.extend([o.data, o.validity])
                layout.append(("f",))
        return flat, tuple(layout)

    def _emit(self, schema: StructType, global_cols: Sequence[jax.Array],
              counts: np.ndarray, cap: int,
              layout=None) -> List[Optional[ColumnarBatch]]:
        """Split flat global outputs back into per-shard batches. Shapes
        per shard derive from each plane's global size / n_shards."""
        if layout is None:
            layout = tuple(
                ("s",) if T.is_string(f.dataType) else ("f",)
                for f in schema.fields)
        outs: List[Optional[ColumnarBatch]] = []
        for s in range(self.n_shards):
            n = int(counts[s])
            cols = []
            gi = 0
            for f, lay in zip(schema.fields, layout):
                if lay[0] == "f":
                    d, v = global_cols[gi], global_cols[gi + 1]
                    gi += 2
                    per = d.shape[0] // self.n_shards
                    cols.append(DeviceColumn(
                        f.dataType, n, d[s * per:(s + 1) * per],
                        v[s * per:(s + 1) * per]))
                else:
                    o, ch, v = (global_cols[gi], global_cols[gi + 1],
                                global_cols[gi + 2])
                    gi += 3
                    po = o.shape[0] // self.n_shards
                    pc = ch.shape[0] // self.n_shards
                    pv = v.shape[0] // self.n_shards
                    cols.append(DeviceColumn(
                        f.dataType, n, None, v[s * pv:(s + 1) * pv],
                        offsets=o[s * po:(s + 1) * po],
                        chars=ch[s * pc:(s + 1) * pc]))
            outs.append(ColumnarBatch(cols, schema, n))
        return outs

    def forecast_mesh_staging(self, child: TpuExec) -> Optional[dict]:
        """The plananalysis per-shard forecast for staging ``child``:
        cap / per-shard rows / staged bytes, computed with the SAME
        helpers the runtime staging paths use (io/mesh_stage) over the
        same chain absorption and item→shard placement — so forecast and
        actual can only diverge through a code change both sides see.
        None when the source's row counts aren't statically known."""
        from ..io import mesh_stage as MS

        base, steps = self._absorb_chain(child)
        items = None
        fn = getattr(base, "mesh_stage_items", None)
        if fn is not None:
            items = fn()
        source = "sharded_scan" if items is not None else "host"
        if items is None:
            pr = getattr(base, "partition_rows", None)
            if pr is None:
                return None
            items = pr()
            if items is None:
                return None
        assign = MS.round_robin(len(items), self.n_shards)
        per_shard = [sum(items[i] for i in idxs) for idxs in assign]
        cap = MS.mesh_shard_cap(per_shard, self.conf.shape_bucket_min)
        fields = base.output_schema.fields
        fixed = all(T.is_fixed_width(f.dataType) for f in fields)
        return {
            "source": source,
            "n_shards": self.n_shards,
            "cap": cap,
            "per_shard_rows": per_shard,
            "staged_bytes": (
                [MS.shard_plane_bytes(cap, fields)] * self.n_shards
                if fixed else None),
            "absorbed_steps": [s.node_name for s in steps],
            "columns": [
                (f.name, f.dataType.simpleString) for f in fields
            ],
        }

    def _record_staging(self, staged: StagedChild, which: str = "") -> None:
        key = f"staging{('_' + which) if which else ''}"
        self.mesh_actuals[key] = {
            "cap": staged.cap,
            "per_shard_rows": [int(c) for c in staged.counts],
            "staged_bytes": list(staged.staged_bytes),
            "source": staged.source,
        }

    def _record_run(self, outs, dispatch_ns: int) -> None:
        """Per-chip completion lanes: block on each shard's output buffers
        in shard order and emit one device-lane op_span per chip (track
        '<op> [chip k]' in Perfetto). Polling is sequential, so each value
        is an UPPER bound on that chip's completion — exact per-chip
        device occupancy needs the device profiler; these lanes show skew
        and make all n chips visible on the timeline."""
        import time as _time

        from .. import events as EV
        from .. import obs as _obs

        per_chip: List[int] = []
        for s in range(self.n_shards):
            for a in outs:
                shards = getattr(a, "addressable_shards", None)
                if shards is not None and s < len(shards):
                    jax.block_until_ready(shards[s].data)
            per_chip.append(_time.perf_counter_ns() - dispatch_ns)
        self.mesh_actuals["per_chip_ns"] = per_chip
        if EV.enabled():
            for s, dur in enumerate(per_chip):
                EV.emit("op_span", op=self.node_name, section="spmd",
                        start=dispatch_ns, dur=dur, lane="device", shard=s)
        if _obs.enabled():
            for s, dur in enumerate(per_chip):
                _obs.inc("tpu_mesh_shard_seconds", dur / 1e9,
                         device=str(s))

    def _note_program_miss(self) -> None:
        self.mesh_actuals["programs"] = (
            self.mesh_actuals.get("programs", 0) + 1)

    # -- forecast hooks (plugin/plananalysis.forecast_mesh) ----------------
    mesh_site = "mesh"

    def mesh_program_bound(self, cap: int) -> int:
        """Upper bound on compiled SPMD programs for one materialization
        (1 + capacity-overflow retries). Subclasses with retry loops
        override with the same doubling arithmetic the loop runs."""
        return 1

    @staticmethod
    def _doubling_bound(start: int, cap: int) -> int:
        """Programs a double-until-cap retry loop can compile: the first
        attempt plus one per doubling until the cap disables slicing."""
        n, g = 1, start
        while 0 < g < cap:
            g = min(g * 2, cap)
            n += 1
        return n

    def _materialize(self) -> None:
        raise NotImplementedError

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        if self._outputs is None:
            with self.op_timed():
                self._materialize()
        b = self._outputs[index]
        if b is not None and b.num_rows > 0:
            yield self.record_batch(b)

    def describe(self):
        return f"{self.node_name}(mesh={self.n_shards})"


_PROGRAM_CACHE: dict = {}


def _cached_program(key, builder, site: Optional[str] = None,
                    on_miss=None):
    from .base import cached_pipeline

    def build():
        if on_miss is not None:
            on_miss()
        return builder()

    return cached_pipeline(_PROGRAM_CACHE, key, site, build,
                           max_entries=256)


class TpuMeshAggregateExec(_MeshStage):
    """partial-agg -> hash all_to_all -> final merge -> result projection,
    one SPMD program (reference plan: GpuHashAggregateExec(PARTIAL) ->
    GpuShuffleExchangeExec -> GpuHashAggregateExec(FINAL)).

    The buffer layout / update-merge op split is borrowed from a PARTIAL
    TpuHashAggregateExec (never executed — only its bound metadata)."""

    def __init__(self, conf, group_exprs, agg_exprs, child):
        _MeshStage.__init__(self, conf, [child])
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        plan = XA.TpuHashAggregateExec(
            conf, group_exprs, agg_exprs, child, mode=A.PARTIAL)
        self._key_fields = plan._key_fields
        self._bound_keys = plan._bound_keys
        self._bound_funcs = plan._bound_funcs
        self._buf_fields = plan._buf_fields
        self._buf_slices = plan._buf_slices
        self._update_exprs = plan._update_exprs
        self._update_ops = plan._update_ops
        self._merge_ops = plan._merge_ops
        fields = list(self._key_fields)
        for ae, f in zip(self.agg_exprs, self._bound_funcs):
            fields.append(StructField(ae.resolved_name(), f.dtype, True))
        self._schema = StructType(tuple(fields))

    def _key_dtypes(self):
        return tuple(f.dataType for f in self._key_fields)

    @property
    def output_schema(self):
        return self._schema

    def describe(self):
        keys = ", ".join(str(k) for k in self.group_exprs)
        return f"TpuMeshAggregateExec(mesh={self.n_shards}, keys=[{keys}])"

    mesh_site = "mesh_agg"

    def mesh_program_bound(self, cap: int) -> int:
        from ..conf import MESH_AGG_EXCHANGE_CAP

        g = min(bucket_rows(self.conf.get(MESH_AGG_EXCHANGE_CAP),
                            self.conf.shape_bucket_min), cap)
        return self._doubling_bound(g, cap)

    def _materialize(self) -> None:
        import time as _time

        child = self.children[0]
        staged = self._stage_child(child)
        self._record_staging(staged)
        global_cols, counts, cap = staged.cols, staged.counts, staged.cap
        layout, smls, steps = staged.layout, staged.smls, staged.steps
        nk = len(self._key_fields)
        key_dtypes = list(self._key_dtypes())
        bound_keys = tuple(self._bound_keys)
        update_exprs = tuple(self._update_exprs)
        update_ops = tuple(self._update_ops)
        merge_ops = tuple(self._merge_ops)
        buf_fields = tuple(self._buf_fields)
        bound_funcs = tuple(self._bound_funcs)
        buf_slices = tuple(self._buf_slices)
        n_shards = self.n_shards
        mesh = self.mesh
        # static byte bound per STRING group key: the referenced source
        # column's staged max (planner gates string keys to direct refs;
        # absorbed chains are fixed-width so smls stay aligned)
        key_smls = tuple(
            smls[b.ordinal]
            for b in bound_keys
            if isinstance(b, E.BoundReference) and T.is_string(b.dtype)
            and not steps and b.ordinal < len(smls)
        )
        # post-PARTIAL exchange capacity: slice the partial output to the
        # group cardinality before it crosses ICI (overflow retries with
        # the cap doubled; string keys disable slicing inside dist_groupby)
        from ..conf import MESH_AGG_EXCHANGE_CAP

        gcap = min(
            bucket_rows(self.conf.get(MESH_AGG_EXCHANGE_CAP),
                        self.conf.shape_bucket_min),
            cap)
        if key_smls or any(lay[0] != "f" for lay in layout):
            gcap = 0  # strings cross at full capacity (no slicing)

        while True:
            out_layouts: dict = {}
            group_cap = 0 if gcap >= cap else gcap

            def build(group_cap=group_cap, out_layouts=out_layouts):
                def shard_fn(*flat):
                    *colflat, cnt = flat
                    cols = self._cols_of_flat(colflat, layout)
                    n = cnt[0]
                    live = jnp.arange(cap, dtype=jnp.int32) < n
                    cols, live = self._apply_steps(steps, cols, live, cap)
                    keys = [lower(b, cols, cap) for b in bound_keys]
                    vals = [
                        None if e is None else lower(e, cols, cap)
                        for e in update_exprs
                    ]
                    rkeys, raggs, rn, ok = D.dist_groupby(
                        keys, key_dtypes, vals, list(update_ops),
                        list(merge_ops), live, AXIS, n_shards,
                        str_max_lens=key_smls, group_cap=group_cap)
                    # result projection over [keys..., buffers...] per shard
                    allv = list(rkeys) + list(raggs)
                    rcap = allv[0].validity.shape[0] if allv else 1
                    exprs: List[E.Expression] = [
                        E.BoundReference(i, f.dataType, f.nullable)
                        for i, f in enumerate(self._key_fields)
                    ]
                    for f, (s, e) in zip(bound_funcs, buf_slices):
                        refs = tuple(
                            E.BoundReference(
                                nk + j, buf_fields[j].dataType, True)
                            for j in range(s, e)
                        )
                        exprs.append(f.evaluate(refs))
                    outs = [lower(x, allv, rcap) for x in exprs]
                    flat_out, out_lay = self._flatten_vals(outs)
                    out_layouts["lay"] = out_lay
                    flat_out.append(rn.reshape(1))
                    flat_out.append(ok.reshape(1))
                    return tuple(flat_out)

                nin = len(global_cols)
                fn = shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple([P(AXIS)] * nin + [P(AXIS)]),
                    out_specs=P(AXIS),
                )
                return jax.jit(fn), out_layouts

            sig = tuple((str(a.dtype), a.shape) for a in global_cols)
            fn, out_layouts = _cached_program(
                ("agg", self.fusion_sig(), staged.steps_sig(), sig, cap,
                 n_shards, key_smls, group_cap),
                build, site="mesh_agg", on_miss=self._note_program_miss)
            cnt_in = jax.device_put(
                np.asarray(counts, np.int32), row_sharding(mesh))
            t0 = _time.perf_counter_ns()
            res = fn(*global_cols, cnt_in)
            *out_cols, out_counts, oks = res
            if group_cap == 0 or bool(np.all(_np_of(oks))):
                self._record_run(list(out_cols) + [out_counts], t0)
                self.mesh_actuals["exchange_cap"] = group_cap or cap
                break
            # a shard had more groups than the exchange cap: double it
            # (the aggregate analog of the join's output-capacity retry)
            gcap = min(gcap * 2, cap)
        out_lay = out_layouts.get("lay") or tuple(
            ("s",) if T.is_string(f.dataType) else ("f",)
            for f in self._schema.fields)
        self._outputs = self._emit(
            self._schema, list(out_cols), _np_of(out_counts), 0,
            layout=out_lay)

    def fusion_sig(self):
        return (
            tuple(self._bound_keys), tuple(self._update_exprs),
            tuple(self._update_ops), tuple(self._merge_ops),
        )


class TpuMeshSortExec(_MeshStage):
    """local sort -> sampled range all_to_all -> merge sort, one SPMD
    program (reference plan: GpuRangePartitioning exchange + GpuSortExec);
    output partition i globally precedes partition i+1."""

    def __init__(self, conf, sort_ordinals: Sequence[int],
                 orders: Sequence[Tuple[bool, bool]], child: TpuExec):
        _MeshStage.__init__(self, conf, [child])
        self.key_indices = list(sort_ordinals)
        self.orders = [SortOrder(a, nf) for a, nf in orders]
        self._schema = child.output_schema

    @property
    def output_schema(self):
        return self._schema

    mesh_site = "mesh_sort"

    def mesh_program_bound(self, cap: int) -> int:
        from ..conf import MESH_EXCHANGE_BUCKET_FACTOR

        factor = self.conf.get(MESH_EXCHANGE_BUCKET_FACTOR)
        if factor <= 0 or self.n_shards <= 1:
            return 1
        b = min(bucket_rows(max(int(cap * factor / self.n_shards), 1),
                            self.conf.shape_bucket_min), cap)
        return self._doubling_bound(b, cap)

    def _materialize(self) -> None:
        import time as _time

        child = self.children[0]
        staged = self._stage_child(child)
        self._record_staging(staged)
        global_cols, counts, cap = staged.cols, staged.counts, staged.cap
        layout, smls, steps = staged.layout, staged.smls, staged.steps
        key_dtypes = [
            self._schema.fields[i].dataType for i in self.key_indices
        ]
        n_shards, mesh = self.n_shards, self.mesh
        key_ix, orders = list(self.key_indices), list(self.orders)
        key_smls = tuple(
            smls[i] for i in key_ix
            if T.is_string(self._schema.fields[i].dataType) and not steps
            and i < len(smls))
        # exchange granule: the sampled range bounds spread rows roughly
        # evenly, so ~factor x fair share per target keeps the receive
        # surface O(cap) instead of O(n_shards x cap); skew overflows the
        # block and retries with the granule doubled
        from ..conf import MESH_EXCHANGE_BUCKET_FACTOR

        factor = self.conf.get(MESH_EXCHANGE_BUCKET_FACTOR)
        bcap = 0
        if factor > 0 and n_shards > 1 and all(
                lay[0] == "f" for lay in layout):
            bcap = min(
                bucket_rows(max(int(cap * factor / n_shards), 1),
                            self.conf.shape_bucket_min),
                cap)

        while True:
            out_layouts: dict = {}
            bucket_cap = 0 if bcap >= cap else bcap

            def build(bucket_cap=bucket_cap, out_layouts=out_layouts):
                def shard_fn(*flat):
                    *colflat, cnt = flat
                    cols = self._cols_of_flat(colflat, layout)
                    live = jnp.arange(cap, dtype=jnp.int32) < cnt[0]
                    cols, live = self._apply_steps(steps, cols, live, cap)
                    out, rn, ok = D.dist_sort(
                        cols, key_ix, key_dtypes, orders, live, AXIS,
                        n_shards, str_max_lens=key_smls,
                        bucket_cap=bucket_cap)
                    flat_out, out_lay = self._flatten_vals(out)
                    out_layouts["lay"] = out_lay
                    flat_out.append(rn.reshape(1))
                    flat_out.append(ok.reshape(1))
                    return tuple(flat_out)

                nin = len(global_cols)
                return jax.jit(shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple([P(AXIS)] * (nin + 1)),
                    out_specs=P(AXIS))), out_layouts

            sig = tuple((str(a.dtype), a.shape) for a in global_cols)
            fn, out_layouts = _cached_program(
                ("sort", tuple(key_ix),
                 tuple((o.ascending, o.nulls_first) for o in orders),
                 staged.steps_sig(), sig, n_shards, key_smls, bucket_cap),
                build, site="mesh_sort", on_miss=self._note_program_miss)
            cnt_in = jax.device_put(
                np.asarray(counts, np.int32), row_sharding(mesh))
            t0 = _time.perf_counter_ns()
            res = fn(*global_cols, cnt_in)
            *out_cols, out_counts, oks = res
            if bucket_cap == 0 or bool(np.all(_np_of(oks))):
                self._record_run(list(out_cols) + [out_counts], t0)
                self.mesh_actuals["exchange_cap"] = bucket_cap or cap
                break
            bcap = min(bcap * 2, cap)
        out_lay = out_layouts.get("lay") or tuple(
            ("s",) if T.is_string(f.dataType) else ("f",)
            for f in self._schema.fields)
        self._outputs = self._emit(
            self._schema, list(out_cols), _np_of(out_counts), 0,
            layout=out_lay)


class TpuMeshWindowExec(_MeshStage):
    """hash all_to_all on the PARTITION keys -> per-shard window, one SPMD
    program (reference plan: GpuShuffleExchangeExec(HashPartitioning)
    feeding GpuWindowExec). Window partitions are independent, so placing
    every row of a partition key on one shard preserves exact semantics;
    the per-shard body is the SAME traceable window kernel the
    single-device exec jits (exec/window.TpuWindowExec.window_fn — one
    radix sort + O(n) scans). Fixed-width columns with direct
    partition-key references only (the planner gates)."""

    def __init__(self, conf, window_exprs, child):
        _MeshStage.__init__(self, conf, [child])
        from .window import TpuWindowExec

        self._plan = TpuWindowExec(conf, window_exprs, child)
        self._schema = self._plan.output_schema
        self._part_ords = [b.ordinal for b in self._plan._part_keys]
        self._part_dtypes = [b.dtype for b in self._plan._part_keys]

    @property
    def output_schema(self):
        return self._schema

    def describe(self):
        names = ", ".join(
            we.resolved_name() for we in self._plan.window_exprs)
        return f"TpuMeshWindowExec(mesh={self.n_shards}, [{names}])"

    mesh_site = "mesh_window"

    def mesh_program_bound(self, cap: int) -> int:
        from ..conf import MESH_EXCHANGE_BUCKET_FACTOR

        factor = self.conf.get(MESH_EXCHANGE_BUCKET_FACTOR)
        if factor <= 0 or self.n_shards <= 1:
            return 1
        b = min(bucket_rows(max(int(cap * factor / self.n_shards), 1),
                            self.conf.shape_bucket_min), cap)
        return self._doubling_bound(b, cap)

    def _materialize(self) -> None:
        import time as _time

        from ..ops import hashing
        from ..parallel.collective import all_to_all_exchange

        child = self.children[0]
        staged = self._stage_child(child)
        self._record_staging(staged)
        global_cols, counts, cap = staged.cols, staged.counts, staged.cap
        layout, steps = staged.layout, staged.steps
        n_shards, mesh = self.n_shards, self.mesh
        part_ords = list(self._part_ords)
        part_dtypes = list(self._part_dtypes)
        window_fn = self._plan.window_fn
        from ..conf import MESH_EXCHANGE_BUCKET_FACTOR

        factor = self.conf.get(MESH_EXCHANGE_BUCKET_FACTOR)
        bcap = 0
        if factor > 0 and n_shards > 1 and all(
                lay[0] == "f" for lay in layout):
            bcap = min(
                bucket_rows(max(int(cap * factor / n_shards), 1),
                            self.conf.shape_bucket_min),
                cap)

        while True:
            out_layouts: dict = {}
            bucket_cap = 0 if bcap >= cap else bcap

            def build(bucket_cap=bucket_cap, out_layouts=out_layouts):
                def shard_fn(*flat):
                    *colflat, cnt = flat
                    cols = self._cols_of_flat(colflat, layout)
                    live = jnp.arange(cap, dtype=jnp.int32) < cnt[0]
                    cols, live = self._apply_steps(steps, cols, live, cap)
                    kc = [cols[i] for i in part_ords]
                    h = hashing.murmur3(kc, part_dtypes)
                    pids = hashing.partition_ids(h, n_shards)
                    recvd, rn, ok = all_to_all_exchange(
                        cols, pids, live, AXIS, n_shards,
                        bucket_cap=bucket_cap)
                    rcap = recvd[0].validity.shape[0]
                    out = window_fn(rcap, ())(recvd, rn)
                    flat_out, out_lay = self._flatten_vals(out)
                    out_layouts["lay"] = out_lay
                    flat_out.append(rn.reshape(1))
                    flat_out.append(ok.reshape(1))
                    return tuple(flat_out)

                nin = len(global_cols)
                return jax.jit(shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple([P(AXIS)] * (nin + 1)),
                    out_specs=P(AXIS))), out_layouts

            sig = tuple((str(a.dtype), a.shape) for a in global_cols)
            fn, out_layouts = _cached_program(
                ("window", tuple(part_ords),
                 repr(tuple(self._plan._bound_funcs)),
                 repr(tuple(self._plan._order_keys)),
                 tuple((o.ascending, o.nulls_first)
                       for o in self._plan._orders),
                 staged.steps_sig(), sig, n_shards, bucket_cap),
                build, site="mesh_window", on_miss=self._note_program_miss)
            cnt_in = jax.device_put(
                np.asarray(counts, np.int32), row_sharding(mesh))
            t0 = _time.perf_counter_ns()
            res = fn(*global_cols, cnt_in)
            *out_cols, out_counts, oks = res
            if bucket_cap == 0 or bool(np.all(_np_of(oks))):
                self._record_run(list(out_cols) + [out_counts], t0)
                self.mesh_actuals["exchange_cap"] = bucket_cap or cap
                break
            bcap = min(bcap * 2, cap)
        out_lay = out_layouts.get("lay") or tuple(
            ("s",) if T.is_string(f.dataType) else ("f",)
            for f in self._schema.fields)
        self._outputs = self._emit(
            self._schema, list(out_cols), _np_of(out_counts), 0,
            layout=out_lay)


class TpuMeshHashJoinExec(_MeshStage):
    """hash all_to_all both sides -> local join, one SPMD program
    (reference plan: two GpuShuffleExchangeExecs feeding
    GpuShuffledHashJoinExec). Inner equi-joins, no residual condition."""

    def __init__(self, conf, left: TpuExec, right: TpuExec,
                 left_ordinals: Sequence[int], right_ordinals: Sequence[int]):
        _MeshStage.__init__(self, conf, [left, right])
        self.left_ix = list(left_ordinals)
        self.right_ix = list(right_ordinals)
        lf = left.output_schema.fields
        rf = right.output_schema.fields
        self._schema = StructType(tuple(lf) + tuple(rf))
        self._key_dtypes = [
            left.output_schema.fields[i].dataType for i in self.left_ix
        ]

    @property
    def output_schema(self):
        return self._schema

    mesh_site = "mesh_join"

    def mesh_program_bound(self, cap: int) -> int:
        return 8  # the output-capacity retry limit of _materialize

    def _materialize(self) -> None:
        import time as _time

        left, right = self.children
        lstaged = self._stage_child(left)
        rstaged = self._stage_child(right)
        self._record_staging(lstaged, "left")
        self._record_staging(rstaged, "right")
        l_cols, l_counts, lcap = lstaged.cols, lstaged.counts, lstaged.cap
        llay, lsml, lsteps = lstaged.layout, lstaged.smls, lstaged.steps
        r_cols, r_counts, rcap = rstaged.cols, rstaged.counts, rstaged.cap
        rlay, rsml, rsteps = rstaged.layout, rstaged.smls, rstaged.steps
        if lsteps or rsteps:
            lsml = tuple(0 for _ in left.output_schema.fields)
            rsml = tuple(0 for _ in right.output_schema.fields)
        n_shards, mesh = self.n_shards, self.mesh
        l_ix, r_ix, kd = list(self.left_ix), list(self.right_ix), list(
            self._key_dtypes)
        lf = left.output_schema.fields
        rf = right.output_schema.fields
        out_cap = bucket_rows(
            max(lcap, rcap) * 2, self.conf.shape_bucket_min)
        # string keys compare via chunk keys: the byte bound must be
        # SHARED by both sides (same word count per key)
        key_smls = tuple(
            max(lsml[li], rsml[ri])
            for li, ri in zip(l_ix, r_ix)
            if T.is_string(lf[li].dataType)
        )
        # per-shard byte pools for string outputs: the post-exchange pool
        # is n_shards x the staged local pool; 1:1 joins fit, fan-out
        # retries double alongside out_cap
        base_ccaps = tuple(
            [lay[1] * n_shards for lay in llay if lay[0] == "s"]
            + [lay[1] * n_shards for lay in rlay if lay[0] == "s"])
        ccap_scale = 1
        # per-side exchange granule (~factor x fair share): hash
        # partitioning spreads keys evenly, so the receive surface stays
        # O(cap); a skewed side overflows and the retry below doubles the
        # granule along with the output capacity
        from ..conf import MESH_EXCHANGE_BUCKET_FACTOR

        factor = self.conf.get(MESH_EXCHANGE_BUCKET_FACTOR)

        def bcap_of(cap_side, lay):
            if factor <= 0 or n_shards <= 1 or any(
                    L[0] != "f" for L in lay):
                return 0
            return min(
                bucket_rows(max(int(cap_side * factor / n_shards), 1),
                            self.conf.shape_bucket_min),
                cap_side)

        l_bcap = bcap_of(lcap, llay)
        r_bcap = bcap_of(rcap, rlay)

        for attempt in range(8):
            xcaps = (0 if l_bcap >= lcap else l_bcap,
                     0 if r_bcap >= rcap else r_bcap)
            out_ccaps = tuple(
                bucket_rows(c * ccap_scale, 128) for c in base_ccaps)

            def build(out_cap=out_cap, out_ccaps=out_ccaps, xcaps=xcaps):
                def shard_fn(*flat):
                    nlp = sum(2 if lay[0] == "f" else 3 for lay in llay)
                    lflat = flat[:nlp]
                    rflat = flat[nlp:-2]
                    lcnt, rcnt = flat[-2], flat[-1]
                    lc = self._cols_of_flat(lflat, llay)
                    rc = self._cols_of_flat(rflat, rlay)
                    ln_, rn_ = lcnt[0], rcnt[0]
                    if lsteps:
                        from ..ops.filter_gather import filter_cols

                        live = jnp.arange(lcap, dtype=jnp.int32) < ln_
                        lc, live = self._apply_steps(lsteps, lc, live, lcap)
                        lc, ln_ = filter_cols(lc, live, None)
                    if rsteps:
                        from ..ops.filter_gather import filter_cols

                        live = jnp.arange(rcap, dtype=jnp.int32) < rn_
                        rc, live = self._apply_steps(rsteps, rc, live, rcap)
                        rc, rn_ = filter_cols(rc, live, None)
                    out, cnt, ok = D.dist_hash_join(
                        lc, l_ix, rc, r_ix, kd, ln_, rn_,
                        AXIS, n_shards, out_cap,
                        key_str_max_lens=key_smls,
                        out_char_caps=out_ccaps,
                        exchange_bucket_caps=xcaps)
                    flat_out, out_lay = self._flatten_vals(out)
                    out_layouts["lay"] = out_lay
                    flat_out.append(cnt.reshape(1))
                    flat_out.append(ok.reshape(1))
                    return tuple(flat_out)

                nin = len(l_cols) + len(r_cols) + 2
                return jax.jit(shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple([P(AXIS)] * nin),
                    out_specs=P(AXIS))), out_layouts

            out_layouts: dict = {}
            sig = (
                tuple((str(a.dtype), a.shape) for a in l_cols),
                tuple((str(a.dtype), a.shape) for a in r_cols),
            )
            fn, out_layouts = _cached_program(
                ("join", tuple(l_ix), tuple(r_ix),
                 lstaged.steps_sig(), rstaged.steps_sig(), sig, out_cap,
                 n_shards, key_smls, out_ccaps, xcaps),
                build, site="mesh_join", on_miss=self._note_program_miss)
            sh = row_sharding(mesh)
            t0 = _time.perf_counter_ns()
            res = fn(*l_cols, *r_cols,
                     jax.device_put(np.asarray(l_counts, np.int32), sh),
                     jax.device_put(np.asarray(r_counts, np.int32), sh))
            *out_cols, out_counts, oks = res
            if bool(np.all(_np_of(oks))):
                self._record_run(list(out_cols) + [out_counts], t0)
                out_lay = out_layouts.get("lay") or tuple(
                    ("s",) if T.is_string(f.dataType) else ("f",)
                    for f in self._schema.fields)
                self._outputs = self._emit(
                    self._schema, list(out_cols), _np_of(out_counts), 0,
                    layout=out_lay)
                return
            # overflow: double the per-shard output capacity AND the
            # exchange granules and recompile — the ok flag does not say
            # which surface overflowed, so every capacity grows together
            # (the reference's bounce-buffer windowing retries similarly)
            out_cap *= 2
            ccap_scale *= 2
            if l_bcap:
                l_bcap = min(l_bcap * 2, lcap)
            if r_bcap:
                r_bcap = min(r_bcap * 2, rcap)
        raise RuntimeError("mesh join output capacity retry limit exceeded")


# ---------------------------------------------------------------------------
# planner eligibility
# ---------------------------------------------------------------------------
def mesh_mode(conf: RapidsConf) -> str:
    from ..conf import SHUFFLE_MODE

    return conf.get(SHUFFLE_MODE)


def mesh_available(conf: RapidsConf) -> bool:
    mode = mesh_mode(conf)
    if mode == "host":
        return False
    if mode == "ici":
        return True
    from ..parallel.mesh import device_count

    return device_count() > 1


def fixed_width_schema(schema: StructType) -> bool:
    return all(T.is_fixed_width(f.dataType) for f in schema.fields)
