"""Mesh-lowered exchange stages: whole shuffle-bounded plan fragments as ONE
shard_map program over the device mesh.

Reference analog: the accelerated shuffle path the planner actually selects
(RapidsShuffleInternalManager.scala:58-150 + the UCX transport): there, a
PARTIAL aggregate, a device-cached shuffle write, an RDMA fetch, and a FINAL
aggregate are four separately-scheduled stages. Here the planner lowers the
whole exchange-bounded stage — partial aggregate -> all_to_all -> final
merge -> result projection, or local-sort -> sampled range exchange -> merge
sort, or hash-exchange both sides -> local join — into ONE jitted SPMD
computation over a jax.sharding.Mesh (parallel/distributed.py), with child
partition i living on mesh shard i % n. XLA schedules the ICI collectives
against compute; nothing touches the host between the child batches and the
stage output.

Fixed-width columns cross the mesh as data/validity planes; STRING columns
cross as offsets/chars/validity planes with the chars riding the
collective's byte-plane all_to_all (parallel/collective.py) — the same
type-agnostic contract as the reference's UCX transport
(RapidsShuffleClient.scala:35-98). Staging computes a static max byte
length per string column, so string GROUP KEYS must be direct column
references (computed string keys have no staged bound and stay on the
single-host exchange, as do binary columns).
"""
from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map as _shard_map_impl  # jax >= 0.6
    _SM_KW = {"check_vma": False}
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SM_KW = {"check_rep": False}


def shard_map(f, mesh, in_specs, out_specs, **_ignored):
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **_SM_KW)

from .. import types as T
from ..columnar import ColumnarBatch, DeviceColumn
from ..conf import RapidsConf
from ..expr import aggregates as A
from ..expr import expressions as E
from ..expr.eval import ColV, lower
from ..ops.sort import SortOrder
from ..parallel import distributed as D
from ..parallel.mesh import AXIS, get_mesh, row_sharding
from ..types import StructField, StructType
from ..utils.bucketing import bucket_rows
from . import aggregate as XA
from .base import TpuExec

P = jax.sharding.PartitionSpec


def _np_of(arr) -> np.ndarray:
    from .base import host_pull

    return np.asarray(host_pull(arr))


class _MeshStage(TpuExec):
    """Base: stage child partitions onto the mesh, run one SPMD program,
    emit one output partition per shard."""

    def __init__(self, conf: RapidsConf, children: Sequence[TpuExec]):
        super().__init__(conf, children)
        from ..conf import SHUFFLE_MESH_SIZE

        self.mesh = get_mesh(conf.get(SHUFFLE_MESH_SIZE) or None)
        self.n_shards = int(self.mesh.devices.size)
        self._outputs: Optional[List[Optional[ColumnarBatch]]] = None

    @property
    def num_partitions(self) -> int:
        return self.n_shards

    # -- staging -----------------------------------------------------------
    def _stage_child(self, child: TpuExec):
        """Materialize every child partition and lay rows onto the mesh:
        returns (flat global arrays, per-shard counts, per-shard cap,
        layout, str_max_lens). Child partition p maps to shard p % n.

        layout[i] is ("f",) for a fixed column or ("s", char_cap) for a
        string column (offsets/chars/validity planes); str_max_lens[i] is
        0 for fixed columns and the bucketed max byte length for string
        columns (a STATIC bound the sort / hash kernels need, computed
        host-side here — staging already touches every byte)."""
        schema = child.output_schema
        per_shard: List[List[ColumnarBatch]] = [[] for _ in range(self.n_shards)]
        for p in range(child.num_partitions):
            for b in child.execute_partition(p):
                per_shard[p % self.n_shards].append(b)
        counts = np.zeros(self.n_shards, np.int32)
        rows_per_shard = [
            sum(int(b.num_rows) for b in bs) for bs in per_shard
        ]
        from .. import obs as _obs

        if _obs.enabled():
            # the per-chip lane of the live plane: how staging spread the
            # input over the mesh (a skewed shard shows up immediately)
            for s, r in enumerate(rows_per_shard):
                _obs.inc("tpu_mesh_staged_rows", r, device=str(s))
        cap = bucket_rows(max(max(rows_per_shard), 1),
                          self.conf.shape_bucket_min)
        fields = schema.fields
        ncols = len(fields)
        is_str = [T.is_string(f.dataType) for f in fields]
        # gather host views once (dict-encoded strings materialize: the
        # mesh planes splice raw offset/chars byte pools across shards)
        from .base import materialized_batch

        host: List[List[tuple]] = [[] for _ in range(self.n_shards)]
        for s, bs in enumerate(per_shard):
            for b in bs:
                b = materialized_batch(b)
                n = int(b.num_rows)
                row = []
                for c in b.columns:
                    if c.is_string:
                        row.append((
                            _np_of(c.offsets), _np_of(c.chars),
                            _np_of(c.validity), n))
                    else:
                        row.append((_np_of(c.data), _np_of(c.validity), n))
                host[s].append(row)
            counts[s] = sum(int(b.num_rows) for b in bs)
        # per string column: per-shard byte totals -> common char cap + sml
        layout: List[tuple] = []
        smls: List[int] = []
        for j in range(ncols):
            if not is_str[j]:
                layout.append(("f",))
                smls.append(0)
                continue
            max_bytes = 1
            max_len = 1
            for s in range(self.n_shards):
                tot = 0
                for row in host[s]:
                    offs, _, _, n = row[j]
                    tot += int(offs[n])
                    if n:
                        max_len = max(
                            max_len, int((offs[1:n + 1] - offs[:n]).max()))
                max_bytes = max(max_bytes, tot)
            ccap = bucket_rows(max_bytes, 128)
            layout.append(("s", ccap))
            smls.append(max(4, bucket_rows(max_len, 4)))
        # build global planes
        planes: List[np.ndarray] = []
        for j in range(ncols):
            if layout[j][0] == "f":
                d = np.zeros((self.n_shards, cap), fields[j].dataType.to_numpy())
                v = np.zeros((self.n_shards, cap), bool)
                for s in range(self.n_shards):
                    pos = 0
                    for row in host[s]:
                        data, valid, n = row[j]
                        d[s, pos:pos + n] = data[:n]
                        v[s, pos:pos + n] = valid[:n]
                        pos += n
                planes.extend([d, v])
            else:
                ccap = layout[j][1]
                o = np.zeros((self.n_shards, cap + 1), np.int32)
                ch = np.zeros((self.n_shards, ccap), np.uint8)
                v = np.zeros((self.n_shards, cap), bool)
                for s in range(self.n_shards):
                    pos = 0
                    bpos = 0
                    for row in host[s]:
                        offs, chars, valid, n = row[j]
                        nb = int(offs[n])
                        o[s, pos + 1: pos + n + 1] = bpos + offs[1: n + 1]
                        ch[s, bpos: bpos + nb] = chars[:nb]
                        v[s, pos:pos + n] = valid[:n]
                        pos += n
                        bpos += nb
                    o[s, pos + 1:] = bpos
                planes.extend([o, ch, v])
        sh = row_sharding(self.mesh)
        out = [jax.device_put(a.reshape(-1), sh) for a in planes]
        return out, counts, cap, tuple(layout), tuple(smls)

    @staticmethod
    def _cols_of_flat(colflat: Sequence[jax.Array], layout) -> List:
        """Per-shard flat planes -> ColV/StrV column list (inside
        shard_map: a string column is offsets/chars/validity planes)."""
        from ..expr.eval import StrV

        cols: List = []
        gi = 0
        for lay in layout:
            if lay[0] == "f":
                cols.append(ColV(colflat[gi], colflat[gi + 1]))
                gi += 2
            else:
                cols.append(
                    StrV(colflat[gi], colflat[gi + 1], colflat[gi + 2]))
                gi += 3
        return cols

    @staticmethod
    def _flatten_vals(outs) -> Tuple[List[jax.Array], Tuple[tuple, ...]]:
        """Column values -> flat planes + an output layout for _emit."""
        from ..expr.eval import StrV

        flat: List[jax.Array] = []
        layout: List[tuple] = []
        for o in outs:
            if isinstance(o, StrV):
                flat.extend([o.offsets, o.chars, o.validity])
                layout.append(("s",))
            else:
                flat.extend([o.data, o.validity])
                layout.append(("f",))
        return flat, tuple(layout)

    def _emit(self, schema: StructType, global_cols: Sequence[jax.Array],
              counts: np.ndarray, cap: int,
              layout=None) -> List[Optional[ColumnarBatch]]:
        """Split flat global outputs back into per-shard batches. Shapes
        per shard derive from each plane's global size / n_shards."""
        if layout is None:
            layout = tuple(
                ("s",) if T.is_string(f.dataType) else ("f",)
                for f in schema.fields)
        outs: List[Optional[ColumnarBatch]] = []
        for s in range(self.n_shards):
            n = int(counts[s])
            cols = []
            gi = 0
            for f, lay in zip(schema.fields, layout):
                if lay[0] == "f":
                    d, v = global_cols[gi], global_cols[gi + 1]
                    gi += 2
                    per = d.shape[0] // self.n_shards
                    cols.append(DeviceColumn(
                        f.dataType, n, d[s * per:(s + 1) * per],
                        v[s * per:(s + 1) * per]))
                else:
                    o, ch, v = (global_cols[gi], global_cols[gi + 1],
                                global_cols[gi + 2])
                    gi += 3
                    po = o.shape[0] // self.n_shards
                    pc = ch.shape[0] // self.n_shards
                    pv = v.shape[0] // self.n_shards
                    cols.append(DeviceColumn(
                        f.dataType, n, None, v[s * pv:(s + 1) * pv],
                        offsets=o[s * po:(s + 1) * po],
                        chars=ch[s * pc:(s + 1) * pc]))
            outs.append(ColumnarBatch(cols, schema, n))
        return outs

    def _materialize(self) -> None:
        raise NotImplementedError

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        if self._outputs is None:
            with self.op_timed():
                self._materialize()
        b = self._outputs[index]
        if b is not None and b.num_rows > 0:
            yield self.record_batch(b)

    def describe(self):
        return f"{self.node_name}(mesh={self.n_shards})"


_PROGRAM_CACHE: dict = {}


def _cached_program(key, builder):
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        if len(_PROGRAM_CACHE) > 256:
            _PROGRAM_CACHE.clear()
        fn = _PROGRAM_CACHE[key] = builder()
    return fn


class TpuMeshAggregateExec(_MeshStage):
    """partial-agg -> hash all_to_all -> final merge -> result projection,
    one SPMD program (reference plan: GpuHashAggregateExec(PARTIAL) ->
    GpuShuffleExchangeExec -> GpuHashAggregateExec(FINAL)).

    The buffer layout / update-merge op split is borrowed from a PARTIAL
    TpuHashAggregateExec (never executed — only its bound metadata)."""

    def __init__(self, conf, group_exprs, agg_exprs, child):
        _MeshStage.__init__(self, conf, [child])
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        plan = XA.TpuHashAggregateExec(
            conf, group_exprs, agg_exprs, child, mode=A.PARTIAL)
        self._key_fields = plan._key_fields
        self._bound_keys = plan._bound_keys
        self._bound_funcs = plan._bound_funcs
        self._buf_fields = plan._buf_fields
        self._buf_slices = plan._buf_slices
        self._update_exprs = plan._update_exprs
        self._update_ops = plan._update_ops
        self._merge_ops = plan._merge_ops
        fields = list(self._key_fields)
        for ae, f in zip(self.agg_exprs, self._bound_funcs):
            fields.append(StructField(ae.resolved_name(), f.dtype, True))
        self._schema = StructType(tuple(fields))

    def _key_dtypes(self):
        return tuple(f.dataType for f in self._key_fields)

    @property
    def output_schema(self):
        return self._schema

    def describe(self):
        keys = ", ".join(str(k) for k in self.group_exprs)
        return f"TpuMeshAggregateExec(mesh={self.n_shards}, keys=[{keys}])"

    def _materialize(self) -> None:
        child = self.children[0]
        global_cols, counts, cap, layout, smls = self._stage_child(child)
        nk = len(self._key_fields)
        key_dtypes = list(self._key_dtypes())
        bound_keys = tuple(self._bound_keys)
        update_exprs = tuple(self._update_exprs)
        update_ops = tuple(self._update_ops)
        merge_ops = tuple(self._merge_ops)
        buf_fields = tuple(self._buf_fields)
        bound_funcs = tuple(self._bound_funcs)
        buf_slices = tuple(self._buf_slices)
        n_shards = self.n_shards
        mesh = self.mesh
        # static byte bound per STRING group key: the referenced source
        # column's staged max (planner gates string keys to direct refs)
        key_smls = tuple(
            smls[b.ordinal]
            for b in bound_keys
            if isinstance(b, E.BoundReference) and T.is_string(b.dtype)
        )
        out_layouts: dict = {}

        def build():
            def shard_fn(*flat):
                *colflat, cnt = flat
                cols = self._cols_of_flat(colflat, layout)
                n = cnt[0]
                keys = [lower(b, cols, cap) for b in bound_keys]
                vals = [
                    None if e is None else lower(e, cols, cap)
                    for e in update_exprs
                ]
                rkeys, raggs, rn = D.dist_groupby(
                    keys, key_dtypes, vals, list(update_ops),
                    list(merge_ops), n, AXIS, n_shards,
                    str_max_lens=key_smls)
                # result projection over [keys..., buffers...], per shard
                allv = list(rkeys) + list(raggs)
                rcap = allv[0].validity.shape[0] if allv else 1
                exprs: List[E.Expression] = [
                    E.BoundReference(i, f.dataType, f.nullable)
                    for i, f in enumerate(self._key_fields)
                ]
                for f, (s, e) in zip(bound_funcs, buf_slices):
                    refs = tuple(
                        E.BoundReference(nk + j, buf_fields[j].dataType, True)
                        for j in range(s, e)
                    )
                    exprs.append(f.evaluate(refs))
                outs = [lower(x, allv, rcap) for x in exprs]
                flat_out, out_lay = self._flatten_vals(outs)
                out_layouts["lay"] = out_lay
                flat_out.append(rn.reshape(1))
                return tuple(flat_out)

            nin = len(global_cols)
            fn = shard_map(
                shard_fn, mesh=mesh,
                in_specs=tuple([P(AXIS)] * nin + [P(AXIS)]),
                out_specs=P(AXIS),
            )
            return jax.jit(fn), out_layouts

        sig = tuple((str(a.dtype), a.shape) for a in global_cols)
        fn, out_layouts = _cached_program(
            ("agg", self.fusion_sig(), sig, cap, n_shards, key_smls), build)
        cnt_in = jax.device_put(
            np.asarray(counts, np.int32), row_sharding(mesh))
        res = fn(*global_cols, cnt_in)
        *out_cols, out_counts = res
        out_lay = out_layouts.get("lay") or tuple(
            ("s",) if T.is_string(f.dataType) else ("f",)
            for f in self._schema.fields)
        self._outputs = self._emit(
            self._schema, list(out_cols), _np_of(out_counts), 0,
            layout=out_lay)

    def fusion_sig(self):
        return (
            tuple(self._bound_keys), tuple(self._update_exprs),
            tuple(self._update_ops), tuple(self._merge_ops),
        )


class TpuMeshSortExec(_MeshStage):
    """local sort -> sampled range all_to_all -> merge sort, one SPMD
    program (reference plan: GpuRangePartitioning exchange + GpuSortExec);
    output partition i globally precedes partition i+1."""

    def __init__(self, conf, sort_ordinals: Sequence[int],
                 orders: Sequence[Tuple[bool, bool]], child: TpuExec):
        _MeshStage.__init__(self, conf, [child])
        self.key_indices = list(sort_ordinals)
        self.orders = [SortOrder(a, nf) for a, nf in orders]
        self._schema = child.output_schema

    @property
    def output_schema(self):
        return self._schema

    def _materialize(self) -> None:
        child = self.children[0]
        global_cols, counts, cap, layout, smls = self._stage_child(child)
        key_dtypes = [
            self._schema.fields[i].dataType for i in self.key_indices
        ]
        n_shards, mesh = self.n_shards, self.mesh
        key_ix, orders = list(self.key_indices), list(self.orders)
        key_smls = tuple(
            smls[i] for i in key_ix
            if T.is_string(self._schema.fields[i].dataType))
        out_layouts: dict = {}

        def build():
            def shard_fn(*flat):
                *colflat, cnt = flat
                cols = self._cols_of_flat(colflat, layout)
                out, rn = D.dist_sort(
                    cols, key_ix, key_dtypes, orders, cnt[0], AXIS, n_shards,
                    str_max_lens=key_smls)
                flat_out, out_lay = self._flatten_vals(out)
                out_layouts["lay"] = out_lay
                flat_out.append(rn.reshape(1))
                return tuple(flat_out)

            nin = len(global_cols)
            return jax.jit(shard_map(
                shard_fn, mesh=mesh,
                in_specs=tuple([P(AXIS)] * (nin + 1)),
                out_specs=P(AXIS))), out_layouts

        sig = tuple((str(a.dtype), a.shape) for a in global_cols)
        fn, out_layouts = _cached_program(
            ("sort", tuple(key_ix), tuple((o.ascending, o.nulls_first)
                                          for o in orders), sig, n_shards,
             key_smls),
            build)
        cnt_in = jax.device_put(np.asarray(counts, np.int32), row_sharding(mesh))
        res = fn(*global_cols, cnt_in)
        *out_cols, out_counts = res
        out_lay = out_layouts.get("lay") or tuple(
            ("s",) if T.is_string(f.dataType) else ("f",)
            for f in self._schema.fields)
        self._outputs = self._emit(
            self._schema, list(out_cols), _np_of(out_counts), 0,
            layout=out_lay)


class TpuMeshHashJoinExec(_MeshStage):
    """hash all_to_all both sides -> local join, one SPMD program
    (reference plan: two GpuShuffleExchangeExecs feeding
    GpuShuffledHashJoinExec). Inner equi-joins, no residual condition."""

    def __init__(self, conf, left: TpuExec, right: TpuExec,
                 left_ordinals: Sequence[int], right_ordinals: Sequence[int]):
        _MeshStage.__init__(self, conf, [left, right])
        self.left_ix = list(left_ordinals)
        self.right_ix = list(right_ordinals)
        lf = left.output_schema.fields
        rf = right.output_schema.fields
        self._schema = StructType(tuple(lf) + tuple(rf))
        self._key_dtypes = [
            left.output_schema.fields[i].dataType for i in self.left_ix
        ]

    @property
    def output_schema(self):
        return self._schema

    def _materialize(self) -> None:
        left, right = self.children
        l_cols, l_counts, lcap, llay, lsml = self._stage_child(left)
        r_cols, r_counts, rcap, rlay, rsml = self._stage_child(right)
        n_shards, mesh = self.n_shards, self.mesh
        l_ix, r_ix, kd = list(self.left_ix), list(self.right_ix), list(
            self._key_dtypes)
        lf = left.output_schema.fields
        rf = right.output_schema.fields
        out_cap = bucket_rows(
            max(lcap, rcap) * 2, self.conf.shape_bucket_min)
        # string keys compare via chunk keys: the byte bound must be
        # SHARED by both sides (same word count per key)
        key_smls = tuple(
            max(lsml[li], rsml[ri])
            for li, ri in zip(l_ix, r_ix)
            if T.is_string(lf[li].dataType)
        )
        # per-shard byte pools for string outputs: the post-exchange pool
        # is n_shards x the staged local pool; 1:1 joins fit, fan-out
        # retries double alongside out_cap
        base_ccaps = tuple(
            [lay[1] * n_shards for lay in llay if lay[0] == "s"]
            + [lay[1] * n_shards for lay in rlay if lay[0] == "s"])
        ccap_scale = 1

        for attempt in range(8):
            out_ccaps = tuple(
                bucket_rows(c * ccap_scale, 128) for c in base_ccaps)

            def build(out_cap=out_cap, out_ccaps=out_ccaps):
                def shard_fn(*flat):
                    nlp = sum(2 if lay[0] == "f" else 3 for lay in llay)
                    lflat = flat[:nlp]
                    rflat = flat[nlp:-2]
                    lcnt, rcnt = flat[-2], flat[-1]
                    lc = self._cols_of_flat(lflat, llay)
                    rc = self._cols_of_flat(rflat, rlay)
                    out, cnt, ok = D.dist_hash_join(
                        lc, l_ix, rc, r_ix, kd, lcnt[0], rcnt[0],
                        AXIS, n_shards, out_cap,
                        key_str_max_lens=key_smls,
                        out_char_caps=out_ccaps)
                    flat_out, out_lay = self._flatten_vals(out)
                    out_layouts["lay"] = out_lay
                    flat_out.append(cnt.reshape(1))
                    flat_out.append(ok.reshape(1))
                    return tuple(flat_out)

                nin = len(l_cols) + len(r_cols) + 2
                return jax.jit(shard_map(
                    shard_fn, mesh=mesh,
                    in_specs=tuple([P(AXIS)] * nin),
                    out_specs=P(AXIS))), out_layouts

            out_layouts: dict = {}
            sig = (
                tuple((str(a.dtype), a.shape) for a in l_cols),
                tuple((str(a.dtype), a.shape) for a in r_cols),
            )
            fn, out_layouts = _cached_program(
                ("join", tuple(l_ix), tuple(r_ix), sig, out_cap, n_shards,
                 key_smls, out_ccaps),
                build)
            sh = row_sharding(mesh)
            res = fn(*l_cols, *r_cols,
                     jax.device_put(np.asarray(l_counts, np.int32), sh),
                     jax.device_put(np.asarray(r_counts, np.int32), sh))
            *out_cols, out_counts, oks = res
            if bool(np.all(_np_of(oks))):
                out_lay = out_layouts.get("lay") or tuple(
                    ("s",) if T.is_string(f.dataType) else ("f",)
                    for f in self._schema.fields)
                self._outputs = self._emit(
                    self._schema, list(out_cols), _np_of(out_counts), 0,
                    layout=out_lay)
                return
            # overflow: double the per-shard output capacity and recompile
            # (the reference's bounce-buffer windowing retries similarly)
            out_cap *= 2
            ccap_scale *= 2
        raise RuntimeError("mesh join output capacity retry limit exceeded")


# ---------------------------------------------------------------------------
# planner eligibility
# ---------------------------------------------------------------------------
def mesh_mode(conf: RapidsConf) -> str:
    from ..conf import SHUFFLE_MODE

    return conf.get(SHUFFLE_MODE)


def mesh_available(conf: RapidsConf) -> bool:
    mode = mesh_mode(conf)
    if mode == "host":
        return False
    if mode == "ici":
        return True
    from ..parallel.mesh import device_count

    return device_count() > 1


def fixed_width_schema(schema: StructType) -> bool:
    return all(T.is_fixed_width(f.dataType) for f in schema.fields)
