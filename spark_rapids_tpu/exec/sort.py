"""Sort exec.

Reference analog: GpuSortExec (GpuSortExec.scala:51) — local per-partition
sort, or global sort (the reference range-partitions first; until the
exchange layer lands, global sorts gather to one partition, which is also
what a single-partition collect needs anyway). The kernel is ops/sort.py's
radix-key bitonic sort; batches within a partition concatenate first
(RequireSingleBatch coalesce goal in the reference).
"""
from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch
from ..conf import RapidsConf
from ..expr import expressions as E
from ..expr.eval import StrV, lower
from ..ops import filter_gather
from ..ops.sort import SortOrder, max_string_len, sort_permutation
from ..types import StructType
from ..columnar.column import choose_capacity
from .base import (
    TOTAL_TIME,
    TpuExec,
    batch_from_vals,
    batch_signature,
    count_scalar,
    timed,
    vals_of_batch,
)
from .join import _concat_all


class TpuSortExec(TpuExec):
    def __init__(
        self,
        conf: RapidsConf,
        sort_exprs: Sequence[E.Expression],
        orders: Sequence[Tuple[bool, object]],  # (ascending, nulls_first|None)
        child: TpuExec,
        global_sort: bool = True,
    ):
        super().__init__(conf, [child])
        self.sort_exprs = list(sort_exprs)
        self.orders = [SortOrder(a, nf) for a, nf in orders]
        self.global_sort = global_sort
        self._bound = [
            E.bind_references(e, child.output_schema) for e in self.sort_exprs
        ]
        self._jits = {}

    @property
    def output_schema(self) -> StructType:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        return 1 if self.global_sort else self.children[0].num_partitions

    def describe(self):
        ks = ", ".join(
            f"{e}{'' if o.ascending else ' DESC'}"
            for e, o in zip(self.sort_exprs, self.orders)
        )
        return f"TpuSortExec [{ks}]" + ("" if self.global_sort else " (local)")

    def _gather_input(self, index: int):
        if self.global_sort:
            return _concat_all(self.conf, self.children[0])
        batches = [
            b for b in self.children[0].execute_partition(index)
            if b.num_rows > 0
        ]
        if not batches:
            return None
        if len(batches) == 1:
            return batches[0]
        from .basic import TpuCoalesceBatchesExec

        co = TpuCoalesceBatchesExec(self.conf, self.children[0], target_rows=1 << 62)
        return co._flush(batches)

    def _str_lens(self, batch) -> Tuple[int, ...]:
        lens = []
        for b in self._bound:
            if isinstance(b.dtype, (T.StringType, T.BinaryType)):
                if isinstance(b, E.BoundReference):
                    c = batch.columns[b.ordinal]
                    m = int(max_string_len(StrV(c.offsets, c.chars, c.validity)))
                else:
                    m = 64
                lens.append(max(4, choose_capacity(max(1, m), 4)))
        return tuple(lens)

    def _sort_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """One sort dispatch over one batch (compiled per capacity/
        signature — a split-and-retry half compiles its own half-capacity
        program)."""
        cap = batch.capacity
        sml = self._str_lens(batch)

        def run(cols, num_rows):
            live = filter_gather.live_of(num_rows, cap)
            keys = [lower(b, cols, cap) for b in self._bound]
            perm = sort_permutation(
                keys, [b.dtype for b in self._bound], self.orders, live, sml)
            live_sorted = jnp.take(live, perm, mode="clip")
            return filter_gather.gather(cols, perm, live_sorted)

        key = (batch_signature(batch), cap, sml)
        # the shared pipeline-cache guard: miss accounting + the
        # compiled-program cost plane ride cached_pipeline (xla_cost.py)
        from .base import cached_pipeline

        fn = cached_pipeline(self._jits, key, "sort",
                             lambda: jax.jit(run))
        vals = fn(
            vals_of_batch(batch), count_scalar(batch.num_rows_lazy))
        return batch_from_vals(
            vals, self.output_schema, batch.num_rows_lazy)

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        batch = self._gather_input(index)
        if batch is None:
            return
        from ..memory.retry import concat_batches, with_oom_retry
        from .base import materialized_batch

        batch = materialized_batch(batch)  # chunk keys want plain bytes

        def combine(pieces):
            # split-and-retry re-join: the halves are each sorted but the
            # stitch is not globally ordered — re-sort the concatenation
            # (stable, so equal keys keep their piece order). The final
            # program runs at the stitched capacity; if THAT still OOMs
            # the harness escalates to the typed verdict.
            return self._sort_batch(concat_batches(self.conf, pieces))

        with self.op_timed():
            out = with_oom_retry(self.node_name, self._sort_batch, batch,
                                 self.conf, combine=combine)
        yield self.record_batch(out)
