"""Window exec.

Reference analog: GpuWindowExec (GpuWindowExec.scala:92) — one exec per
(partition by, order by) spec computing every window expression over it.
TPU re-design: ONE radix sort by (partition keys, order keys) and pure
O(n) scan kernels (ops/window.py) — no per-partition looping, no rolling
windows kernel library.

Until the exchange layer lands, the exec gathers its input to a single
partition (window semantics need all rows of a partition key together).
"""
from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch
from ..conf import RapidsConf
from ..expr import aggregates as A
from ..expr import expressions as E
from ..expr import windows as W
from ..expr.eval import ColV, StrV, lower
from ..ops import filter_gather
from ..ops import window as window_ops
from ..ops.sort import (
    SortOrder,
    fixed_radix_keys,
    max_string_len,
    sort_with_radix_keys,
    string_chunk_keys,
)
from ..types import StructField, StructType
from ..columnar.column import choose_capacity
from .base import (
    TOTAL_TIME,
    TpuExec,
    batch_from_vals,
    batch_signature,
    count_scalar,
    timed,
    vals_of_batch,
)
from .join import _concat_all


class TpuWindowExec(TpuExec):
    def __init__(
        self,
        conf: RapidsConf,
        window_exprs: Sequence[W.WindowExpression],
        child: TpuExec,
    ):
        super().__init__(conf, [child])
        if not window_exprs:
            raise ValueError("window exec needs at least one window expression")
        self.window_exprs = list(window_exprs)
        spec = window_exprs[0].spec
        for we in window_exprs[1:]:
            if (we.spec.partition_by, we.spec.order_by, we.spec.orders) != (
                spec.partition_by, spec.order_by, spec.orders
            ):
                raise ValueError(
                    "one TpuWindowExec handles one (partition, order) spec")
        self.spec = spec
        cs = child.output_schema
        self._part_keys = [E.bind_references(k, cs) for k in spec.partition_by]
        self._order_keys = [E.bind_references(k, cs) for k in spec.order_by]
        self._orders = [SortOrder(a, nf) for a, nf in spec.orders] or [
            SortOrder(True, None) for _ in self._order_keys
        ]
        self._bound_funcs: List[E.Expression] = []
        fields = list(cs.fields)
        for we in self.window_exprs:
            f = we.func
            if isinstance(f, (W.Lead, W.Lag)) or isinstance(f, A.AggregateFunction):
                if getattr(f, "child", None) is not None:
                    f = dataclasses.replace(f, child=E.bind_references(f.child, cs))
            self._bound_funcs.append(f)
            fields.append(StructField(we.resolved_name(), f.dtype, True))
        self._schema = StructType(tuple(fields))
        self._jits = {}

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return 1

    def describe(self):
        names = ", ".join(we.resolved_name() for we in self.window_exprs)
        return f"TpuWindowExec [{names}]"

    def _str_lens(self, batch, keys) -> Tuple[int, ...]:
        lens = []
        for b in keys:
            if isinstance(b.dtype, (T.StringType, T.BinaryType)):
                if isinstance(b, E.BoundReference):
                    c = batch.columns[b.ordinal]
                    m = int(max_string_len(StrV(c.offsets, c.chars, c.validity)))
                else:
                    m = 64
                lens.append(max(4, choose_capacity(max(1, m), 4)))
        return tuple(lens)

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        assert index == 0
        batch = _concat_all(self.conf, self.children[0])
        if batch is None:
            return
        cap = batch.capacity
        all_keys = self._part_keys + self._order_keys
        sml = self._str_lens(batch, all_keys)
        run = self.window_fn(cap, sml)
        key = (batch_signature(batch), cap, sml)
        # the shared pipeline-cache guard: miss accounting + the
        # compiled-program cost plane ride cached_pipeline (xla_cost.py)
        from .base import cached_pipeline

        fn = cached_pipeline(self._jits, key, "window",
                             lambda: jax.jit(run))
        with self.op_timed():
            vals = fn(
                vals_of_batch(batch), count_scalar(batch.num_rows_lazy))
        yield self.record_batch(
            batch_from_vals(vals, self._schema, batch.num_rows_lazy))

    def window_fn(self, cap: int, sml: Tuple[int, ...]):
        """The pure, trace-safe window body over (cols, num_rows) at
        capacity ``cap``: ONE radix sort by (partition, order) keys plus
        O(n) scan kernels, returning sorted child cols + one value column
        per window expression. Shared seam: the single-device path jits
        it directly; the mesh window stage (exec/mesh.TpuMeshWindowExec)
        runs the SAME body per shard after a hash exchange on the
        partition keys (window partitions are independent, so exchanging
        whole partitions onto shards preserves exact semantics)."""
        all_keys = self._part_keys + self._order_keys
        frame = self.spec.resolved_frame()
        range_frame = frame.frame_type == W.RANGE
        whole = frame.is_whole_partition or not self._order_keys
        bounded = frame.is_bounded_rows and not whole and not frame.is_running
        blo, bhi = frame.row_bounds() if bounded else (0, 0)
        # literal RANGE frame over the single numeric ORDER BY key value
        branged = (frame.is_bounded_range and not whole
                   and bool(self._order_keys))
        # DESC normalizes by NEGATING the key (exec below); "preceding"
        # flips direction with the key, so the offsets carry over as-is:
        # kj in [ki-hi, ki+(-lo)] <=> -kj in [-ki+lo, -ki+hi]
        rlo, rhi = frame.range_bounds() if branged else (None, None)

        def run(cols, num_rows):
            live = filter_gather.live_of(num_rows, cap)
            keys = [lower(k, cols, cap) for k in all_keys]
            dtypes = [k.dtype for k in all_keys]
            orders = [SortOrder(True, True)] * len(self._part_keys) + list(
                self._orders
            )
            perm, radix = sort_with_radix_keys(keys, dtypes, orders, live, sml)
            live_s = jnp.take(live, perm, mode="clip")
            sorted_cols = filter_gather.gather(cols, perm, live_s)

            # split the co-sorted radix arrays back into partition vs order
            counts = []
            si = 0
            for k, dt in zip(all_keys, dtypes):
                if isinstance(dt, (T.StringType, T.BinaryType)):
                    ml = sml[si] if si < len(sml) else 64
                    si += 1
                    counts.append(1 + max(1, (ml + 3) // 4))
                else:
                    counts.append(2)
            npart = sum(counts[: len(self._part_keys)])
            part_radix = tuple(radix[:npart])
            order_radix = tuple(radix[npart: sum(counts)])

            ps, pe, qs, qe, seg = window_ops.boundaries_from_radix(
                part_radix, order_radix, live_s)

            range_key = None
            if branged:
                rk = lower(self._order_keys[0], sorted_cols, cap)
                if not self._orders[0].ascending:
                    rk = ColV(-rk.data, rk.validity)  # ASC-normalize
                range_key = rk
                nf = self._orders[0].nulls_first
                range_nulls_first = (
                    self._orders[0].ascending if nf is None else nf)

            def ranged(op_, v_):
                return window_ops.bounded_range_agg(
                    op_, v_, range_key, ps, pe, qs, qe, live_s, rlo, rhi,
                    range_nulls_first)

            out = list(sorted_cols)
            for we, f in zip(self.window_exprs, self._bound_funcs):
                if isinstance(f, W.RowNumber):
                    out.append(window_ops.row_number(ps, live_s))
                elif isinstance(f, W.Rank):
                    out.append(window_ops.rank(ps, qs, live_s))
                elif isinstance(f, W.DenseRank):
                    out.append(window_ops.dense_rank(ps, qs, live_s))
                elif isinstance(f, (W.Lead, W.Lag)):
                    v = lower(f.child, sorted_cols, cap)
                    off = f.offset if isinstance(f, W.Lead) else -f.offset
                    dflt = (
                        lower(f.default, sorted_cols, cap)
                        if f.default is not None else None
                    )
                    out.append(window_ops.shift_in_partition(
                        v, off, ps, pe, live_s, dflt))
                elif isinstance(f, A.Average):
                    v = lower(E.Cast(f.child, T.DOUBLE), sorted_cols, cap)
                    if branged:
                        s = ranged("sum", v)
                        c = ranged("count", v)
                    elif bounded:
                        s = window_ops.bounded_row_agg(
                            "sum", v, ps, pe, live_s, blo, bhi)
                        c = window_ops.bounded_row_agg(
                            "count", v, ps, pe, live_s, blo, bhi)
                    else:
                        s = window_ops.running_agg(
                            "sum", v, seg, ps, qe, live_s, range_frame,
                            whole, pe)
                        c = window_ops.running_agg(
                            "count", v, seg, ps, qe, live_s, range_frame,
                            whole, pe)
                    data = s.data / jnp.where(c.data == 0, 1, c.data)
                    valid = s.validity & (c.data > 0)
                    out.append(ColV(jnp.where(valid, data, 0.0), valid))
                elif isinstance(f, A.AggregateFunction):
                    op = {
                        A.Count: "count", A.Sum: "sum",
                        A.Min: "min", A.Max: "max",
                    }[type(f)]
                    if isinstance(f, A.Count) and f.input is None:
                        op = "count_star"
                        v = None
                    else:
                        cast_to = f.dtype if isinstance(f, A.Sum) else None
                        e = E.Cast(f.child, cast_to) if cast_to else f.child
                        v = lower(e, sorted_cols, cap)
                    if branged:
                        out.append(ranged(op, v))
                    elif bounded:
                        out.append(window_ops.bounded_row_agg(
                            op, v, ps, pe, live_s, blo, bhi))
                    else:
                        out.append(window_ops.running_agg(
                            op, v, seg, ps, qe, live_s, range_frame,
                            whole, pe))
                else:
                    raise ValueError(f"unsupported window function {f}")
            return out

        return run
