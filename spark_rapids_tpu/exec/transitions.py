"""Row <-> columnar transition execs.

Reference analog: GpuRowToColumnarExec (GpuRowToColumnarExec.scala:37),
GpuColumnarToRowExec (GpuColumnarToRowExec.scala:38), GpuBringBackToHost.
The planner inserts these at every CPU/TPU boundary; the transition
optimizer's job (GpuTransitionOverrides.scala:38) of fusing adjacent
transitions is done here by construction — the overrides pass only ever
creates one transition per boundary.
"""
from __future__ import annotations

from typing import Iterator, List

from ..columnar import ColumnarBatch
from ..columnar.batch import batch_from_rows
from ..conf import MAX_READER_BATCH_SIZE_ROWS, RapidsConf
from ..cpu.plan import CpuExec
from ..types import StructType
from .base import TpuExec


class RowToColumnarExec(TpuExec):
    """CPU rows -> device batches (host build + single upload per batch)."""

    def __init__(self, conf: RapidsConf, cpu_child: CpuExec):
        super().__init__(conf)
        self.cpu_child = cpu_child
        self._batch_rows = conf.get(MAX_READER_BATCH_SIZE_ROWS)

    @property
    def output_schema(self) -> StructType:
        return self.cpu_child.output_schema

    @property
    def num_partitions(self) -> int:
        return self.cpu_child.num_partitions

    def describe(self):
        return "RowToColumnarExec"

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        lines.append(self.cpu_child.tree_string(indent + 1))
        return "\n".join(lines)

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        buf: List[tuple] = []
        for row in self.cpu_child.execute_rows_partition(index):
            buf.append(row)
            if len(buf) >= self._batch_rows:
                yield self.record_batch(batch_from_rows(buf, self.output_schema))
                buf = []
        if buf:
            yield self.record_batch(batch_from_rows(buf, self.output_schema))


class ColumnarToRowExec(CpuExec):
    """Device batches -> host rows (the collect boundary)."""

    def __init__(self, conf: RapidsConf, tpu_child: TpuExec):
        super().__init__(conf)
        self.tpu_child = tpu_child

    @property
    def output_schema(self) -> StructType:
        return self.tpu_child.output_schema

    @property
    def num_partitions(self) -> int:
        return self.tpu_child.num_partitions

    def describe(self):
        return "ColumnarToRowExec"

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        lines.append(self.tpu_child.tree_string(indent + 1))
        return "\n".join(lines)

    def execute_rows_partition(self, index: int) -> Iterator[tuple]:
        for batch in self.tpu_child.execute_partition(index):
            yield from batch.to_rows()
