"""Columnar exchange execs: shuffle and broadcast.

Reference analog: GpuShuffleExchangeExecBase.doExecuteColumnar
(execution/GpuShuffleExchangeExec.scala:70,147) and
GpuBroadcastExchangeExecBase (execution/GpuBroadcastExchangeExec.scala:237).
The map side partitions each child batch with ONE fused device program
(partition-id compute + stable sort + offsets; shuffle/partition.py), syncs
only the (P+1,) offsets vector, slices device pieces, and writes them
through the transport SPI. The reduce side fetches its pieces and concats
them into one dense batch per partition (the GpuShuffleCoalesceExec role).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch
from ..conf import (
    RapidsConf,
    SHUFFLE_COMPRESSION_CODEC,
    SHUFFLE_TRANSPORT_CLASS,
)
from ..expr.eval import ColV, StrV, Val
from ..ops import concat as concat_ops
from ..ops import filter_gather
from ..ops.sort import max_string_len
from ..shuffle.partition import Partitioning, RangePartitioning, partition_cols
from ..shuffle.transport import (
    DeviceShuffleTransport,
    SerializedShuffleTransport,
    ShufflePiece,
    ShuffleTransport,
    new_shuffle_id,
)
from ..types import StructType
from ..utils.locks import ordered_lock
from ..columnar.column import choose_capacity
from .base import (
    TOTAL_TIME,
    TpuExec,
    batch_from_vals,
    batch_signature,
    count_scalar,
    timed,
    vals_of_batch,
)

PARTITION_SIZE = "partitionSize"  # reference metric (GpuExec.scala:27-60)
DATA_SIZE = "dataSize"
# per-shuffle transport metrics (the layer the per-op profiler skipped):
# wire bytes each way plus codec encode/decode time, pulled from the
# transport's cumulative stats() after map/fetch (reference analog: the
# RapidsShuffle* writeTime/fetchWaitTime/compression metrics)
SHUFFLE_BYTES_WRITTEN = "shuffleBytesWritten"
SHUFFLE_BYTES_FETCHED = "shuffleBytesFetched"
CODEC_ENCODE_TIME = "codecEncodeTime"
CODEC_DECODE_TIME = "codecDecodeTime"


def make_transport(conf: RapidsConf) -> ShuffleTransport:
    kind = conf.get(SHUFFLE_TRANSPORT_CLASS)
    if kind == "host":
        return SerializedShuffleTransport(conf.get(SHUFFLE_COMPRESSION_CODEC))
    if kind == "network":
        # conf-selected server/client transport (reference: transport
        # selection by conf, RapidsShuffleTransport.scala:328-411); the
        # process-wide server owns this worker's map output and fetches
        # merge every peer's pieces
        from ..conf import SHUFFLE_NETWORK_LISTEN_PORT, SHUFFLE_NETWORK_PEERS
        from ..shuffle.network import NetworkShuffleTransport, local_server

        remotes = []
        for p in conf.get(SHUFFLE_NETWORK_PEERS).split(","):
            p = p.strip()
            if p:
                host, sep, port = p.rpartition(":")
                if not sep or not host or not port:
                    raise ValueError(
                        "spark.rapids.tpu.shuffle.network.peers: invalid "
                        f"peer entry {p!r} (expected host:port)")
                try:
                    port_n = int(port)
                except ValueError:
                    raise ValueError(
                        "spark.rapids.tpu.shuffle.network.peers: invalid "
                        f"port in peer entry {p!r} (expected host:port)")
                remotes.append((host, port_n))
        return NetworkShuffleTransport(
            server=local_server(conf.get(SHUFFLE_NETWORK_LISTEN_PORT)),
            remotes=tuple(remotes),
            codec=conf.get(SHUFFLE_COMPRESSION_CODEC),
            owns_server=False)
    return DeviceShuffleTransport()


_SLICE_CACHE: Dict[tuple, object] = {}


def _piece_slicer(sig: tuple, pcap: int, ccaps: Tuple[int, ...]):
    """Jitted row-range slice at bucketed output shapes.

    Start/count are TRACED operands, so one compiled program serves every
    piece that lands in the same (capacity, char-cap) bucket — a naive
    ``data[a:b]`` would compile one XLA slice per distinct range.
    """
    key = (sig, pcap, ccaps)

    def build():
        def run(cols, start, n):
            idx = jnp.arange(pcap, dtype=jnp.int32) + start
            valid_slot = jnp.arange(pcap, dtype=jnp.int32) < n
            return filter_gather.gather(cols, idx, valid_slot, ccaps)

        return jax.jit(run)

    from .base import cached_pipeline

    return cached_pipeline(_SLICE_CACHE, key, None, build,
                           max_entries=1024)


def _vals_signature(vals: Sequence[Val]) -> tuple:
    sig = []
    for v in vals:
        if isinstance(v, StrV):
            sig.append(("s", int(v.offsets.shape[0]), int(v.chars.shape[0])))
        else:
            sig.append((str(v.data.dtype), int(v.data.shape[0])))
    return tuple(sig)


def _slice_piece(
    vals: Sequence[Val], a: int, b: int,
    str_bounds: Sequence[Tuple[int, int]],
) -> ShufflePiece:
    """Device-slice rows [a, b) of partition-sorted columns into a piece
    at power-of-two capacity (strings re-based to offset 0 by the gather).

    ``str_bounds[i]`` = (byte_start, byte_end) for the i-th string column
    (host ints synced at the map boundary)."""
    n = b - a
    byte_lens = tuple(bb - ba for ba, bb in str_bounds)
    pcap = choose_capacity(max(1, n))
    ccaps = tuple(choose_capacity(max(1, bl), 128) for bl in byte_lens)
    fn = _piece_slicer(_vals_signature(vals), pcap, ccaps)
    out = fn(vals, jnp.int32(a), jnp.int32(n))
    return ShufflePiece(out, n, byte_lens)


_CONCAT_CACHE: Dict[tuple, object] = {}


def concat_pieces(
    pieces: Sequence[ShufflePiece], schema: StructType
) -> ColumnarBatch:
    """Concat shuffle pieces into one dense batch with ONE jitted program
    per shape set (row/byte counts are traced operands, so arbitrary piece
    sizes reuse the same executable)."""
    lengths = [p.n for p in pieces]
    n_str = len(pieces[0].byte_lens)
    out_cap = choose_capacity(max(1, sum(lengths)))
    out_char_caps = tuple(
        choose_capacity(max(1, sum(p.byte_lens[k] for p in pieces)), 128)
        for k in range(n_str)
    )
    sigs = tuple(_vals_signature(p.vals) for p in pieces)
    key = (sigs, out_cap, out_char_caps)

    def build():
        def run(col_parts, counts, byte_counts):
            return concat_ops.concat_pieces_traced(
                col_parts, counts, byte_counts, out_cap, out_char_caps)

        return jax.jit(run)

    from .base import cached_pipeline

    fn = cached_pipeline(_CONCAT_CACHE, key, None, build,
                         max_entries=1024)
    cols, _n = fn(
        [p.vals for p in pieces],
        [jnp.int32(p.n) for p in pieces],
        [[jnp.int32(b) for b in p.byte_lens] for p in pieces],
    )
    return batch_from_vals(cols, schema, sum(lengths))


class TpuShuffleExchangeExec(TpuExec):
    """Repartition child output by a Partitioning through the transport."""

    def __init__(self, conf: RapidsConf, child: TpuExec,
                 partitioning: Partitioning,
                 transport: Optional[ShuffleTransport] = None):
        super().__init__(conf, [child])
        self.partitioning = partitioning
        self.transport = transport or make_transport(conf)
        self.shuffle_id = new_shuffle_id()
        self._map_done = False
        self._consumed: set = set()
        self._map_lock = ordered_lock("exec.exchange_map", reentrant=True)
        self._jits: Dict[tuple, object] = {}
        self.metrics[PARTITION_SIZE] = self.metric(PARTITION_SIZE)
        self.metrics[DATA_SIZE] = self.metric(DATA_SIZE)

    @property
    def output_schema(self) -> StructType:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    def describe(self):
        return f"TpuShuffleExchangeExec {self.partitioning.describe()}"

    # -- map side ----------------------------------------------------------
    def _part_cache_key(self) -> tuple:
        p = self.partitioning
        if isinstance(p, RangePartitioning) and p.bounds is not None:
            return (p.describe(), tuple(tuple(b) for b in p.bounds))
        return (p.describe(),)

    def _key_str_lens(self, batch: ColumnarBatch) -> Tuple[int, ...]:
        """Per-batch byte-length bucket for each STRING key column, so
        hashing/range-comparison covers full strings (one tiny host sync,
        same place TpuSortExec syncs its string bounds)."""
        lens = []
        for i in getattr(self.partitioning, "key_indices", ()):
            c = batch.columns[i]
            if c.is_string:
                m = int(max_string_len(StrV(c.offsets, c.chars, c.validity)))
                lens.append(max(4, choose_capacity(max(1, m), 4)))
        return tuple(lens)

    def _map_fn(self, sig: tuple, cap: int, schema: StructType,
                sml: Tuple[int, ...]):
        P = self.num_partitions
        key = (sig, cap, P, sml, self._part_cache_key())

        def build():
            part = self.partitioning

            def run(cols, num_rows, map_index):
                live = filter_gather.live_of(num_rows, cap)
                pids = part.partition_ids(
                    cols, schema, live, map_index, str_max_lens=sml)
                sorted_cols, offsets = partition_cols(cols, pids, num_rows, P)
                byte_offs = [
                    jnp.take(c.offsets, offsets, mode="clip")
                    for c in sorted_cols if isinstance(c, StrV)
                ]
                return sorted_cols, offsets, byte_offs

            return jax.jit(run)

        # the shared pipeline-cache guard: miss accounting + the
        # compiled-program cost plane ride cached_pipeline (xla_cost.py)
        # — the shuffle map kernel is often the bandwidth-dominant
        # program and must not be invisible to the roofline report
        from .base import cached_pipeline

        return cached_pipeline(self._jits, key, "exchange", build)

    def _sample_range_bounds(self, parts: List[List[ColumnarBatch]]) -> None:
        """Sample key values host-side and set the range bounds
        (reference: GpuRangePartitioner.sketch/determineBounds)."""
        part = self.partitioning
        assert isinstance(part, RangePartitioning)
        if part.bounds is not None:
            return
        from ..cpu.plan import _SparkOrderKey

        from .base import vals_of_batch

        samples: List[tuple] = []
        for batches in parts:
            for b in batches:
                n = b.num_rows
                if n == 0:
                    continue
                take = min(n, 128)
                step = max(1, n // take)
                # gather the strided sample ON DEVICE, read back only it
                # (a full column readback here would be O(rows) transfer
                # for an O(128) sample)
                idx = jnp.asarray(range(0, n, step), jnp.int32)
                key_vals = [vals_of_batch(b)[i] for i in part.key_indices]
                sampled = filter_gather.gather(
                    key_vals, idx, jnp.ones(idx.shape[0], jnp.bool_))
                from .base import batch_from_vals

                sb = batch_from_vals(
                    sampled,
                    T.StructType(tuple(
                        b.schema.fields[i] for i in part.key_indices)),
                    idx.shape[0],
                )
                hosts = sb.host_columns()
                for r in range(idx.shape[0]):
                    samples.append(tuple(
                        (None if not h.validity[r] else
                         (h.data[r].item()
                          if hasattr(h.data[r], "item") else h.data[r]))
                        for h in hosts
                    ))
        P = part.num_partitions
        if not samples:
            part.bounds = [[None] * (P - 1) for _ in part.key_indices]
            return
        orders = part.orders
        samples.sort(key=lambda row: tuple(
            _SparkOrderKey(v, o.ascending, o.nulls_first_resolved)
            for v, o in zip(row, orders)
        ))
        bounds_rows = []
        for j in range(1, P):
            bounds_rows.append(samples[min(len(samples) - 1,
                                           j * len(samples) // P)])
        part.bounds = [
            [row[k] for row in bounds_rows]
            for k in range(len(part.key_indices))
        ]

    def _run_map_side(self) -> None:
        with self._map_lock:
            if self._map_done:
                return
            child = self.children[0]
            schema = self.output_schema
            str_col_ix = [
                j for j, f in enumerate(schema.fields)
                if isinstance(f.dataType, (T.StringType, T.BinaryType))
            ]
            needs_sample = (
                isinstance(self.partitioning, RangePartitioning)
                and self.partitioning.bounds is None
            )
            if needs_sample:
                parts = [
                    list(child.execute_partition(p))
                    for p in range(child.num_partitions)
                ]
                self._sample_range_bounds(parts)
                batch_iter = [
                    (p, b) for p, bs in enumerate(parts) for b in bs
                ]
            else:
                batch_iter = (
                    (p, b)
                    for p in range(child.num_partitions)
                    for b in child.execute_partition(p)
                )
            from ..memory.retry import named_oom

            P = self.num_partitions
            self.partition_rows = [0] * P
            with self.op_timed(), named_oom(f"{self.node_name}.map"):
                # exchange map-side staging (partition sort + piece
                # slicing) sits outside the per-batch retry harness: a
                # device allocation failure here is a named
                # TpuOutOfDeviceMemory, not a bare XLA traceback
                for map_id, batch in batch_iter:
                    if not batch.columns:
                        continue
                    # dict-encoded columns materialize at the shuffle
                    # boundary: pieces serialize/slice the plain Arrow
                    # layout and peers don't share dictionaries
                    from .base import materialized_batch

                    batch = materialized_batch(batch)
                    cap = batch.capacity
                    fn = self._map_fn(
                        batch_signature(batch), cap, schema,
                        self._key_str_lens(batch))
                    sorted_cols, offsets, byte_offs = fn(
                        vals_of_batch(batch),
                        count_scalar(batch.num_rows_lazy),
                        jnp.int32(map_id),
                    )
                    # ONE host sync for the (P+1,) offsets (+ string bytes)
                    from .base import host_pull

                    off_h, *boffs_h = host_pull([offsets, *byte_offs])
                    for j in range(P):
                        a, b = int(off_h[j]), int(off_h[j + 1])
                        if a == b:
                            continue
                        str_bounds = [
                            (int(bo[j]), int(bo[j + 1])) for bo in boffs_h
                        ]
                        piece = _slice_piece(sorted_cols, a, b, str_bounds)
                        self.transport.write(
                            self.shuffle_id, map_id, j, piece, schema)
                        self.metrics[PARTITION_SIZE].add(b - a)
                        # per-reduce-partition row stats: the AQE reader
                        # re-plans from these (reference: MapOutputStats
                        # feeding ShuffledBatchRDD's partition specs)
                        self.partition_rows[j] += b - a
            self.metrics[DATA_SIZE].set(self.transport.bytes_written())
            self._note_transport_stats()
            self._map_done = True

    def _note_transport_stats(self) -> None:
        """Refresh the per-shuffle transport metrics from the transport's
        cumulative counters (set, not add: stats() is already a running
        total, and AQE readers share this exchange's transport)."""
        st = self.transport.stats()
        self.metric(SHUFFLE_BYTES_WRITTEN, "bytes").set(st["bytes_written"])
        self.metric(SHUFFLE_BYTES_FETCHED, "bytes").set(st["bytes_fetched"])
        if st["encode_ns"]:
            self.metric(CODEC_ENCODE_TIME, "ns").set(st["encode_ns"])
        if st["decode_ns"]:
            self.metric(CODEC_DECODE_TIME, "ns").set(st["decode_ns"])

    # -- reduce side -------------------------------------------------------
    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        self._run_map_side()
        pieces = self.transport.fetch(self.shuffle_id, index)
        self._note_transport_stats()
        # the consumed-set transition runs under the map latch: parallel
        # reduce partitions otherwise race the len() check-then-act —
        # two threads can both see the set full and double-release the
        # transport, or a late add lands after clear() and wedges the
        # NEXT execution's release forever
        with self._map_lock:
            self._consumed.add(index)
            if len(self._consumed) >= self.num_partitions:
                # every reduce partition fetched once: drop the cached
                # pieces (the reference ties shuffle buffer lifetime to
                # the stage) and reset the map latch so a re-execution
                # rebuilds them
                self.transport.release(self.shuffle_id)
                self._consumed.clear()
                self._map_done = False
        if not pieces:
            return
        from ..memory.retry import named_oom

        schema = self.output_schema
        with named_oom(f"{self.node_name}.reduce"):
            out = concat_pieces(pieces, schema)
        yield self.record_batch(out)


# ---------------------------------------------------------------------------
# AQE-lite: post-exchange stats -> re-planned reads
# ---------------------------------------------------------------------------
class TpuAQEShuffleReadExec(TpuExec):
    """Adaptive shuffle read: COALESCES small reduce partitions and SPLITS
    skewed ones using the exchange's materialized per-partition row stats.

    Reference analog: GpuCustomShuffleReaderExec.scala + ShuffledBatchRDD's
    CoalescedPartitionSpec / PartialReducerPartitionSpec (:31-157). Specs:
      ("range", lo, hi)     read reduce partitions [lo, hi) concatenated
      ("slice", rid, j, k)  read slice j of k of reduce partition rid
                            (pieces grouped by cumulative rows — the
                            skewed-join split; only valid where the
                            consumer tolerates a partition appearing in
                            several tasks, i.e. the join PROBE side)
    """

    def __init__(self, conf: RapidsConf, exchange: TpuShuffleExchangeExec,
                 specs: List[tuple]):
        super().__init__(conf, [exchange])
        self.specs = specs
        self._consumed: set = set()

    @property
    def output_schema(self) -> StructType:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        return len(self.specs)

    def describe(self):
        nr = sum(1 for s in self.specs if s[0] == "range")
        ns = len(self.specs) - nr
        return f"TpuAQEShuffleReadExec({nr} coalesced, {ns} skew slices)"

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        ex: TpuShuffleExchangeExec = self.children[0]  # type: ignore
        ex._run_map_side()
        spec = self.specs[index]
        pieces: List[ShufflePiece] = []
        if spec[0] == "range":
            _, lo, hi = spec
            for rid in range(lo, hi):
                pieces.extend(ex.transport.fetch(ex.shuffle_id, rid))
        else:
            _, rid, j, k = spec
            allp = ex.transport.fetch(ex.shuffle_id, rid)
            pieces = _slice_pieces_by_rows(allp, j, k)
        ex._note_transport_stats()
        self._consumed.add(index)
        if len(self._consumed) >= len(self.specs):
            ex.transport.release(ex.shuffle_id)
            self._consumed.clear()
            ex._map_done = False
        if not pieces:
            return
        yield self.record_batch(concat_pieces(pieces, self.output_schema))


def _slice_pieces_by_rows(
    pieces: List[ShufflePiece], j: int, k: int
) -> List[ShufflePiece]:
    """Split a piece list into k row-balanced groups; return group j.
    (The reference splits skewed partitions by MAP ranges —
    PartialReducerPartitionSpec; grouping whole pieces is the same cut.)"""
    total = sum(p.n for p in pieces)
    bounds = [total * i // k for i in range(k + 1)]
    out = []
    acc = 0
    for p in pieces:
        mid = acc + p.n // 2
        if bounds[j] <= mid < bounds[j + 1]:
            out.append(p)
        acc += p.n
    return out


def plan_aqe_coalesce(
    conf: RapidsConf, exchange: TpuShuffleExchangeExec
) -> "TpuAQEShuffleReadExec":
    """Coalesce-only re-plan (safe for FINAL aggregates: merging whole
    key-disjoint partitions keeps them key-disjoint)."""
    from ..conf import AQE_TARGET_ROWS

    exchange._run_map_side()
    rows = exchange.partition_rows
    target = conf.get(AQE_TARGET_ROWS)
    specs: List[tuple] = []
    lo = 0
    acc = 0
    for p, r in enumerate(rows):
        if acc > 0 and acc + r > target:
            specs.append(("range", lo, p))
            lo, acc = p, 0
        acc += r
    if lo < len(rows):
        specs.append(("range", lo, len(rows)))
    return TpuAQEShuffleReadExec(conf, exchange, specs)


def plan_aqe_join_pair(
    conf: RapidsConf,
    left_ex: TpuShuffleExchangeExec,
    right_ex: TpuShuffleExchangeExec,
    probe_left: bool = True,
) -> Tuple["TpuAQEShuffleReadExec", "TpuAQEShuffleReadExec"]:
    """Joint re-plan of a co-partitioned join's two exchanges: specs stay
    index-ALIGNED so partition p of one side still meets partition p of
    the other. Skewed PROBE partitions split into row-balanced slices,
    each paired with the full matching build partition (reference:
    OptimizeSkewedJoin + ShuffledBatchRDD:31-157); small pairs coalesce.
    """
    from ..conf import AQE_SKEW_FACTOR, AQE_TARGET_ROWS

    left_ex._run_map_side()
    right_ex._run_map_side()
    probe_ex = left_ex if probe_left else right_ex
    build_ex = right_ex if probe_left else left_ex
    prows = probe_ex.partition_rows
    target = conf.get(AQE_TARGET_ROWS)
    factor = conf.get(AQE_SKEW_FACTOR)
    nz = sorted(r for r in prows if r > 0) or [0]
    median = nz[len(nz) // 2]
    skew_at = max(int(median * factor), target)

    probe_specs: List[tuple] = []
    build_specs: List[tuple] = []
    run_lo = None
    run_rows = 0

    def flush_run(hi):
        nonlocal run_lo, run_rows
        if run_lo is not None:
            probe_specs.append(("range", run_lo, hi))
            build_specs.append(("range", run_lo, hi))
            run_lo, run_rows = None, 0

    for p, r in enumerate(prows):
        if r > skew_at:
            flush_run(p)
            k = max(2, -(-r // target))
            for j in range(k):
                probe_specs.append(("slice", p, j, k))
                build_specs.append(("range", p, p + 1))
            continue
        if run_lo is None:
            run_lo = p
        elif run_rows + r > target:
            flush_run(p)
            run_lo = p
        run_rows += r
    flush_run(len(prows))

    probe_read = TpuAQEShuffleReadExec(conf, probe_ex, probe_specs)
    build_read = TpuAQEShuffleReadExec(conf, build_ex, build_specs)
    return ((probe_read, build_read) if probe_left
            else (build_read, probe_read))


class TpuLazyAQEReadExec(TpuExec):
    """Defers AQE spec planning to first touch: stats exist only after the
    exchange's map side materializes (reference: AQE re-optimizes at query
    stage boundaries). Coalesce-only unless a joint join resolver is
    supplied."""

    def __init__(self, conf: RapidsConf, exchange: TpuShuffleExchangeExec,
                 resolver=None):
        super().__init__(conf, [exchange])
        self._resolver = resolver
        self._inner: Optional[TpuAQEShuffleReadExec] = None

    def _resolve(self) -> TpuAQEShuffleReadExec:
        if self._inner is None:
            if self._resolver is not None:
                self._inner = self._resolver()
            else:
                self._inner = plan_aqe_coalesce(
                    self.conf, self.children[0])  # type: ignore[arg-type]
        return self._inner

    @property
    def output_schema(self) -> StructType:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        from .base import in_planning

        if self._inner is None and in_planning():
            # plan-time heuristics must NOT materialize the stage (review
            # finding: a downstream sort's partition-count check was
            # executing the whole stage during plan conversion)
            return self.children[0].num_partitions
        return self._resolve().num_partitions

    def describe(self):
        if self._inner is not None:
            return f"TpuLazyAQEReadExec -> {self._inner.describe()}"
        return "TpuLazyAQEReadExec (unplanned)"

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        yield from self._resolve().execute_partition(index)


def lazy_aqe_join_pair(
    conf: RapidsConf,
    left_ex: TpuShuffleExchangeExec,
    right_ex: TpuShuffleExchangeExec,
    probe_left: bool = True,
) -> Tuple[TpuLazyAQEReadExec, TpuLazyAQEReadExec]:
    """Two lazy reads over a co-partitioned join pair that resolve their
    (index-aligned) specs JOINTLY on first touch."""
    state: Dict[str, tuple] = {}

    def resolve_pair():
        if "pair" not in state:
            state["pair"] = plan_aqe_join_pair(
                conf, left_ex, right_ex, probe_left)
        return state["pair"]

    return (
        TpuLazyAQEReadExec(conf, left_ex, lambda: resolve_pair()[0]),
        TpuLazyAQEReadExec(conf, right_ex, lambda: resolve_pair()[1]),
    )


class TpuBroadcastExchangeExec(TpuExec):
    """Materialize the child into one batch every consumer partition reads.

    Reference analog: GpuBroadcastExchangeExecBase
    (GpuBroadcastExchangeExec.scala:237) — the build side is concatenated
    once and shared; on one host "broadcast" is reuse of the same
    device-resident batch (serialized through the host path only when the
    host transport is configured, mirroring the serialize-for-driver step).
    """

    def __init__(self, conf: RapidsConf, child: TpuExec):
        super().__init__(conf, [child])
        self._built: Optional[ColumnarBatch] = None
        self._lock = threading.Lock()

    @property
    def output_schema(self) -> StructType:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        return 1

    def describe(self):
        return "TpuBroadcastExchangeExec"

    def materialize(self) -> Optional[ColumnarBatch]:
        with self._lock:
            if self._built is None:
                from .join import _concat_all

                built = _concat_all(self.conf, self.children[0])
                if (
                    built is not None
                    and self.conf.get(SHUFFLE_TRANSPORT_CLASS) == "host"
                ):
                    from ..shuffle.serializer import (
                        deserialize_batch,
                        serialize_batch,
                    )

                    built = deserialize_batch(serialize_batch(
                        built, self.conf.get(SHUFFLE_COMPRESSION_CODEC)))
                if built is not None:
                    # broadcast batches are registered spillable, like the
                    # reference's SerializeConcatHostBuffersDeserializeBatch
                    # living in the catalog (GpuBroadcastExchangeExec.scala);
                    # only the handle keeps a reference, so a spill really
                    # frees the device copy
                    from ..memory import SpillableColumnarBatch
                    from .. import xla_cost as _xc

                    # scoped registration: materialize() runs on first
                    # consumer pull, outside op_timed, so the ledger
                    # needs the op pushed explicitly
                    with _xc.op_scope(self.node_name):
                        self._spillable = SpillableColumnarBatch(
                            built, ledger_kind="plan_state")
                self._built = True  # latch: build attempted
            if getattr(self, "_spillable", None) is not None:
                return self._spillable.get_batch()
            return None

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        b = self.materialize()
        if b is not None:
            yield self.record_batch(b)
