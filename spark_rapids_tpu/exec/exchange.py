"""Columnar exchange execs: shuffle and broadcast.

Reference analog: GpuShuffleExchangeExecBase.doExecuteColumnar
(execution/GpuShuffleExchangeExec.scala:70,147) and
GpuBroadcastExchangeExecBase (execution/GpuBroadcastExchangeExec.scala:237).
The map side partitions each child batch with ONE fused device program
(partition-id compute + stable sort + offsets; shuffle/partition.py), syncs
only the (P+1,) offsets vector, slices device pieces, and writes them
through the transport SPI. The reduce side fetches its pieces and concats
them into one dense batch per partition (the GpuShuffleCoalesceExec role).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch
from ..conf import (
    RapidsConf,
    SHUFFLE_COMPRESSION_CODEC,
    SHUFFLE_TRANSPORT_CLASS,
)
from ..expr.eval import ColV, StrV, Val
from ..ops import concat as concat_ops
from ..ops import filter_gather
from ..ops.sort import max_string_len
from ..shuffle.partition import Partitioning, RangePartitioning, partition_cols
from ..shuffle.transport import (
    DeviceShuffleTransport,
    SerializedShuffleTransport,
    ShufflePiece,
    ShuffleTransport,
    new_shuffle_id,
)
from ..types import StructType
from ..utils.bucketing import bucket_rows
from .base import (
    TOTAL_TIME,
    TpuExec,
    batch_from_vals,
    batch_signature,
    count_scalar,
    timed,
    vals_of_batch,
)

PARTITION_SIZE = "partitionSize"  # reference metric (GpuExec.scala:27-60)
DATA_SIZE = "dataSize"


def make_transport(conf: RapidsConf) -> ShuffleTransport:
    kind = conf.get(SHUFFLE_TRANSPORT_CLASS)
    if kind == "host":
        return SerializedShuffleTransport(conf.get(SHUFFLE_COMPRESSION_CODEC))
    return DeviceShuffleTransport()


_SLICE_CACHE: Dict[tuple, object] = {}


def _piece_slicer(sig: tuple, pcap: int, ccaps: Tuple[int, ...]):
    """Jitted row-range slice at bucketed output shapes.

    Start/count are TRACED operands, so one compiled program serves every
    piece that lands in the same (capacity, char-cap) bucket — a naive
    ``data[a:b]`` would compile one XLA slice per distinct range.
    """
    key = (sig, pcap, ccaps)
    fn = _SLICE_CACHE.get(key)
    if fn is None:

        def run(cols, start, n):
            idx = jnp.arange(pcap, dtype=jnp.int32) + start
            valid_slot = jnp.arange(pcap, dtype=jnp.int32) < n
            return filter_gather.gather(cols, idx, valid_slot, ccaps)

        if len(_SLICE_CACHE) > 1024:
            _SLICE_CACHE.clear()
        fn = _SLICE_CACHE[key] = jax.jit(run)
    return fn


def _vals_signature(vals: Sequence[Val]) -> tuple:
    sig = []
    for v in vals:
        if isinstance(v, StrV):
            sig.append(("s", int(v.offsets.shape[0]), int(v.chars.shape[0])))
        else:
            sig.append((str(v.data.dtype), int(v.data.shape[0])))
    return tuple(sig)


def _slice_piece(
    vals: Sequence[Val], a: int, b: int,
    str_bounds: Sequence[Tuple[int, int]],
) -> ShufflePiece:
    """Device-slice rows [a, b) of partition-sorted columns into a piece
    at power-of-two capacity (strings re-based to offset 0 by the gather).

    ``str_bounds[i]`` = (byte_start, byte_end) for the i-th string column
    (host ints synced at the map boundary)."""
    n = b - a
    byte_lens = tuple(bb - ba for ba, bb in str_bounds)
    pcap = bucket_rows(max(1, n))
    ccaps = tuple(bucket_rows(max(1, bl), 128) for bl in byte_lens)
    fn = _piece_slicer(_vals_signature(vals), pcap, ccaps)
    out = fn(vals, jnp.int32(a), jnp.int32(n))
    return ShufflePiece(out, n, byte_lens)


_CONCAT_CACHE: Dict[tuple, object] = {}


def concat_pieces(
    pieces: Sequence[ShufflePiece], schema: StructType
) -> ColumnarBatch:
    """Concat shuffle pieces into one dense batch with ONE jitted program
    per shape set (row/byte counts are traced operands, so arbitrary piece
    sizes reuse the same executable)."""
    lengths = [p.n for p in pieces]
    n_str = len(pieces[0].byte_lens)
    out_cap = bucket_rows(max(1, sum(lengths)))
    out_char_caps = tuple(
        bucket_rows(max(1, sum(p.byte_lens[k] for p in pieces)), 128)
        for k in range(n_str)
    )
    sigs = tuple(_vals_signature(p.vals) for p in pieces)
    key = (sigs, out_cap, out_char_caps)
    fn = _CONCAT_CACHE.get(key)
    if fn is None:

        def run(col_parts, counts, byte_counts):
            return concat_ops.concat_pieces_traced(
                col_parts, counts, byte_counts, out_cap, out_char_caps)

        if len(_CONCAT_CACHE) > 1024:
            _CONCAT_CACHE.clear()
        fn = _CONCAT_CACHE[key] = jax.jit(run)
    cols, _n = fn(
        [p.vals for p in pieces],
        [jnp.int32(p.n) for p in pieces],
        [[jnp.int32(b) for b in p.byte_lens] for p in pieces],
    )
    return batch_from_vals(cols, schema, sum(lengths))


class TpuShuffleExchangeExec(TpuExec):
    """Repartition child output by a Partitioning through the transport."""

    def __init__(self, conf: RapidsConf, child: TpuExec,
                 partitioning: Partitioning,
                 transport: Optional[ShuffleTransport] = None):
        super().__init__(conf, [child])
        self.partitioning = partitioning
        self.transport = transport or make_transport(conf)
        self.shuffle_id = new_shuffle_id()
        self._map_done = False
        self._consumed: set = set()
        self._map_lock = threading.Lock()
        self._jits: Dict[tuple, object] = {}
        self.metrics[PARTITION_SIZE] = self.metric(PARTITION_SIZE)
        self.metrics[DATA_SIZE] = self.metric(DATA_SIZE)

    @property
    def output_schema(self) -> StructType:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    def describe(self):
        return f"TpuShuffleExchangeExec {self.partitioning.describe()}"

    # -- map side ----------------------------------------------------------
    def _part_cache_key(self) -> tuple:
        p = self.partitioning
        if isinstance(p, RangePartitioning) and p.bounds is not None:
            return (p.describe(), tuple(tuple(b) for b in p.bounds))
        return (p.describe(),)

    def _key_str_lens(self, batch: ColumnarBatch) -> Tuple[int, ...]:
        """Per-batch byte-length bucket for each STRING key column, so
        hashing/range-comparison covers full strings (one tiny host sync,
        same place TpuSortExec syncs its string bounds)."""
        lens = []
        for i in getattr(self.partitioning, "key_indices", ()):
            c = batch.columns[i]
            if c.is_string:
                m = int(max_string_len(StrV(c.offsets, c.chars, c.validity)))
                lens.append(max(4, bucket_rows(max(1, m), 4)))
        return tuple(lens)

    def _map_fn(self, sig: tuple, cap: int, schema: StructType,
                sml: Tuple[int, ...]):
        P = self.num_partitions
        key = (sig, cap, P, sml, self._part_cache_key())
        fn = self._jits.get(key)
        if fn is None:
            part = self.partitioning

            def run(cols, num_rows, map_index):
                live = filter_gather.live_of(num_rows, cap)
                pids = part.partition_ids(
                    cols, schema, live, map_index, str_max_lens=sml)
                sorted_cols, offsets = partition_cols(cols, pids, num_rows, P)
                byte_offs = [
                    jnp.take(c.offsets, offsets, mode="clip")
                    for c in sorted_cols if isinstance(c, StrV)
                ]
                return sorted_cols, offsets, byte_offs

            fn = self._jits[key] = jax.jit(run)
        return fn

    def _sample_range_bounds(self, parts: List[List[ColumnarBatch]]) -> None:
        """Sample key values host-side and set the range bounds
        (reference: GpuRangePartitioner.sketch/determineBounds)."""
        part = self.partitioning
        assert isinstance(part, RangePartitioning)
        if part.bounds is not None:
            return
        from ..cpu.plan import _SparkOrderKey

        from .base import vals_of_batch

        samples: List[tuple] = []
        for batches in parts:
            for b in batches:
                n = b.num_rows
                if n == 0:
                    continue
                take = min(n, 128)
                step = max(1, n // take)
                # gather the strided sample ON DEVICE, read back only it
                # (a full column readback here would be O(rows) transfer
                # for an O(128) sample)
                idx = jnp.asarray(range(0, n, step), jnp.int32)
                key_vals = [vals_of_batch(b)[i] for i in part.key_indices]
                sampled = filter_gather.gather(
                    key_vals, idx, jnp.ones(idx.shape[0], jnp.bool_))
                from .base import batch_from_vals

                sb = batch_from_vals(
                    sampled,
                    T.StructType(tuple(
                        b.schema.fields[i] for i in part.key_indices)),
                    idx.shape[0],
                )
                hosts = sb.host_columns()
                for r in range(idx.shape[0]):
                    samples.append(tuple(
                        (None if not h.validity[r] else
                         (h.data[r].item()
                          if hasattr(h.data[r], "item") else h.data[r]))
                        for h in hosts
                    ))
        P = part.num_partitions
        if not samples:
            part.bounds = [[None] * (P - 1) for _ in part.key_indices]
            return
        orders = part.orders
        samples.sort(key=lambda row: tuple(
            _SparkOrderKey(v, o.ascending, o.nulls_first_resolved)
            for v, o in zip(row, orders)
        ))
        bounds_rows = []
        for j in range(1, P):
            bounds_rows.append(samples[min(len(samples) - 1,
                                           j * len(samples) // P)])
        part.bounds = [
            [row[k] for row in bounds_rows]
            for k in range(len(part.key_indices))
        ]

    def _run_map_side(self) -> None:
        with self._map_lock:
            if self._map_done:
                return
            child = self.children[0]
            schema = self.output_schema
            str_col_ix = [
                j for j, f in enumerate(schema.fields)
                if isinstance(f.dataType, (T.StringType, T.BinaryType))
            ]
            needs_sample = (
                isinstance(self.partitioning, RangePartitioning)
                and self.partitioning.bounds is None
            )
            if needs_sample:
                parts = [
                    list(child.execute_partition(p))
                    for p in range(child.num_partitions)
                ]
                self._sample_range_bounds(parts)
                batch_iter = [
                    (p, b) for p, bs in enumerate(parts) for b in bs
                ]
            else:
                batch_iter = (
                    (p, b)
                    for p in range(child.num_partitions)
                    for b in child.execute_partition(p)
                )
            P = self.num_partitions
            with timed(self.metrics[TOTAL_TIME]):
                for map_id, batch in batch_iter:
                    if not batch.columns:
                        continue
                    cap = batch.capacity
                    fn = self._map_fn(
                        batch_signature(batch), cap, schema,
                        self._key_str_lens(batch))
                    sorted_cols, offsets, byte_offs = fn(
                        vals_of_batch(batch),
                        count_scalar(batch.num_rows_lazy),
                        jnp.int32(map_id),
                    )
                    # ONE host sync for the (P+1,) offsets (+ string bytes)
                    off_h, *boffs_h = jax.device_get([offsets, *byte_offs])
                    for j in range(P):
                        a, b = int(off_h[j]), int(off_h[j + 1])
                        if a == b:
                            continue
                        str_bounds = [
                            (int(bo[j]), int(bo[j + 1])) for bo in boffs_h
                        ]
                        piece = _slice_piece(sorted_cols, a, b, str_bounds)
                        self.transport.write(
                            self.shuffle_id, map_id, j, piece, schema)
                        self.metrics[PARTITION_SIZE].add(b - a)
            self.metrics[DATA_SIZE].set(self.transport.bytes_written())
            self._map_done = True

    # -- reduce side -------------------------------------------------------
    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        self._run_map_side()
        pieces = self.transport.fetch(self.shuffle_id, index)
        self._consumed.add(index)
        if len(self._consumed) >= self.num_partitions:
            # every reduce partition fetched once: drop the cached pieces
            # (the reference ties shuffle buffer lifetime to the stage) and
            # reset the map latch so a re-execution rebuilds them
            self.transport.release(self.shuffle_id)
            self._consumed.clear()
            self._map_done = False
        if not pieces:
            return
        schema = self.output_schema
        yield self.record_batch(concat_pieces(pieces, schema))


class TpuBroadcastExchangeExec(TpuExec):
    """Materialize the child into one batch every consumer partition reads.

    Reference analog: GpuBroadcastExchangeExecBase
    (GpuBroadcastExchangeExec.scala:237) — the build side is concatenated
    once and shared; on one host "broadcast" is reuse of the same
    device-resident batch (serialized through the host path only when the
    host transport is configured, mirroring the serialize-for-driver step).
    """

    def __init__(self, conf: RapidsConf, child: TpuExec):
        super().__init__(conf, [child])
        self._built: Optional[ColumnarBatch] = None
        self._lock = threading.Lock()

    @property
    def output_schema(self) -> StructType:
        return self.children[0].output_schema

    @property
    def num_partitions(self) -> int:
        return 1

    def describe(self):
        return "TpuBroadcastExchangeExec"

    def materialize(self) -> Optional[ColumnarBatch]:
        with self._lock:
            if self._built is None:
                from .join import _concat_all

                built = _concat_all(self.conf, self.children[0])
                if (
                    built is not None
                    and self.conf.get(SHUFFLE_TRANSPORT_CLASS) == "host"
                ):
                    from ..shuffle.serializer import (
                        deserialize_batch,
                        serialize_batch,
                    )

                    built = deserialize_batch(serialize_batch(
                        built, self.conf.get(SHUFFLE_COMPRESSION_CODEC)))
                if built is not None:
                    # broadcast batches are registered spillable, like the
                    # reference's SerializeConcatHostBuffersDeserializeBatch
                    # living in the catalog (GpuBroadcastExchangeExec.scala);
                    # only the handle keeps a reference, so a spill really
                    # frees the device copy
                    from ..memory import SpillableColumnarBatch

                    self._spillable = SpillableColumnarBatch(built)
                self._built = True  # latch: build attempted
            if getattr(self, "_spillable", None) is not None:
                return self._spillable.get_batch()
            return None

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        b = self.materialize()
        if b is not None:
            yield self.record_batch(b)
