"""Hash-aggregate exec (sort-compatible implementation on TPU).

Reference analog: GpuHashAggregateExec (aggregate.scala:341-806): per-batch
partial aggregation, a concat+merge loop across batches, then the final
projection. The cudf hash groupby is replaced by ops/groupby's
sort+segment-reduce (one fused XLA program per batch); the merge loop reuses
the same kernel with each function's merge ops, exactly mirroring Spark's
update/merge aggregate split so partial results can cross an exchange.

Modes (expr/aggregates.py): COMPLETE (no exchange), PARTIAL (emit buffer
columns), FINAL (merge buffer columns, evaluate results).
"""
from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch
from ..conf import RapidsConf
from ..expr import aggregates as A
from ..expr import expressions as E
from ..expr.eval import ColV, DictV, StrV, Val, lower, materialize_dict
from ..ops import concat as concat_ops
from ..ops import groupby as groupby_ops
from ..ops.sort import max_string_len
from ..types import StructField, StructType
from ..columnar.column import choose_capacity
from .base import (
    TpuExec,
    batch_from_vals,
    batch_signature,
    count_scalar,
    vals_of_batch,
)


_AGG_CACHE: dict = {}


# ---------------------------------------------------------------------------
# Aggregation strategy chooser (conf sql.agg.strategy). The cost model
# reads the SAME roofline peaks the profiler's roofline report measures
# against (spark.rapids.tpu.roofline.peakHbmGBps/.peakTflops, with
# xla_cost.BACKEND_PEAKS per-backend defaults) — one peak source, so a
# deployment that calibrates the conf moves the chooser and the report
# together. The DERATE fractions below are calibrated from the r05
# profile, not spec sheets: the profiled one-hot limb matmul ran at
# ~7e11 MAC/s (143 ms for cap=2^26 x ~12 limbs x B=128) — ~0.7% of the
# v5e MXU peak, because the one-hot compare-select feed, not the
# multiply, is the bottleneck. That gap is exactly what makes the
# bandwidth-sized lowerings competitive. Re-check on a TPU-backed round.
# ---------------------------------------------------------------------------
#: measured effective one-hot limb-matmul MAC rate / MXU peak MAC rate
_MATMUL_PEAK_FRAC = 7.3e-3
#: sustained streaming fraction of peak HBM bandwidth
_HBM_DERATE = 0.6
#: near-serial TPU scatter cost per row (why min/max batch per family)
_SCATTER_SEC_PER_ROW = 10e-9
#: first hash tier (ops/groupby.py B0) — the optimistic common-case
#: matmul price; wider key ranges escalate tiers and multiply it
_FIRST_TIER_B = 128
#: CPU-backend AUTO: below this capacity the native scatter's serial
#: walk is cheap and the radix sort dominates, so SCATTER keeps its
#: round-1-measured win; at or above it the SCATTER dialect's byte
#: amplification (the while-loop accumulator XLA charges per
#: instruction — 19.4 GB vs a 772 MB bound at cap=2^24, BENCH_r09)
#: is the dominant cost and the tiled RADIX lowering takes over.
#: Lowered 2^22 -> 2^21 in round 14: the join and parquet bench shapes
#: both feed a cap=2^21 aggregate whose scatter plan alone charged
#: 2.36 GB / 1.77 GB (the bulk of those shapes' 29.8x / 15x
#: amplification) — the byte model says the flip point sits below the
#: old threshold, and the merge gate is bytes, not shared-box wall clock
_RADIX_CPU_MIN_CAP = 1 << 21


def _roofline_peaks(conf: RapidsConf, backend: str) -> Tuple[float, float]:
    """(peak HBM bytes/s, peak MAC/s) for the chooser: the conf-declared
    roofline peaks when set, else the per-backend defaults — the same
    resolution order the roofline report uses."""
    from ..xla_cost import (BACKEND_PEAKS, ROOFLINE_PEAK_HBM_GBPS,
                            ROOFLINE_PEAK_TFLOPS)

    dg, dt = BACKEND_PEAKS.get(backend, BACKEND_PEAKS["cpu"])
    g = conf.get(ROOFLINE_PEAK_HBM_GBPS) or dg
    t = conf.get(ROOFLINE_PEAK_TFLOPS) or dt
    return g * 1e9, t * 1e12 / 2.0


def choose_agg_strategy(
    conf: RapidsConf,
    cap: int,
    update_ops: Sequence[str],
    update_exprs: Sequence[Optional[E.Expression]],
    key_dtypes: Sequence[T.DataType],
    backend: Optional[str] = None,
) -> Tuple[str, str]:
    """Pick the grouped-aggregation lowering for ONE plan shape from its
    STATIC layout — capacity bucket, aggregated column count/widths, key
    widths — never from data (the choice must be a trace-time constant or
    it would churn the compile cache). Returns ``(strategy, reason)``;
    the reason rides into explain_metrics and the 'agg_strategy' event so
    a wrong prediction is debuggable offline. AUTO resolves:

      * CPU backend -> SCATTER below _RADIX_CPU_MIN_CAP (native segment
        scatters; both the materialized one-hot and the bitonic sort
        lose there in wall clock, measured in round 1), RADIX at or
        above it — the scatter dialect's XLA-charged byte amplification
        dominates at scale and the merge gate is bytes, not the wall
        clock of a shared box;
      * otherwise the cheaper of MATMUL (cap x limbs x B MACs at the
        derated peak MAC rate) and RADIX (bitonic radix-key sort passes
        + one tile-resident bandwidth pass per reduced stream at the
        derated peak HBM rate). Exact float sums without
        variableFloatAgg keep RADIX out of AUTO (its stream split is
        order-insensitive) and compare MATMUL against SORT instead,
        whose float sums stay on the order-preserving scatter path.
    """
    from ..conf import AGG_STRATEGY, IMPROVED_FLOAT_OPS

    mode = conf.get(AGG_STRATEGY)
    if mode != "AUTO":
        return mode, "forced by spark.rapids.tpu.sql.agg.strategy"
    if backend is None:
        backend = jax.default_backend()
    approx = conf.get(IMPROVED_FLOAT_OPS)
    n_int = n_cnt = n_fapprox = n_fexact = n_other = 0
    for op, e in zip(update_ops, update_exprs):
        floating = e is not None and getattr(e.dtype, "is_floating", False)
        if op in ("count", "count_star"):
            n_cnt += 1
        elif op == "sum" and not floating:
            n_int += 1
            n_cnt += 1  # nullability count rides the same pass
        elif op == "sum" and approx:
            n_fapprox += 1
            n_cnt += 1
        elif op == "sum":
            n_fexact += 1
            n_cnt += 1
        else:
            n_other += 1  # min/max/first/last
    # exact float sums demand the order-preserving scatter adds; RADIX's
    # NORMAL/BIG stream split is order-insensitive, so AUTO may only
    # pick it when the query opted into variableFloatAgg semantics
    radix_ok = n_fexact == 0
    if backend == "cpu":
        if cap >= _RADIX_CPU_MIN_CAP and radix_ok:
            return ("RADIX",
                    "AUTO: CPU backend at cap>=2^21 — the scatter "
                    "dialect's while-loop accumulator amplifies "
                    "XLA-charged bytes ~25x past the layout bound "
                    "(BENCH_r09); the tiled radix lowering is sized to "
                    "the bound")
        return ("SCATTER",
                "AUTO: CPU backend — native segment scatters beat both "
                "the materialized one-hot and the bitonic sort")
    hbm_bps, mac_s = _roofline_peaks(conf, backend)
    hbm_eff = _HBM_DERATE * hbm_bps
    mac_eff = _MATMUL_PEAK_FRAC * mac_s
    limbs = 8 * n_int + n_cnt + 2 * n_fapprox
    matmul_s = cap * limbs * _FIRST_TIER_B / mac_eff
    import math

    lg = max(1, math.ceil(math.log2(max(2, cap))))
    sort_passes = lg * (lg + 1) / 2  # bitonic compare-exchange rounds
    from ..plugin.plananalysis import _storage_bytes

    key_bytes = 0
    for dt in key_dtypes:
        try:
            key_bytes += _storage_bytes(dt)
        except Exception:  # strings etc: radix chunks, ~8B per pass
            key_bytes += 8
    key_bytes = key_bytes or 4
    # every reduced stream is one tile-resident bandwidth pass under
    # RADIX (winner sorts ride tile-local memory); under SORT min/max/
    # first/last and float sums keep their scatter families, which
    # cancel against the matmul side's identical scatters
    bw_cols = n_int + n_fapprox + n_cnt + (n_other if radix_ok else 0)
    bw_s = (cap * (key_bytes + 4) * sort_passes
            + cap * 8 * max(1, bw_cols) * 3) / hbm_eff
    bw_pick = "RADIX" if radix_ok else "SORT"
    pick = bw_pick if bw_s < matmul_s else "MATMUL"
    return (pick,
            f"AUTO: est matmul {matmul_s * 1e3:.1f}ms "
            f"({limbs} limbs x B={_FIRST_TIER_B}) vs {bw_pick.lower()} "
            f"{bw_s * 1e3:.1f}ms ({sort_passes:.0f} passes, "
            f"{bw_cols} stream(s)) at cap={cap}, "
            f"peaks {hbm_bps / 1e9:.0f}GB/s {2 * mac_s / 1e12:.0f}TF")


def _agg_pipeline(
    chain,  # fusable execs below this aggregate (fused into the update step)
    key_exprs: Tuple[E.Expression, ...],
    key_dtypes: Tuple[T.DataType, ...],
    value_exprs: Tuple[Optional[E.Expression], ...],
    ops: Tuple[str, ...],
    sig: tuple,
    cap: int,
    str_max_lens: Tuple[int, ...],
    approx_float_sum: bool = False,
    sides: Sequence[tuple] = (),
    str_val_max_lens: Tuple[int, ...] = (),
    nonnull: Tuple[bool, ...] = (),
    strategy: Optional[str] = None,
    donate: Tuple[int, ...] = (),
):
    """ONE fused program: child chain (filter/project/join probe...),
    key+input projection, groupby reduce — a whole query stage per
    dispatch. ``str_val_max_lens``: static byte bound per string-typed
    min/max input, in order (drives the rank sort's chunk count).
    ``nonnull``: the plan analyzer's validity-elision flags for the input
    columns (ops/filter_gather.elide_validity). ``strategy``: the
    resolved aggregation lowering (part of the cache key — a strategy
    flip is a different program)."""
    from .base import side_signature

    key = (
        tuple(e.fusion_key() for e in chain), key_exprs, key_dtypes,
        value_exprs, ops, sig, cap, str_max_lens, approx_float_sum,
        side_signature(sides), str_val_max_lens, nonnull, strategy,
    )
    chain_t = tuple(chain)

    def build():
        def run(cols, num_rows, side_args):
            from ..ops.filter_gather import elide_validity, live_of

            live = live_of(num_rows, cap)
            cols = elide_validity(cols, live, nonnull)
            for e, s in zip(chain_t, side_args):
                cols, live = e.lower_batch(cols, live, cap, s)
            keys = [lower(e, cols, cap) for e in key_exprs]
            vals: List[Optional[ColV]] = []
            for e in value_exprs:
                vals.append(None if e is None else lower(e, cols, cap))
            if key_exprs:
                return groupby_ops.groupby_agg(
                    keys, list(key_dtypes), vals, list(ops), live,
                    str_max_lens, approx_float_sum=approx_float_sum,
                    str_val_max_lens=str_val_max_lens,
                    strategy=strategy,
                )
            outs = groupby_ops.reduce_no_keys(
                vals, list(ops), live, str_val_max_lens=str_val_max_lens)
            return [], outs, jnp.int32(1)

        return jax.jit(run, donate_argnums=donate)

    from .base import cached_pipeline

    return cached_pipeline(_AGG_CACHE, key, "agg_update", build,
                           donate=donate)


def _fused_agg_trace(key_exprs, key_dts, value_exprs, update_ops, merge_ops,
                     eval_exprs, approx, bucket_min, chain_t,
                     strategy=None):
    """The shared in-trace core of BOTH fused aggregate programs (the
    scan→agg stage fusion and the whole-plan fusion): returns
    ``(update_batch, finish)`` closures. ``update_batch`` lowers one
    batch's fused child chain + key/value projection + update groupby;
    ``finish`` concat-pads the partials, runs the merge groupby, and
    applies the result projection (non-PARTIAL). One definition so the
    two paths can never drift semantically — only their ingest differs
    (decoded row groups vs direct batch columns)."""
    nkeys = len(key_exprs)

    def agg_once(keys, vals, ops_, live):
        if key_exprs:
            k_, a_, nseg = groupby_ops.groupby_agg(
                keys, list(key_dts), vals, list(ops_), live,
                (), approx_float_sum=approx, strategy=strategy)
            return list(k_) + list(a_), nseg
        a_ = groupby_ops.reduce_no_keys(vals, list(ops_), live)
        return list(a_), jnp.int32(1)

    def update_batch(cols, live, cap, side_args):
        for e, s in zip(chain_t, side_args):
            cols, live = e.lower_batch(cols, live, cap, s)
        keys = [lower(e, cols, cap) for e in key_exprs]
        vals = [None if e is None else lower(e, cols, cap)
                for e in value_exprs]
        return agg_once(keys, vals, update_ops, live)

    def finish(partial_sets):
        if len(partial_sets) == 1:
            merged_vals, nseg = partial_sets[0]
        else:
            # batches/row groups may carry DIFFERENT dictionaries: dict
            # group keys expand before the cross-partial concat
            col_parts = [
                [materialize_dict(c) if isinstance(c, DictV) else c
                 for c in p[0]]
                for p in partial_sets
            ]
            counts = [p[1] for p in partial_sets]
            pcaps = [p[0][0].validity.shape[0] for p in partial_sets]
            out_cap = choose_capacity(sum(pcaps), bucket_min)
            cols2, mask, _ = concat_ops.concat_padded_cols(
                col_parts, counts, out_cap)
            merged_vals, nseg = agg_once(
                cols2[:nkeys], cols2[nkeys:], merge_ops, mask)
        if eval_exprs is not None:
            ocap = (merged_vals[0].validity.shape[0]
                    if merged_vals else 1)
            return [lower(e, merged_vals, ocap)
                    for e in eval_exprs], nseg
        return merged_vals, nseg

    return update_batch, finish


class TpuHashAggregateExec(TpuExec):
    def __init__(
        self,
        conf: RapidsConf,
        group_exprs: Sequence[E.Expression],
        agg_exprs: Sequence[A.AggregateExpression],
        child: TpuExec,
        mode: str = A.COMPLETE,
    ):
        super().__init__(conf, [child])
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        self.mode = mode
        child_schema = child.output_schema

        # group key output fields. FINAL consumes a partial's
        # [keys..., buffers...] output, where computed key EXPRESSIONS are
        # already evaluated — keys bind positionally there, never by
        # re-binding the original expression (whose input columns no
        # longer exist; reference: the FINAL GpuHashAggregateExec binds
        # against the partial attributes, aggregate.scala:341)
        self._key_fields: List[StructField] = []
        self._bound_keys: List[E.Expression] = []
        for i, g in enumerate(self.group_exprs):
            name = g.name if isinstance(g, (E.UnresolvedAttribute,)) else (
                g.name if isinstance(g, E.Alias) else f"key{i}"
            )
            if self.mode == A.FINAL:
                cf = child_schema.fields[i]
                b: E.Expression = E.BoundReference(
                    i, cf.dataType, cf.nullable)
            else:
                b = E.bind_references(g, child_schema)
            self._key_fields.append(StructField(name, b.dtype, b.nullable))
            self._bound_keys.append(b)

        # bind each aggregate function's input against the child schema so
        # dtype/buffer layout resolve (reference: boundInputReferences in
        # aggregate.scala)
        import dataclasses as _dc

        nk = len(self.group_exprs)
        self._bound_funcs: List[A.AggregateFunction] = []
        bufpos = nk
        for ae in self.agg_exprs:
            f = ae.func
            if f.input is not None:
                if self.mode == A.FINAL:
                    # child emits [keys..., buffers...]: bind the function's
                    # input to its first buffer column so dtype/layout
                    # resolve from the partial's output types
                    bf = child_schema.fields[bufpos]
                    f = _dc.replace(
                        f, child=E.BoundReference(bufpos, bf.dataType, True)
                    )
                else:
                    f = _dc.replace(
                        f, child=E.bind_references(f.child, child_schema)
                    )
            self._bound_funcs.append(f)
            bufpos += f.num_buffers

        # per-function buffer layout
        self._buf_fields: List[StructField] = []
        self._update_exprs: List[Optional[E.Expression]] = []
        self._update_ops: List[str] = []
        self._merge_ops: List[str] = []
        self._buf_slices: List[Tuple[int, int]] = []  # [start, end) per func
        pos = 0
        for ai, f in enumerate(self._bound_funcs):
            ops = f.update_ops
            bs = f.buffer_schema
            self._buf_slices.append((pos, pos + len(ops)))
            for j, ((op, in_expr), bdt) in enumerate(zip(ops, bs)):
                self._buf_fields.append(
                    StructField(f"agg{ai}_buf{j}", bdt, True)
                )
                if in_expr is None:
                    self._update_exprs.append(None)
                else:
                    if self.mode == A.FINAL:
                        # inputs are the buffer columns of the child
                        self._update_exprs.append(None)  # filled below
                    else:
                        self._update_exprs.append(
                            E.bind_references(in_expr, child_schema)
                        )
                self._update_ops.append(op)
                pos += 1
            self._merge_ops.extend(f.merge_ops)

        if self.mode == A.FINAL:
            # child emits [keys..., buffers...]; merge those buffers
            nk = len(self._key_fields)
            self._update_exprs = []
            self._update_ops = list(self._merge_ops)
            for j, bf in enumerate(self._buf_fields):
                cf = child_schema.fields[nk + j]
                self._update_exprs.append(
                    E.BoundReference(nk + j, cf.dataType, True)
                )
            # keys come straight from the child's key columns
            self._bound_keys = [
                E.BoundReference(i, f.dataType, f.nullable)
                for i, f in enumerate(child_schema.fields[:nk])
            ]
            self._key_fields = [
                StructField(kf.name, cf.dataType, cf.nullable)
                for kf, cf in zip(self._key_fields, child_schema.fields[:nk])
            ] if self._key_fields else []

        # output schema
        if self.mode == A.PARTIAL:
            self._schema = StructType(tuple(self._key_fields + self._buf_fields))
        else:
            fields = list(self._key_fields)
            for ae, f in zip(self.agg_exprs, self._bound_funcs):
                fields.append(StructField(ae.resolved_name(), f.dtype, True))
            self._schema = StructType(tuple(fields))

        # the evaluate projection runs over [keys..., buffers...]
        self._buffer_schema = StructType(tuple(self._key_fields + self._buf_fields))
        # aggregation strategy (conf sql.agg.strategy): resolved lazily
        # per capacity bucket — the choice must see the real batch shape —
        # and memoized so AUTO never flips mid-plan (the recompile guard
        # in tests/test_metrics.py pins this)
        self._strategy_by_cap: dict = {}
        self._strategy_choice: Optional[Tuple[str, str]] = None

    @property
    def output_schema(self):
        return self._schema

    def describe(self):
        keys = ", ".join(str(k) for k in self.group_exprs)
        aggs = ", ".join(a.resolved_name() for a in self.agg_exprs)
        strat = (f", strategy={self._strategy_choice[0]}"
                 if self._strategy_choice is not None else "")
        return (f"TpuHashAggregateExec(mode={self.mode}, keys=[{keys}], "
                f"aggs=[{aggs}]{strat})")

    def resolved_strategy(self, cap: int) -> Optional[str]:
        """Resolve (and memoize per capacity bucket) the aggregation
        lowering for this plan. The choice lands in describe() — and thus
        explain_metrics() — and emits ONE 'agg_strategy' event per
        (exec, capacity), so tools/tpu_profile.py can hold the chooser
        accountable against the measured op spans of the same log."""
        if not self.group_exprs:
            return None  # grand aggregates use the plain masked reduces
        hit = self._strategy_by_cap.get(cap)
        if hit is not None:
            return hit
        strategy, reason = choose_agg_strategy(
            self.conf, cap, self._update_ops, self._update_exprs,
            self._key_dtypes())
        self._strategy_by_cap[cap] = strategy
        self._strategy_choice = (strategy, reason)
        from .. import events as _events
        from .. import obs as _obs

        if _events.enabled():
            _events.emit("agg_strategy", op=self.node_name,
                         strategy=strategy, reason=reason, cap=cap)
        if _obs.enabled():
            _obs.inc("tpu_agg_strategy", 1, strategy=strategy)
        return strategy

    # -- helpers -----------------------------------------------------------
    def _key_dtypes(self) -> Tuple[T.DataType, ...]:
        return tuple(f.dataType for f in self._key_fields)

    def _exprs_str_max_lens(self, exprs, batch: ColumnarBatch,
                            direct: bool) -> Tuple[int, ...]:
        """Static byte-length buckets for the string-typed expressions in
        ``exprs`` (host sync only when plain string columns exist).
        ``direct``: batch columns match the bound ordinals; otherwise (a
        fused chain below) any string passed through from a source string
        column, so the max over all source string columns is a safe
        bound."""
        lens = []
        source_max = None
        for b in exprs:
            if isinstance(b.dtype, (T.StringType, T.BinaryType)):
                if direct and isinstance(b, E.BoundReference):
                    col = batch.columns[b.ordinal]
                    if col.is_dict:
                        # dict columns carry a STATIC length bound — the
                        # one case string keys need no host sync at all
                        m = col.dictv.max_len
                    else:
                        m = int(max_string_len(StrV(col.offsets, col.chars, col.validity)))
                else:
                    if source_max is None:
                        ms = [
                            (c.dictv.max_len if c.is_dict else
                             int(max_string_len(
                                 StrV(c.offsets, c.chars, c.validity))))
                            for c in batch.columns if c.is_string
                        ]
                        source_max = max(ms) if ms else 64
                    m = source_max
                lens.append(max(4, choose_capacity(max(1, m), 4)))
        return tuple(lens)

    def _str_max_lens(self, batch: ColumnarBatch, direct: bool) -> Tuple[int, ...]:
        """Static byte-length buckets for string group keys."""
        return self._exprs_str_max_lens(self._bound_keys, batch, direct)

    def _run_batch(self, batch: ColumnarBatch, ops: Sequence[str],
                   value_exprs: Sequence[Optional[E.Expression]],
                   chain=(), live=None, nonnull=None,
                   donate_input: bool = False) -> ColumnarBatch:
        """Aggregate one (source) batch into a [keys..., buffers...] batch,
        fusing any fusable child execs into the same XLA program. The group
        count stays a device scalar — no sync. ``live``: optional (cap,)
        bool mask overriding the batch's prefix row count (used by the
        sync-free merge, where live rows are NOT a prefix).
        ``donate_input``: only the streaming per-batch UPDATE path sets
        it — merge callers re-dispatch the same partials under
        with_oom_retry_nosplit, so their inputs are never dead (the
        agg_merge verdict in plugin/donation.py)."""
        cap = batch.capacity  # batches carry their bucket even zero-column
        sml = self._str_max_lens(batch, direct=not chain)
        # string-typed min/max inputs need a static byte bound for the
        # rank sort (one per such input, in op order)
        minmax_strs = [
            e for op, e in zip(ops, value_exprs)
            if op in ("min", "max") and e is not None
            and isinstance(e.dtype, (T.StringType, T.BinaryType))
        ]
        svml = self._exprs_str_max_lens(minmax_strs, batch,
                                        direct=not chain)
        from ..conf import IMPROVED_FLOAT_OPS

        if nonnull is None:  # cold callers (merge, zero-row grand agg)
            from ..plugin.plananalysis import entry_nonnull_flags

            nonnull = entry_nonnull_flags(batch.schema, self.conf)
        sides = [e.side_vals() for e in chain]
        from .base import _donation

        don = _donation()
        mask = (don.dispatch_mask("agg_update", batch, self.conf)
                if donate_input else ())
        fn = _agg_pipeline(
            chain, tuple(self._bound_keys), self._key_dtypes(),
            tuple(value_exprs), tuple(ops), batch_signature(batch), cap, sml,
            approx_float_sum=self.conf.get(IMPROVED_FLOAT_OPS),
            sides=sides, str_val_max_lens=svml, nonnull=nonnull,
            strategy=self.resolved_strategy(cap), donate=mask,
        )
        nr = (live if live is not None
              else count_scalar(batch.num_rows_lazy))
        if mask:
            # split-and-retry re-dispatches this batch on OOM, so the
            # guard snapshots its planes and restores them on failure
            with don.guard("agg_update", batch, op=self.node_name,
                           conf=self.conf,
                           metric=self.metric("donatedBytes")):
                keys, aggs, nseg = fn(vals_of_batch(batch), nr, sides)
        else:
            keys, aggs, nseg = fn(vals_of_batch(batch), nr, sides)
        vals = list(keys) + list(aggs)
        return batch_from_vals(vals, self._buffer_schema, nseg)

    #: sync-free merges stack partials at CAPACITY; above this many stacked
    #: rows the dead-row blowup outweighs the saved host RTT (low-
    #: cardinality aggregates over many batches), so the synced path wins
    _SYNC_FREE_MERGE_MAX_ROWS = 1 << 24

    def _merge_fixed_width(self, partials: List[ColumnarBatch]) -> ColumnarBatch:
        """Sync-free merge for fixed-width buffer schemas: partials stack
        at capacity on device with a live mask, so row counts never leave
        the device (a host pull costs a full tunnel RTT per batch)."""
        caps = [max(1, b.capacity) for b in partials]
        out_cap = choose_capacity(sum(caps), self.conf.shape_bucket_min)
        cols, mask, total = concat_ops.concat_padded_cols(
            [vals_of_batch(b) for b in partials],
            [count_scalar(b.num_rows_lazy) for b in partials], out_cap)
        merged_in = batch_from_vals(cols, self._buffer_schema, total)
        nk = len(self._key_fields)
        merge_exprs: List[Optional[E.Expression]] = [
            E.BoundReference(nk + j, f.dataType, True)
            for j, f in enumerate(self._buf_fields)
        ]
        saved_bound = self._bound_keys
        self._bound_keys = [
            E.BoundReference(i, f.dataType, f.nullable)
            for i, f in enumerate(self._key_fields)
        ]
        try:
            return self._run_batch(
                merged_in, self._merge_ops, merge_exprs, live=mask)
        finally:
            self._bound_keys = saved_bound

    def _merge(self, partials: List[ColumnarBatch]) -> ColumnarBatch:
        """Concat partial batches and re-aggregate with merge ops
        (reference: concatenateBatches + merge pass, aggregate.scala:451-476).
        A single partial passes through untouched (dict-encoded group keys
        stay encoded); multi-partial merges materialize dict keys — the
        concat kernels splice byte pools."""
        if len(partials) > 1:
            from .base import materialized_batch

            partials = [materialized_batch(b) for b in partials]
        str_cols = [
            j for j, f in enumerate(self._buffer_schema.fields)
            if isinstance(f.dataType, (T.StringType, T.BinaryType))
        ]
        import jax as _jx

        # the sync-free merge stacks partials at CAPACITY to spare a host
        # RTT per batch — the right trade only over a high-latency device
        # link. On the CPU backend the pull is free and the synced path
        # merges at the REAL row counts (~group-count rows, not millions)
        if (len(partials) > 1 and not str_cols
                and _jx.default_backend() != "cpu"
                and sum(max(1, b.capacity) for b in partials)
                <= self._SYNC_FREE_MERGE_MAX_ROWS):
            return self._merge_fixed_width(partials)
        while len(partials) > 1:
            # ONE batched host pull for every row count and string byte
            # length (each separate pull pays a tunnel RTT)
            from .base import host_pull

            head = [count_scalar(b.num_rows_lazy) for b in partials]
            nb = len(partials)
            for b in partials:
                for j in str_cols:
                    c = b.columns[j]
                    nr = b.num_rows_lazy
                    idx = (min(nr, c.offsets.shape[0] - 1)
                           if isinstance(nr, int) else nr)
                    head.append(c.offsets[idx])
            pulled = [int(x) for x in host_pull(head)]
            lengths = pulled[:nb]
            for b, n in zip(partials, lengths):
                if not isinstance(b.num_rows_lazy, int):
                    b._num_rows = n
                    for c in b.columns:
                        c.length = n
            total = sum(lengths)
            out_cap = choose_capacity(total, self.conf.shape_bucket_min)
            ns = len(str_cols)
            byte_lengths = [
                pulled[nb + i * ns : nb + (i + 1) * ns]
                for i in range(nb)
            ]
            out_char_caps = [
                choose_capacity(max(1, sum(bl[k] for bl in byte_lengths)), 128)
                for k in range(len(str_cols))
            ]
            cols, n = concat_ops.concat_batches_cols(
                [vals_of_batch(b) for b in partials], lengths, byte_lengths,
                out_cap, out_char_caps,
            )
            merged_in = batch_from_vals(cols, self._buffer_schema, n)
            nk = len(self._key_fields)
            merge_exprs: List[Optional[E.Expression]] = [
                E.BoundReference(nk + j, f.dataType, True)
                for j, f in enumerate(self._buf_fields)
            ]
            saved_bound = self._bound_keys
            self._bound_keys = [
                E.BoundReference(i, f.dataType, f.nullable)
                for i, f in enumerate(self._key_fields)
            ]
            try:
                partials = [
                    self._run_batch(merged_in, self._merge_ops, merge_exprs)
                ]
            finally:
                self._bound_keys = saved_bound
        return partials[0]

    def _eval_exprs(self) -> List[E.Expression]:
        """Result projection over [keys..., buffers...]."""
        exprs: List[E.Expression] = [
            E.BoundReference(i, f.dataType, f.nullable)
            for i, f in enumerate(self._key_fields)
        ]
        nk = len(self._key_fields)
        for f, (s, e) in zip(self._bound_funcs, self._buf_slices):
            refs = tuple(
                E.BoundReference(nk + j, self._buf_fields[j].dataType, True)
                for j in range(s, e)
            )
            exprs.append(f.evaluate(refs))
        return exprs

    def _evaluate(self, buffers: ColumnarBatch) -> ColumnarBatch:
        """Final projection from [keys..., buffers...] to results."""
        exprs = self._eval_exprs()
        from ..plugin.plananalysis import entry_nonnull_flags
        from .basic import _project_pipeline

        cap = buffers.columns[0].capacity if buffers.columns else 1
        fn = _project_pipeline(
            tuple(exprs), batch_signature(buffers), cap,
            entry_nonnull_flags(buffers.schema, self.conf))
        vals = fn(vals_of_batch(buffers), count_scalar(buffers.num_rows_lazy))
        return batch_from_vals(vals, self._schema, buffers.num_rows_lazy)

    # -- whole-stage fusion ------------------------------------------------
    def _can_fuse_stage(self) -> bool:
        """Fused scan→agg stages cover fixed-width keys/buffers updating
        straight from a source (string keys need a host max-length sync;
        FINAL mode consumes exchanged partials, not a scan)."""
        if self.mode == A.FINAL:
            return False
        return not any(
            isinstance(f.dataType, (T.StringType, T.BinaryType))
            for f in self._buffer_schema.fields
        )

    def _stage_fusion_on(self) -> bool:
        """Conf-gated, backend-adaptive (see sql.stageFusion): fusion buys
        fewer dispatches at the price of re-decoding pages every execution;
        on the CPU backend dispatch is free and the scan cache makes the
        separate decode a one-time cost, so AUTO skips fusion there."""
        from ..conf import STAGE_FUSION

        mode = self.conf.get(STAGE_FUSION)
        if mode != "AUTO":
            return mode == "ON"
        import jax

        return jax.default_backend() != "cpu"

    def _run_fused_stage(self, stage, chain) -> ColumnarBatch:
        """ONE jitted program for the whole stage: per-row-group parquet
        decode → fused child chain → update groupby → padded concat →
        merge groupby → (COMPLETE) result projection. Collapsing the stage
        to a single executable removes every intermediate program boundary
        — each boundary costs a dispatch/queue round trip on the TPU host
        link, and intermediate batches cost extra HBM passes (reference
        contrast: the GPU plan runs one kernel set per exec,
        aggregate.scala:341; TPU+XLA lets the whole stage fuse)."""
        from ..conf import IMPROVED_FLOAT_OPS
        from .base import side_signature

        approx = self.conf.get(IMPROVED_FLOAT_OPS)
        sides = [e.side_vals() for e in chain]
        chain_t = tuple(chain)
        rg_meta = []  # structural identity per row group
        all_args = []
        all_runs = []
        for n, cap, entries in stage:
            rg_meta.append((n, cap, tuple(k for (_, k, _, _) in entries)))
            all_args.append([list(a) for (a, _, _, _) in entries])
            all_runs.append([r for (_, _, r, _) in entries])
        eval_exprs = (tuple(self._eval_exprs())
                      if self.mode != A.PARTIAL else None)
        # one strategy per fused program: resolve at the LARGEST row-group
        # capacity — that is where the reduction cost sits, so a small
        # leading row group must not dictate the lowering for the big ones
        strategy = (self.resolved_strategy(max(c for (_, c, _) in stage))
                    if stage else None)
        key = (
            "stage", tuple(rg_meta),
            tuple(e.fusion_key() for e in chain_t),
            tuple(self._bound_keys), self._key_dtypes(),
            tuple(self._update_exprs), tuple(self._update_ops),
            tuple(self._merge_ops), eval_exprs, self.mode, approx,
            side_signature(sides), self.conf.shape_bucket_min, strategy,
        )
        def build():
            update_batch, finish = _fused_agg_trace(
                tuple(self._bound_keys), self._key_dtypes(),
                tuple(self._update_exprs), tuple(self._update_ops),
                tuple(self._merge_ops), eval_exprs, approx,
                self.conf.shape_bucket_min, chain_t, strategy=strategy)
            metas = tuple(rg_meta)
            runs_t = tuple(tuple(r) for r in all_runs)

            def run(args_nested, side_args):
                from ..ops.filter_gather import live_of

                partial_sets = []
                for (n, cap, _), rg_args, rg_runs in zip(
                        metas, args_nested, runs_t):
                    cols: List[Val] = []
                    for a, r in zip(rg_args, rg_runs):
                        out = r(a)
                        if isinstance(out, DictV):
                            cols.append(out)  # dict-retained string decode
                        else:
                            cols.append(
                                ColV(out[0], out[1]) if len(out) == 2
                                else StrV(out[0], out[1], out[2]))
                    partial_sets.append(
                        update_batch(cols, live_of(n, cap), cap, side_args))
                return finish(partial_sets)

            return jax.jit(run)

        from .base import cached_pipeline

        fn = cached_pipeline(_AGG_CACHE, key, "agg_stage", build)
        vals, nseg = fn(all_args, sides)
        schema = (self._buffer_schema if self.mode == A.PARTIAL
                  else self._schema)
        return batch_from_vals(vals, schema, nseg)

    # -- whole-plan fusion: update+merge+eval as ONE program ---------------
    def _can_fuse_plan(self) -> bool:
        """The fused plan program covers fixed-width keys/buffers (string
        keys need a host max-length sync and the in-trace padded concat
        has no byte-pool splice). Unlike stage fusion it covers FINAL mode
        too — exchanged partials are just fixed-width batches here."""
        return not any(
            isinstance(f.dataType, (T.StringType, T.BinaryType))
            for f in self._buffer_schema.fields
        )

    def _fused_plan_on(self, nbatches: int) -> bool:
        """AGG_FUSED_PLAN gate. AUTO declines only multi-batch runs on the
        CPU backend: the in-trace merge stacks partials at CAPACITY to
        stay sync-free (the right trade over a high-latency device link),
        while the CPU backend's synced merge works at real row counts."""
        from ..conf import AGG_FUSED_PLAN

        mode = self.conf.get(AGG_FUSED_PLAN)
        if mode != "AUTO":
            return mode == "ON"
        import jax as _jx

        return nbatches == 1 or _jx.default_backend() != "cpu"

    def _run_fused_plan(self, batches: List[ColumnarBatch],
                        chain) -> ColumnarBatch:
        """ONE jitted program for the whole aggregate over its input
        batches: per-batch fused child chain -> key/value projection ->
        update groupby, a padded concat of the partials, the merge
        groupby, and (non-PARTIAL) the result projection. The update and
        merge passes of the round-5 engine were separate executables with
        the partial batches crossing a program boundary between them;
        collapsing them removes every intermediate dispatch/queue round
        trip AND the intermediate partials' extra HBM round trips, and
        batches dispatch as ONE async program — no host sync anywhere
        (group counts stay device scalars). Profiler evidence for why:
        see docs/tuning.md (the agg shape's device time was dominated by
        per-program dispatch gaps, not kernel time)."""
        from ..conf import IMPROVED_FLOAT_OPS
        from .base import side_signature

        approx = self.conf.get(IMPROVED_FLOAT_OPS)
        sides = [e.side_vals() for e in chain]
        chain_t = tuple(chain)
        sigs = tuple(batch_signature(b) for b in batches)
        caps = tuple(
            b.capacity if b.columns else choose_capacity(
                b.num_rows, self.conf.shape_bucket_min)
            for b in batches
        )
        eval_exprs = (tuple(self._eval_exprs())
                      if self.mode != A.PARTIAL else None)
        # one strategy per fused program, resolved at the LARGEST batch
        # capacity (a small first batch must not pick the lowering for
        # the big ones; see _run_fused_stage)
        strategy = self.resolved_strategy(max(caps)) if caps else None
        key = (
            "plan", sigs, caps, tuple(e.fusion_key() for e in chain_t),
            tuple(self._bound_keys), self._key_dtypes(),
            tuple(self._update_exprs), tuple(self._update_ops),
            tuple(self._merge_ops), eval_exprs, self.mode, approx,
            side_signature(sides), self.conf.shape_bucket_min, strategy,
        )
        def build():
            update_batch, finish = _fused_agg_trace(
                tuple(self._bound_keys), self._key_dtypes(),
                tuple(self._update_exprs), tuple(self._update_ops),
                tuple(self._merge_ops), eval_exprs, approx,
                self.conf.shape_bucket_min, chain_t, strategy=strategy)
            caps_t = caps

            def run(all_cols, all_nr, side_args):
                from ..ops.filter_gather import live_of

                partial_sets = [
                    update_batch(cols, live_of(nr, cap), cap, side_args)
                    for cols, nr, cap in zip(all_cols, all_nr, caps_t)
                ]
                return finish(partial_sets)

            return jax.jit(run, donate_argnums=mask)

        from .base import _donation, cached_pipeline

        don = _donation()
        # argnum 0 is EVERY buffered batch's plane pytree: the mask is
        # non-empty only when all of them are donatable, because one
        # shared batch in the list poisons the whole dispatch
        mask = don.dispatch_mask("agg_plan", batches, self.conf)
        fn = cached_pipeline(_AGG_CACHE, key, "agg_plan", build,
                             donate=mask)
        all_nr = [count_scalar(b.num_rows_lazy) for b in batches]
        if mask:
            # the device-OOM fallback (flush_buffered) re-reads the
            # buffered batches, so the guard snapshots/restores them
            with don.guard("agg_plan", batches, op=self.node_name,
                           conf=self.conf,
                           metric=self.metric("donatedBytes")):
                vals, nseg = fn(
                    [vals_of_batch(b) for b in batches], all_nr, sides)
        else:
            vals, nseg = fn(
                [vals_of_batch(b) for b in batches], all_nr, sides)
        schema = (self._buffer_schema if self.mode == A.PARTIAL
                  else self._schema)
        return don.mark_exclusive(batch_from_vals(vals, schema, nseg))

    #: fused-plan guard: above this many stacked capacity rows the
    #: in-trace padded merge's dead-row blowup outweighs the saved
    #: dispatches, so the per-batch path (and its synced/sync-free merge
    #: choice) takes over
    _FUSED_PLAN_MAX_ROWS = 1 << 24
    #: fused-plan guard: the trace unrolls one update pass per batch and
    #: the cache key carries every batch's signature — past this many
    #: batches the compile blowup and near-zero cache reuse beat the
    #: saved dispatches
    _FUSED_PLAN_MAX_BATCHES = 16
    #: fused-plan guard: buffered INPUT batches (which may carry wide
    #: string columns even when the buffer schema is fixed-width) may pin
    #: at most this many bytes of device memory before the streaming
    #: per-batch path takes over
    _FUSED_PLAN_MAX_BYTES = 2 << 30

    # -- execution ---------------------------------------------------------
    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        partials: List[ColumnarBatch] = []
        ops = self._update_ops
        exprs = self._update_exprs
        # fuse any fusable execs below us into the update dispatch
        child = self.children[0]
        if child.fusable:
            source, chain = child.fused_source_chain()
        else:
            source, chain = child, ()
        if chain and any(
            op in ("min", "max") and e is not None
            and isinstance(e.dtype, (T.StringType, T.BinaryType))
            for op, e in zip(ops, exprs)
        ):
            # string min/max needs an EXACT byte bound for its rank sort.
            # Under a fused chain the bound is measured on the SOURCE
            # batch, which under-bounds a string computed by a projection
            # below us (concat/pad can grow past every source column and
            # the rank would compare only a prefix — silently wrong
            # winners). Run the chain as real execs instead: the value is
            # then a direct column of OUR input batch and its measured
            # max length is exact.
            source, chain = child, ()
        fsp = getattr(source, "fused_stage_plans", None)
        if fsp is not None and self._can_fuse_stage() and self._stage_fusion_on():
            stage = fsp(index)
            if stage:
                with self.op_timed("stage"):
                    out = self._run_fused_stage(stage, tuple(chain))
                yield self.record_batch(out)
                return
        # fused-plan buffering is INCREMENTAL: ineligible plans (OFF mode,
        # string keys/buffers) never buffer raw batches at all, and an
        # eligible run that outgrows the guards (rows, batch count,
        # AUTO-on-CPU multi-batch) flushes its buffer into streaming
        # per-batch updates — peak memory stays one input batch + partials
        # exactly as round 5, except for the bounded window the fused
        # program needs.
        from ..conf import AGG_FUSED_PLAN

        from .base import batch_bytes

        fp_mode = self.conf.get(AGG_FUSED_PLAN)
        use_fused = fp_mode != "OFF" and self._can_fuse_plan()
        batches: List[ColumnarBatch] = []
        cap_sum = 0
        byte_sum = 0
        # per-partition constant: the source schema's elision flags
        # (recomputing per batch would put a conf+schema walk on the
        # per-batch dispatch hot path)
        from ..memory.retry import is_device_oom, with_oom_retry
        from ..plugin.plananalysis import entry_nonnull_flags

        src_nonnull = entry_nonnull_flags(source.output_schema, self.conf)

        def update_with_retry(b):
            # the per-batch update under the OOM harness: a split hands
            # back one partial PER HALF — exactly what the merge path
            # already consumes (combine="list"), so the aggregate
            # completes on half-capacity update programs
            partials.extend(with_oom_retry(
                self.node_name,
                lambda piece: self._run_batch(
                    piece, ops, exprs, tuple(chain), nonnull=src_nonnull,
                    donate_input=True),
                b, self.conf, combine="list",
                on_pressure=getattr(source, "invalidate_prefetch", None)))

        def flush_buffered():
            for b in batches:
                with self.op_timed("update"):
                    update_with_retry(b)
            batches.clear()

        for batch in source.execute_partition(index):
            nr = batch.num_rows_lazy
            if isinstance(nr, int) and nr == 0 and self.group_exprs and not chain:
                continue
            if not use_fused:
                with self.op_timed("update"):
                    update_with_retry(batch)
                continue
            batches.append(batch)
            cap_sum += max(1, batch.capacity if batch.columns else 1)
            byte_sum += batch_bytes(batch)
            if (cap_sum > self._FUSED_PLAN_MAX_ROWS
                    or byte_sum > self._FUSED_PLAN_MAX_BYTES
                    or len(batches) > self._FUSED_PLAN_MAX_BATCHES
                    or not self._fused_plan_on(len(batches))):
                use_fused = False
                flush_buffered()
        if use_fused and batches:
            try:
                with self.op_timed("plan"):
                    from .. import faults as _faults

                    if _faults.enabled():
                        # the fused whole-plan program is the aggregate's
                        # pipeline-dispatch boundary when it runs —
                        # injected OOMs must reach it (the recovery is
                        # the flush-to-streaming fallback below)
                        _faults.check(
                            "oom", self.node_name,
                            cap=max(b.capacity for b in batches))
                    out = self._run_fused_plan(batches, tuple(chain))
                yield self.record_batch(out)
                return
            except Exception as e:  # noqa: BLE001 - filtered below
                from ..memory.retry import OOM_RETRY_ENABLED

                if not is_device_oom(e) \
                        or not self.conf.get(OOM_RETRY_ENABLED):
                    # oomRetry.enabled off = the raw pre-recovery
                    # behavior everywhere, fallback included
                    raise
                # the whole-plan fused program (every batch stacked into
                # one trace) exhausted device memory: degrade to the
                # streaming per-batch path, whose updates run under the
                # retry/split harness individually
                from ..memory.retry import _emit_retry

                _emit_retry(self.node_name, "fused_plan_fallback", 1, 0)
                flush_buffered()
        if not partials:
            if self.group_exprs:
                return  # grouped aggregate over empty input -> no rows
            # grand aggregate over empty input still yields one row
            # (count=0, sum=null): reduce a zero-row batch
            child_schema = self.children[0].output_schema
            zb = ColumnarBatch.from_pydict(
                {f.name: [] for f in child_schema.fields}, child_schema
            )
            with self.op_timed("update"):
                partials = [self._run_batch(zb, ops, exprs)]
        from ..memory.retry import with_oom_retry_nosplit

        def merge_and_eval():
            merged = self._merge(partials)
            return merged if self.mode == A.PARTIAL \
                else self._evaluate(merged)

        with self.op_timed("merge"):
            # the merge consumes compacted partials (group-cardinality
            # sized, not input sized) — not meaningfully splittable, so
            # it gets the retry-only harness: spill + backoff, then the
            # typed TpuRetryOOM verdict
            out = with_oom_retry_nosplit(
                self.node_name + ".merge", merge_and_eval, self.conf)
        # the merged/evaluated output leaves this generator as its only
        # live reference (the partials list is never read again after
        # the yield), so downstream certified sites may donate it
        from .base import _donation

        yield self.record_batch(_donation().mark_exclusive(out))
