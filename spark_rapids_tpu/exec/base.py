"""Exec base class, metrics, and batch<->traced-value plumbing.

Reference analog: GpuExec.scala:27-150 — the metric names/builders
(GpuMetricNames) and the ``doExecuteColumnar(): RDD[ColumnarBatch]``
contract. Here the unit of data parallelism is the partition index; an exec
exposes ``num_partitions`` and ``execute_partition(i)`` and the driver (or
the exchange layer) decides where partitions run.
"""
from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import jax

from ..columnar import ColumnarBatch, DeviceColumn
from ..conf import RapidsConf
from ..expr.eval import ColV, StrV, Val
from ..types import StructType

# Standard metric names (reference: GpuMetricNames in GpuExec.scala:27-60)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
TOTAL_TIME = "totalTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"


class Metric:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, v: int) -> None:
        self.value += v

    def set(self, v: int) -> None:
        self.value = v

    def __repr__(self):
        return f"{self.name}={self.value}"


@contextlib.contextmanager
def timed(metric: Optional[Metric], trace_name: str = "", trace: bool = False):
    """Time a hot section into a metric; optionally emit a profiler range
    (reference: NvtxWithMetrics.scala -> jax.profiler.TraceAnnotation)."""
    ctx = (
        jax.profiler.TraceAnnotation(trace_name or (metric.name if metric else "op"))
        if trace
        else contextlib.nullcontext()
    )
    start = time.perf_counter_ns()
    with ctx:
        yield
    if metric is not None:
        metric.add(time.perf_counter_ns() - start)


class TpuExec:
    """Base physical operator producing columnar batches on TPU."""

    def __init__(self, conf: RapidsConf, children: Sequence["TpuExec"] = ()):
        self.conf = conf
        self.children: List[TpuExec] = list(children)
        self.metrics: Dict[str, Metric] = {}
        for name in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, TOTAL_TIME):
            self.metrics[name] = Metric(name)

    # -- contracts ---------------------------------------------------------
    @property
    def output_schema(self) -> StructType:
        raise NotImplementedError(type(self).__name__)

    @property
    def num_partitions(self) -> int:
        if self.children:
            return self.children[0].num_partitions
        return 1

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        raise NotImplementedError(type(self).__name__)

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """All partitions, serially (driver-side collect path)."""
        for p in range(self.num_partitions):
            yield from self.execute_partition(p)

    # -- conveniences ------------------------------------------------------
    def metric(self, name: str) -> Metric:
        if name not in self.metrics:
            self.metrics[name] = Metric(name)
        return self.metrics[name]

    def record_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        self.metrics[NUM_OUTPUT_ROWS].add(batch.num_rows)
        self.metrics[NUM_OUTPUT_BATCHES].add(1)
        return batch

    def collect(self) -> List[tuple]:
        """Columnar-to-row boundary for the whole plan
        (reference: GpuColumnarToRowExec / GpuBringBackToHost)."""
        rows: List[tuple] = []
        for batch in self.execute_columnar():
            rows.extend(batch.to_rows())
        return rows

    @property
    def node_name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.node_name

    def __repr__(self):
        return self.tree_string()


# ---------------------------------------------------------------------------
# ColumnarBatch <-> traced value plumbing
# ---------------------------------------------------------------------------
def vals_of_batch(batch: ColumnarBatch) -> List[Val]:
    out: List[Val] = []
    for c in batch.columns:
        if c.is_string:
            out.append(StrV(c.offsets, c.chars, c.validity))
        else:
            out.append(ColV(c.data, c.validity))
    return out


def batch_from_vals(
    vals: Sequence[Val], schema: StructType, num_rows: int
) -> ColumnarBatch:
    cols = []
    for f, v in zip(schema.fields, vals):
        if isinstance(v, StrV):
            cols.append(
                DeviceColumn(f.dataType, num_rows, None, v.validity, v.offsets, v.chars)
            )
        else:
            cols.append(DeviceColumn(f.dataType, num_rows, v.data, v.validity))
    return ColumnarBatch(cols, schema, num_rows)


def batch_signature(batch: ColumnarBatch) -> tuple:
    """Structural cache key for compiled per-exec pipelines: dtype + shapes."""
    sig = []
    for f, c in zip(batch.schema.fields, batch.columns):
        if c.is_string:
            sig.append((f.dataType, int(c.offsets.shape[0]), int(c.chars.shape[0])))
        else:
            sig.append((f.dataType, int(c.data.shape[0])))
    return tuple(sig)
