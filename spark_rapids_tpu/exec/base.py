"""Exec base class, metrics, and batch<->traced-value plumbing.

Reference analog: GpuExec.scala:27-150 — the metric names/builders
(GpuMetricNames) and the ``doExecuteColumnar(): RDD[ColumnarBatch]``
contract. Here the unit of data parallelism is the partition index; an exec
exposes ``num_partitions`` and ``execute_partition(i)`` and the driver (or
the exchange layer) decides where partitions run.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax

from .. import events as _events
from .. import faults as _faults
from .. import obs as _obs
from .. import xla_cost as _xla_cost
from ..serve import program_cache as _progcache
from ..columnar import ColumnarBatch, DeviceColumn
from ..conf import RapidsConf
from ..expr.eval import ColV, DictV, StrV, Val
from ..types import StructType
from ..utils.locks import ordered_lock

# Standard metric names (reference: GpuMetricNames in GpuExec.scala:27-60)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
TOTAL_TIME = "totalTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
#: device-accurate per-op time under metrics.deviceSync.enabled (the
#: block-until-ready wait for the op's own output; see RapidsConf doc)
OP_TIME_DEVICE = "opTimeDevice"
#: output bytes per op: rows x row-bytes from the batch layout
BYTES_TOUCHED = "bytesTouched"


# ---------------------------------------------------------------------------
# Compile cache-miss accounting (profiler): every pipeline cache in the
# engine notes its misses here, so a recompile storm (ragged shapes, a
# fusion key that churns) is visible in explain_metrics() instead of only
# as mysterious wall-clock (reference contrast: the JVM plugin surfaces
# cudf JIT compiles in its buildTime metric).
# ---------------------------------------------------------------------------
class CompileCounter:
    __slots__ = ("total", "by_site", "_lock")

    def __init__(self):
        self.total = 0
        self.by_site: Dict[str, int] = {}
        # concurrent sessions compile concurrently: unguarded += would
        # lose counts and break the recompile-guard tests' exact deltas
        self._lock = ordered_lock("exec.compile_counter")

    def note(self, site: str) -> None:
        with self._lock:
            self.total += 1
            self.by_site[site] = self.by_site.get(site, 0) + 1

    def snapshot(self) -> tuple:
        with self._lock:
            return self.total, dict(self.by_site)


COMPILE_COUNTER = CompileCounter()


# ---------------------------------------------------------------------------
# Shared guard for the process-global jit pipeline caches. Every cache in
# the engine (fused_chain/project/agg/mesh/exchange/pq_decode/
# upload_unpack) had the same get-then-build shape, which under
# concurrent sessions is a check-then-act race: two threads both see a
# miss, both count it, and both build — the recompile guarantees ("this
# plan compiles exactly once") silently break. One helper, one lock:
# the fast path stays a lock-free dict read (GIL-atomic), the slow path
# double-checks under the lock before counting + building. Builders only
# CONSTRUCT the jitted callable (tracing/compilation is deferred to the
# first call, which jax serializes internally), so holding the lock
# across build() is cheap.
# ---------------------------------------------------------------------------
_PIPELINE_CACHE_LOCK = ordered_lock("exec.pipeline_cache", reentrant=True)

#: cache dicts that have passed through cached_pipeline (dedup by
#: identity, O(1) via the id set) — the clear_pipeline_caches() sweep
#: set. BOUNDED: most caches are module globals (~15 across the
#: engine), but sort/window/join/exchange also route per-INSTANCE
#: ``self._jits`` dicts through here, and registering those forever
#: would pin every exec instance's compiled executables for the
#: process lifetime (dicts aren't weakref-able). Past the cap new
#: dicts simply aren't registered — they stay collectable with their
#: owners, and the sweep (a test/maintenance helper) loses nothing it
#: needs: a fresh session builds fresh exec instances anyway.
_PIPELINE_CACHE_REGISTRY_CAP = 64
_ALL_PIPELINE_CACHES: List[dict] = []
_ALL_PIPELINE_CACHE_IDS: set = set()


def clear_pipeline_caches() -> int:
    """Drop every in-memory compiled-pipeline entry (returns how many).
    Test/maintenance helper: a cleared process re-enters the compile
    path on its next batch — with the persistent AOT program cache
    (serve/program_cache.py) enabled that path is a disk lookup, which
    is exactly how the warm-hit tests exercise it in-process."""
    with _PIPELINE_CACHE_LOCK:
        n = sum(len(c) for c in _ALL_PIPELINE_CACHES)
        for c in _ALL_PIPELINE_CACHES:
            c.clear()
        return n


def cached_pipeline(cache: dict, key, site: Optional[str],
                    build: Callable[[], Callable],
                    max_entries: int = 512,
                    donate: Tuple[int, ...] = ()) -> Callable:
    if donate:
        # the donation mask is part of the program's identity: a
        # donating and a non-donating dispatch of the same logical
        # pipeline are DIFFERENT executables (input/output aliasing
        # differs), and the fold below also reaches the AOT
        # program-cache entry name (entry_name hashes the key repr) so
        # a warm process can never load a non-donating export into a
        # donating call site. tools/tpu_donate.py TPU203 flags any
        # donate_argnums declared outside this chokepoint.
        key = (key, ("donate", tuple(donate)))
    fn = cache.get(key)
    if fn is not None:
        return fn
    with _PIPELINE_CACHE_LOCK:
        fn = cache.get(key)
        if fn is None:
            if (id(cache) not in _ALL_PIPELINE_CACHE_IDS
                    and len(_ALL_PIPELINE_CACHES)
                    < _PIPELINE_CACHE_REGISTRY_CAP):
                _ALL_PIPELINE_CACHES.append(cache)
                _ALL_PIPELINE_CACHE_IDS.add(id(cache))
            if len(cache) > max_entries:
                cache.clear()
            pc = (_progcache.active()
                  if site is not None and _progcache.enabled() else None)
            if pc is not None:
                # persistent AOT program cache (serve/program_cache.py):
                # a disk hit deserializes the executable — no trace, no
                # backend compile, no compile_miss — and re-emits the
                # persisted cost payload flagged from_cache at first
                # call. Anything else (entry absent, corrupt, identity
                # mismatch) returns None and the plain path below runs.
                fn = pc.lookup(site, key, build, donate=donate)
            if fn is None:
                if _faults.enabled():
                    # injected compile failure (chaos testing): raised
                    # BEFORE the miss is counted or the entry installed,
                    # so a failed build never pollutes the cache or the
                    # miss accounting
                    _faults.check("compile", site or "<anon>")
                if site is not None:
                    note_compile_miss(site)
                if pc is not None:
                    # miss with the cache on: the store probe exports +
                    # persists at first call AND subsumes the cost-plane
                    # harvest (it falls back to xla_cost.wrap itself for
                    # programs that cannot participate)
                    fn = pc.wrap_store(build(), site, key, donate=donate)
                else:
                    # compiled-program cost plane (xla_cost.py): while a
                    # cost consumer is active (events / obs / the
                    # bench-harness FORCE_HARVEST hook), the fresh jit
                    # callable is wrapped so its first call times
                    # trace+compile separately and harvests
                    # cost_analysis()/memory_analysis() into ONE
                    # program_cost record; with everything off (the
                    # default) wrap() returns the value untouched and
                    # cost_analysis is never called
                    fn = _xla_cost.wrap(build(), site, key)
            cache[key] = fn
    return fn


def note_compile_miss(site: str) -> None:
    COMPILE_COUNTER.note(site)
    # misses are rare (that's the point); the event names the site so the
    # offline profiler can attribute recompile storms without a rerun
    _events.emit("compile_miss", site=site, total=COMPILE_COUNTER.total)
    if _obs.enabled():
        # live twin: the registry's miss ring feeds the watchdog's
        # recompile-storm window
        _obs.note_compile_miss(site)


def compile_miss_count() -> int:
    """Total pipeline-cache misses so far (tests snapshot/diff this to
    guard against recompile regressions)."""
    return COMPILE_COUNTER.total


# ---------------------------------------------------------------------------
# Sanctioned device→host sync points. EVERY host pull in exec/ops/expr
# goes through these two helpers (tools/tpu_lint.py enforces it): a sync
# costs a full tunnel RTT, so funneling them here keeps the hot path
# auditable — grep for host_pull and you have the complete sync story.
# ---------------------------------------------------------------------------
def host_pull(tree):
    """ONE batched device→host transfer of a pytree of arrays.

    Callers batch every scalar they need into a single call (a list) —
    each separate pull pays a tunnel round trip. This is the only
    sanctioned way to read device values on the host outside this
    module; tools/tpu_lint.py flags raw jax.device_get/.item() sites."""
    out = jax.device_get(tree)
    if _events.enabled() or _obs.enabled():
        nb = sum(int(getattr(a, "nbytes", 0))
                 for a in jax.tree_util.tree_leaves(out))
        _events.emit("transfer", direction="d2h", bytes=nb,
                     site="host_pull")
        if _obs.enabled():
            _obs.inc("tpu_transfers", 1, direction="d2h")
            _obs.inc("tpu_transfer_bytes", nb, direction="d2h")
    return out


def host_fence(arrays):
    """Block until the given device buffers are computed (the profiling /
    ordering fence; the device-sync metric path uses it). Returns the
    arrays so call sites can chain."""
    out = jax.block_until_ready(arrays)
    if _events.enabled():
        _events.emit("transfer", direction="fence", bytes=0,
                     site="host_fence")
    if _obs.enabled():
        _obs.inc("tpu_transfers", 1, direction="fence")
    return out


_PLANNING = threading.local()


@contextlib.contextmanager
def planning_mode():
    """Marks plan CONSTRUCTION: adaptive reads report their static
    partition count instead of materializing their stage (reference: AQE
    only re-plans at stage boundaries during execution, never in
    explain)."""
    prev = getattr(_PLANNING, "on", False)
    _PLANNING.on = True
    try:
        yield
    finally:
        _PLANNING.on = prev


def in_planning() -> bool:
    return getattr(_PLANNING, "on", False)


class Metric:
    """One named counter. ``kind`` drives explain_metrics() formatting:
    'ns' (rendered as ms), 'bytes', or 'count'; inferred from the name so
    lazily-created metrics format like registered ones."""

    __slots__ = ("name", "value", "kind")

    def __init__(self, name: str, kind: Optional[str] = None):
        self.name = name
        self.value = 0
        if kind is None:
            if "Time" in name or name == TOTAL_TIME:
                kind = "ns"
            elif name.startswith("bytes") or name.endswith("Bytes"):
                kind = "bytes"
            else:
                kind = "count"
        self.kind = kind

    def add(self, v: int) -> None:
        self.value += v

    def set(self, v: int) -> None:
        self.value = v

    def pretty(self) -> str:
        if self.kind == "ns":
            return f"{self.value / 1e6:.1f}ms"
        if self.kind == "bytes":
            return f"{self.value / 1e6:.1f}MB"
        return str(self.value)

    def __repr__(self):
        return f"{self.name}={self.value}"


@contextlib.contextmanager
def timed(metric: Optional[Metric], trace_name: str = "", trace: bool = False,
          event_op: Optional[str] = None, event_section: str = ""):
    """Time a hot section into a metric; optionally emit a profiler range
    (reference: NvtxWithMetrics.scala -> jax.profiler.TraceAnnotation).
    ``event_op`` (set only while event logging is on) additionally emits a
    host-lane ``op_span`` event, so the offline timeline shares the same
    start/dur the metric accumulated."""
    ctx = (
        jax.profiler.TraceAnnotation(trace_name or (metric.name if metric else "op"))
        if trace
        else contextlib.nullcontext()
    )
    start = time.perf_counter_ns()
    with ctx:
        yield
    dur = time.perf_counter_ns() - start
    if metric is not None:
        metric.add(dur)
    if event_op is not None:
        _events.emit("op_span", op=event_op, section=event_section,
                     start=start, dur=dur, lane="host")


@contextlib.contextmanager
def _op_scoped(inner, op: str):
    """Cost-plane attribution wrapper (built only while a cost consumer
    is on): programs compiled inside this exec's hot section record
    op=<node_name> so the roofline report can join XLA bytes/flops
    against the op's measured device lane."""
    with _xla_cost.op_scope(op):
        with inner:
            yield


@contextlib.contextmanager
def _obs_timed(inner, op: str, section: str):
    """op_timed's live-metrics wrapper (built ONLY while the obs plane is
    on — the disabled fast path returns the plain timed() context): the
    open-span table is what the watchdog samples for stall detection, so
    registration must precede the body, not follow it."""
    token = _obs.span_open(op, section)
    start = time.perf_counter_ns()
    try:
        with inner:
            yield
    finally:
        _obs.span_close(token)
        _obs.add_op_time(op, "host", time.perf_counter_ns() - start)


class TpuExec:
    """Base physical operator producing columnar batches on TPU.

    Whole-stage fusion (TPU-first design, no reference analog): execs that
    set ``fusable`` and implement ``lower_batch``/``fusion_key`` are traced
    together into ONE XLA program per maximal single-child chain — project,
    filter, and the aggregate's update step all fuse, so a scan->filter->
    project->aggregate pipeline is a single device dispatch with zero
    intermediate host syncs (row counts ride along as device scalars).
    The reference launches one cudf kernel per expression node instead.
    """

    #: True when this exec can lower into a shared fused trace
    fusable = False

    def __init__(self, conf: RapidsConf, children: Sequence["TpuExec"] = ()):
        from ..conf import ENABLE_TRACE, METRICS_DEVICE_SYNC

        self.conf = conf
        self.children: List[TpuExec] = list(children)
        self.metrics: Dict[str, Metric] = {}
        self._trace = conf.get(ENABLE_TRACE)
        self._device_sync = conf.get(METRICS_DEVICE_SYNC)
        for name in (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, TOTAL_TIME):
            self._register_metric(name)

    # -- contracts ---------------------------------------------------------
    @property
    def output_schema(self) -> StructType:
        raise NotImplementedError(type(self).__name__)

    @property
    def num_partitions(self) -> int:
        if self.children:
            return self.children[0].num_partitions
        return 1

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        raise NotImplementedError(type(self).__name__)

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """All partitions, serially (driver-side collect path).

        Each partition holds the TPU concurrency semaphore while its device
        work runs (reference: GpuSemaphore.acquireIfNecessary before the
        first device allocation of a task, released at task end)."""
        from ..memory import TpuSemaphore

        sem = TpuSemaphore.initialize(self.conf)
        for p in range(self.num_partitions):
            sem.acquire_if_necessary()
            try:
                yield from self.execute_partition(p)
            finally:
                sem.release_if_necessary()

    def host_prefetch(self) -> None:
        """Serving-path pipelining hook: start this plan's host-side work
        (file reads, parquet decode on the shared pools) BEFORE the
        caller takes the device semaphore, so an admitted query's host
        phase overlaps the running query's device compute. Default:
        recurse — scans override (exec/scan.py). Must not block on the
        work it starts and must be safe to call at most once per plan."""
        for c in self.children:
            c.host_prefetch()

    #: True when lower_batch may clear liveness bits (filters); tells the
    #: chain driver a final compaction is needed for standalone output
    sparsifies = False

    # -- fusion ------------------------------------------------------------
    def fusion_key(self) -> tuple:
        """Structural identity of this exec's lowering (cache key part)."""
        raise NotImplementedError(type(self).__name__)

    def lower_batch(self, cols, live, cap, side=()):
        """Pure traced transform: (cols, live_mask) -> (cols, live_mask).

        ``live`` is a (cap,) bool mask — filters just clear bits instead of
        gathering rows (TPU gathers are slow; reductions consume the mask
        for free). Compaction happens only at chain boundaries that need
        dense batches.

        ``side``: this exec's :meth:`side_vals` arrays as traced jit
        ARGUMENTS (e.g. a join's build-side table) — passing them as args
        instead of closure constants keeps one compiled chain serving
        every build."""
        raise NotImplementedError(type(self).__name__)

    def side_vals(self) -> tuple:
        """Device arrays this exec's ``lower_batch`` needs beyond the
        child batch (passed through the fused jit as arguments)."""
        return ()

    def fusion_stream_child(self) -> Optional["TpuExec"]:
        """The child whose batches stream through this exec's lowering.
        Single-child execs stream their only child; a fast-path join
        streams its probe side (the build side enters via side_vals)."""
        return self.children[0] if len(self.children) == 1 else None

    def fused_source_chain(self):
        """(source exec, [fusable execs bottom-up ending at self])."""
        node = self
        chain: List[TpuExec] = []
        while node.fusable:
            nxt = node.fusion_stream_child()
            if nxt is None:
                break
            chain.append(node)
            node = nxt
        return node, list(reversed(chain))

    # -- conveniences ------------------------------------------------------
    def _register_metric(self, name: str, kind: Optional[str] = None) -> Metric:
        """THE metric construction path — constructor-declared and
        lazily-created metrics both land here, so every metric carries a
        kind and shows up in explain_metrics()."""
        m = Metric(name, kind)
        self.metrics[name] = m
        return m

    def metric(self, name: str, kind: Optional[str] = None) -> Metric:
        if name not in self.metrics:
            return self._register_metric(name, kind)
        return self.metrics[name]

    def op_timed(self, section: str = "", metric_name: str = TOTAL_TIME):
        """Shared hot-section timer: host wall-clock into ``metric_name``
        plus a profiler TraceAnnotation named after the exec when
        sql.trace.enabled is on — EVERY exec wraps its per-batch device
        work in this (reference: NvtxWithMetrics.scala pairing each hot
        section with a GpuMetric + NVTX range)."""
        name = self.node_name + ("." + section if section else "")
        # event args attach only while logging is on, so the disabled fast
        # path is byte-for-byte the pre-event-log behavior
        ctx = timed(self.metric(metric_name), name, self._trace,
                    event_op=self.node_name if _events.enabled() else None,
                    event_section=section)
        if _obs.enabled():
            # live plane: per-op time counters + the open-span table the
            # stall watchdog samples (wrapper only exists while obs is on)
            ctx = _obs_timed(ctx, self.node_name, section)
        if (_xla_cost.harvesting() or _events.enabled()
                or _obs.enabled()):
            # ambient op attribution has two consumers: the cost-plane
            # harvester (programs compiled in this hot section record
            # op=<node_name>) and the HBM ledger (buffers registered in
            # it carry an owning op — the ledger arms exactly when
            # events or obs are on, so ride the same gates); the
            # disabled fast path stays the plain timed() context
            ctx = _op_scoped(ctx, self.node_name)
        return ctx

    def record_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        nr = batch.num_rows_lazy
        if self._device_sync:
            # device-accurate op timing: the wait-for-output fence. With
            # the conf on plan-wide, inputs were already fenced by the
            # child's record_batch, so this wait is THIS op's device time
            # (+ one dispatch) — the CUDA-event-timing analog.
            t0 = time.perf_counter_ns()
            jax.block_until_ready(batch_arrays(batch))
            dt = time.perf_counter_ns() - t0
            self.metric(OP_TIME_DEVICE, "ns").add(dt)
            if _obs.enabled():
                _obs.add_op_time(self.node_name, "device", dt)
            if _events.enabled():
                # the device lane: THIS op's isolated device wait (inputs
                # were fenced by the child's record_batch under the
                # plan-wide conf — see the deviceSync doc)
                _events.emit("op_span", op=self.node_name,
                             section="device_wait", start=t0, dur=dt,
                             lane="device")
            if not isinstance(nr, int):
                nr = int(jax.device_get(nr))  # free: buffers are ready
        if isinstance(nr, int):
            self.metrics[NUM_OUTPUT_ROWS].add(nr)
        self.metrics[NUM_OUTPUT_BATCHES].add(1)
        by = batch_bytes(batch, nr if isinstance(nr, int) else None)
        self.metric(BYTES_TOUCHED, "bytes").add(by)
        if _events.enabled():
            _events.emit("op_batch", op=self.node_name,
                         rows=nr if isinstance(nr, int) else None, bytes=by)
        if _obs.enabled():
            # live counters + the per-query progress numerators /status
            # divides into the analyzer's row/batch forecasts
            _obs.note_op_batch(self.node_name,
                               nr if isinstance(nr, int) else None, by)
        return batch

    def collect(self) -> List[tuple]:
        """Columnar-to-row boundary for the whole plan
        (reference: GpuColumnarToRowExec / GpuBringBackToHost)."""
        rows: List[tuple] = []
        for batch in self.execute_columnar():
            rows.extend(batch.to_rows())
        return rows

    @property
    def node_name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.node_name

    def __repr__(self):
        return self.tree_string()


# ---------------------------------------------------------------------------
# Profiler plumbing: batch introspection + the explain_metrics report
# ---------------------------------------------------------------------------
def batch_arrays(batch: ColumnarBatch) -> List:
    """Every device buffer a batch owns (the block_until_ready fence set)."""
    out: List = []
    for c in batch.columns:
        if c.is_dict:
            d = c.dictv
            out.extend((d.codes, d.dictionary.offsets, d.dictionary.chars,
                        d.validity))
        elif c.is_string:
            out.extend((c.offsets, c.chars, c.validity))
        else:
            out.extend((c.data, c.validity))
    nr = batch.num_rows_lazy
    if not isinstance(nr, int):
        out.append(nr)
    return out


def batch_bytes(batch: ColumnarBatch, rows: Optional[int] = None) -> int:
    """rows x row-bytes from the batch layout: fixed-width columns count
    their storage width + 1 validity byte per row; strings add 4 offset
    bytes plus their chars pool; dict columns count 4 code bytes plus the
    dictionary. ``rows`` falls back to the padded capacity when the row
    count is still a device scalar (no sync just for accounting)."""
    import numpy as np

    total = 0
    for c in batch.columns:
        r = rows if rows is not None else c.capacity
        if c.is_dict:
            d = c.dictv
            total += r * 5 + int(d.dictionary.chars.shape[0])
            total += 4 * int(d.dictionary.offsets.shape[0])
        elif c.is_string:
            total += r * 5 + int(c.chars.shape[0])
        else:
            total += r * (np.dtype(c.data.dtype).itemsize + 1)
    return total


def compile_snapshot() -> tuple:
    """(total, by_site) snapshot for delta reporting (sessions snapshot
    before executing a plan so explain_metrics attributes misses to THAT
    plan, not to everything compiled since process start)."""
    return COMPILE_COUNTER.snapshot()


def format_metrics(plan: TpuExec, since: Optional[tuple] = None,
                   cost_since: Optional[int] = None) -> str:
    """Per-operator metrics report — the profiler's user-facing output
    (reference: the SQL-UI metric table GpuExec publishes per node). One
    line per exec with its metrics prettied by kind, plus a derived HBM
    GB/s LABELED BY THE LANE THAT FED IT: ``hbm_gbps[device]`` (layout
    bytes / opTimeDevice, deviceSync runs) is preferred whenever the
    device lane exists; without it the column degrades to
    ``hbm_gbps[host]`` (layout bytes / host wall-clock) — an async
    dispatch makes the host lane far smaller than the device work it
    queued, so an UNLABELED figure fed by it silently overstates
    bandwidth. ``cost_since`` (an xla_cost.snapshot()) additionally adds
    per-op XLA-compiler columns (xla_bytes/xla_flops/xla_gbps) for
    programs harvested during this run, and a footer reports
    pipeline-cache compile misses by site plus the harvested
    trace/compile split (relative to the ``since`` compile_snapshot)."""
    lines: List[str] = []
    cost_recs = (_xla_cost.records_since(cost_since)
                 if cost_since is not None else [])
    cost_by_op: Dict[str, List[dict]] = {}
    for r in cost_recs:
        if r.get("op"):
            cost_by_op.setdefault(r["op"], []).append(r)
    # cost attribution is by CLASS name (op_scope pushes node_name): a
    # class appearing at several plan nodes prints its harvested costs
    # ONCE (first visit pops the entry), and gets no xla_gbps — any
    # single node's device lane is the wrong denominator for the
    # class-wide byte sum
    name_counts: Dict[str, int] = {}

    def count_names(n: TpuExec) -> None:
        name_counts[n.node_name] = name_counts.get(n.node_name, 0) + 1
        for c in n.children:
            count_names(c)

    count_names(plan)

    def walk(node: TpuExec, depth: int) -> None:
        parts = []
        for m in node.metrics.values():
            if m.value:
                parts.append(f"{m.name}={m.pretty()}")
        dev = node.metrics.get(OP_TIME_DEVICE)
        host = node.metrics.get(TOTAL_TIME)
        byt = node.metrics.get(BYTES_TOUCHED)
        if byt is not None and byt.value:
            # bandwidth the op actually demanded: its INPUT stream (the
            # children's output bytes) plus its own output — output alone
            # would misdiagnose a reducing op (an aggregate streaming GBs
            # into 100 group rows) as latency-bound
            in_bytes = sum(
                c.metrics[BYTES_TOUCHED].value
                for c in node.children if BYTES_TOUCHED in c.metrics
            )
            io_bytes = byt.value + in_bytes
            if io_bytes and dev is not None and dev.value:
                parts.append(f"hbm_gbps[device]={io_bytes / dev.value:.2f}")
            elif io_bytes and host is not None and host.value:
                parts.append(f"hbm_gbps[host]={io_bytes / host.value:.2f}")
        recs = cost_by_op.pop(node.node_name, None)
        if recs:
            xb = sum(r["bytes_accessed"] for r in recs
                     if r.get("bytes_accessed") is not None)
            xf = sum(r["flops"] for r in recs if r.get("flops") is not None)
            if xb:
                parts.append(f"xla_bytes={xb / 1e6:.1f}MB")
            if xf:
                parts.append(f"xla_flops={xf / 1e6:.1f}M")
            if (xb and dev is not None and dev.value
                    and name_counts.get(node.node_name) == 1):
                parts.append(f"xla_gbps[device]={xb / dev.value:.2f}")
        lines.append("  " * depth + node.describe()
                     + (": " + ", ".join(parts) if parts else ""))
        for c in node.children:
            walk(c, depth + 1)

    walk(plan, 0)
    base_total, base_sites = (0, {}) if since is None else since
    now_total, now_sites = COMPILE_COUNTER.snapshot()
    total = now_total - base_total
    deltas = {
        k: v - base_sites.get(k, 0)
        for k, v in now_sites.items()
        if v - base_sites.get(k, 0)
    }
    sites = ", ".join(f"{k}={v}" for k, v in sorted(deltas.items()))
    lines.append(f"compile cache misses: {total}"
                 + (f" ({sites})" if sites else ""))
    if cost_recs:
        trace_ms = sum(r.get("trace_ms") or 0 for r in cost_recs)
        comp_ms = sum(r.get("compile_ms") or 0 for r in cost_recs)
        temps = [r["temp_bytes"] for r in cost_recs
                 if r.get("temp_bytes") is not None]
        lines.append(
            f"programs harvested: {len(cost_recs)} "
            f"(trace {trace_ms:.1f}ms + compile {comp_ms:.1f}ms"
            + (f", largest temp {max(temps) / 1e6:.1f}MB" if temps else "")
            + ")")
    lines.append(memory_footer())
    return "\n".join(lines)


def memory_footer() -> str:
    """The explain_metrics memory line: the buffer catalog's live device
    bytes, the peak watermark, and the spill/unspill story (process-wide
    counters — the catalog is a process singleton, like the reference's
    RapidsBufferCatalog). ``spilled_bytes`` was tracked since the catalog
    landed but never reported anywhere; this is its user-facing surface."""
    from ..memory.catalog import BufferCatalog

    cat = BufferCatalog.get()
    m = cat.metrics

    def mb(v: int) -> str:
        return f"{v / 1e6:.1f}MB"

    line = (f"memory: device {mb(cat.device_bytes)} "
            f"(peak {mb(m.peak_device_bytes)}), "
            f"spilled {mb(m.spilled_bytes)} in {m.device_to_host} "
            f"spill(s) ({m.host_to_disk} to disk), "
            f"{m.unspills} unspill(s)")
    # the HBM ledger (when armed) decomposes that peak by owning op —
    # the "who held the bytes" column the bare watermark can't answer
    peaks = {op: b for op, b in cat.ledger.op_peaks().items() if b > 0}
    if peaks:
        rows = sorted(peaks.items(), key=lambda kv: kv[1], reverse=True)
        line += "\nmemory by op (peak): " + ", ".join(
            f"{op} {mb(b)}" for op, b in rows)
        leaked = cat.ledger.stats()["leaked_live"]
        if leaked:
            line += f"; LEAKED {leaked} buffer(s)"
    return line


# ---------------------------------------------------------------------------
# ColumnarBatch <-> traced value plumbing
# ---------------------------------------------------------------------------
def vals_of_batch(batch: ColumnarBatch) -> List[Val]:
    from ..columnar import column as _colmod

    out: List[Val] = []
    for c in batch.columns:
        if c.is_dict:
            if _colmod.DICT_MATERIALIZE_EAGERLY:
                c = c.materialize()
                out.append(StrV(c.offsets, c.chars, c.validity))
            else:
                out.append(c.dictv)
        elif c.is_string:
            out.append(StrV(c.offsets, c.chars, c.validity))
        else:
            out.append(ColV(c.data, c.validity))
    return out


def materialized_batch(batch: ColumnarBatch) -> ColumnarBatch:
    """Batch with every dict-encoded column expanded to the plain string
    layout — the boundary call for execs without a dict path (sort keys,
    joins, window partitioning, exchange serialization)."""
    if not any(c.is_dict for c in batch.columns):
        return batch
    return ColumnarBatch(
        [c.materialize() for c in batch.columns], batch.schema,
        batch.num_rows_lazy)


def batch_from_vals(
    vals: Sequence[Val], schema: StructType, num_rows: int,
    capacity: Optional[int] = None,
) -> ColumnarBatch:
    cols = []
    for f, v in zip(schema.fields, vals):
        if isinstance(v, DictV):
            cols.append(DeviceColumn.dict_encoded(f.dataType, num_rows, v))
        elif isinstance(v, StrV):
            cols.append(
                DeviceColumn(f.dataType, num_rows, None, v.validity, v.offsets, v.chars)
            )
        else:
            cols.append(DeviceColumn(f.dataType, num_rows, v.data, v.validity))
    # ``capacity`` matters only for zero-column outputs (fully-pruned
    # projections): the batch then has no column to carry the bucket
    return ColumnarBatch(cols, schema, num_rows, capacity=capacity)


_FUSED_CACHE: Dict[tuple, Callable] = {}


def count_scalar(num_rows):
    """Row count as a traced int32 scalar (host int or device scalar in)."""
    import jax.numpy as jnp

    return jnp.int32(num_rows) if isinstance(num_rows, int) else num_rows


def side_signature(sides: Sequence[tuple]) -> tuple:
    """Structural cache key for chain side inputs (shape+dtype per array)."""
    return tuple(
        tuple((tuple(a.shape), str(a.dtype)) for a in s) for s in sides
    )


def _donation():
    """Lazy handle on plugin/donation.py — plugin/__init__ imports the
    overrides layer which imports this module, so a module-level import
    here would cycle; by first dispatch everything is in sys.modules."""
    from ..plugin import donation

    return donation


def fused_pipeline(chain: Sequence[TpuExec], sig: tuple, cap: int,
                   sides: Sequence[tuple] = (), nonnull: tuple = (),
                   donate: Tuple[int, ...] = ()):
    """One jitted program applying every exec in ``chain`` bottom-up.

    The chain threads a liveness MASK between stages; if any stage
    sparsified it (a filter), rows compact once at the end so the emitted
    batch is dense — otherwise the input row count passes straight through.

    ``nonnull``: per-input-column elision flags from the static plan
    analyzer's nullability lattice (plugin/plananalysis.py) — flagged
    columns enter the chain with the iota-derived liveness mask as their
    validity instead of reading the stored plane (see
    ops/filter_gather.elide_validity for why that is bit-identical).
    """
    key = (tuple(e.fusion_key() for e in chain), sig, cap,
           side_signature(sides), nonnull)

    def build():
        chain_t = tuple(chain)
        needs_compact = any(e.sparsifies for e in chain_t)

        def run(cols, num_rows, side_args):
            from ..ops import filter_gather

            live = filter_gather.live_of(num_rows, cap)
            cols = filter_gather.elide_validity(cols, live, nonnull)
            for e, s in zip(chain_t, side_args):
                cols, live = e.lower_batch(cols, live, cap, s)
            if needs_compact:
                cols, count = filter_gather.filter_cols(cols, live, num_rows)
                return cols, count
            return cols, num_rows

        return jax.jit(run, donate_argnums=donate)

    return cached_pipeline(_FUSED_CACHE, key, "fused_chain", build,
                           max_entries=1024, donate=donate)


def run_fused_chain(exec_self: TpuExec, index: int) -> Iterator[ColumnarBatch]:
    """Shared execute_partition for fusable execs: the whole chain below
    (and including) ``exec_self`` runs as one XLA dispatch per batch, with
    the row count threaded through as a device scalar (no host syncs).

    Each dispatch runs under the OOM retry harness (memory/retry.py): a
    device allocation failure spills + re-attempts, and exhausted retries
    split the batch in half — the halves recompile the chain at their
    smaller capacity buckets and the piece outputs re-join row-wise
    (exact: the chain is row-local by construction)."""
    from ..memory.retry import with_oom_retry
    from ..plugin.plananalysis import entry_nonnull_flags

    source, chain = exec_self.fused_source_chain()
    out_schema = exec_self.output_schema
    sides = [e.side_vals() for e in chain]
    nonnull = entry_nonnull_flags(source.output_schema, exec_self.conf)
    # pressure hook: a scan source's staged prefetch holds device
    # residency an OOM recovery wants back (exec/scan.py)
    on_pressure = getattr(source, "invalidate_prefetch", None)

    def attempt(b: ColumnarBatch) -> ColumnarBatch:
        don = _donation()
        cap = b.capacity
        mask = don.dispatch_mask("fused_chain", b, exec_self.conf)
        fn = fused_pipeline(chain, batch_signature(b), cap, sides,
                            nonnull, donate=mask)
        if mask:
            # donating dispatch: the guard snapshots b's planes so
            # split-and-retry can re-read them on failure, accounts
            # donated_bytes, and (under the witness) asserts the
            # donated buffers really died
            with don.guard("fused_chain", b, op=exec_self.node_name,
                           conf=exec_self.conf,
                           metric=exec_self.metric("donatedBytes")):
                vals, nr = fn(vals_of_batch(b),
                              count_scalar(b.num_rows_lazy), sides)
        else:
            vals, nr = fn(
                vals_of_batch(b), count_scalar(b.num_rows_lazy), sides)
        # the output planes come straight out of the program — no other
        # reference exists, so the next certified site may donate them
        return don.mark_exclusive(
            batch_from_vals(vals, out_schema, nr, capacity=cap))

    for batch in source.execute_partition(index):
        with exec_self.op_timed():
            out = with_oom_retry(exec_self.node_name, attempt, batch,
                                 exec_self.conf, on_pressure=on_pressure)
        yield exec_self.record_batch(out)


def batch_signature(batch: ColumnarBatch) -> tuple:
    """Structural cache key for compiled per-exec pipelines: dtype + shapes."""
    from ..columnar import column as _colmod

    sig = []
    for f, c in zip(batch.schema.fields, batch.columns):
        if c.is_dict and not _colmod.DICT_MATERIALIZE_EAGERLY:
            d = c.dictv
            sig.append((f.dataType, "dict", int(d.codes.shape[0]),
                        d.dict_size, int(d.dictionary.chars.shape[0]),
                        d.mat_cap, d.max_len, d.unique))
        elif c.is_dict:  # eager-materialize hook: sign as the plain layout
            d = c.dictv
            sig.append((f.dataType, int(d.codes.shape[0]) + 1, d.mat_cap))
        elif c.is_string:
            sig.append((f.dataType, int(c.offsets.shape[0]), int(c.chars.shape[0])))
        else:
            sig.append((f.dataType, int(c.data.shape[0])))
    return tuple(sig)
