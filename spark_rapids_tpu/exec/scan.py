"""File-source scan exec: splits -> device batches.

Reference analog: GpuFileSourceScanExec.scala (569) + PartitionReaderIterator
+ ColumnarPartitionReaderWithPartitionValues (constant partition columns).
The host half (footer parse, prune, column-chunk read) happened in the
scanner; here each split's arrow table uploads buffer-level and partition
values append as constant device columns.
"""
from __future__ import annotations

import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..columnar import ColumnarBatch
from ..columnar.column import DeviceColumn
from ..conf import RapidsConf
from ..types import StructType
from ..columnar.column import choose_capacity
from .base import TpuExec

SCAN_TIME = "scanTime"  # reference metric name (GpuMetricNames)
DECODE_TIME = "tpuDecodeTime"

# Serving-path prefetch pool: host_prefetch() submits whole-split reads
# here. DISTINCT from the srtpu-pqdec chunk-decode pool on purpose — a
# split read fans out chunk decodes onto that pool, so running the outer
# task on the same bounded pool could occupy every worker with waiters
# (classic nested-pool deadlock). Two workers is enough: the point is
# overlap with the device phase, not parallel split storms.
_PREFETCH_POOL = None
_PREFETCH_POOL_LOCK = threading.Lock()


def _prefetch_pool():
    global _PREFETCH_POOL
    if _PREFETCH_POOL is None:
        with _PREFETCH_POOL_LOCK:
            if _PREFETCH_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _PREFETCH_POOL = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="srtpu-prefetch")
    return _PREFETCH_POOL


def constant_string_column(value, n: int, cap: int) -> DeviceColumn:
    """One value repeated n times (partition-value column) — O(1) python."""
    import jax.numpy as jnp

    if value is None:
        return DeviceColumn(
            T.STRING, n, None, jnp.zeros(cap, bool),
            offsets=jnp.zeros(cap + 1, jnp.int32),
            chars=jnp.zeros(1, jnp.uint8))
    b = str(value).encode("utf-8")
    L = len(b)
    ccap = choose_capacity(max(1, L * n), 128)
    offsets = np.minimum(np.arange(cap + 1, dtype=np.int64) * L,
                         L * n).astype(np.int32)
    chars = np.zeros(ccap, np.uint8)
    if L:
        chars[: L * n] = np.frombuffer(b * n, np.uint8)
    valid = np.zeros(cap, bool)
    valid[:n] = True
    return DeviceColumn(
        T.STRING, n, None, jnp.asarray(valid),
        offsets=jnp.asarray(offsets), chars=jnp.asarray(chars))


class MeshShardedScanExec(TpuExec):
    """Leaf over PER-SHARD host column arrays — the decoded form a
    data-parallel scan hands the mesh. Partition ``i`` is shard ``i``'s
    data: ``stage_mesh_planes`` uploads it straight to mesh device
    ``i % n`` as that device's slice of a NamedSharding-committed global
    array (io/mesh_stage.stage_sharded — no host gather, decode of shard
    k+1 overlapping the upload of shard k). Off-mesh execution builds
    ordinary device batches, so the same exec drives the 1-device
    baseline of the bench mesh lane.

    ``parts``: one entry per partition — a list of (data, validity)
    numpy pairs (schema order) plus the live row count."""

    def __init__(self, conf: RapidsConf, parts, schema: StructType):
        super().__init__(conf)
        self._parts = [
            (list(arrays), int(rows)) for arrays, rows in parts
        ]
        self._schema = schema

    @property
    def output_schema(self) -> StructType:
        return self._schema

    @property
    def num_partitions(self) -> int:
        return max(1, len(self._parts))

    def describe(self):
        return f"MeshShardedScanExec({len(self._parts)} shard parts)"

    def partition_rows(self):
        """Static per-partition row counts (the plananalysis mesh
        forecast's input)."""
        return [rows for _, rows in self._parts]

    def mesh_stage_items(self):
        """Per-item row counts the sharded-scan staging will round-robin
        (None = the fast path would decline; forecast mirrors runtime)."""
        from ..io import mesh_stage as MS

        if not MS.stageable_schema(self._schema):
            return None
        return self.partition_rows()

    def stage_mesh_planes(self, mesh, n_shards: int, conf, on_shard=None):
        from ..io import mesh_stage as MS

        if not MS.stageable_schema(self._schema):
            return None
        assign = MS.round_robin(len(self._parts), n_shards)
        rows_per_shard = [
            sum(self._parts[i][1] for i in idxs) for idxs in assign
        ]

        def decode_shard(s: int) -> "MS.ShardPayload":
            arrays = []
            total = rows_per_shard[s]
            for j, f in enumerate(self._schema.fields):
                dt = f.dataType.to_numpy()
                d = np.empty(total, dt)
                v = np.empty(total, bool)
                pos = 0
                for i in assign[s]:
                    part, rows = self._parts[i]
                    data, valid = part[j]
                    d[pos:pos + rows] = data[:rows]
                    v[pos:pos + rows] = valid[:rows]
                    pos += rows
                arrays.append((d, v))
            return MS.ShardPayload(arrays, total)

        return MS.stage_sharded(
            mesh, n_shards, self._schema, decode_shard, rows_per_shard,
            self.conf.shape_bucket_min, on_shard=on_shard)

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        import jax.numpy as jnp

        if index >= len(self._parts):
            return
        arrays, n = self._parts[index]
        if n == 0:
            return
        cap = choose_capacity(max(1, n))
        cols = []
        for f, (data, valid) in zip(self._schema.fields, arrays):
            d = np.zeros(cap, f.dataType.to_numpy())
            v = np.zeros(cap, bool)
            d[:n] = data[:n]
            v[:n] = valid[:n]
            cols.append(DeviceColumn(
                f.dataType, n, jnp.asarray(d), jnp.asarray(v)))
        yield self.record_batch(ColumnarBatch(cols, self._schema, n))


class TpuFileSourceScanExec(TpuExec):
    """Columnar scan over a file scanner's splits (one split = one
    partition; the MULTITHREADED reader prefetches neighbors)."""

    def __init__(self, conf: RapidsConf, scanner, fmt: str):
        super().__init__(conf)
        self.scanner = scanner
        self.fmt = fmt
        self._prefetch = None  # MULTITHREADED reader futures
        self._prefetch_dev = None  # host_prefetch device-path futures
        #: splits already drained — a prefetch table rebuilt after an
        #: OOM-pressure invalidation must not resubmit (and then retain)
        #: reads nobody will consume again
        self._consumed_splits: set = set()
        self.metrics[SCAN_TIME] = self.metric(SCAN_TIME)
        self.metrics[DECODE_TIME] = self.metric(DECODE_TIME)

    @property
    def output_schema(self) -> StructType:
        return self.scanner.schema

    @property
    def num_partitions(self) -> int:
        return max(1, self.scanner.num_splits())

    def describe(self):
        return f"TpuFileSourceScanExec {self.fmt} {getattr(self.scanner, 'path', '')}"

    def _read_split(self, index: int):
        """Split read, optionally through the MULTITHREADED prefetcher:
        cloud-path scans buffer EVERY split in a thread pool on first
        touch so later partitions find their bytes already fetched
        (reference: MultiFileCloudParquetPartitionReader
        GpuParquetScan.scala:1299-1333). The serving path's
        host_prefetch() fills the same future table ahead of the drain,
        so an already-started prefetch is consumed whatever the reader
        type."""
        rt = getattr(self.scanner, "reader_type", lambda: "PERFILE")()
        self._consumed_splits.add(index)
        if rt != "MULTITHREADED" and self._prefetch is None:
            return self.scanner.read_split_i(index)
        if self._prefetch is None:
            from concurrent.futures import ThreadPoolExecutor

            from ..conf import PARQUET_MULTITHREAD_READ_NUM_THREADS

            pool = ThreadPoolExecutor(
                max_workers=self.conf.get(PARQUET_MULTITHREAD_READ_NUM_THREADS),
                thread_name_prefix="srtpu-scan")
            # splits already drained (this one included) stay None: a
            # table rebuilt after invalidate_prefetch must not resubmit
            # reads nobody will consume again
            self._prefetch = [
                pool.submit(self.scanner.read_split_i, i)
                if i not in self._consumed_splits else None
                for i in range(self.scanner.num_splits())
            ]
            pool.shutdown(wait=False)
        fut = self._prefetch[index]
        self._prefetch[index] = None  # free the decoded table once consumed
        if fut is None:  # consumed marker, or invalidated mid-drain
            return self.scanner.read_split_i(index)
        return fut.result()

    def _attach_partition_cols(self, batch: ColumnarBatch, pvals):
        schema = self.output_schema
        pkeys = list(getattr(self.scanner, "partition_cols", ()))
        if not pkeys:
            return batch
        pmap = dict(pvals)
        n, cap = batch.num_rows, max(batch.capacity, 1)
        cols = list(batch.columns)
        for k in pkeys:
            cols.append(constant_string_column(pmap.get(k), n, cap))
        return ColumnarBatch(cols, schema, n)

    def _mesh_row_groups(self):
        """Flat (path, row_group, rows) list for mesh round-robin — the
        sharded scan places row group i on shard i % n. None when the
        scanner's splits don't expose row groups (csv) or a row group's
        metadata is unreadable."""
        splits = getattr(self.scanner, "splits", None)
        if splits is None:
            return None
        try:
            import pyarrow.parquet as pq

            out = []
            mds = {}
            for sp in splits():
                rgs = getattr(sp, "row_groups", None)
                if rgs is None:
                    return None
                md = mds.get(sp.path)
                if md is None:
                    md = mds[sp.path] = pq.ParquetFile(sp.path).metadata
                for rg in rgs:
                    out.append((sp.path, rg, md.row_group(rg).num_rows))
            return out
        except Exception:
            return None

    def stage_mesh_planes(self, mesh, n_shards: int, conf, on_shard=None):
        """Data-parallel parquet ingestion: row groups round-robined
        across mesh shards, each shard's groups host-decoded on a worker
        thread while the previous shard's padded planes upload to ITS
        device (io/mesh_stage.stage_sharded) — PR 7's decode→upload
        pipeline extended across devices. Fixed-width file columns only
        (partition-value columns are strings and keep the generic path).
        Reads bypass the device scan cache: the cache holds default-
        device batches, which would have to cross devices again."""
        from ..io import mesh_stage as MS

        if getattr(self.scanner, "partition_cols", None):
            return None
        schema = self.output_schema
        if not MS.stageable_schema(schema):
            return None
        rgs = self._mesh_row_groups()
        if rgs is None:
            return None
        assign = MS.round_robin(len(rgs), n_shards)
        rows_per_shard = [
            sum(rgs[i][2] for i in idxs) for idxs in assign
        ]
        columns = [f.name for f in schema.fields]

        def decode_shard(s: int) -> "MS.ShardPayload":
            import pyarrow.parquet as pq

            from ..io.arrow_convert import _np_from_arrow_array

            with self.op_timed("mesh_decode", DECODE_TIME):
                by_path = {}
                for i in assign[s]:
                    path, rg, _ = rgs[i]
                    by_path.setdefault(path, []).append(rg)
                tables = [
                    pq.ParquetFile(p).read_row_groups(g, columns=columns)
                    for p, g in by_path.items()
                ]
                total = rows_per_shard[s]
                arrays = []
                for j, f in enumerate(schema.fields):
                    d = np.empty(total, f.dataType.to_numpy())
                    v = np.empty(total, bool)
                    pos = 0
                    for t in tables:
                        arr = t.column(j).combine_chunks()
                        data, valid = _np_from_arrow_array(arr, f.dataType)
                        n = len(t)
                        d[pos:pos + n] = data[:n]
                        v[pos:pos + n] = valid[:n]
                        pos += n
                    arrays.append((d, v))
            return MS.ShardPayload(arrays, total)

        return MS.stage_sharded(
            mesh, n_shards, schema, decode_shard, rows_per_shard,
            self.conf.shape_bucket_min, on_shard=on_shard)

    def partition_rows(self):
        """Static per-split row counts from parquet metadata (None when
        unknowable) — the plananalysis mesh forecast's input."""
        rgs = self._mesh_row_groups()
        if rgs is None:
            return None
        per = [0] * self.scanner.num_splits()
        for i, sp in enumerate(self.scanner.splits()):
            per[i] = sum(r for p, rg, r in rgs
                         if p == sp.path and rg in sp.row_groups)
        return per

    def mesh_stage_items(self):
        """Per-ROW-GROUP rows the sharded scan round-robins (the mesh
        forecast's mirror of stage_mesh_planes' eligibility + placement;
        None = the fast path would decline)."""
        from ..io import mesh_stage as MS

        if getattr(self.scanner, "partition_cols", None):
            return None
        if not MS.stageable_schema(self.output_schema):
            return None
        rgs = self._mesh_row_groups()
        if rgs is None:
            return None
        return [r for _, _, r in rgs]

    def fused_stage_plans(self, index: int):
        """Stage fusion: hand the consumer exec the traced per-row-group
        decode programs so scan→…→aggregate compiles to ONE executable
        (each extra program in a dependency chain pays a dispatch/queue
        round trip on the TPU host link). None = use execute_partition."""
        if index >= self.scanner.num_splits():
            return None
        fn = getattr(self.scanner, "device_stage_plans", None)
        if fn is None:
            return None
        with self.op_timed("plan", SCAN_TIME):
            return fn(index)

    def host_prefetch(self) -> None:
        """Serving-path phase split: start every split's host decode (+
        staged upload dispatch on the device path) on the prefetch pool
        NOW, before the caller blocks on the TPU semaphore — host work
        of an admitted query overlaps the running query's device
        compute. The drain consumes the futures instead of re-reading."""
        n = self.scanner.num_splits()
        if n == 0:
            return
        if hasattr(self.scanner, "read_split_device"):
            if self._prefetch_dev is None:
                self._prefetch_dev = [
                    _prefetch_pool().submit(
                        self.scanner.read_split_device, i)
                    if i not in self._consumed_splits else None
                    for i in range(n)
                ]
        elif self._prefetch is None:
            self._prefetch = [
                _prefetch_pool().submit(self.scanner.read_split_i, i)
                if i not in self._consumed_splits else None
                for i in range(n)
            ]

    def invalidate_prefetch(self) -> None:
        """OOM-pressure hook (memory/retry.py ``on_pressure``): cancel
        pending prefetch futures and drop the tables — the device path's
        futures hold STAGED device uploads, exactly the residency an OOM
        recovery wants back. Already-running futures finish and are
        garbage-collected; the drain falls back to direct re-reads, so
        results are identical either way."""
        for futs in (self._prefetch_dev, self._prefetch):
            if futs:
                for f in futs:
                    if f is not None:
                        f.cancel()
        self._prefetch_dev = None
        self._prefetch = None

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        from ..io.arrow_convert import arrow_to_batch

        if index >= self.scanner.num_splits():
            return
        # TPU-side page decode (reference: GPU decode via Table.readParquet,
        # GpuParquetScan.scala:1157): host uploads encoded bytes, XLA
        # kernels expand dictionary/RLE pages on-device
        if hasattr(self.scanner, "read_split_device"):
            with self.op_timed("decode", DECODE_TIME):
                self._consumed_splits.add(index)
                fut = None
                if self._prefetch_dev is not None:
                    fut = self._prefetch_dev[index]
                    self._prefetch_dev[index] = None
                if fut is not None:
                    dev, pvals = fut.result()
                else:
                    dev, pvals = self.scanner.read_split_device(index)
            if dev is not None:
                for b in dev:
                    yield self.record_batch(
                        self._attach_partition_cols(b, pvals))
                return
        from ..memory.retry import named_oom

        with self.op_timed("read", SCAN_TIME):
            table, pvals = self._read_split(index)
        with self.op_timed("decode", DECODE_TIME), \
                named_oom(f"{self.node_name}.decode"):
            # scan staging sits OUTSIDE the retry harness (there is no
            # input batch to split yet): a device allocation failure
            # uploading the decoded split surfaces as the named
            # TpuOutOfDeviceMemory instead of a bare XLA traceback
            schema = self.output_schema
            # the schema only carries the partition keys common to every
            # file (scanner.partition_cols); a split may report extra keys
            # on ragged layouts — select by schema key, not raw count
            pkeys = list(getattr(self.scanner, "partition_cols", ()))
            file_fields = schema.fields[: len(schema.fields) - len(pkeys)]
            batch = arrow_to_batch(
                table, T.StructType(tuple(file_fields)))
            batch = self._attach_partition_cols(batch, pvals)
        yield self.record_batch(batch)
