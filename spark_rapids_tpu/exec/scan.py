"""File-source scan exec: splits -> device batches.

Reference analog: GpuFileSourceScanExec.scala (569) + PartitionReaderIterator
+ ColumnarPartitionReaderWithPartitionValues (constant partition columns).
The host half (footer parse, prune, column-chunk read) happened in the
scanner; here each split's arrow table uploads buffer-level and partition
values append as constant device columns.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..columnar import ColumnarBatch
from ..columnar.column import DeviceColumn
from ..conf import RapidsConf
from ..types import StructType
from ..columnar.column import choose_capacity
from .base import TpuExec

SCAN_TIME = "scanTime"  # reference metric name (GpuMetricNames)
DECODE_TIME = "tpuDecodeTime"


def constant_string_column(value, n: int, cap: int) -> DeviceColumn:
    """One value repeated n times (partition-value column) — O(1) python."""
    import jax.numpy as jnp

    if value is None:
        return DeviceColumn(
            T.STRING, n, None, jnp.zeros(cap, bool),
            offsets=jnp.zeros(cap + 1, jnp.int32),
            chars=jnp.zeros(1, jnp.uint8))
    b = str(value).encode("utf-8")
    L = len(b)
    ccap = choose_capacity(max(1, L * n), 128)
    offsets = np.minimum(np.arange(cap + 1, dtype=np.int64) * L,
                         L * n).astype(np.int32)
    chars = np.zeros(ccap, np.uint8)
    if L:
        chars[: L * n] = np.frombuffer(b * n, np.uint8)
    valid = np.zeros(cap, bool)
    valid[:n] = True
    return DeviceColumn(
        T.STRING, n, None, jnp.asarray(valid),
        offsets=jnp.asarray(offsets), chars=jnp.asarray(chars))


class TpuFileSourceScanExec(TpuExec):
    """Columnar scan over a file scanner's splits (one split = one
    partition; the MULTITHREADED reader prefetches neighbors)."""

    def __init__(self, conf: RapidsConf, scanner, fmt: str):
        super().__init__(conf)
        self.scanner = scanner
        self.fmt = fmt
        self._prefetch = None  # MULTITHREADED reader futures
        self.metrics[SCAN_TIME] = self.metric(SCAN_TIME)
        self.metrics[DECODE_TIME] = self.metric(DECODE_TIME)

    @property
    def output_schema(self) -> StructType:
        return self.scanner.schema

    @property
    def num_partitions(self) -> int:
        return max(1, self.scanner.num_splits())

    def describe(self):
        return f"TpuFileSourceScanExec {self.fmt} {getattr(self.scanner, 'path', '')}"

    def _read_split(self, index: int):
        """Split read, optionally through the MULTITHREADED prefetcher:
        cloud-path scans buffer EVERY split in a thread pool on first
        touch so later partitions find their bytes already fetched
        (reference: MultiFileCloudParquetPartitionReader
        GpuParquetScan.scala:1299-1333)."""
        rt = getattr(self.scanner, "reader_type", lambda: "PERFILE")()
        if rt != "MULTITHREADED":
            return self.scanner.read_split_i(index)
        if self._prefetch is None:
            from concurrent.futures import ThreadPoolExecutor

            from ..conf import PARQUET_MULTITHREAD_READ_NUM_THREADS

            pool = ThreadPoolExecutor(
                max_workers=self.conf.get(PARQUET_MULTITHREAD_READ_NUM_THREADS),
                thread_name_prefix="srtpu-scan")
            self._prefetch = [
                pool.submit(self.scanner.read_split_i, i)
                for i in range(self.scanner.num_splits())
            ]
            pool.shutdown(wait=False)
        fut = self._prefetch[index]
        self._prefetch[index] = None  # free the decoded table once consumed
        return fut.result()

    def _attach_partition_cols(self, batch: ColumnarBatch, pvals):
        schema = self.output_schema
        pkeys = list(getattr(self.scanner, "partition_cols", ()))
        if not pkeys:
            return batch
        pmap = dict(pvals)
        n, cap = batch.num_rows, max(batch.capacity, 1)
        cols = list(batch.columns)
        for k in pkeys:
            cols.append(constant_string_column(pmap.get(k), n, cap))
        return ColumnarBatch(cols, schema, n)

    def fused_stage_plans(self, index: int):
        """Stage fusion: hand the consumer exec the traced per-row-group
        decode programs so scan→…→aggregate compiles to ONE executable
        (each extra program in a dependency chain pays a dispatch/queue
        round trip on the TPU host link). None = use execute_partition."""
        if index >= self.scanner.num_splits():
            return None
        fn = getattr(self.scanner, "device_stage_plans", None)
        if fn is None:
            return None
        with self.op_timed("plan", SCAN_TIME):
            return fn(index)

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        from ..io.arrow_convert import arrow_to_batch

        if index >= self.scanner.num_splits():
            return
        # TPU-side page decode (reference: GPU decode via Table.readParquet,
        # GpuParquetScan.scala:1157): host uploads encoded bytes, XLA
        # kernels expand dictionary/RLE pages on-device
        if hasattr(self.scanner, "read_split_device"):
            with self.op_timed("decode", DECODE_TIME):
                dev, pvals = self.scanner.read_split_device(index)
            if dev is not None:
                for b in dev:
                    yield self.record_batch(
                        self._attach_partition_cols(b, pvals))
                return
        with self.op_timed("read", SCAN_TIME):
            table, pvals = self._read_split(index)
        with self.op_timed("decode", DECODE_TIME):
            schema = self.output_schema
            # the schema only carries the partition keys common to every
            # file (scanner.partition_cols); a split may report extra keys
            # on ragged layouts — select by schema key, not raw count
            pkeys = list(getattr(self.scanner, "partition_cols", ()))
            file_fields = schema.fields[: len(schema.fields) - len(pkeys)]
            batch = arrow_to_batch(
                table, T.StructType(tuple(file_fields)))
            batch = self._attach_partition_cols(batch, pvals)
        yield self.record_batch(batch)
