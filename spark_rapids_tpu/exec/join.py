"""Equi-join execs (hash-join family on TPU).

Reference analog: GpuHashJoin.doJoin (execution/GpuHashJoin.scala:158-263) —
build-side table concat + per-stream-batch cudf join; join types inner/left/
right/full/semi/anti (doJoinLeftRight :265). TPU re-design: the build side
is concatenated and radix-SORTED once (ops/join.py), each probe batch runs a
fused count+expand program, and the only host syncs are the build size and
one match-total per probe batch (cudf syncs output sizes at the same
boundaries).

Right joins run as left joins with the sides swapped and the output columns
re-permuted, like the reference's buildSide handling.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch
from ..conf import RapidsConf
from ..expr import expressions as E
from ..expr.eval import ColV, StrV, Val, lower
from ..ops import concat as concat_ops
from ..ops import filter_gather
from ..ops import join as join_ops
from ..ops.sort import max_string_len, sort_with_radix_keys, SortOrder
from ..types import StructField, StructType
from ..columnar.column import choose_capacity


class _SpillableBuild:
    """Join build side as catalog-registered spillable buffers: the sorted
    build columns + radix words + liveness round-trip device<->host under
    pressure and re-materialize at probe time (reference:
    SpillableColumnarBatch around the concatenated build table)."""

    def __init__(self, cols, words, live):
        from ..memory import ACTIVE_BATCHING_PRIORITY, SpillableVals
        from ..memory.catalog import SpillableHandle

        # ledger_kind="plan_state": the build side is retained with the
        # exec instance for re-execution — designed to outlive queries,
        # so the leak sentinel must not flag it
        self._cols = SpillableVals(cols, ACTIVE_BATCHING_PRIORITY,
                                   ledger_kind="plan_state")
        aux = {f"w{i}": w for i, w in enumerate(words)}
        aux["live"] = live
        self._aux = SpillableHandle(aux, ACTIVE_BATCHING_PRIORITY,
                                    ledger_kind="plan_state")
        self._nw = len(words)

    def get(self):
        cols = self._cols.get_vals()
        a = self._aux.materialize()
        return cols, [a[f"w{i}"] for i in range(self._nw)], a["live"]
from .base import (
    NUM_OUTPUT_BATCHES,
    TOTAL_TIME,
    TpuExec,
    batch_from_vals,
    batch_signature,
    count_scalar,
    timed,
    vals_of_batch,
)

_JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti", "cross")

# ---------------------------------------------------------------------------
# Join strategy chooser (conf sql.join.strategy) — the join twin of
# exec/aggregate.choose_agg_strategy. Reads the SAME conf-declared
# roofline peaks the profiler's roofline report measures against, so a
# calibrated deployment moves the chooser and the report together.
# ---------------------------------------------------------------------------
#: CPU-backend AUTO: below this build capacity the direct-address
#: table's two scatters are cheap and the whole-join fusion into the
#: consumer chain wins; at or above it the CPU scatter dialect's charged
#: byte amplification dominates (BENCH_r10: the join shape's fused
#: direct tables + downstream scatter agg touched 29.8x the layout
#: bound) and the co-sorted RADIX merge takes over
_RADIX_JOIN_CPU_MIN_BUILD = 1 << 16
#: near-serial accelerator random-gather cost per element (the binary
#: search's per-step price; same figure ops/join's docstrings cite)
_GATHER_SEC_PER_ELEM = 15e-9


def _key_word_count(key_dtypes) -> Tuple[int, bool]:
    """(radix key words, fixed-width-only) for the chooser's static cost
    model; strings price at their chunk granularity (~2 words/chunk)."""
    words = 0
    fixed = True
    for dt in key_dtypes:
        if isinstance(dt, (T.StringType, T.BinaryType)):
            fixed = False
            words += 4  # typical 16-byte chunk surface
        else:
            words += 2 if dt.to_numpy().itemsize == 8 else 1
    return words, fixed


def choose_join_strategy(
    conf: RapidsConf,
    build_cap: int,
    key_dtypes,
    join_type: str,
    backend: "Optional[str]" = None,
) -> "Tuple[str, str]":
    """Pick the probe lowering for ONE join plan from its STATIC build
    layout — build capacity bucket, key widths, backend — never from
    data (the choice must be a trace-time constant or it would churn the
    compile cache; the runtime fits/unique check inside the DIRECT tier
    stays a lax.cond). Returns ``(strategy, reason)``; the reason rides
    into describe()/explain_metrics and the 'join_strategy' event.

    AUTO resolves:

      * legacy sql.join.pallasProbe.enabled forces PALLAS (back compat);
      * CPU backend -> DIRECT below _RADIX_JOIN_CPU_MIN_BUILD for
        single fixed-width keys (two cheap scatters + consumer fusion),
        RADIX at or above it (the scatter dialect's charged bytes
        dominate — the r10 join shape's 29.8x amplification);
      * otherwise the cheapest of DIRECT (near-serial scatter build +
        two-gather probe), RADIX (bitonic co-sort passes at the derated
        peak HBM rate) and SEARCH (log2(build) gather passes), with
        DIRECT only priced for single fixed-width keys.
    """
    import math

    from ..conf import JOIN_PALLAS_PROBE, JOIN_STRATEGY

    mode = conf.get(JOIN_STRATEGY)
    if mode != "AUTO":
        return mode, "forced by spark.rapids.tpu.sql.join.strategy"
    if conf.get(JOIN_PALLAS_PROBE):
        return ("PALLAS",
                "AUTO: sql.join.pallasProbe.enabled (legacy toggle) — "
                "VMEM-tiled probe kernel")
    if backend is None:
        backend = jax.default_backend()
    words, fixed = _key_word_count(key_dtypes)
    direct_ok = fixed and 0 < words <= 2
    if backend == "cpu":
        if direct_ok and build_cap < _RADIX_JOIN_CPU_MIN_BUILD:
            return ("DIRECT",
                    "AUTO: CPU backend, single fixed-width key, build "
                    f"cap {build_cap} < 2^16 — direct-address tables "
                    "are two cheap scatters and the probe fuses into "
                    "its consumer chain")
        return ("RADIX",
                "AUTO: CPU backend at build cap "
                f"{build_cap} — the scatter dialect charges the "
                "direct-address tables far past the layout bound "
                "(BENCH_r10 join: 29.8x); the co-sorted merge is sized "
                "to the bound")
    from .aggregate import _HBM_DERATE, _roofline_peaks

    if (direct_ok and build_cap <= (1 << 20)
            and join_type in ("inner", "left", "semi", "anti")):
        # the direct table probes with two gathers AND fuses the whole
        # join into its consumer chain (one dispatch) — for the
        # dense-dim-key case the fusion is worth more than any probe
        # micro-cost; past ~2^20 the 4x-cap tables and their scatter
        # build stop amortizing. Full joins can never fuse (the
        # unmatched-build pass), so they fall to the cost comparison
        # below instead of paying the scatter build for nothing
        return ("DIRECT",
                f"AUTO: single fixed-width key, build cap {build_cap} "
                "<= 2^20 — the direct-address table probes with two "
                "gathers and fuses into its consumer chain")
    hbm_bps, _ = _roofline_peaks(conf, backend)
    hbm_eff = _HBM_DERATE * hbm_bps
    lg = max(1, math.ceil(math.log2(max(2, build_cap))))
    key_bytes = 4 * max(1, words)
    # probe capacity is not known at build time; a probe side at least
    # as large as the build is the hash-join common case, so per-side
    # costs use build_cap for both surfaces. The search's gather chain
    # is priced at the chip's near-serial random-access gather rate —
    # the reason the sequential-bandwidth merge exists at all
    search_s = (2 * lg * build_cap * max(1, words)
                * _GATHER_SEC_PER_ELEM)
    sort_passes = lg * (lg + 1) / 2  # bitonic compare-exchange rounds
    radix_s = (2 * build_cap * (key_bytes + 12) * sort_passes
               + 4 * build_cap * 8) / hbm_eff
    pick = "RADIX" if radix_s < search_s else "SEARCH"
    return (pick,
            f"AUTO: est radix {radix_s * 1e3:.1f}ms "
            f"({sort_passes:.0f} passes) vs search "
            f"{search_s * 1e3:.1f}ms ({2 * lg} gather passes) at build "
            f"cap={build_cap}, {hbm_bps / 1e9:.0f}GB/s peak")


def _concat_all(conf, exec_: TpuExec) -> Optional[ColumnarBatch]:
    """Materialize every partition of an exec into ONE batch (build side)."""
    batches: List[ColumnarBatch] = []
    for p in range(exec_.num_partitions):
        for b in exec_.execute_partition(p):
            if b.num_rows > 0:
                batches.append(b)
    return _concat_batches(exec_.output_schema, batches)


def _concat_partition(exec_: TpuExec, index: int) -> Optional[ColumnarBatch]:
    """Materialize ONE partition of an exec into one batch."""
    batches = [
        b for b in exec_.execute_partition(index) if b.num_rows > 0
    ]
    return _concat_batches(exec_.output_schema, batches)


def _concat_batches(
    schema: StructType, batches: List[ColumnarBatch]
) -> Optional[ColumnarBatch]:
    if not batches:
        return None
    # sort/window/join kernels want the plain Arrow string layout (byte
    # chunk keys, row-repeating gathers): dict columns materialize here
    from .base import materialized_batch

    batches = [materialized_batch(b) for b in batches]
    if len(batches) == 1:
        return batches[0]
    lengths = [b.num_rows for b in batches]
    str_cols = [
        j for j, f in enumerate(schema.fields)
        if isinstance(f.dataType, (T.StringType, T.BinaryType))
    ]
    byte_lengths = [
        [int(b.columns[j].offsets[b.num_rows]) for j in str_cols]
        for b in batches
    ]
    out_cap = choose_capacity(sum(lengths))
    out_char_caps = [
        choose_capacity(max(1, sum(bl[k] for bl in byte_lengths)), 128)
        for k in range(len(str_cols))
    ]
    cols, n = concat_ops.concat_batches_cols(
        [vals_of_batch(b) for b in batches], lengths, byte_lengths,
        out_cap, out_char_caps,
    )
    return batch_from_vals(cols, schema, n)


class TpuShuffledHashJoinExec(TpuExec):
    """Build right side once, stream probe batches from the left.

    Handles inner/left/right/full/semi/anti equi-joins plus an optional
    residual condition on inner joins (reference: GpuShuffledHashJoinBase +
    GpuHashJoin condition handling)."""

    def __init__(
        self,
        conf: RapidsConf,
        left: TpuExec,
        right: TpuExec,
        left_keys: Sequence[E.Expression],
        right_keys: Sequence[E.Expression],
        join_type: str = "inner",
        condition: Optional[E.Expression] = None,
        partitioned: bool = False,
    ):
        super().__init__(conf, [left, right])
        if join_type not in _JOIN_TYPES:
            raise ValueError(f"unknown join type {join_type}")
        self.join_type = join_type
        #: True when both sides are co-partitioned by the join keys (the
        #: planner inserted hash exchanges): build/probe stay per-partition
        self.partitioned = partitioned
        self.condition = condition
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        # right joins: swap sides, permute output columns back at the end
        self._swap = join_type == "right"
        self._probe = right if self._swap else left
        self._build = left if self._swap else right
        self._probe_keys = [
            E.bind_references(k, self._probe.output_schema)
            for k in (right_keys if self._swap else left_keys)
        ]
        self._build_keys = [
            E.bind_references(k, self._build.output_schema)
            for k in (left_keys if self._swap else right_keys)
        ]
        self._jt = "left" if self._swap else join_type

        lf = left.output_schema.fields
        rf = right.output_schema.fields
        if join_type in ("semi", "anti"):
            self._schema = StructType(tuple(lf))
        else:
            nl = join_type in ("right", "full")
            nr = join_type in ("left", "full")
            self._schema = StructType(tuple(
                [StructField(f.name, f.dataType, f.nullable or nl) for f in lf]
                + [StructField(f.name, f.dataType, f.nullable or nr) for f in rf]
            ))
        if condition is not None:
            if join_type != "inner":
                raise ValueError(
                    "residual join conditions only supported for inner joins")
            comb = StructType(tuple(lf) + tuple(rf))
            self._cond = E.bind_references(condition, comb)
        else:
            self._cond = None
        self._built = None  # lazy build-side state
        self._fast_built = None  # lazy direct-address build (None=untried)
        self._build_batch = None  # concatenated build input, shared by both paths
        # join strategy (conf sql.join.strategy): resolved lazily per
        # build capacity bucket — the choice must see the real build
        # shape — and memoized so AUTO never flips mid-plan (same
        # contract as the aggregate's _strategy_by_cap)
        self._strategy_by_cap: dict = {}
        self._join_strategy_choice: Optional[Tuple[str, str]] = None

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        # full outer needs a global unmatched-build pass: single partition
        # unless the sides are co-partitioned (unmatched rows stay local)
        if self.join_type == "full" and not self.partitioned:
            return 1
        return self._probe.num_partitions

    def describe(self):
        strat = (f", strategy={self._join_strategy_choice[0]}"
                 if self._join_strategy_choice is not None else "")
        return f"TpuShuffledHashJoinExec({self.join_type}{strat})"

    def resolved_strategy(self, build_cap: int) -> str:
        """Resolve (and memoize per build capacity bucket) the probe
        lowering for this plan. The choice lands in describe() — and
        thus explain_metrics() — and emits ONE 'join_strategy' event per
        (exec, build capacity), so tools/tpu_profile.py can hold the
        chooser accountable against the measured op spans of the same
        log (the agg resolved_strategy contract)."""
        hit = self._strategy_by_cap.get(build_cap)
        if hit is not None:
            return hit
        strategy, reason = choose_join_strategy(
            self.conf, build_cap,
            [k.dtype for k in self._build_keys], self._jt)
        self._strategy_by_cap[build_cap] = strategy
        self._join_strategy_choice = (strategy, reason)
        from .. import events as _events
        from .. import obs as _obs

        if _events.enabled():
            _events.emit("join_strategy", op=self.node_name,
                         strategy=strategy, reason=reason,
                         build_cap=build_cap)
        if _obs.enabled():
            _obs.inc("tpu_join_strategy", 1, strategy=strategy)
        return strategy

    # -- build side --------------------------------------------------------
    def _key_str_lens(self, batch, keys) -> Tuple[int, ...]:
        lens = []
        for k in keys:
            if isinstance(k.dtype, (T.StringType, T.BinaryType)):
                if isinstance(k, E.BoundReference):
                    c = batch.columns[k.ordinal]
                    m = int(max_string_len(StrV(c.offsets, c.chars, c.validity)))
                else:
                    m = 64
                lens.append(max(4, choose_capacity(max(1, m), 4)))
        return tuple(lens)

    def _concat_build(self) -> ColumnarBatch:
        """Concatenate the whole build side ONCE, shared between the fast
        direct-address build and the sorted general build (a runtime
        fast-path rejection must not re-execute the build subtree)."""
        if self._build_batch is None:
            batch = _concat_all(self.conf, self._build)
            if batch is None:
                bschema = self._build.output_schema
                batch = ColumnarBatch.from_pydict(
                    {f.name: [] for f in bschema.fields}, bschema)
            self._build_batch = batch
        return self._build_batch

    def _get_build(self, index: Optional[int] = None):
        """Build-side state; ``index`` keys per-partition builds when the
        sides are co-partitioned."""
        if self._built is None:
            self._built = {}
        if index in self._built:
            return self._built[index]
        if index is not None:
            batch = _concat_partition(self._build, index)
            if batch is None:
                bschema = self._build.output_schema
                batch = ColumnarBatch.from_pydict(
                    {f.name: [] for f in bschema.fields}, bschema)
        else:
            batch = self._concat_build()
        cap = batch.capacity
        n = batch.num_rows
        sml = self._key_str_lens(batch, self._build_keys)
        strategy = self.resolved_strategy(cap)

        def prep(cols, num_rows):
            live = filter_gather.live_of(num_rows, cap)
            keys = [lower(k, cols, cap) for k in self._build_keys]
            words, any_null = join_ops.radix_key_words(
                keys, [k.dtype for k in self._build_keys], sml)
            ok = live & ~any_null
            # sort build rows: joinable rows first, then live null-key rows
            # (they can never match, but full outer must still emit them),
            # dead padding last
            order_rank = jnp.where(ok, 0, jnp.where(live, 1, 2))
            perm, sorted_radix = sort_with_radix_keys(
                keys, [k.dtype for k in self._build_keys],
                [SortOrder(True, True) for _ in keys],
                order_rank == 0, sml)
            live_all = jnp.take(live, perm, mode="clip")
            sorted_cols = filter_gather.gather(cols, perm, live_all)
            sorted_words = [jnp.take(w, perm, mode="clip") for w in words]
            count = jnp.sum(ok.astype(jnp.int32))
            return sorted_cols, sorted_words, count, live_all

        fn = self._jit_cache_get(
            ("build", batch_signature(batch), cap, sml, strategy), prep)
        sorted_cols, sorted_words, count, live_all = fn(
            vals_of_batch(batch), count_scalar(n))
        # the build side is registered with the buffer catalog so memory
        # pressure can spill it between build and probe (reference:
        # SpillableColumnarBatch around the concatenated build table,
        # GpuShuffledHashJoinExec). The registration runs under this
        # exec's op scope: builds happen lazily on first probe — outside
        # any op_timed section — so the HBM ledger would otherwise book
        # the plan-state bytes as unattributed.
        from .. import xla_cost as _xc

        with _xc.op_scope(self.node_name):
            sb = _SpillableBuild(sorted_cols, sorted_words, live_all)
        # the raw concatenated batch must NOT ride in the tuple: the handle
        # is the only reference so a spill actually frees the device copy
        built = (sb, int(count), cap, sml)
        self._built[index] = built
        if index is None:
            self._build_batch = None  # sorted spillable state replaces it
        return built

    # -- fused fast paths (fusable) ----------------------------------------
    # When the probe can run as a pure masked transform — no expansion
    # plan, no output-size sync — the whole join FUSES into the consumer
    # chain (e.g. scan->join->aggregate is ONE XLA dispatch). Two
    # variants, picked by the resolved strategy:
    #
    #   * DIRECT: the build keys form a dense-enough range (TPC-DS
    #     dim-key case) AND are unique (or the join only needs a
    #     membership bit) — one packed (first,count) table lookup + one
    #     packed build-row gather per probe batch;
    #   * RADIX:  the build keys are UNIQUE (any fixed-width key set, no
    #     density requirement; semi/anti need not even that) — the probe
    #     co-sorts against the HBM-resident sorted build words
    #     (ops/join.radix_probe_ranges) INSIDE the fused program, so no
    #     scatter-built table and no cap-sized join output ever
    #     materializes; a matched probe row gathers its single build row
    #     at lo.
    #
    # Each syncs ONE feasibility word per build (fits/unique for DIRECT,
    # unique for RADIX) — the only host round trip the fast paths take.
    # Reference contract: GpuHashJoin.doJoinLeftRight
    # (execution/GpuHashJoin.scala:265) — cudf probes a hash table.

    def _fast_static_ok(self, strategy: str = "DIRECT") -> bool:
        if self.partitioned or self._jt not in ("inner", "left", "semi", "anti"):
            return False
        words = 0
        for k in self._build_keys:
            if isinstance(k.dtype, (T.StringType, T.BinaryType)):
                return False
            words += 2 if k.dtype.to_numpy().itemsize == 8 else 1
        if len(self._build_keys) == 0:
            return False
        if strategy == "DIRECT" and words > 2:
            return False  # the packed table key is one u64
        if self._jt in ("inner", "left"):
            # appended build columns gather as one packed matrix: fixed,
            # packable dtypes only (f64 has no lossless 32-bit split)
            from ..ops.filter_gather import packable_dtype

            for f in self._build.output_schema.fields:
                if isinstance(f.dataType, (T.StringType, T.BinaryType)):
                    return False
                if not packable_dtype(f.dataType.to_numpy()):
                    return False
        return True

    def _try_fast_build(self):
        """Build the fused fast-path state once (see the section comment);
        returns the state dict or False."""
        if self._fast_built is not None:
            return self._fast_built
        if not self._fast_static_ok("ANY"):
            self._fast_built = False
            return False
        batch = self._concat_build()
        strategy = self.resolved_strategy(batch.capacity)
        if strategy == "RADIX":
            # no RADIX-specific static precondition beyond the common
            # "ANY" gate above (any fixed-width key set qualifies)
            self._fast_built = self._radix_fast_build(batch)
            return self._fast_built
        from ..conf import JOIN_PALLAS_PROBE, JOIN_STRATEGY

        legacy_pallas = (strategy == "PALLAS"
                         and self.conf.get(JOIN_STRATEGY) == "AUTO"
                         and self.conf.get(JOIN_PALLAS_PROBE))
        if strategy == "DIRECT" or legacy_pallas:
            # the fused whole-join fast path. The legacy pallasProbe
            # toggle only ever governed the GENERAL probe path — the
            # direct fast path pre-empted it before the strategy conf
            # existed, so under AUTO it still does (the conf's
            # keep-their-behavior contract); a forced
            # sql.join.strategy=PALLAS does disable it
            if not self._fast_static_ok("DIRECT"):
                self._fast_built = False
                return False
        else:
            # SEARCH / forced PALLAS (and infeasible shapes) probe
            # through the general per-batch path
            self._fast_built = False
            return False
        bcap = batch.capacity
        tbl = 4 * bcap
        need_mat = self._jt in ("inner", "left")
        kd = [k.dtype for k in self._build_keys]

        def prep(cols, num_rows):
            from ..ops import filter_gather

            live = filter_gather.live_of(num_rows, bcap)
            keys = [lower(k, cols, bcap) for k in self._build_keys]
            words, any_null = join_ops.radix_key_words(keys, kd, ())
            ok = live & ~any_null
            key64 = join_ops._pack_u64(words)
            has = jnp.any(ok)
            kmin = jnp.min(jnp.where(ok, key64, jnp.uint64(2**64 - 1)))
            kmax = jnp.max(jnp.where(ok, key64, jnp.uint64(0)))
            fits = (~has) | ((kmax - kmin) < jnp.uint64(tbl))
            diffu = key64 - kmin
            off = jnp.where(ok & (diffu < jnp.uint64(tbl)), diffu, jnp.uint64(tbl)
                            ).astype(jnp.int64)
            bidx = jnp.arange(bcap, dtype=jnp.int32)
            first = jnp.full(tbl, bcap, jnp.int32).at[off].min(bidx, mode="drop")
            cnt = jnp.zeros(tbl, jnp.int32).at[off].add(1, mode="drop")
            unique = jnp.max(cnt) <= 1
            packed_tbl = jnp.stack([first, cnt], axis=-1)
            outs = (packed_tbl, kmin, fits, unique)
            if need_mat:
                from ..ops.filter_gather import pack_fixed_cols

                outs = outs + (pack_fixed_cols(list(cols)),)
            return outs

        fn = self._jit_cache_get(
            ("fastbuild", batch_signature(batch), bcap, need_mat,
             "DIRECT"), prep)
        res = fn(vals_of_batch(batch), count_scalar(batch.num_rows_lazy))
        packed_tbl, kmin, fits, unique = res[:4]
        from .base import host_pull

        fits_h, unique_h = (bool(x) for x in host_pull((fits, unique)))
        if not fits_h or (not unique_h and self._jt in ("inner", "left")):
            self._fast_built = False
            return False
        from ..memory import ACTIVE_BATCHING_PRIORITY
        from ..memory.catalog import SpillableHandle

        from .. import xla_cost as _xc

        arrays = {"tbl": packed_tbl, "kmin": kmin}
        if need_mat:
            arrays["mat"] = res[4]
        # fast builds run at fusion-planning time, outside op_timed:
        # scope the registration so the ledger attributes the state
        with _xc.op_scope(self.node_name):
            handle = SpillableHandle(arrays, ACTIVE_BATCHING_PRIORITY,
                                     ledger_kind="plan_state")
        state = {
            "kind": "direct",
            "handle": handle,
            "has_mat": need_mat,
        }
        if need_mat:
            state["dtypes"] = tuple(
                c.data.dtype for c in vals_of_batch(batch)
            )
        self._fast_built = state
        # the raw concatenated batch is no longer needed: only the
        # spill-registered table/matrix state survives (holding both would
        # pin two copies of the build side in HBM)
        self._build_batch = None
        return state

    def _radix_fast_build(self, batch):
        """RADIX fused-probe state: the sorted build key words (+ packed
        build-column matrix for inner/left). Inner/left require UNIQUE
        build keys — a probe row then owns at most one output row and
        the join stays a pure masked transform; semi/anti only need the
        membership bit and take any build. Syncs ONE unique flag."""
        bcap = batch.capacity
        need_mat = self._jt in ("inner", "left")
        kd = [k.dtype for k in self._build_keys]

        def prep(cols, num_rows):
            live = filter_gather.live_of(num_rows, bcap)
            keys = [lower(k, cols, bcap) for k in self._build_keys]
            words, any_null = join_ops.radix_key_words(keys, kd, ())
            ok = live & ~any_null
            perm, _ = sort_with_radix_keys(
                keys, kd, [SortOrder(True, True) for _ in keys], ok, ())
            sorted_words = [jnp.take(w, perm, mode="clip") for w in words]
            count = jnp.sum(ok.astype(jnp.int32))
            # unique = no adjacent equal keys among the joinable prefix
            idx = jnp.arange(bcap, dtype=jnp.int32)
            inner_pos = (idx >= 1) & (idx < count)
            same = inner_pos
            for w in sorted_words:
                same = same & (w == jnp.concatenate([w[:1], w[:-1]]))
            unique = ~jnp.any(same)
            outs = (sorted_words, count, unique)
            if need_mat:
                from ..ops.filter_gather import pack_fixed_cols

                live_all = jnp.take(live, perm, mode="clip")
                sorted_cols = filter_gather.gather(cols, perm, live_all)
                outs = outs + (pack_fixed_cols(list(sorted_cols)),)
            return outs

        fn = self._jit_cache_get(
            ("fastbuild", batch_signature(batch), bcap, need_mat,
             "RADIX"), prep)
        res = fn(vals_of_batch(batch), count_scalar(batch.num_rows_lazy))
        sorted_words, count, unique = res[:3]
        from .base import host_pull

        if need_mat and not bool(host_pull(unique)):
            return False  # duplicate build keys: general RADIX path
        from ..memory import ACTIVE_BATCHING_PRIORITY
        from ..memory.catalog import SpillableHandle

        from .. import xla_cost as _xc

        arrays = {f"w{i}": w for i, w in enumerate(sorted_words)}
        arrays["count"] = count
        if need_mat:
            arrays["mat"] = res[3]
        with _xc.op_scope(self.node_name):
            handle = SpillableHandle(arrays, ACTIVE_BATCHING_PRIORITY,
                                     ledger_kind="plan_state")
        state = {
            "kind": "radix",
            "handle": handle,
            "has_mat": need_mat,
            "nwords": len(sorted_words),
        }
        if need_mat:
            state["dtypes"] = tuple(
                c.data.dtype for c in vals_of_batch(batch))
        self._build_batch = None
        return state

    @property
    def fusable(self):
        return bool(self._try_fast_build())

    @property
    def sparsifies(self):
        return self._jt in ("inner", "semi", "anti")

    def fusion_stream_child(self):
        return self._probe

    def fusion_key(self):
        st = self._fast_built if isinstance(self._fast_built, dict) else {}
        return (
            "join_fast", st.get("kind", "direct"), self._jt, self._swap,
            tuple(repr(k) for k in self._probe_keys), repr(self._cond),
            tuple(str(dt) for dt in st.get("dtypes", ())),
        )

    def side_vals(self) -> tuple:
        st = self._try_fast_build()
        assert isinstance(st, dict)
        a = st["handle"].materialize()
        if st["kind"] == "radix":
            out = tuple(a[f"w{i}"] for i in range(st["nwords"]))
            out = out + (a["count"],)
        else:
            out = (a["tbl"], a["kmin"])
        if st["has_mat"]:
            out = out + (a["mat"],)
        return out

    def lower_batch(self, cols, live, cap, side=()):
        from ..expr.values import DictV as _DictV, as_plain_str

        st = self._fast_built
        keys = [lower(k, cols, cap) for k in self._probe_keys]
        # dict-encoded probe keys expand to bytes for the radix words;
        # non-key dict columns stream through encoded (mask-only path)
        keys = [as_plain_str(v) if isinstance(v, _DictV) else v for v in keys]
        words, any_null = join_ops.radix_key_words(
            keys, [k.dtype for k in self._probe_keys], ())
        ok = live & ~any_null
        if st["kind"] == "radix":
            # co-sorted merge against the HBM-resident sorted build
            # words, INSIDE the fused program: zero scatters, no table
            nw = st["nwords"]
            bwords = list(side[:nw])
            lo, hi, _ = join_ops.radix_probe_ranges(
                bwords, side[nw].astype(jnp.int32), words, ok,
                lo_matched_only=True)
            matched = ok & (hi > lo)
            brow = jnp.where(matched, lo, 0)
            mat_idx = nw + 1
        else:
            packed_tbl, kmin = side[0], side[1]
            tbl = packed_tbl.shape[0]
            key64 = join_ops._pack_u64(words)
            diffu = key64 - kmin
            pin = ok & (key64 >= kmin) & (diffu < jnp.uint64(tbl))
            pc = jnp.where(pin, diffu, jnp.uint64(0)).astype(jnp.int32)
            fc = jnp.take(packed_tbl, pc, axis=0, mode="clip")
            matched = pin & (fc[:, 1] > 0)
            brow = jnp.where(matched, fc[:, 0], 0)
            mat_idx = 2
        jt = self._jt
        if jt == "semi":
            return list(cols), live & matched
        if jt == "anti":
            return list(cols), live & ~matched
        from ..ops.filter_gather import unpack_fixed_cols

        bvals = unpack_fixed_cols(
            jnp.take(side[mat_idx], brow, axis=0, mode="clip"),
            list(st["dtypes"]), matched)
        out = (
            list(bvals) + list(cols) if self._swap
            else list(cols) + list(bvals)
        )
        live_out = (live & matched) if jt == "inner" else live
        if self._cond is not None:
            c = lower(self._cond, out, cap)
            live_out = live_out & c.data & c.validity
        return out, live_out

    # -- probe -------------------------------------------------------------
    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        if self._try_fast_build():
            from .base import run_fused_chain

            yield from run_fused_chain(self, index)
            return
        (sb, build_count, build_cap, bsml) = self._get_build(
            index if self.partitioned else None)
        build_cols, build_words, build_live_all = sb.get()
        build_schema = self._build.output_schema
        matched_any = (
            jnp.zeros(build_cap, jnp.bool_) if self.join_type == "full" else None
        )
        probe_parts = (
            range(self._probe.num_partitions)
            if self.join_type == "full" and not self.partitioned
            else [index]
        )
        from ..memory.retry import with_oom_retry

        def probe_attempt(b):
            from .base import materialized_batch

            # join expansion repeats rows: dict columns materialize
            # up front (their byte bound only covers row subsets)
            return self._probe_batch(
                materialized_batch(b), build_cols, build_words,
                build_count, build_cap)

        for pi in probe_parts:
            for pbatch in self._probe.execute_partition(pi):
                # probe rows are row-local against the intact build side,
                # so split-and-retry streams each half's output as its
                # own batch (combine="list") — half-capacity probe
                # programs, exact results
                with self.op_timed("probe"):
                    outs = with_oom_retry(
                        self.node_name, probe_attempt, pbatch, self.conf,
                        combine="list")
                for out in outs:
                    if out is None:
                        continue
                    batch, matched = out
                    if matched is not None and matched_any is not None:
                        matched_any = matched_any | matched
                    if batch is not None and batch.num_rows > 0:
                        yield self.record_batch(batch)
        if self.join_type == "full":
            yield from self._unmatched_build(
                build_cols, build_live_all, matched_any)

    def _probe_batch(self, pbatch, build_cols, build_words, build_count, build_cap):
        cap = pbatch.capacity if pbatch.columns else 128
        psml = self._key_str_lens(pbatch, self._probe_keys)
        jt = self._jt
        strategy = self.resolved_strategy(build_cap)
        # full outer under RADIX derives the matched-build mask from the
        # SAME co-sorted merge (scatter-free); other tiers keep the
        # eager range-delta mask (one scatter pair)
        radix_matched = self.join_type == "full" and strategy == "RADIX"

        # build words/count enter as jit ARGUMENTS (not closure constants):
        # with per-partition builds the same compiled probe must serve every
        # partition's build data
        def count_phase(cols, num_rows, bwords, bcount):
            live = filter_gather.live_of(num_rows, cap)
            keys = [lower(k, cols, cap) for k in self._probe_keys]
            words, any_null = join_ops.radix_key_words(
                keys, [k.dtype for k in self._probe_keys], psml)
            ok = live & ~any_null
            matched_b = None
            if radix_matched:
                lo, hi, matched_b = join_ops.radix_probe_ranges(
                    bwords, bcount.astype(jnp.int32), words, ok,
                    want_matched=True)
            else:
                lo, hi = join_ops.probe_ranges(
                    bwords, bcount.astype(jnp.int32), words, ok,
                    strategy=strategy)
            counts = hi - lo
            if jt in ("semi", "anti"):
                keep = (counts > 0) if jt == "semi" else (live & (counts == 0))
                if jt == "semi":
                    keep = keep & ok
                return lo, counts, keep, live, matched_b
            if jt in ("left", "full"):
                ex_counts = jnp.where(live & (counts == 0), 1, counts)
                ex_counts = jnp.where(live, ex_counts, 0)
            else:  # inner probe side
                ex_counts = jnp.where(live, counts, 0)
            return lo, counts, ex_counts, live, matched_b

        ckey = ("count", batch_signature(pbatch), cap, psml, build_cap,
                len(build_words), strategy)
        fn = self._jit_cache_get(ckey, count_phase)
        lo, counts, aux, live, matched = fn(
            vals_of_batch(pbatch), count_scalar(pbatch.num_rows_lazy),
            list(build_words), jnp.int32(build_count))

        if self.join_type == "full" and matched is None:
            matched = join_ops.matched_build_mask(lo, lo + counts, live, build_cap)

        if jt in ("semi", "anti"):
            from .base import _donation as _don_semi

            vals, count = filter_gather.filter_cols(
                vals_of_batch(pbatch), aux, pbatch.num_rows_lazy)
            # the compacted output's planes are freshly gathered — no
            # other reference exists, so downstream sites may donate
            return _don_semi().mark_exclusive(
                batch_from_vals(vals, self._schema, count)), matched

        total = int(jnp.sum(aux))
        if total == 0:
            return None, matched
        out_cap = choose_capacity(total, self.conf.shape_bucket_min)

        # the RADIX tier expands scatter-free (prefix-sum searchsorted);
        # other tiers keep the two-repeat plan (scatter+cumsum under the
        # hood, ~20x faster than the search on TPU)
        expand_plan = (join_ops.radix_expansion_plan
                       if strategy == "RADIX" else join_ops.expansion_plan)
        has_strings = any(isinstance(c, StrV) for c in build_cols) or any(
            c.is_string for c in pbatch.columns)
        if has_strings:
            # string outputs need host-synced byte capacities; keep the
            # original eager path for those
            p, build_row, slot_live = expand_plan(aux, lo, out_cap)
            pad_slot = slot_live & (jnp.take(counts, p, mode="clip") == 0)
            build_live = slot_live & ~pad_slot

            def str_caps(cols, rows, live_mask):
                caps = []
                for c in cols:
                    if isinstance(c, StrV):
                        lens = c.offsets[1:] - c.offsets[:-1]
                        need = jnp.sum(jnp.where(
                            live_mask, jnp.take(lens, rows, mode="clip"), 0))
                        caps.append(choose_capacity(max(1, int(need)), 128))
                return caps

            probe_side = filter_gather.gather(
                vals_of_batch(pbatch), p, slot_live,
                str_caps(vals_of_batch(pbatch), p, slot_live))
            build_side = filter_gather.gather(
                build_cols, build_row, build_live,
                str_caps(build_cols, build_row, build_live))
        else:
            # fixed-width: the whole expansion (plan + pad mask + both
            # gathers) is ONE jitted program — eager per-op dispatch over
            # out_cap-sized arrays dominated join wallclock otherwise
            def expand_phase(pvals, bcols, lo_, counts_, aux_):
                p, build_row, slot_live = expand_plan(aux_, lo_, out_cap)
                pad_slot = slot_live & (
                    jnp.take(counts_, p, mode="clip") == 0)
                build_live = slot_live & ~pad_slot
                return (
                    filter_gather.gather(pvals, p, slot_live),
                    filter_gather.gather(bcols, build_row, build_live),
                )

            ekey = ("expand", batch_signature(pbatch), out_cap,
                    len(build_cols),
                    tuple(int(c.data.shape[0]) for c in build_cols),
                    strategy)
            from .base import _donation

            don = _donation()
            # the expand dispatch is the LAST read of the probe planes
            # (count_phase above already ran) — the one join program
            # certified to donate; build_cols (argnum 1) serve every
            # probe batch and never donate
            mask = don.dispatch_mask("join", pbatch, self.conf)
            fne = self._jit_cache_get(ekey, expand_phase, donate=mask)
            if mask:
                # with_oom_retry re-dispatches this probe batch on OOM,
                # so the guard snapshots/restores its planes
                with don.guard("join", pbatch, op=self.node_name,
                               conf=self.conf,
                               metric=self.metric("donatedBytes")):
                    probe_side, build_side = fne(
                        vals_of_batch(pbatch), list(build_cols), lo,
                        counts, aux)
            else:
                probe_side, build_side = fne(
                    vals_of_batch(pbatch), list(build_cols), lo, counts,
                    aux)
        left_side, right_side = (
            (build_side, probe_side) if self._swap else (probe_side, build_side)
        )
        vals = list(left_side) + list(right_side)
        out = batch_from_vals(vals, self._schema, total)
        if self._cond is not None:
            ocap = out.capacity

            def apply_cond(cols, num_rows):
                livec = filter_gather.live_of(num_rows, ocap)
                c = lower(self._cond, cols, ocap)
                mask = livec & c.data & c.validity
                return filter_gather.filter_cols(cols, mask, num_rows)

            fnc = self._jit_cache_get(
                ("cond", batch_signature(out), ocap), apply_cond)
            vals2, cnt = fnc(
                vals_of_batch(out), count_scalar(out.num_rows_lazy))
            out = batch_from_vals(vals2, self._schema, cnt)
        from .base import _donation as _don_out

        # join outputs are freshly gathered planes with exactly one
        # reference (this yield path) — certified downstream sites
        # (agg over a join, a second join's probe) may donate them
        return _don_out().mark_exclusive(out), matched

    def _jit_cache_get(self, key, fn, donate=()):
        cache = getattr(self, "_jits", None)
        if cache is None:
            cache = self._jits = {}
        # the shared pipeline-cache guard: miss accounting + the
        # compiled-program cost plane ride cached_pipeline (xla_cost.py)
        from .base import cached_pipeline

        return cached_pipeline(cache, key, "join",
                               lambda: jax.jit(fn, donate_argnums=donate),
                               donate=donate)

    def _unmatched_build(self, build_cols, build_live_all, matched_any):
        """full outer: emit build rows no probe row matched (including live
        null-key rows, which can never match), null-padded on the left."""
        unmatched = build_live_all & ~matched_any
        vals, count = filter_gather.filter_cols(build_cols, unmatched, None)
        n = int(count)
        if n == 0:
            return
        lf = self.children[0].output_schema.fields
        cap_out = vals[0].validity.shape[0] if vals else 128
        null_left: List[Val] = []
        for f in lf:
            if isinstance(f.dataType, (T.StringType, T.BinaryType)):
                null_left.append(StrV(
                    jnp.zeros(cap_out + 1, jnp.int32),
                    jnp.zeros(1, jnp.uint8),
                    jnp.zeros(cap_out, jnp.bool_),
                ))
            else:
                null_left.append(ColV(
                    jnp.zeros(cap_out, dtype=f.dataType.to_numpy()),
                    jnp.zeros(cap_out, jnp.bool_),
                ))
        out = batch_from_vals(null_left + list(vals), self._schema, n)
        yield self.record_batch(out)


class TpuBroadcastNestedLoopJoinExec(TpuExec):
    """Cartesian/conditioned nested-loop join (reference:
    GpuBroadcastNestedLoopJoinExec.scala:311, GpuCartesianProductExec).

    Inner-only: every (probe, build) pair is generated with static shapes
    and the condition filters it."""

    def __init__(self, conf: RapidsConf, left: TpuExec, right: TpuExec,
                 condition: Optional[E.Expression] = None):
        super().__init__(conf, [left, right])
        lf, rf = left.output_schema.fields, right.output_schema.fields
        self._schema = StructType(tuple(lf) + tuple(rf))
        self._cond = (
            E.bind_references(condition, self._schema)
            if condition is not None else None
        )
        self._built = None

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def describe(self):
        return self.node_name

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        if self._built is None:
            self._built = _concat_all(self.conf, self.children[1])
        build = self._built
        if build is None:
            return
        nb = build.num_rows
        build_vals = vals_of_batch(build)
        for pbatch in self.children[0].execute_partition(index):
            from .base import materialized_batch

            pbatch = materialized_batch(pbatch)
            np_ = pbatch.num_rows
            if np_ == 0 or nb == 0:
                continue
            out_cap = choose_capacity(np_ * nb, self.conf.shape_bucket_min)
            pcap = pbatch.capacity
            pcaps = [
                choose_capacity(max(1, int(c.offsets[np_]) * nb), 128)
                for c in pbatch.columns if c.is_string
            ]
            bcaps = [
                choose_capacity(max(1, int(c.offsets[nb]) * np_), 128)
                for c in build.columns if c.is_string
            ]

            def expand(pcols, bcols):
                j = jnp.arange(out_cap, dtype=jnp.int32)
                pi = j // nb
                bi = j % nb
                slot_live = j < (np_ * nb)
                left_side = filter_gather.gather(pcols, pi, slot_live, pcaps)
                right_side = filter_gather.gather(bcols, bi, slot_live, bcaps)
                cols = list(left_side) + list(right_side)
                if self._cond is not None:
                    c = lower(self._cond, cols, out_cap)
                    mask = slot_live & c.data & c.validity
                    cols, count = filter_gather.filter_cols(cols, mask, np_ * nb)
                    return cols, count
                return cols, jnp.int32(np_ * nb)

            cache = getattr(self, "_jits", None)
            if cache is None:
                cache = self._jits = {}
            key = (batch_signature(pbatch), out_cap, np_, nb)
            from .base import _donation, cached_pipeline

            don = _donation()
            # probe planes (argnum 0) are dead after the expansion —
            # the build side (argnum 1) is retained for every probe
            # batch and never donates (the "join" certification)
            mask = don.dispatch_mask("join", pbatch, self.conf)
            fn = cached_pipeline(cache, key, "join",
                                 lambda: jax.jit(expand,
                                                 donate_argnums=mask),
                                 donate=mask)
            with self.op_timed():
                if mask:
                    # no retry harness wraps this dispatch: skip the
                    # guard's host snapshot leg
                    with don.guard("join", pbatch, op=self.node_name,
                                   snapshot=False,
                                   metric=self.metric("donatedBytes")):
                        vals, count = fn(vals_of_batch(pbatch),
                                         build_vals)
                else:
                    vals, count = fn(vals_of_batch(pbatch), build_vals)
                n = int(count)
            if n:
                yield self.record_batch(batch_from_vals(vals, self._schema, n))


class TpuCartesianProductExec(TpuBroadcastNestedLoopJoinExec):
    """Unconditioned cross join (reference: GpuCartesianProductExec.scala:304
    — the same pair-expansion kernel as the nested-loop join, no residual
    condition)."""

    def __init__(self, conf: RapidsConf, left: TpuExec, right: TpuExec):
        super().__init__(conf, left, right, condition=None)
