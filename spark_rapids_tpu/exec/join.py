"""Equi-join execs (hash-join family on TPU).

Reference analog: GpuHashJoin.doJoin (execution/GpuHashJoin.scala:158-263) —
build-side table concat + per-stream-batch cudf join; join types inner/left/
right/full/semi/anti (doJoinLeftRight :265). TPU re-design: the build side
is concatenated and radix-SORTED once (ops/join.py), each probe batch runs a
fused count+expand program, and the only host syncs are the build size and
one match-total per probe batch (cudf syncs output sizes at the same
boundaries).

Right joins run as left joins with the sides swapped and the output columns
re-permuted, like the reference's buildSide handling.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..columnar import ColumnarBatch
from ..conf import RapidsConf
from ..expr import expressions as E
from ..expr.eval import ColV, StrV, Val, lower
from ..ops import concat as concat_ops
from ..ops import filter_gather
from ..ops import join as join_ops
from ..ops.sort import max_string_len, sort_with_radix_keys, SortOrder
from ..types import StructField, StructType
from ..utils.bucketing import bucket_rows


class _SpillableBuild:
    """Join build side as catalog-registered spillable buffers: the sorted
    build columns + radix words + liveness round-trip device<->host under
    pressure and re-materialize at probe time (reference:
    SpillableColumnarBatch around the concatenated build table)."""

    def __init__(self, cols, words, live):
        from ..memory import ACTIVE_BATCHING_PRIORITY, SpillableVals
        from ..memory.catalog import SpillableHandle

        self._cols = SpillableVals(cols, ACTIVE_BATCHING_PRIORITY)
        aux = {f"w{i}": w for i, w in enumerate(words)}
        aux["live"] = live
        self._aux = SpillableHandle(aux, ACTIVE_BATCHING_PRIORITY)
        self._nw = len(words)

    def get(self):
        cols = self._cols.get_vals()
        a = self._aux.materialize()
        return cols, [a[f"w{i}"] for i in range(self._nw)], a["live"]
from .base import (
    NUM_OUTPUT_BATCHES,
    TOTAL_TIME,
    TpuExec,
    batch_from_vals,
    batch_signature,
    count_scalar,
    timed,
    vals_of_batch,
)

_JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti", "cross")


def _concat_all(conf, exec_: TpuExec) -> Optional[ColumnarBatch]:
    """Materialize every partition of an exec into ONE batch (build side)."""
    batches: List[ColumnarBatch] = []
    for p in range(exec_.num_partitions):
        for b in exec_.execute_partition(p):
            if b.num_rows > 0:
                batches.append(b)
    return _concat_batches(exec_.output_schema, batches)


def _concat_partition(exec_: TpuExec, index: int) -> Optional[ColumnarBatch]:
    """Materialize ONE partition of an exec into one batch."""
    batches = [
        b for b in exec_.execute_partition(index) if b.num_rows > 0
    ]
    return _concat_batches(exec_.output_schema, batches)


def _concat_batches(
    schema: StructType, batches: List[ColumnarBatch]
) -> Optional[ColumnarBatch]:
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    lengths = [b.num_rows for b in batches]
    str_cols = [
        j for j, f in enumerate(schema.fields)
        if isinstance(f.dataType, (T.StringType, T.BinaryType))
    ]
    byte_lengths = [
        [int(b.columns[j].offsets[b.num_rows]) for j in str_cols]
        for b in batches
    ]
    out_cap = bucket_rows(sum(lengths))
    out_char_caps = [
        bucket_rows(max(1, sum(bl[k] for bl in byte_lengths)), 128)
        for k in range(len(str_cols))
    ]
    cols, n = concat_ops.concat_batches_cols(
        [vals_of_batch(b) for b in batches], lengths, byte_lengths,
        out_cap, out_char_caps,
    )
    return batch_from_vals(cols, schema, n)


class TpuShuffledHashJoinExec(TpuExec):
    """Build right side once, stream probe batches from the left.

    Handles inner/left/right/full/semi/anti equi-joins plus an optional
    residual condition on inner joins (reference: GpuShuffledHashJoinBase +
    GpuHashJoin condition handling)."""

    def __init__(
        self,
        conf: RapidsConf,
        left: TpuExec,
        right: TpuExec,
        left_keys: Sequence[E.Expression],
        right_keys: Sequence[E.Expression],
        join_type: str = "inner",
        condition: Optional[E.Expression] = None,
        partitioned: bool = False,
    ):
        super().__init__(conf, [left, right])
        if join_type not in _JOIN_TYPES:
            raise ValueError(f"unknown join type {join_type}")
        self.join_type = join_type
        #: True when both sides are co-partitioned by the join keys (the
        #: planner inserted hash exchanges): build/probe stay per-partition
        self.partitioned = partitioned
        self.condition = condition
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        # right joins: swap sides, permute output columns back at the end
        self._swap = join_type == "right"
        self._probe = right if self._swap else left
        self._build = left if self._swap else right
        self._probe_keys = [
            E.bind_references(k, self._probe.output_schema)
            for k in (right_keys if self._swap else left_keys)
        ]
        self._build_keys = [
            E.bind_references(k, self._build.output_schema)
            for k in (left_keys if self._swap else right_keys)
        ]
        self._jt = "left" if self._swap else join_type

        lf = left.output_schema.fields
        rf = right.output_schema.fields
        if join_type in ("semi", "anti"):
            self._schema = StructType(tuple(lf))
        else:
            nl = join_type in ("right", "full")
            nr = join_type in ("left", "full")
            self._schema = StructType(tuple(
                [StructField(f.name, f.dataType, f.nullable or nl) for f in lf]
                + [StructField(f.name, f.dataType, f.nullable or nr) for f in rf]
            ))
        if condition is not None:
            if join_type != "inner":
                raise ValueError(
                    "residual join conditions only supported for inner joins")
            comb = StructType(tuple(lf) + tuple(rf))
            self._cond = E.bind_references(condition, comb)
        else:
            self._cond = None
        self._built = None  # lazy build-side state

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        # full outer needs a global unmatched-build pass: single partition
        # unless the sides are co-partitioned (unmatched rows stay local)
        if self.join_type == "full" and not self.partitioned:
            return 1
        return self._probe.num_partitions

    def describe(self):
        return f"TpuShuffledHashJoinExec({self.join_type})"

    # -- build side --------------------------------------------------------
    def _key_str_lens(self, batch, keys) -> Tuple[int, ...]:
        lens = []
        for k in keys:
            if isinstance(k.dtype, (T.StringType, T.BinaryType)):
                if isinstance(k, E.BoundReference):
                    c = batch.columns[k.ordinal]
                    m = int(max_string_len(StrV(c.offsets, c.chars, c.validity)))
                else:
                    m = 64
                lens.append(max(4, bucket_rows(max(1, m), 4)))
        return tuple(lens)

    def _get_build(self, index: Optional[int] = None):
        """Build-side state; ``index`` keys per-partition builds when the
        sides are co-partitioned."""
        if self._built is None:
            self._built = {}
        if index in self._built:
            return self._built[index]
        batch = (
            _concat_partition(self._build, index)
            if index is not None
            else _concat_all(self.conf, self._build)
        )
        if batch is None:
            bschema = self._build.output_schema
            batch = ColumnarBatch.from_pydict(
                {f.name: [] for f in bschema.fields}, bschema)
        cap = batch.capacity if batch.columns else 128
        n = batch.num_rows
        sml = self._key_str_lens(batch, self._build_keys)

        def prep(cols, num_rows):
            live = filter_gather.live_of(num_rows, cap)
            keys = [lower(k, cols, cap) for k in self._build_keys]
            words, any_null = join_ops.radix_key_words(
                keys, [k.dtype for k in self._build_keys], sml)
            ok = live & ~any_null
            # sort build rows: joinable rows first, then live null-key rows
            # (they can never match, but full outer must still emit them),
            # dead padding last
            order_rank = jnp.where(ok, 0, jnp.where(live, 1, 2))
            perm, sorted_radix = sort_with_radix_keys(
                keys, [k.dtype for k in self._build_keys],
                [SortOrder(True, True) for _ in keys],
                order_rank == 0, sml)
            live_all = jnp.take(live, perm, mode="clip")
            sorted_cols = filter_gather.gather(cols, perm, live_all)
            sorted_words = [jnp.take(w, perm, mode="clip") for w in words]
            count = jnp.sum(ok.astype(jnp.int32))
            return sorted_cols, sorted_words, count, live_all

        fn = self._jit_cache_get(
            ("build", batch_signature(batch), cap, sml), prep)
        sorted_cols, sorted_words, count, live_all = fn(
            vals_of_batch(batch), count_scalar(n))
        # the build side is registered with the buffer catalog so memory
        # pressure can spill it between build and probe (reference:
        # SpillableColumnarBatch around the concatenated build table,
        # GpuShuffledHashJoinExec)
        sb = _SpillableBuild(sorted_cols, sorted_words, live_all)
        # the raw concatenated batch must NOT ride in the tuple: the handle
        # is the only reference so a spill actually frees the device copy
        built = (sb, int(count), cap, sml)
        self._built[index] = built
        return built

    # -- probe -------------------------------------------------------------
    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        (sb, build_count, build_cap, bsml) = self._get_build(
            index if self.partitioned else None)
        build_cols, build_words, build_live_all = sb.get()
        build_schema = self._build.output_schema
        matched_any = (
            jnp.zeros(build_cap, jnp.bool_) if self.join_type == "full" else None
        )
        probe_parts = (
            range(self._probe.num_partitions)
            if self.join_type == "full" and not self.partitioned
            else [index]
        )
        for pi in probe_parts:
            for pbatch in self._probe.execute_partition(pi):
                out = self._probe_batch(
                    pbatch, build_cols, build_words, build_count, build_cap)
                if out is None:
                    continue
                batch, matched = out
                if matched is not None and matched_any is not None:
                    matched_any = matched_any | matched
                if batch is not None and batch.num_rows > 0:
                    yield self.record_batch(batch)
        if self.join_type == "full":
            yield from self._unmatched_build(
                build_cols, build_live_all, matched_any)

    def _probe_batch(self, pbatch, build_cols, build_words, build_count, build_cap):
        cap = pbatch.capacity if pbatch.columns else 128
        psml = self._key_str_lens(pbatch, self._probe_keys)
        jt = self._jt

        # build words/count enter as jit ARGUMENTS (not closure constants):
        # with per-partition builds the same compiled probe must serve every
        # partition's build data
        def count_phase(cols, num_rows, bwords, bcount):
            live = filter_gather.live_of(num_rows, cap)
            keys = [lower(k, cols, cap) for k in self._probe_keys]
            words, any_null = join_ops.radix_key_words(
                keys, [k.dtype for k in self._probe_keys], psml)
            ok = live & ~any_null
            lo, hi = join_ops.probe_ranges(
                bwords, bcount.astype(jnp.int32), words, ok)
            counts = hi - lo
            if jt in ("semi", "anti"):
                keep = (counts > 0) if jt == "semi" else (live & (counts == 0))
                if jt == "semi":
                    keep = keep & ok
                return lo, counts, keep, live
            if jt in ("left", "full"):
                ex_counts = jnp.where(live & (counts == 0), 1, counts)
                ex_counts = jnp.where(live, ex_counts, 0)
            else:  # inner probe side
                ex_counts = jnp.where(live, counts, 0)
            return lo, counts, ex_counts, live

        ckey = ("count", batch_signature(pbatch), cap, psml, build_cap,
                len(build_words))
        fn = self._jit_cache_get(ckey, count_phase)
        lo, counts, aux, live = fn(
            vals_of_batch(pbatch), count_scalar(pbatch.num_rows_lazy),
            list(build_words), jnp.int32(build_count))

        matched = None
        if self.join_type == "full":
            matched = join_ops.matched_build_mask(lo, lo + counts, live, build_cap)

        if jt in ("semi", "anti"):
            vals, count = filter_gather.filter_cols(
                vals_of_batch(pbatch), aux, pbatch.num_rows_lazy)
            return batch_from_vals(vals, self._schema, count), matched

        total = int(jnp.sum(aux))
        if total == 0:
            return None, matched
        out_cap = bucket_rows(total, self.conf.shape_bucket_min)

        has_strings = any(isinstance(c, StrV) for c in build_cols) or any(
            c.is_string for c in pbatch.columns)
        if has_strings:
            # string outputs need host-synced byte capacities; keep the
            # original eager path for those
            p, build_row, slot_live = join_ops.expansion_plan(aux, lo, out_cap)
            pad_slot = slot_live & (jnp.take(counts, p, mode="clip") == 0)
            build_live = slot_live & ~pad_slot

            def str_caps(cols, rows, live_mask):
                caps = []
                for c in cols:
                    if isinstance(c, StrV):
                        lens = c.offsets[1:] - c.offsets[:-1]
                        need = jnp.sum(jnp.where(
                            live_mask, jnp.take(lens, rows, mode="clip"), 0))
                        caps.append(bucket_rows(max(1, int(need)), 128))
                return caps

            probe_side = filter_gather.gather(
                vals_of_batch(pbatch), p, slot_live,
                str_caps(vals_of_batch(pbatch), p, slot_live))
            build_side = filter_gather.gather(
                build_cols, build_row, build_live,
                str_caps(build_cols, build_row, build_live))
        else:
            # fixed-width: the whole expansion (plan + pad mask + both
            # gathers) is ONE jitted program — eager per-op dispatch over
            # out_cap-sized arrays dominated join wallclock otherwise
            def expand_phase(pvals, bcols, lo_, counts_, aux_):
                p, build_row, slot_live = join_ops.expansion_plan(
                    aux_, lo_, out_cap)
                pad_slot = slot_live & (
                    jnp.take(counts_, p, mode="clip") == 0)
                build_live = slot_live & ~pad_slot
                return (
                    filter_gather.gather(pvals, p, slot_live),
                    filter_gather.gather(bcols, build_row, build_live),
                )

            ekey = ("expand", batch_signature(pbatch), out_cap,
                    len(build_cols),
                    tuple(int(c.data.shape[0]) for c in build_cols))
            fne = self._jit_cache_get(ekey, expand_phase)
            probe_side, build_side = fne(
                vals_of_batch(pbatch), list(build_cols), lo, counts, aux)
        left_side, right_side = (
            (build_side, probe_side) if self._swap else (probe_side, build_side)
        )
        vals = list(left_side) + list(right_side)
        out = batch_from_vals(vals, self._schema, total)
        if self._cond is not None:
            ocap = out.capacity

            def apply_cond(cols, num_rows):
                livec = filter_gather.live_of(num_rows, ocap)
                c = lower(self._cond, cols, ocap)
                mask = livec & c.data & c.validity
                return filter_gather.filter_cols(cols, mask, num_rows)

            fnc = self._jit_cache_get(
                ("cond", batch_signature(out), ocap), apply_cond)
            vals2, cnt = fnc(
                vals_of_batch(out), count_scalar(out.num_rows_lazy))
            out = batch_from_vals(vals2, self._schema, cnt)
        return out, matched

    def _jit_cache_get(self, key, fn):
        cache = getattr(self, "_jits", None)
        if cache is None:
            cache = self._jits = {}
        if key not in cache:
            cache[key] = jax.jit(fn)
        return cache[key]

    def _unmatched_build(self, build_cols, build_live_all, matched_any):
        """full outer: emit build rows no probe row matched (including live
        null-key rows, which can never match), null-padded on the left."""
        unmatched = build_live_all & ~matched_any
        vals, count = filter_gather.filter_cols(build_cols, unmatched, None)
        n = int(count)
        if n == 0:
            return
        lf = self.children[0].output_schema.fields
        cap_out = vals[0].validity.shape[0] if vals else 128
        null_left: List[Val] = []
        for f in lf:
            if isinstance(f.dataType, (T.StringType, T.BinaryType)):
                null_left.append(StrV(
                    jnp.zeros(cap_out + 1, jnp.int32),
                    jnp.zeros(1, jnp.uint8),
                    jnp.zeros(cap_out, jnp.bool_),
                ))
            else:
                null_left.append(ColV(
                    jnp.zeros(cap_out, dtype=f.dataType.to_numpy()),
                    jnp.zeros(cap_out, jnp.bool_),
                ))
        out = batch_from_vals(null_left + list(vals), self._schema, n)
        yield self.record_batch(out)


class TpuBroadcastNestedLoopJoinExec(TpuExec):
    """Cartesian/conditioned nested-loop join (reference:
    GpuBroadcastNestedLoopJoinExec.scala:311, GpuCartesianProductExec).

    Inner-only: every (probe, build) pair is generated with static shapes
    and the condition filters it."""

    def __init__(self, conf: RapidsConf, left: TpuExec, right: TpuExec,
                 condition: Optional[E.Expression] = None):
        super().__init__(conf, [left, right])
        lf, rf = left.output_schema.fields, right.output_schema.fields
        self._schema = StructType(tuple(lf) + tuple(rf))
        self._cond = (
            E.bind_references(condition, self._schema)
            if condition is not None else None
        )
        self._built = None

    @property
    def output_schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def describe(self):
        return self.node_name

    def execute_partition(self, index: int) -> Iterator[ColumnarBatch]:
        if self._built is None:
            self._built = _concat_all(self.conf, self.children[1])
        build = self._built
        if build is None:
            return
        nb = build.num_rows
        build_vals = vals_of_batch(build)
        for pbatch in self.children[0].execute_partition(index):
            np_ = pbatch.num_rows
            if np_ == 0 or nb == 0:
                continue
            out_cap = bucket_rows(np_ * nb, self.conf.shape_bucket_min)
            pcap = pbatch.capacity
            pcaps = [
                bucket_rows(max(1, int(c.offsets[np_]) * nb), 128)
                for c in pbatch.columns if c.is_string
            ]
            bcaps = [
                bucket_rows(max(1, int(c.offsets[nb]) * np_), 128)
                for c in build.columns if c.is_string
            ]

            def expand(pcols, bcols):
                j = jnp.arange(out_cap, dtype=jnp.int32)
                pi = j // nb
                bi = j % nb
                slot_live = j < (np_ * nb)
                left_side = filter_gather.gather(pcols, pi, slot_live, pcaps)
                right_side = filter_gather.gather(bcols, bi, slot_live, bcaps)
                cols = list(left_side) + list(right_side)
                if self._cond is not None:
                    c = lower(self._cond, cols, out_cap)
                    mask = slot_live & c.data & c.validity
                    cols, count = filter_gather.filter_cols(cols, mask, np_ * nb)
                    return cols, count
                return cols, jnp.int32(np_ * nb)

            cache = getattr(self, "_jits", None)
            if cache is None:
                cache = self._jits = {}
            key = (batch_signature(pbatch), out_cap, np_, nb)
            if key not in cache:
                cache[key] = jax.jit(expand)
            vals, count = cache[key](vals_of_batch(pbatch), build_vals)
            n = int(count)
            if n:
                yield self.record_batch(batch_from_vals(vals, self._schema, n))


class TpuCartesianProductExec(TpuBroadcastNestedLoopJoinExec):
    """Unconditioned cross join (reference: GpuCartesianProductExec.scala:304
    — the same pair-expansion kernel as the nested-loop join, no residual
    condition)."""

    def __init__(self, conf: RapidsConf, left: TpuExec, right: TpuExec):
        super().__init__(conf, left, right, condition=None)
