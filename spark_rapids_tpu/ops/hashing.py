"""Spark-compatible Murmur3_x86_32 hashing, vectorized over columns.

Reference analog: HashFunctions.scala (GpuMurmur3Hash) and the murmur3 used
by GpuHashPartitioning (GpuHashPartitioning.scala:29-121), which must agree
bit-for-bit with Spark CPU so repartitioned data lands identically whichever
side produced it. Implemented here as uint32 jnp arithmetic (wrapping
multiply/rotate come free); strings hash their UTF-8 bytes in 4-byte
little-endian blocks plus sign-extended tail bytes, exactly like
org.apache.spark.unsafe.hash.Murmur3_x86_32.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import types as T
from ..expr.eval import ColV, StrV, Val

DEFAULT_SEED = 42

# numpy scalars on purpose: importing this module must not touch any JAX
# backend (module-level jnp constants would materialize eagerly on the
# default platform, breaking CPU-mesh fallback on hosts with a broken TPU)
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl(x: jax.Array, r: int) -> jax.Array:
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1: jax.Array) -> jax.Array:
    k1 = k1 * _C1
    k1 = _rotl(k1, 15)
    return k1 * _C2


def _mix_h1(h1: jax.Array, k1: jax.Array) -> jax.Array:
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1: jax.Array, length: jax.Array) -> jax.Array:
    h1 = h1 ^ length.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def _hash_int_words(words: jax.Array, seed: jax.Array, n_bytes: int) -> jax.Array:
    h1 = seed
    h1 = _mix_h1(h1, _mix_k1(words))
    return _fmix(h1, jnp.uint32(n_bytes))


def hash_int(data: jax.Array, seed: jax.Array) -> jax.Array:
    """hashInt: one 4-byte word (int/short/byte/bool/date/float-bits)."""
    return _hash_int_words(data.astype(jnp.int32).astype(jnp.uint32), seed, 4)


def hash_long(data: jax.Array, seed: jax.Array) -> jax.Array:
    """hashLong: low word then high word (Murmur3_x86_32.hashLong)."""
    u = data.astype(jnp.int64).astype(jnp.uint64)
    low = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    high = (u >> 32).astype(jnp.uint32)
    h1 = _mix_h1(seed, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, jnp.uint32(8))


def hash_string(col: StrV, seed: jax.Array, max_len: int) -> jax.Array:
    """hashUnsafeBytes over UTF-8: 4-byte LE blocks + sign-extended tail.

    ``max_len`` is a static bound on byte length (bucketed by the caller).
    """
    starts = col.offsets[:-1]
    ends = col.offsets[1:]
    lens = ends - starts
    nchars = col.chars.shape[0]
    h1 = jnp.broadcast_to(seed, starts.shape)

    def byte_at(pos: jax.Array) -> jax.Array:
        return jnp.take(col.chars, jnp.clip(pos, 0, nchars - 1), mode="clip")

    nblocks = max_len // 4 + 1
    for c in range(nblocks):
        base = starts + 4 * c
        full = base + 4 <= ends
        word = jnp.zeros(starts.shape, jnp.uint32)
        for b in range(4):  # little-endian within the word
            word = word | (byte_at(base + b).astype(jnp.uint32) << (8 * b))
        h1 = jnp.where(full, _mix_h1(h1, _mix_k1(word)), h1)
    aligned = starts + (lens & ~jnp.int32(3))
    for b in range(3):
        pos = aligned + b
        has = pos < ends
        sbyte = byte_at(pos).astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        h1 = jnp.where(has, _mix_h1(h1, _mix_k1(sbyte)), h1)
    return _fmix(h1, lens.astype(jnp.uint32))


def hash_column(
    col: Val, dtype: T.DataType, seed: jax.Array, str_max_len: int = 64
) -> jax.Array:
    """Hash one column into the running per-row seed; nulls leave it as-is."""
    if isinstance(col, StrV):
        h = hash_string(col, seed, str_max_len)
        return jnp.where(col.validity, h, seed)
    data = col.data
    if isinstance(dtype, T.BooleanType):
        h = hash_int(data.astype(jnp.int32), seed)
    elif isinstance(dtype, T.FloatType):
        d = jnp.where(jnp.isnan(data), jnp.float32(jnp.nan), data)
        d = jnp.where(d == 0.0, jnp.float32(0.0), d)  # -0.0 -> 0.0
        h = hash_int(lax.bitcast_convert_type(d, jnp.int32), seed)
    elif isinstance(dtype, T.DoubleType):
        d = jnp.where(jnp.isnan(data), jnp.float64(jnp.nan), data)
        d = jnp.where(d == 0.0, jnp.float64(0.0), d)
        h = hash_long(lax.bitcast_convert_type(d, jnp.int64), seed)
    elif isinstance(dtype, (T.LongType, T.TimestampType, T.DecimalType)):
        h = hash_long(data, seed)
    else:  # byte/short/int/date
        h = hash_int(data, seed)
    return jnp.where(col.validity, h, seed)


def murmur3(
    cols: Sequence[Val],
    dtypes: Sequence[T.DataType],
    seed: int = DEFAULT_SEED,
    str_max_lens: Sequence[int] = (),
) -> jax.Array:
    """Spark Murmur3Hash(expr*) — int32 result, seed chained across columns."""
    cap = (
        cols[0].offsets.shape[0] - 1
        if isinstance(cols[0], StrV)
        else cols[0].validity.shape[0]
    )
    h = jnp.full((cap,), jnp.uint32(seed))
    si = 0
    for c, dt in zip(cols, dtypes):
        if isinstance(c, StrV):
            ml = str_max_lens[si] if si < len(str_max_lens) else 64
            si += 1
            h = hash_column(c, dt, h, ml)
        else:
            h = hash_column(c, dt, h)
    return h.astype(jnp.int32)


def partition_ids(
    hashes: jax.Array, num_partitions: int
) -> jax.Array:
    """Spark's pmod(hash, n) partition assignment (HashPartitioning)."""
    m = hashes % jnp.int32(num_partitions)
    return jnp.where(m < 0, m + num_partitions, m)
