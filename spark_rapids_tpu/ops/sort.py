"""Multi-key stable sort with Spark SQL ordering semantics.

Reference analog: cudf ``table.orderBy`` used by GpuSortExec
(GpuSortExec.scala:51, SortUtils.scala). TPU re-design: every key column is
bijected into order-preserving unsigned "radix keys" (int sign-flip trick,
IEEE-754 total-order trick with NaN/-0.0 canonicalized to Spark semantics,
4-byte big-endian chunks for strings), then one ``lax.sort`` call over
[padding_rank, k1_nulls, k1_value..., row_id] yields the permutation. XLA
lowers this to the TPU's bitonic sort; gathering the permuted rows afterwards
reuses the filter_gather kernels.

Spark ordering rules implemented here:
  * ASC defaults to NULLS FIRST, DESC to NULLS LAST (explicit here).
  * NaN compares equal to NaN and greater than any other double.
  * -0.0 == 0.0.
  * Strings compare as unsigned UTF-8 bytes (UTF8String.compareTo).
  * Padding slots (row >= num_rows) always sort last.
"""
from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .. import types as T
from ..expr.eval import ColV, StrV, Val
from .filter_gather import gather


@dataclasses.dataclass(frozen=True)
class SortOrder:
    """One sort key: column index + direction (reference: SortUtils.scala)."""

    ascending: bool = True
    nulls_first: bool = None  # type: ignore[assignment]  # None = Spark default

    @property
    def nulls_first_resolved(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def _flip(k: jax.Array) -> jax.Array:
    return ~k


def _int_radix(data: jax.Array) -> jax.Array:
    """Order-preserving signed->unsigned bijection (sign-bit flip)."""
    nbits = data.dtype.itemsize * 8
    if nbits <= 32:
        u = data.astype(jnp.int32).astype(jnp.uint32)
        return u ^ jnp.uint32(1 << 31)
    u = data.astype(jnp.uint64)
    return u ^ jnp.uint64(1 << 63)


def _float_radix(data: jax.Array) -> jax.Array:
    """IEEE total-order trick with Spark's NaN-largest / -0.0==0.0 rules."""
    if data.dtype == jnp.float32:
        canon_nan = jnp.float32(jnp.nan)
        zero = jnp.float32(0.0)
        d = jnp.where(jnp.isnan(data), canon_nan, data)
        d = jnp.where(d == zero, zero, d)  # folds -0.0 to +0.0
        bits = lax.bitcast_convert_type(d, jnp.uint32)
        sign = jnp.uint32(1 << 31)
        return jnp.where(bits & sign != 0, ~bits, bits ^ sign)
    canon_nan64 = jnp.float64(jnp.nan)
    zero64 = jnp.float64(0.0)
    d = jnp.where(jnp.isnan(data), canon_nan64, data)
    d = jnp.where(d == zero64, zero64, d)
    import jax as _jax

    if _jax.default_backend() == "cpu":
        bits = lax.bitcast_convert_type(d, jnp.uint64)
        sign64 = jnp.uint64(1 << 63)
        return jnp.where(bits & sign64 != 0, ~bits, bits ^ sign64)
    # TPU: f64 is f32-PAIR emulated and the x64 rewriter has no 64-bit
    # bitcast. The pair decomposition (hi = fl32(x), lo = x - hi) is
    # order-preserving — hi is monotone in x, lo orders equal-hi values —
    # and captures every value this number system can represent.
    hi = d.astype(jnp.float32)
    lo = (d - hi.astype(jnp.float64)).astype(jnp.float32)
    lo = jnp.where(jnp.isnan(lo), jnp.float32(0), lo)  # NaN rows: hi wins

    def f32key(x):
        b = lax.bitcast_convert_type(x, jnp.uint32)
        s = jnp.uint32(1 << 31)
        return jnp.where(b & s != 0, ~b, b ^ s)

    return (f32key(hi).astype(jnp.uint64) << 32) | f32key(lo).astype(
        jnp.uint64)


def fixed_radix_keys(col: ColV, dtype: T.DataType, order: SortOrder) -> List[jax.Array]:
    """[null_rank, value_key] for a fixed-width column."""
    if dtype.is_floating:
        k = _float_radix(col.data)
    elif isinstance(dtype, T.BooleanType):
        k = col.data.astype(jnp.uint32)
    else:  # integral / date / timestamp / decimal(int64)
        k = _int_radix(col.data)
    if not order.ascending:
        k = _flip(k)
    null_rank = jnp.where(
        col.validity,
        jnp.uint32(1),
        jnp.uint32(0) if order.nulls_first_resolved else jnp.uint32(2),
    )
    # zero the key for nulls so null rows compare equal regardless of padding
    k = jnp.where(col.validity, k, jnp.zeros((), k.dtype))
    return [null_rank, k]


def string_chunk_keys(
    col: StrV, order: SortOrder, max_len: int
) -> List[jax.Array]:
    """[null_rank, chunk0, chunk1, ...]: 4-byte big-endian uint32 chunks.

    Lexicographic comparison over the chunk sequence equals unsigned byte
    comparison (shorter strings zero-padded, and a zero chunk sorts before
    any longer content — matching UTF8String binary order).
    ``max_len`` must be a static bound on byte length (bucketed by caller).
    """
    cap = col.offsets.shape[0] - 1
    nchunks = max(1, (max_len + 3) // 4)
    starts = col.offsets[:-1]
    ends = col.offsets[1:]
    nchars = col.chars.shape[0]
    keys: List[jax.Array] = []
    null_rank = jnp.where(
        col.validity,
        jnp.uint32(1),
        jnp.uint32(0) if order.nulls_first_resolved else jnp.uint32(2),
    )
    keys.append(null_rank)
    for c in range(nchunks):
        chunk = jnp.zeros(cap, jnp.uint32)
        for b in range(4):
            pos = starts + (4 * c + b)
            byte = jnp.where(
                pos < ends,
                jnp.take(col.chars, jnp.clip(pos, 0, nchars - 1), mode="clip"),
                jnp.zeros((), jnp.uint8),
            ).astype(jnp.uint32)
            chunk = (chunk << 8) | byte
        if not order.ascending:
            chunk = _flip(chunk)
        chunk = jnp.where(col.validity, chunk, jnp.zeros((), jnp.uint32))
        keys.append(chunk)
    return keys


def sort_with_radix_keys(
    key_cols: Sequence[Val],
    key_dtypes: Sequence[T.DataType],
    orders: Sequence[SortOrder],
    num_rows: Union[int, jax.Array],
    str_max_lens: Sequence[int] = (),
) -> Tuple[jax.Array, List[jax.Array]]:
    """(permutation, sorted radix key arrays); padding rows sort last.

    The returned key arrays are already in sorted order (``lax.sort``
    co-sorts every operand), letting group-by derive segment boundaries by
    comparing adjacent radix keys instead of re-comparing raw columns —
    string equality in particular falls out of the chunk keys for free.
    ``str_max_lens`` supplies the static byte-length bound for each string
    key, in order of appearance.
    """
    cap = (
        key_cols[0].offsets.shape[0] - 1
        if isinstance(key_cols[0], StrV)
        else key_cols[0].validity.shape[0]
    )
    from .filter_gather import live_of

    pad_rank = (~live_of(num_rows, cap)).astype(jnp.uint32)
    operands: List[jax.Array] = [pad_rank]
    si = 0
    for colv, dtype, order in zip(key_cols, key_dtypes, orders):
        if isinstance(colv, StrV):
            ml = str_max_lens[si] if si < len(str_max_lens) else 64
            si += 1
            operands.extend(string_chunk_keys(colv, order, ml))
        else:
            operands.extend(fixed_radix_keys(colv, dtype, order))
    row_id = jnp.arange(cap, dtype=jnp.int32)
    operands.append(row_id)
    sorted_ops = lax.sort(operands, num_keys=len(operands) - 1, is_stable=True)
    return sorted_ops[-1], sorted_ops[1:-1]


def sort_permutation(
    key_cols: Sequence[Val],
    key_dtypes: Sequence[T.DataType],
    orders: Sequence[SortOrder],
    num_rows: Union[int, jax.Array],
    str_max_lens: Sequence[int] = (),
) -> jax.Array:
    """Stable sort permutation over the given keys; padding rows go last."""
    perm, _ = sort_with_radix_keys(
        key_cols, key_dtypes, orders, num_rows, str_max_lens
    )
    return perm


def sort_cols(
    cols: Sequence[Val],
    key_indices: Sequence[int],
    key_dtypes: Sequence[T.DataType],
    orders: Sequence[SortOrder],
    num_rows: Union[int, jax.Array],
    str_max_lens: Sequence[int] = (),
) -> List[Val]:
    """Sort all columns by the keys at ``key_indices``."""
    cap = cols[0].validity.shape[0] if not isinstance(cols[0], StrV) else cols[0].offsets.shape[0] - 1
    perm = sort_permutation(
        [cols[i] for i in key_indices], key_dtypes, orders, num_rows, str_max_lens
    )
    valid_slot = jnp.arange(cap, dtype=jnp.int32) < num_rows
    return gather(cols, perm, valid_slot)


def max_string_len(col: StrV) -> jax.Array:
    """Device scalar max byte length (callers bucket it host-side)."""
    return jnp.max(col.offsets[1:] - col.offsets[:-1])
