"""Bucket reductions under THREE interchangeable lowerings: one-hot limb
matmul (MXU), native segment scatter, and sort + prefix-sum differences
(HBM bandwidth).

TPU-first design with no reference analog: XLA's scatter (what
``jax.ops.segment_sum`` lowers to) runs near-serially on TPU (~10ns/row),
while the MXU multiplies 256x256 tiles for free. A bucket reduction
``out[b] = sum(x[i] for seg[i]==b)`` is exactly ``one_hot(seg) @ x`` — and
XLA fuses the one-hot generation into the matmul so the (n, B) matrix never
materializes.

The matmul prices the reduction in MXU flops (cap x limbs x B MACs); the
round-5 profile showed the agg program touching HBM at 1.3% of roofline
while ~100% of device wait sat inside it, so round 7 adds a lowering
sized to BANDWIDTH instead: order rows by bucket id, then every bucket's
sum is a difference of prefix sums at the bucket boundaries
(:func:`contiguous_segment_reduce`) — one stable sort, one cumsum pass
per dtype family, 2*(B+1) boundary gathers, zero scatters and no one-hot.
The strategy is selected per plan by the aggregate exec's chooser
(``spark.rapids.tpu.sql.agg.strategy``, exec/aggregate.py) and recorded
in the event log so a wrong prediction is visible in tools/tpu_profile.

Exactness: f32 matmuls (precision=HIGHEST) are exact for addends < 2^24.
int64 values split into 8x8-bit limbs reduced in row-blocks of 65536
(block limb sum <= 65536*255 < 2^24), block partials accumulate in int64 —
bit-exact integer sums at matmul speed, including Java wraparound. The
8-bit/65536-row shape keeps the per-block partial tensor (nblocks, L, B)
tiny; 16-bit limbs would force 256-row blocks and a gigabyte-scale
transient. Counts are a ones-limb. Doubles use a hi/lo float split (not
bit-exact, order-insensitive — the reference gates float aggregation the
same way: spark.rapids.sql.variableFloatAgg.enabled).

Out-of-range segment ids (padding/dead rows) one-hot to a zero row and
drop out of every reduction for free.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

BLOCK_R = 1 << 16  # rows per block: 65536 * 255 < 2^24 keeps f32 exact
N_LIMBS = 8  # 8-bit limbs per int64

_HI = jax.lax.Precision.HIGHEST

#: test hook: force the MXU limb-matmul lowering even on the CPU backend
#: (differential tests diff it against the scatter lowering)
FORCE_MATMUL = False

#: test hook: run every reduction ONE COLUMN AT A TIME instead of fusing
#: all columns into a single limb-matmul / scatter family — the
#: differential baseline the fused path is diffed against (same spirit as
#: FORCE_MATMUL: a lowering switch, never a semantics switch)
FORCE_PER_COLUMN = False


def _resolve_strategy(strategy=None) -> str:
    """Resolve the lowering for one reduction (trace-time static, so each
    jit cache entry is per-strategy and per-backend). ``strategy`` is an
    already-chosen MATMUL/SCATTER/SORT/PALLAS from the aggregate exec's
    chooser; None/AUTO falls back to the backend default: the MXU
    tradeoff inverts on XLA CPU, where the one-hot never fuses — it
    materializes (n, B) compare-selects at ~7ns/element (measured:
    1.7-2.3 s for 2M rows x 128 buckets) while scatter runs a tight
    serial loop (~0.2 s for the same shape, 4-10x faster). On TPU
    scatter is the near-serial one (~10ns/row) and the matmul is free.
    ``FORCE_MATMUL`` (test hook) outranks everything so the MXU limb
    path stays differentially covered on the CPU backend."""
    if FORCE_MATMUL:
        return "MATMUL"
    if strategy in ("MATMUL", "SCATTER", "SORT", "PALLAS"):
        return strategy
    return "SCATTER" if jax.default_backend() == "cpu" else "MATMUL"


def _bucket_reduce_scatter(
    seg: jax.Array,
    B: int,
    int_cols: Sequence[Tuple[jax.Array, jax.Array]],
    count_cols: Sequence[jax.Array],
    float_cols: Sequence[Tuple[jax.Array, jax.Array]],
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """CPU lowering of :func:`bucket_reduce`: native-dtype segment sums,
    one batched scatter per dtype family. No limb splitting — int64 adds
    are native here and wrap mod 2^64 exactly like the limb accumulate;
    float sums run in f64 (at least as accurate as the hi/lo split).
    Counts ride the f64 scatter (exact below 2^53, and row capacities are
    far below that) so the common sum+count aggregate is ONE scatter pass.
    Out-of-range ids drop, matching the one-hot zero row."""
    ints = [
        jnp.where(valid, data.astype(jnp.int64), jnp.int64(0))
        for data, valid in int_cols
    ]
    out_int: List[jax.Array] = []
    if ints:
        s = jax.ops.segment_sum(
            jnp.stack(ints, axis=-1), seg, num_segments=B)
        out_int = [s[:, i] for i in range(len(ints))]
    out_cnt: List[jax.Array] = []
    out_flt: List[jax.Array] = []
    fcols = [valid.astype(jnp.float64) for valid in count_cols] + [
        jnp.where(valid, data, 0.0).astype(jnp.float64)
        for data, valid in float_cols
    ]
    if fcols:
        f = jax.ops.segment_sum(jnp.stack(fcols, axis=-1), seg,
                                num_segments=B)
        out_cnt = [f[:, i].astype(jnp.int64) for i in range(len(count_cols))]
        out_flt = [f[:, len(count_cols) + i]
                   for i in range(len(float_cols))]
    return out_int, out_cnt, out_flt


def _prefix_boundaries(sorted_seg: jax.Array, B: int) -> jax.Array:
    """``bounds[b]`` = first position in the NONDECREASING id array with
    id >= b, shape (B+1,). Out-of-range ids (padding/dead rows, id >= B)
    sort past ``bounds[B]`` and drop out of every prefix difference;
    negative ids sort before ``bounds[0]`` and drop the same way."""
    return jnp.searchsorted(
        sorted_seg, jnp.arange(B + 1, dtype=sorted_seg.dtype), side="left")


def contiguous_segment_reduce(
    seg: jax.Array,
    B: int,
    int_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
    count_cols: Sequence[jax.Array] = (),
    float_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """Per-bucket sums/counts over a NONDECREASING ``seg`` as prefix-sum
    differences at the bucket boundaries — the bandwidth-sized reduction:
    one cumsum pass per dtype family plus 2*(B+1) boundary gathers, zero
    scatters, no one-hot. Integer sums and counts are BIT-exact: prefix
    sums wrap mod 2^64 and differences of wrapped prefixes equal the
    wrapped segment sum (Java int64 wraparound included). Float sums are
    order-insensitive like the matmul hi/lo split (callers gate them
    behind variableFloatAgg the same way); a non-finite row would poison
    every later bucket's prefix, so those rows detour through a rare
    scatter correction cond'd on actually seeing one (the matmul overflow
    pattern). Callers with unsorted ids use the SORT lowering of
    :func:`bucket_reduce`, which stable-sorts by id first; ops/groupby's
    radix-sorted path feeds its already-contiguous segment ids straight
    in."""
    bounds = _prefix_boundaries(seg, B)
    lo, hi = bounds[:-1], bounds[1:]

    def diffs(mat: jax.Array) -> jax.Array:
        c = jnp.cumsum(mat, axis=0)
        padded = jnp.concatenate(
            [jnp.zeros((1, mat.shape[1]), mat.dtype), c])
        return (jnp.take(padded, hi, axis=0, mode="clip")
                - jnp.take(padded, lo, axis=0, mode="clip"))

    out_int: List[jax.Array] = []
    out_cnt: List[jax.Array] = []
    out_flt: List[jax.Array] = []
    icols = [
        jnp.where(valid, data.astype(jnp.int64),
                  jnp.int64(0)).astype(jnp.uint64)
        for data, valid in int_cols
    ]
    ccols = [valid.astype(jnp.uint64) for valid in count_cols]
    if icols or ccols:
        s = diffs(jnp.stack(icols + ccols, axis=-1))
        out_int = [s[:, i].astype(jnp.int64) for i in range(len(icols))]
        out_cnt = [s[:, len(icols) + i].astype(jnp.int64)
                   for i in range(len(ccols))]
    if float_cols:
        # route non-finite AND huge-magnitude rows through the (rare)
        # scatter correction: a NaN/inf poisons every later bucket's
        # prefix, and a ~1e300 value annihilates the prefix's low bits —
        # the matmul lowering's F32_MAX overflow detour, same idea
        F64_BIG = jnp.float64(2.0) ** 500
        finite_cols: List[jax.Array] = []
        corrections: List[Tuple[jax.Array, jax.Array]] = []
        for data, valid in float_cols:
            d = jnp.where(valid, data, 0.0).astype(jnp.float64)
            bad = ~jnp.isfinite(d) | (jnp.abs(d) > F64_BIG)
            finite_cols.append(jnp.where(bad, 0.0, d))
            corrections.append((jnp.any(bad), jnp.where(bad, d, 0.0)))
        f = diffs(jnp.stack(finite_cols, axis=-1))
        for i, (any_bad, d_bad) in enumerate(corrections):
            corr = jax.lax.cond(
                any_bad,
                lambda d=d_bad: jax.ops.segment_sum(d, seg, num_segments=B),
                lambda: jnp.zeros(B, jnp.float64),
            )
            out_flt.append(f[:, i] + corr)
    return out_int, out_cnt, out_flt


def _bucket_reduce_sort(
    seg: jax.Array,
    B: int,
    int_cols: Sequence[Tuple[jax.Array, jax.Array]],
    count_cols: Sequence[jax.Array],
    float_cols: Sequence[Tuple[jax.Array, jax.Array]],
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """SORT lowering: stable-sort rows by bucket id (one ``lax.sort``
    carrying a row permutation — the same machinery ops/groupby's radix
    path uses), gather every column into bucket order with ONE row take
    per column, then reduce each now-contiguous bucket with
    :func:`contiguous_segment_reduce`. Every pass is elementwise or a
    contiguous stream — HBM bandwidth, not MXU flops or scatter latency,
    is the price."""
    from jax import lax

    n = seg.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    sseg, perm = lax.sort([seg, iota], num_keys=1, is_stable=True)

    def g(a: jax.Array) -> jax.Array:
        return jnp.take(a, perm, mode="clip")

    return contiguous_segment_reduce(
        sseg, B,
        [(g(d), g(v)) for d, v in int_cols],
        [g(v) for v in count_cols],
        [(g(d), g(v)) for d, v in float_cols],
    )


def bucket_reduce(
    seg: jax.Array,
    B: int,
    int_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
    count_cols: Sequence[jax.Array] = (),
    float_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
    strategy: str = None,
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """ALL requested reductions across ALL columns in one fused pass.

    Multi-column fusion is the point: every column's limbs stack into one
    ``(n, L_total)`` operand (8 int limbs + 1 count limb + 2 float limbs
    per column) so a single one-hot matmul per row-block serves the whole
    aggregate plan — the contraction over ``n`` is shared and the MXU sees
    one wide matmul instead of C narrow ones. The CPU lowering fuses the
    same way: one batched scatter per dtype family, not one per column.
    ``FORCE_PER_COLUMN`` is the differential baseline (one pass per
    column) that tests diff this fusion against.

    seg: (n,) int32 bucket ids; ids >= B are dropped.
    int_cols:   [(data int64/int32, valid bool)] -> exact int64 sums (B,)
    count_cols: [valid bool] -> int64 counts (B,)
    float_cols: [(data f64/f32, valid bool)] -> f64 sums (B,) (hi/lo split)
    strategy:   MATMUL / SCATTER / SORT, or None for the backend default
                (see :func:`_resolve_strategy`).
    """
    if FORCE_PER_COLUMN:
        out_int: List[jax.Array] = []
        out_cnt: List[jax.Array] = []
        out_flt: List[jax.Array] = []
        for spec in int_cols:
            out_int += _bucket_reduce_pass(seg, B, [spec], (), (),
                                           strategy)[0]
        for valid in count_cols:
            out_cnt += _bucket_reduce_pass(seg, B, (), [valid], (),
                                           strategy)[1]
        for spec in float_cols:
            out_flt += _bucket_reduce_pass(seg, B, (), (), [spec],
                                           strategy)[2]
        return out_int, out_cnt, out_flt
    return _bucket_reduce_pass(seg, B, int_cols, count_cols, float_cols,
                               strategy)


def _bucket_reduce_pass(
    seg: jax.Array,
    B: int,
    int_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
    count_cols: Sequence[jax.Array] = (),
    float_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
    strategy: str = None,
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    resolved = _resolve_strategy(strategy)
    if resolved == "SCATTER":
        return _bucket_reduce_scatter(seg, B, int_cols, count_cols, float_cols)
    if resolved == "SORT":
        return _bucket_reduce_sort(seg, B, int_cols, count_cols, float_cols)
    if resolved == "PALLAS":
        from .pallas_groupby import pallas_bucket_reduce

        return pallas_bucket_reduce(seg, B, int_cols, count_cols,
                                    float_cols)
    n = seg.shape[0]
    limbs: List[jax.Array] = []
    for data, valid in int_cols:
        # split into u32 halves first: all limb math stays 32-bit (64-bit
        # elementwise ops are emulated on TPU at ~2-4x cost)
        halves = jax.lax.bitcast_convert_type(
            data.astype(jnp.int64), jnp.uint32)  # (n, 2) little-endian
        for half in (halves[..., 0], halves[..., 1]):
            h = jnp.where(valid, half, jnp.uint32(0))
            for i in range(4):
                limbs.append(((h >> (8 * i)) & jnp.uint32(0xFF)).astype(jnp.float32))
    for valid in count_cols:
        limbs.append(valid.astype(jnp.float32))
    nf_start = len(limbs)
    F32_MAX = jnp.float64(3.4028234663852886e38)
    flt_corrections: List[Tuple[jax.Array, jax.Array]] = []
    for data, valid in float_cols:
        d = jnp.where(valid, data, 0.0).astype(jnp.float64)
        # |x| beyond f32 range would make hi=inf and lo=NaN; zero those rows
        # out of the matmul path and scatter-add them separately (cond'd on
        # actually seeing one, so the common case pays no scatter). NaN
        # rows must detour too — abs(NaN) > x is False, and a NaN in the
        # matmul stream poisons EVERY bucket through the one-hot dot
        ovf = ~(jnp.abs(d) <= F32_MAX)
        d_main = jnp.where(ovf, 0.0, d)
        hi = d_main.astype(jnp.float32)
        lo = (d_main - hi.astype(jnp.float64)).astype(jnp.float32)
        limbs.append(hi)
        limbs.append(lo)
        flt_corrections.append((jnp.any(ovf), jnp.where(ovf, d, 0.0)))
    if not limbs:
        return [], [], []
    cols = jnp.stack(limbs, axis=-1)  # (n, L)
    L = cols.shape[1]

    R = min(BLOCK_R, n)
    nb = n // R
    S_parts = []
    if nb:
        oh_src = seg[: nb * R].reshape(nb, R)
        c = cols[: nb * R].reshape(nb, R, L)
        oh = jax.nn.one_hot(oh_src, B, dtype=jnp.float32)
        S_parts.append(jnp.einsum("brl,brB->blB", c, oh, precision=_HI))
    tail = n - nb * R
    if tail:
        oh_t = jax.nn.one_hot(seg[nb * R:], B, dtype=jnp.float32)
        St = jnp.einsum("rl,rB->lB", cols[nb * R:], oh_t, precision=_HI)
        S_parts.append(St[None])
    S = jnp.concatenate(S_parts, axis=0) if len(S_parts) > 1 else S_parts[0]
    acc_i = S[:, :nf_start, :].astype(jnp.int64).sum(axis=0)  # exact
    acc_f = S[:, nf_start:, :].astype(jnp.float64).sum(axis=0)

    out_int: List[jax.Array] = []
    k = 0
    for _ in int_cols:
        total = jnp.zeros(B, jnp.uint64)
        for i in range(N_LIMBS):
            total = total + (acc_i[k].astype(jnp.uint64) << (8 * i))
            k += 1
        out_int.append(total.astype(jnp.int64))
    out_cnt: List[jax.Array] = []
    for _ in count_cols:
        out_cnt.append(acc_i[k])
        k += 1
    out_flt: List[jax.Array] = []
    k = 0
    for (any_ovf, d_ovf) in flt_corrections:
        corr = jax.lax.cond(
            any_ovf,
            lambda d=d_ovf: jax.ops.segment_sum(d, seg, num_segments=B),
            lambda: jnp.zeros(B, jnp.float64),
        )
        out_flt.append(acc_f[k] + acc_f[k + 1] + corr)
        k += 2
    return out_int, out_cnt, out_flt


def bucket_min_max(
    seg: jax.Array, B: int, op: str, cols: Sequence[jax.Array],
    strategy: str = None,
) -> List[jax.Array]:
    """Per-bucket min/max for ALL columns of one (op, dtype) family in ONE
    segment scatter — the scatter-side analog of the fused limb matmul:
    the near-serial walk over ``seg`` (the expensive part on TPU) happens
    once per family instead of once per column. ``cols`` are (n,) arrays
    of one dtype, already masked to the op's identity fill by the caller
    (invalid/dead rows hold +/-inf, dtype extremes, etc. so they never
    win); callers overwrite empty buckets via their count mask. Returns
    (B,) arrays aligned with ``cols``. Under the PALLAS strategy the
    winners reduce in the VMEM-resident word kernel instead of a
    scatter."""
    if _resolve_strategy(strategy) == "PALLAS":
        from .pallas_groupby import pallas_bucket_min_max

        return pallas_bucket_min_max(seg, B, op, cols)
    fn = jax.ops.segment_max if op == "max" else jax.ops.segment_min
    if FORCE_PER_COLUMN or len(cols) == 1:
        return [fn(d, seg, num_segments=B) for d in cols]
    stacked = jnp.stack(cols, axis=-1)  # (n, C)
    r = fn(stacked, seg, num_segments=B)  # (B, C)
    return [r[:, i] for i in range(len(cols))]


def bucket_lookup_u32(
    seg: jax.Array, B: int, table: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Per-row lookup of a u32 table value by bucket id, exactly, via two
    16-bit-limb one-hot matmuls. Returns (lo, hi) f32 per row (each < 2^16,
    exact). Rows with seg >= B read 0."""
    if _resolve_strategy() == "SCATTER":
        # CPU: a plain clipped gather is exact and ~B x cheaper than the
        # materialized one-hot
        t = jnp.where(
            (seg >= 0) & (seg < B),
            jnp.take(table, jnp.clip(seg, 0, B - 1), mode="clip"),
            jnp.uint32(0))
        return ((t & jnp.uint32(0xFFFF)).astype(jnp.float32),
                (t >> 16).astype(jnp.float32))
    n = seg.shape[0]
    lo = (table & jnp.uint32(0xFFFF)).astype(jnp.float32)
    hi = (table >> 16).astype(jnp.float32)
    t2 = jnp.stack([lo, hi], axis=-1)  # (B, 2)
    R = min(4096, n)
    nb = n // R
    parts = []
    if nb:
        head = seg[: nb * R].reshape(nb, R)
        oh = jax.nn.one_hot(head, B, dtype=jnp.float32)
        parts.append(
            jnp.einsum("brB,Bt->brt", oh, t2, precision=_HI).reshape(nb * R, 2))
    tail = n - nb * R
    if tail:
        oh_t = jax.nn.one_hot(seg[nb * R:], B, dtype=jnp.float32)
        parts.append(jnp.einsum("rB,Bt->rt", oh_t, t2, precision=_HI))
    vals = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    return vals[:, 0], vals[:, 1]


def bucket_equal_check(
    seg: jax.Array,
    B: int,
    word: jax.Array,
    rep_table: jax.Array,
    live: jax.Array,
) -> jax.Array:
    """True iff every live row's u32 ``word`` equals its bucket's
    representative (exact collision detection for hash groupby)."""
    lo, hi = bucket_lookup_u32(seg, B, rep_table)
    wlo = (word & jnp.uint32(0xFFFF)).astype(jnp.float32)
    whi = (word >> 16).astype(jnp.float32)
    mismatch = live & ((lo != wlo) | (hi != whi))
    return ~jnp.any(mismatch)
