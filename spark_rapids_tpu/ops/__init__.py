"""TPU kernel layer: the surface cudf provided to the reference plugin
(SURVEY.md §2.12 item 1), re-designed as jit-compiled XLA computations over
fixed-capacity column arrays.

Modules:
  filter_gather — mask compaction + row gather (cudf table.filter/gather)
  sort          — multi-key stable sort with Spark null/NaN ordering
  groupby       — sort-based segment-reduce aggregation (cudf groupBy.aggregate)
  hashing       — murmur3 (Spark-compatible) for hash partitioning & hash exprs
  join          — sort + searchsorted join expansion (cudf join family)
"""
from . import filter_gather, groupby, hashing, sort  # noqa: F401
