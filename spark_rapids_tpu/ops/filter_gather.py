"""Row selection kernels: mask compaction and row gather.

Reference analog: cudf ``table.filter(mask)`` (used by GpuFilter,
basicPhysicalOperators.scala:113-129) and ``table.gather`` — C++ kernels with
dynamic output sizes. TPU re-design: output stays at a *static* capacity
(selected rows compacted to the front, tail slots zeroed with validity=False)
so one XLA executable serves every batch in a capacity bucket. The logical
row count comes back as a device scalar; callers materialize it only at batch
boundaries, mirroring where cudf syncs for the output row count.

All functions are pure and trace-safe (usable under jit/shard_map).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ..expr.eval import ColV, DictV, StrV, Val


def live_of(num_rows_or_mask, cap: int) -> jax.Array:
    """Normalize a row count (host int or device scalar) or a bool mask
    into a (cap,) liveness mask. The mask form lets filters defer row
    compaction entirely — downstream fused ops reduce over the mask."""
    x = num_rows_or_mask
    if isinstance(x, jax.Array) and x.dtype == jnp.bool_ and x.ndim == 1:
        return x
    return jnp.arange(cap, dtype=jnp.int32) < x


def elide_validity(cols: Sequence[Val], live: jax.Array,
                   nonnull: Sequence[bool]) -> List[Val]:
    """Validity-plane elision for statically NON_NULL columns (the
    analyzer's nullability lattice, plugin/plananalysis.py, decides
    ``nonnull``): at a pipeline entry a non-null column's stored validity
    is exactly the liveness mask — padding slots are always invalid, live
    rows always valid — so the iota-derived ``live`` replaces it bit for
    bit. The validity plane is then never read from HBM and every
    downstream validity AND / null-park ``where`` folds against a
    computed mask instead of a loaded one."""
    if not nonnull or not any(nonnull):
        return list(cols)
    out: List[Val] = []
    for c, nn in zip(cols, nonnull):
        if not nn:
            out.append(c)
        elif isinstance(c, DictV):
            out.append(DictV(c.codes, c.dictionary, live,
                             c.mat_cap, c.max_len, c.unique))
        elif isinstance(c, StrV):
            out.append(StrV(c.offsets, c.chars, live))
        else:
            out.append(ColV(c.data, live))
    out.extend(cols[len(out):])  # defensive: flags never exceed columns
    return out


def rows_of_positions(offsets: jax.Array, npos: int) -> jax.Array:
    """Row id per output position given row-boundary offsets (cap+1,).

    One boundary scatter + one cumsum. The obvious searchsorted costs
    log2(cap) gather passes over all npos positions — on TPU, where each
    gather pass runs at HBM-random-access speed, that is ~20x slower; this
    is the canonical position->row mapper for every ragged kernel."""
    cap = offsets.shape[0] - 1
    marks = (
        jnp.zeros(npos, jnp.int32)
        .at[offsets[1:cap]]
        .add(1, mode="drop")
    )
    return jnp.cumsum(marks)


def piecewise_by_row(values: jax.Array, new_offsets: jax.Array,
                     npos: int) -> jax.Array:
    """Expand per-row ``values`` to per-position (piecewise constant over
    each row's [new_offsets[i], new_offsets[i+1]) range): ONE scatter-add
    of boundary deltas + a cumsum. Half the cost of
    values[rows_of_positions(...)], which needs the scatter+cumsum AND a
    full-size gather. Deltas of empty rows collide at one position and
    accumulate, so the net is still right. int32 domain."""
    cap = new_offsets.shape[0] - 1
    v = values.astype(jnp.int32)
    inc = v[1:] - v[:-1]
    arr = (
        jnp.zeros(npos, jnp.int32)
        .at[new_offsets[1:cap]]
        .add(inc[: cap - 1], mode="drop")
    )
    return jnp.cumsum(arr) + v[0]


def compaction_indices(mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Destination-order gather indices for selected rows.

    Returns (indices, count): ``indices[j]`` = row of the j-th selected row
    for j < count; tail entries point at row 0 (callers mask them out).

    O(n): prefix-sum destinations + one scatter of row ids (a sort-based
    selected-first permutation costs log^2 n passes on the TPU's bitonic
    sorter — 100x more HBM traffic).
    """
    cap = mask.shape[0]
    csum = jnp.cumsum(mask.astype(jnp.int32))
    count = csum[cap - 1]
    dest = jnp.where(mask, csum - 1, cap)  # cap = out of bounds -> dropped
    indices = (
        jnp.zeros(cap, jnp.int32)
        .at[dest]
        .set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
    )
    return indices, count


def gather_fixed(col: ColV, indices: jax.Array, valid_slot: jax.Array) -> ColV:
    """Gather rows of a fixed-width column; ``valid_slot`` marks live outputs."""
    data = jnp.take(col.data, indices, mode="clip")
    validity = jnp.take(col.validity, indices, mode="clip") & valid_slot
    data = jnp.where(validity, data, jnp.zeros((), dtype=data.dtype))
    return ColV(data, validity)


def packable_dtype(dt) -> bool:
    """True when :func:`pack_fixed_cols` can carry this dtype losslessly.

    f64 is excluded: the TPU x64 rewriter has no 64-bit bitcast, and an
    arithmetic f32 hi/lo split drops mantissa bits on real-f64 backends."""
    dt = jnp.dtype(dt)
    return dt != jnp.float64


def _split64_i32(d: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(lo, hi) int32 words of a 64-bit integer column, via shifts/masks —
    the x64 emulation pass supports arithmetic but NOT 64-bit bitcasts."""
    u = d.astype(jnp.uint64)
    lo = jax.lax.convert_element_type(u & jnp.uint64(0xFFFFFFFF), jnp.uint32)
    hi = jax.lax.convert_element_type(u >> 32, jnp.uint32)
    return (
        jax.lax.bitcast_convert_type(lo, jnp.int32),
        jax.lax.bitcast_convert_type(hi, jnp.int32),
    )


def _join64(lo_i32: jax.Array, hi_i32: jax.Array, dt) -> jax.Array:
    lo = jax.lax.bitcast_convert_type(lo_i32, jnp.uint32).astype(jnp.int64)
    hi = jax.lax.bitcast_convert_type(hi_i32, jnp.int32).astype(jnp.int64)
    return (lo | (hi << 32)).astype(dt)


def pack_fixed_cols(cols: Sequence[ColV]) -> jax.Array:
    """Pack fixed-width columns (+ their validity bits) into ONE
    (cap, W) int32 matrix.

    TPU gathers pay ~15ns PER ELEMENT regardless of width, but a 2D row
    gather amortizes that over the whole row (measured 2-4x on v5e, up to
    16x for small tables) — so a multi-column gather packs first, gathers
    once, and unpacks. Pack/unpack are elementwise: ~100x cheaper than one
    gather pass. Callers must exclude non-:func:`packable_dtype` columns.
    """
    parts: List[jax.Array] = []
    for c in cols:
        d = c.data
        if d.dtype == jnp.bool_:
            parts.append(d.astype(jnp.int32)[:, None])
        elif d.dtype.itemsize == 8:
            lo, hi = _split64_i32(d)
            parts.append(jnp.stack([lo, hi], axis=-1))
        elif d.dtype.itemsize == 4:
            parts.append(jax.lax.bitcast_convert_type(d, jnp.int32)[:, None])
        else:  # i8/i16 and friends: widen
            parts.append(d.astype(jnp.int32)[:, None])
    # validity bits, 32 columns per word
    for i in range(0, len(cols), 32):
        w = jnp.zeros(cols[0].validity.shape[0], jnp.int32)
        for j, c in enumerate(cols[i : i + 32]):
            w = w | (c.validity.astype(jnp.int32) << j)
        parts.append(w[:, None])
    return jnp.concatenate(parts, axis=1)


def unpack_fixed_cols(
    mat: jax.Array, dtypes: Sequence, valid_slot: jax.Array
) -> List[ColV]:
    """Inverse of :func:`pack_fixed_cols` over a gathered matrix.

    ``dtypes``: the numpy dtype of each packed column, in pack order."""
    out: List[ColV] = []
    w = 0
    widths = []
    for dt in dtypes:
        dt = jnp.dtype(dt)
        widths.append(2 if (dt != jnp.bool_ and dt.itemsize == 8) else 1)
    vbase = sum(widths)
    for ci, (dt, nw) in enumerate(zip(dtypes, widths)):
        dt = jnp.dtype(dt)
        vword = mat[:, vbase + ci // 32]
        validity = ((vword >> (ci % 32)) & 1).astype(jnp.bool_) & valid_slot
        if dt == jnp.bool_:
            data = mat[:, w].astype(jnp.bool_)
        elif dt.itemsize == 8:
            data = _join64(mat[:, w], mat[:, w + 1], dt)
        elif dt.itemsize == 4:
            data = jax.lax.bitcast_convert_type(mat[:, w], dt)
        else:
            data = mat[:, w].astype(dt)
        data = jnp.where(validity, data, jnp.zeros((), dtype=data.dtype))
        out.append(ColV(data, validity))
        w += nw
    return out


def gather_string(
    col: StrV, indices: jax.Array, valid_slot: jax.Array, out_char_cap: int
) -> StrV:
    """Gather rows of a string column (Arrow offsets+bytes layout).

    Two-pass like cudf's strings gather: sizes first (new offsets by prefix
    sum), then a byte-level gather computed from the inverse offset map. All
    shapes static: output rows = len(indices), bytes = out_char_cap.
    """
    m = indices.shape[0]
    lens = col.offsets[1:] - col.offsets[:-1]
    validity = jnp.take(col.validity, indices, mode="clip") & valid_slot
    sel_lens = jnp.where(validity, jnp.take(lens, indices, mode="clip"), 0)
    new_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(sel_lens).astype(jnp.int32)]
    )
    total = new_offsets[m]
    j = jnp.arange(out_char_cap, dtype=jnp.int32)
    # src_byte[j] = col.offsets[indices[r]] + (j - new_offsets[r]) where r
    # is j's output row; the bracketed delta is piecewise-constant per row
    # so it expands with one scatter+cumsum instead of three row gathers
    delta = (
        jnp.take(col.offsets, jnp.clip(indices, 0, col.offsets.shape[0] - 1),
                 mode="clip")
        - new_offsets[:-1]
    )
    src_byte = j + piecewise_by_row(delta, new_offsets, out_char_cap)
    in_range = j < total
    nchars = col.chars.shape[0]
    chars = jnp.where(
        in_range,
        jnp.take(col.chars, jnp.clip(src_byte, 0, nchars - 1), mode="clip"),
        jnp.zeros((), jnp.uint8),
    )
    return StrV(new_offsets, chars, validity)


def gather(
    cols: Sequence[Val],
    indices: jax.Array,
    valid_slot: jax.Array,
    char_caps: Optional[Sequence[int]] = None,
) -> List[Val]:
    """Gather each column by row ``indices`` (same output rows for all).

    ``char_caps`` overrides the output byte-pool size per string column (in
    order of appearance) — required when indices repeat rows (join
    expansion), where output bytes can exceed the input pool.

    Fixed-width columns gather as ONE packed (cap, W) int32 row gather
    (see :func:`pack_fixed_cols`); strings keep the two-pass byte path.
    Dict-encoded strings gather as their int32 CODES (riding the packed
    fixed gather — the late-materialization payoff: no byte movement);
    the dictionary passes through untouched. Callers whose indices repeat
    rows must materialize dict columns first (a row-repeat can exceed the
    static ``mat_cap`` byte bound) — row-subset/permute callers are safe."""
    orig_cols = list(cols)
    cols = [
        ColV(c.codes, c.validity) if isinstance(c, DictV) else c
        for c in orig_cols
    ]
    fixed = [
        c for c in cols
        if isinstance(c, ColV) and packable_dtype(c.data.dtype)
    ]
    packed: List[ColV] = []
    if len(fixed) >= 2 or (fixed and fixed[0].data.dtype.itemsize == 8):
        mat = pack_fixed_cols(fixed)
        g = jnp.take(mat, indices, axis=0, mode="clip")
        packed = unpack_fixed_cols(g, [c.data.dtype for c in fixed], valid_slot)
    elif fixed:
        packed = [gather_fixed(fixed[0], indices, valid_slot)]
    out: List[Val] = []
    si = 0
    fi = 0
    for c, oc in zip(cols, orig_cols):
        if isinstance(c, StrV):
            cc = (
                char_caps[si]
                if char_caps is not None and si < len(char_caps)
                else int(c.chars.shape[0])
            )
            si += 1
            out.append(gather_string(c, indices, valid_slot, cc))
            continue
        if not packable_dtype(c.data.dtype):
            g = gather_fixed(c, indices, valid_slot)
        else:
            g = packed[fi]
            fi += 1
        if isinstance(oc, DictV):
            g = DictV(g.data, oc.dictionary, g.validity,
                      oc.mat_cap, oc.max_len, oc.unique)
        out.append(g)
    return out


def filter_cols(
    cols: Sequence[Val], mask: jax.Array, num_rows: Union[int, jax.Array]
) -> Tuple[List[Val], jax.Array]:
    """Compact rows where ``mask`` holds to the front of each column.

    ``mask`` must already be False in padding slots (>= num_rows). Returns
    (new columns, new logical row count as a device scalar).
    """
    del num_rows  # the mask already excludes padding
    indices, count = compaction_indices(mask)
    cap = mask.shape[0]
    valid_slot = jnp.arange(cap, dtype=jnp.int32) < count
    return gather(cols, indices, valid_slot), count


def slice_cols(
    cols: Sequence[Val], start: int, length_cap: int, num_rows: jax.Array
) -> Tuple[List[Val], jax.Array]:
    """Static-shape row slice [start, start+length_cap) of a column set."""
    indices = jnp.arange(length_cap, dtype=jnp.int32) + start
    count = jnp.clip(num_rows - start, 0, length_cap)
    valid_slot = indices < num_rows
    return gather(cols, indices, valid_slot), count
