"""Window kernels over partition-sorted rows: segmented scans + boundary
arithmetic.

Reference analog: cudf rolling-window aggregations driven by GpuWindowExec
(GpuWindowExec.scala:92, GpuWindowExpression.scala:709). TPU re-design:
after ONE sort by (partition keys, order keys), every supported window
function is O(n) scan arithmetic — cumsum/cummax for running frames,
``lax.associative_scan`` with a segment-reset combiner for segmented
min/max, and prefix/boundary gathers for RANGE peer-group semantics. No
per-partition looping: all partitions process in the same pass.

Row indexing convention: arrays are partition-sorted, padding rows last;
``part_start[i]``/``part_end[i]`` give the first/last row index of row i's
partition; ``peer_end[i]`` the last row of its ORDER BY peer group.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..expr.eval import ColV


def boundaries_from_radix(
    part_radix: Tuple[jax.Array, ...],
    order_radix: Tuple[jax.Array, ...],
    live: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """(part_start, part_end, peer_start, peer_end, seg) per row.

    Inputs are the co-sorted radix key arrays (partition keys, order keys)
    and the sorted liveness mask."""
    cap = live.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)

    def change(arrs):
        ch = jnp.zeros(cap, jnp.bool_)
        for a in arrs:
            ch = ch | (a != jnp.roll(a, 1))
        return ch.at[0].set(True)

    part_change = change(part_radix) & live
    peer_change = (part_change | (change(order_radix) if order_radix else jnp.zeros(cap, jnp.bool_))) & live
    part_change = part_change.at[0].set(live[0])
    peer_change = peer_change.at[0].set(live[0])

    seg = jnp.cumsum(part_change.astype(jnp.int32)) - 1
    seg = jnp.where(live, seg, cap)

    # start of current partition / peer group: running max of marked starts
    part_start = lax.cummax(jnp.where(part_change, idx, 0))
    peer_start = lax.cummax(jnp.where(peer_change, idx, 0))

    # end = (next start) - 1, scanned from the right
    def next_start(change_mask):
        nxt = jnp.roll(change_mask, -1).at[-1].set(True)
        marked = jnp.where(nxt, idx, cap - 1)
        return lax.cummin(marked[::-1])[::-1]

    part_end = next_start(part_change | ~live)
    peer_end = next_start(peer_change | ~live)
    return part_start, part_end, peer_start, peer_end, seg


def row_number(part_start: jax.Array, live: jax.Array) -> ColV:
    cap = live.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    return ColV(jnp.where(live, idx - part_start + 1, 0), live)


def rank(part_start: jax.Array, peer_start: jax.Array, live: jax.Array) -> ColV:
    return ColV(jnp.where(live, peer_start - part_start + 1, 0), live)


def dense_rank(
    part_start: jax.Array, peer_start: jax.Array, live: jax.Array
) -> ColV:
    cap = live.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    new_peer = (peer_start == idx) & live
    pre = jnp.cumsum(new_peer.astype(jnp.int32))
    base = lax.cummax(jnp.where(part_start == idx, pre, 0))
    return ColV(jnp.where(live, pre - base + 1, 0), live)


def shift_in_partition(
    col: ColV,
    offset: int,
    part_start: jax.Array,
    part_end: jax.Array,
    live: jax.Array,
    default: Optional[ColV] = None,
) -> ColV:
    """lead (offset>0) / lag (offset<0) within the partition."""
    cap = live.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    target = idx + offset
    in_part = (target >= part_start) & (target <= part_end) & live
    safe = jnp.clip(target, 0, cap - 1)
    data = jnp.take(col.data, safe, mode="clip")
    valid = jnp.take(col.validity, safe, mode="clip") & in_part
    if default is not None:
        data = jnp.where(in_part, data, default.data)
        valid = valid | (~in_part & live & default.validity)
    data = jnp.where(valid, data, jnp.zeros((), data.dtype))
    return ColV(data, valid & live)


def _seg_scan(values: jax.Array, seg: jax.Array, combine):
    """Segmented inclusive scan via associative_scan with reset-on-new-seg."""

    def op(a, b):
        va, sa = a
        vb, sb = b
        return (jnp.where(sa == sb, combine(va, vb), vb), sb)

    out, _ = lax.associative_scan(op, (values, seg))
    return out


def bounded_row_agg(
    op: str,
    col: Optional[ColV],
    part_start: jax.Array,
    part_end: jax.Array,
    live: jax.Array,
    lower: int,
    upper: int,
) -> ColV:
    """sum/count/min/max over a literal ROWS frame [i+lower, i+upper],
    clamped to the partition (reference: GpuWindowExpression.scala:451+ —
    row frames with literal bounds lowered to cudf rolling windows).

    sum/count use prefix sums; min/max a sparse table with static levels
    (the frame width is a literal, so log2(width) unrolls at trace time).
    """
    cap = live.shape[0]
    idx = jnp.arange(cap, dtype=jnp.int32)
    lo = jnp.maximum(idx + lower, part_start)
    hi = jnp.minimum(idx + upper, part_end)  # part_end inclusive
    empty = (hi < lo) | ~live
    lo_c = jnp.clip(lo, 0, cap - 1)
    hi_c = jnp.clip(hi, 0, cap - 1)

    if op == "count_star":
        cnt = jnp.where(empty, 0, hi_c - lo_c + 1)
        return ColV(cnt.astype(jnp.int64), live)

    valid = live & col.validity

    def window_count():
        pre = jnp.concatenate(
            [jnp.zeros(1, jnp.int64), jnp.cumsum(valid.astype(jnp.int64))])
        return jnp.where(empty, 0, pre[hi_c + 1] - pre[lo_c])

    if op == "count":
        return ColV(window_count(), live)
    if op == "sum":
        x = jnp.where(valid, col.data, jnp.zeros((), col.data.dtype))
        pre = jnp.concatenate([jnp.zeros(1, x.dtype), jnp.cumsum(x)])
        s = jnp.where(empty, jnp.zeros((), x.dtype), pre[hi_c + 1] - pre[lo_c])
        has = (window_count() > 0) & ~empty
        return ColV(jnp.where(has, s, jnp.zeros((), s.dtype)), has)
    if op in ("min", "max"):
        isfloat = jnp.issubdtype(col.data.dtype, jnp.floating)
        if op == "max":
            fill = (jnp.array(-jnp.inf, col.data.dtype) if isfloat
                    else jnp.array(jnp.iinfo(col.data.dtype).min,
                                   col.data.dtype))
            combine = jnp.maximum
            x = jnp.where(valid, col.data, fill)
        else:
            fill = (jnp.array(jnp.inf, col.data.dtype) if isfloat
                    else jnp.array(jnp.iinfo(col.data.dtype).max,
                                   col.data.dtype))
            combine = jnp.minimum
            x = col.data
            if isfloat:
                # Spark min skips NaN unless the frame is all-NaN
                x = jnp.where(jnp.isnan(x), jnp.inf, x)
            x = jnp.where(valid, x, fill)
        # sparse table: level k answers any range of length in [2^k, 2^(k+1))
        width = upper - lower + 1
        levels = [x]
        k = 1
        while k < width:
            t = levels[-1]
            shifted = jnp.concatenate([t[k:], jnp.full(k, fill, t.dtype)])
            levels.append(combine(t, shifted))
            k *= 2
        T = jnp.stack(levels)  # (L, cap): T[k, i] = agg over [i, i+2^k)
        ln = (hi_c - lo_c + 1).astype(jnp.float64)
        kq = jnp.floor(jnp.log2(jnp.maximum(ln, 1))).astype(jnp.int32)
        kq = jnp.clip(kq, 0, len(levels) - 1)
        p2 = (1 << kq.astype(jnp.int64)).astype(jnp.int32)
        a = T[kq, lo_c]
        b = T[kq, jnp.clip(hi_c - p2 + 1, 0, cap - 1)]
        r = combine(a, b)
        cnt = window_count()
        has = (cnt > 0) & ~empty
        if op == "min" and isfloat:
            nn = valid & ~jnp.isnan(col.data)
            npre = jnp.concatenate(
                [jnp.zeros(1, jnp.int64), jnp.cumsum(nn.astype(jnp.int64))])
            n_nonnan = jnp.where(empty, 0, npre[hi_c + 1] - npre[lo_c])
            r = jnp.where((n_nonnan == 0) & has, jnp.nan, r)
        return ColV(jnp.where(has, r, jnp.zeros((), r.dtype)), has)
    raise ValueError(f"unsupported bounded window aggregation {op!r}")


def _search_sorted_in_partition(
    keys: jax.Array, lo0: jax.Array, hi0: jax.Array, target: jax.Array,
    side: str,
) -> jax.Array:
    """Vectorized per-row binary search over [lo0, hi0) of a key array
    that is non-decreasing WITHIN each row's partition slice. side='left'
    returns the first index with key >= target, 'right' the first with
    key > target. log2(cap) gather passes — the TPU-shaped replacement
    for cudf's per-row range-window bound search."""
    cap = keys.shape[0]
    iters = max(int(np.ceil(np.log2(max(cap, 2)))) + 1, 1)
    lo, hi = lo0, hi0
    for _ in range(iters):
        mid = (lo + hi) // 2
        v = jnp.take(keys, jnp.clip(mid, 0, cap - 1), mode="clip")
        go_right = (v < target) if side == "left" else (v <= target)
        valid = lo < hi
        lo = jnp.where(valid & go_right, mid + 1, lo)
        hi = jnp.where(valid & ~go_right, mid, hi)
    return lo


def _saturating_offset(kd: jax.Array, off) -> jax.Array:
    """kd + off with integer saturation (offsets are host literals)."""
    if jnp.issubdtype(kd.dtype, jnp.floating):
        return kd + jnp.asarray(off, kd.dtype)
    info = jnp.iinfo(kd.dtype)
    o = int(off)
    if o >= 0:
        return jnp.where(kd > info.max - o, info.max, kd + o)
    return jnp.where(kd < info.min - o, info.min, kd + o)


def bounded_range_agg(
    op: str,
    col: Optional[ColV],
    order_key: ColV,
    part_start: jax.Array,
    part_end: jax.Array,
    peer_start: jax.Array,
    peer_end: jax.Array,
    live: jax.Array,
    lower,  # numeric offset (preceding negative) or None = unbounded
    upper,  # numeric offset or None = unbounded
    nulls_first: bool,
) -> ColV:
    """sum/count over a literal RANGE frame: rows j of the same partition
    with key[j] in [key[i]+lower, key[i]+upper]. ``order_key`` is the
    single numeric ORDER BY key, ASC-normalized (callers negate data and
    swap/negate bounds for DESC). Null-key rows take their peer group —
    all nulls — as the frame (Spark's RangeFrame null semantics).
    Reference: GpuWindowExpression.scala:88,168."""
    cap = live.shape[0]
    kd = order_key.data
    kv = order_key.validity & live
    # park null keys at the end they sort to, keeping the slice monotone;
    # the offset search then naturally excludes them from non-null frames
    if jnp.issubdtype(kd.dtype, jnp.floating):
        park = jnp.array(-jnp.inf if nulls_first else jnp.inf, kd.dtype)
    else:
        info = jnp.iinfo(kd.dtype)
        park = jnp.array(info.min if nulls_first else info.max, kd.dtype)
    keys = jnp.where(kv, kd, park)

    # clamp the searched frame to the partition's NON-NULL span: parked
    # null keys collide with saturating range bounds near the dtype edge
    # (key=int64.min+1 with 5 PRECEDING saturates to int64.min == the
    # nulls-first park value, pulling the null peer block into the frame).
    # Nulls sort to one contiguous end of the partition, so the span is a
    # per-partition null count away from the partition edge.
    nulls = live & ~order_key.validity
    pre_nulls = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(nulls.astype(jnp.int32))])
    n_nulls = pre_nulls[part_end + 1] - pre_nulls[part_start]
    nn_start = part_start + n_nulls if nulls_first else part_start
    nn_end = part_end if nulls_first else part_end - n_nulls

    if lower is None:
        lo = part_start
    else:
        lo = _search_sorted_in_partition(
            keys, nn_start, nn_end + 1,
            _saturating_offset(keys, lower), "left")
    if upper is None:
        hi = part_end
    else:
        hi = _search_sorted_in_partition(
            keys, nn_start, nn_end + 1,
            _saturating_offset(keys, upper), "right") - 1
    # null current rows: a BOUNDED side lands on the null peer block
    # (nulls are mutual peers); an unbounded side keeps the partition edge
    if lower is not None:
        lo = jnp.where(kv, lo, peer_start)
    if upper is not None:
        hi = jnp.where(kv, hi, peer_end)
    empty = (hi < lo) | ~live
    lo_c = jnp.clip(lo, 0, cap - 1)
    hi_c = jnp.clip(hi, 0, cap - 1)

    if op == "count_star":
        cnt = jnp.where(empty, 0, hi_c - lo_c + 1)
        return ColV(cnt.astype(jnp.int64), live)
    valid = live & col.validity

    def window_count():
        pre = jnp.concatenate(
            [jnp.zeros(1, jnp.int64), jnp.cumsum(valid.astype(jnp.int64))])
        return jnp.where(empty, 0, pre[hi_c + 1] - pre[lo_c])

    if op == "count":
        return ColV(window_count(), live)
    if op == "sum":
        x = jnp.where(valid, col.data, jnp.zeros((), col.data.dtype))
        pre = jnp.concatenate([jnp.zeros(1, x.dtype), jnp.cumsum(x)])
        s = jnp.where(
            empty, jnp.zeros((), x.dtype), pre[hi_c + 1] - pre[lo_c])
        has = (window_count() > 0) & ~empty
        return ColV(jnp.where(has, s, jnp.zeros((), s.dtype)), has)
    raise ValueError(f"unsupported bounded-range window aggregation {op!r}")


def running_agg(
    op: str,
    col: Optional[ColV],
    seg: jax.Array,
    part_start: jax.Array,
    peer_end: jax.Array,
    live: jax.Array,
    range_frame: bool,
    whole_partition: bool,
    part_end: jax.Array,
) -> ColV:
    """sum/count/min/max/avg-buffer over a running or whole-partition frame.

    ``range_frame``: include the whole ORDER BY peer group (Spark RANGE
    UNBOUNDED..CURRENT). ``whole_partition`` overrides with the full frame.
    """
    cap = live.shape[0]
    at = part_end if whole_partition else (peer_end if range_frame else None)

    def frame_value(prefix):
        if at is None:
            return prefix
        return jnp.take(prefix, jnp.clip(at, 0, cap - 1), mode="clip")

    if op in ("count", "count_star"):
        valid = live if op == "count_star" else (live & col.validity)
        pre = jnp.cumsum(valid.astype(jnp.int64))
        base = jnp.take(
            pre - valid.astype(jnp.int64),
            jnp.clip(part_start, 0, cap - 1), mode="clip")
        cnt = frame_value(pre) - base
        return ColV(jnp.where(live, cnt, 0), live)
    valid = live & col.validity
    if op == "sum":
        x = jnp.where(valid, col.data, jnp.zeros((), col.data.dtype))
        pre = jnp.cumsum(x)
        base = jnp.take(pre - x, jnp.clip(part_start, 0, cap - 1), mode="clip")
        s = frame_value(pre) - base
        cpre = jnp.cumsum(valid.astype(jnp.int64))
        cbase = jnp.take(
            cpre - valid.astype(jnp.int64),
            jnp.clip(part_start, 0, cap - 1), mode="clip")
        cnt = frame_value(cpre) - cbase
        has = cnt > 0
        return ColV(jnp.where(has, s, jnp.zeros((), s.dtype)), has & live)
    if op in ("min", "max"):
        isfloat = jnp.issubdtype(col.data.dtype, jnp.floating)
        if op == "max":
            if isfloat:
                fill = jnp.array(-jnp.inf, col.data.dtype)
            elif col.data.dtype == jnp.bool_:
                fill = jnp.array(False)
            else:
                fill = jnp.array(jnp.iinfo(col.data.dtype).min, col.data.dtype)
            x = jnp.where(valid, col.data, fill)
            scan = _seg_scan(x, seg, jnp.maximum)
        else:
            if isfloat:
                # Spark min skips NaN unless all-NaN: map NaN -> +inf, fix later
                x = jnp.where(jnp.isnan(col.data), jnp.inf, col.data)
                fill = jnp.array(jnp.inf, col.data.dtype)
            elif col.data.dtype == jnp.bool_:
                x = col.data
                fill = jnp.array(True)
            else:
                x = col.data
                fill = jnp.array(jnp.iinfo(col.data.dtype).max, col.data.dtype)
            x = jnp.where(valid, x, fill)
            scan = _seg_scan(x, seg, jnp.minimum)
        r = frame_value(scan)
        cpre = jnp.cumsum(valid.astype(jnp.int64))
        cbase = jnp.take(
            cpre - valid.astype(jnp.int64),
            jnp.clip(part_start, 0, cap - 1), mode="clip")
        cnt = frame_value(cpre) - cbase
        has = (cnt > 0) & live
        if op == "min" and isfloat:
            nn_valid = valid & ~jnp.isnan(col.data)
            npre = jnp.cumsum(nn_valid.astype(jnp.int64))
            nbase = jnp.take(
                npre - nn_valid.astype(jnp.int64),
                jnp.clip(part_start, 0, cap - 1), mode="clip")
            n_nonnan = frame_value(npre) - nbase
            r = jnp.where((n_nonnan == 0) & has, jnp.nan, r)
        r = jnp.where(has, r, jnp.zeros((), r.dtype))
        return ColV(r, has)
    raise ValueError(f"unsupported window aggregation {op!r}")
