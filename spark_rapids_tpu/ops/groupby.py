"""Sort-based group-by aggregation kernels.

Reference analog: cudf ``table.groupBy(...).aggregate(...)`` as called from
GpuHashAggregateExec (aggregate.scala:806). cudf hash-aggregates; on TPU a
hash table of dynamic size fights XLA, so the design is the classic
sort-compatible alternative the same exec supports: stable-sort rows by the
grouping keys (ops/sort.py), derive segment ids from key-change boundaries,
and reduce each segment with ``jax.ops.segment_*`` — one fused XLA program,
fully static shapes (worst case: every row its own group, so num_segments =
capacity). Null keys form their own group (Spark semantics); aggregate
inputs skip nulls; NaN groups as equal to NaN.

Reductions provided: count_star, count, sum, min, max, first/last (+
ignore-null variants). Average is decomposed by the exec layer into
sum+count partials, mirroring Spark's update/merge model.

String min/max (lexicographic, Spark UTF8String byte order) reduce via
RANKS so every numeric fast path applies unchanged: a dictionary-encoded
column ranks its (small) dictionary once in sorted-code order — the cudf
dictionary32 trick, O(cardinality) — while a plain string column ranks
rows with one radix-chunk sort; the winning rank then maps back to a
code (dict) or row (plain) and the string is gathered out.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .. import types as T
from ..expr.eval import ColV, DictV, StrV, Val
from ..expr.values import materialize_dict
from .filter_gather import gather
from .sort import SortOrder, sort_with_radix_keys, string_chunk_keys


def segment_ids_from_radix_keys(
    sorted_radix_keys: Sequence[jax.Array],
    num_rows: Union[int, jax.Array],
) -> Tuple[jax.Array, jax.Array]:
    """(segment_ids, num_segments) from the co-sorted radix key arrays.

    Two adjacent rows belong to the same group iff every radix key matches
    — the radix encoding already folds Spark's equality rules in
    (null==null via the null-rank key, NaN canonicalized, -0.0 -> 0.0,
    strings as byte chunks). Padding rows get an out-of-range id so every
    segment_* scatter drops them.
    """
    cap = sorted_radix_keys[0].shape[0]
    eq = jnp.ones(cap, jnp.bool_)
    for k in sorted_radix_keys:
        eq = eq & (k == jnp.roll(k, 1))
    from .filter_gather import live_of

    live = live_of(num_rows, cap)
    new_seg = live & (~eq | (jnp.arange(cap) == 0))
    seg = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
    num_segments = jnp.max(jnp.where(live, seg, -1)) + 1
    seg = jnp.where(live, seg, cap)  # out-of-range for padding
    return seg, num_segments


_INT_MIN_MAX = {
    jnp.dtype(jnp.int8): (-(2**7), 2**7 - 1),
    jnp.dtype(jnp.int16): (-(2**15), 2**15 - 1),
    jnp.dtype(jnp.int32): (-(2**31), 2**31 - 1),
    jnp.dtype(jnp.int64): (-(2**63), 2**63 - 1),
}


def _segment_count(valid: jax.Array, seg: jax.Array, ncap: int) -> jax.Array:
    return jax.ops.segment_sum(valid.astype(jnp.int64), seg, num_segments=ncap)


def segment_reduce(
    op: str,
    col: Optional[ColV],
    seg: jax.Array,
    ncap: int,
    live: jax.Array,
) -> ColV:
    """One aggregation over segments. Returns (ncap,)-shaped ColV."""
    if op == "count_star":
        cnt = jax.ops.segment_sum(live.astype(jnp.int64), seg, num_segments=ncap)
        return ColV(cnt, jnp.ones(ncap, jnp.bool_))
    assert col is not None
    valid = col.validity & live
    data = col.data
    if op == "count":
        cnt = _segment_count(valid, seg, ncap)
        return ColV(cnt, jnp.ones(ncap, jnp.bool_))
    cnt = _segment_count(valid, seg, ncap)
    has = cnt > 0
    if op == "sum":
        z = jnp.zeros((), data.dtype)
        s = jax.ops.segment_sum(jnp.where(valid, data, z), seg, num_segments=ncap)
        return ColV(s, has)
    if op in ("min", "max"):
        isfloat = jnp.issubdtype(data.dtype, jnp.floating)
        if isfloat:
            if op == "max":
                # Spark: NaN is the largest double; IEEE max propagates NaN,
                # which is exactly the desired result, so plain masking works
                fill = jnp.array(-jnp.inf, data.dtype)
                d = jnp.where(valid, data, fill)
                r = jax.ops.segment_max(d, seg, num_segments=ncap)
            else:
                # min must *skip* NaN unless the group is all-NaN
                nan_as_inf = jnp.where(jnp.isnan(data), jnp.inf, data)
                d = jnp.where(valid, nan_as_inf, jnp.inf).astype(data.dtype)
                r = jax.ops.segment_min(d, seg, num_segments=ncap)
                non_nan = _segment_count(valid & ~jnp.isnan(data), seg, ncap)
                r = jnp.where((non_nan == 0) & has, jnp.nan, r)
        else:
            lo, hi = _INT_MIN_MAX.get(
                jnp.dtype(data.dtype), (0, 1)
            )
            if data.dtype == jnp.bool_:
                fill = jnp.array(op == "min", jnp.bool_)
                d = jnp.where(valid, data, fill)
                r = (
                    jax.ops.segment_max(d, seg, num_segments=ncap)
                    if op == "max"
                    else jax.ops.segment_min(d, seg, num_segments=ncap)
                )
            else:
                fill = jnp.array(lo if op == "max" else hi, data.dtype)
                d = jnp.where(valid, data, fill)
                r = (
                    jax.ops.segment_max(d, seg, num_segments=ncap)
                    if op == "max"
                    else jax.ops.segment_min(d, seg, num_segments=ncap)
                )
        z = jnp.zeros((), r.dtype)
        return ColV(jnp.where(has, r, z), has)
    if op in ("first", "last", "first_ignorenulls", "last_ignorenulls"):
        cap = data.shape[0]
        idx = jnp.arange(cap, dtype=jnp.int32)
        consider = valid if op.endswith("ignorenulls") else live
        big = jnp.int32(cap)
        if op.startswith("first"):
            pos = jax.ops.segment_min(
                jnp.where(consider, idx, big), seg, num_segments=ncap
            )
        else:
            pos = jax.ops.segment_max(
                jnp.where(consider, idx, jnp.int32(-1)), seg, num_segments=ncap
            )
        found = (pos >= 0) & (pos < cap)
        safe = jnp.clip(pos, 0, cap - 1)
        vals = jnp.take(data, safe, mode="clip")
        val_valid = jnp.take(col.validity, safe, mode="clip") & found
        z = jnp.zeros((), vals.dtype)
        return ColV(jnp.where(val_valid, vals, z), val_valid)
    raise ValueError(f"unknown aggregation op {op!r}")


def _dict_rank(v: DictV) -> Tuple[jax.Array, ColV]:
    """(order, per-row rank) of a dictionary-encoded column: ``order[p]``
    is the dictionary index of the p-th smallest entry (lexicographic
    UTF8 byte order), and the per-row rank rides the codes through one
    int32 gather. ``max_len`` is static metadata — no host sync."""
    d = v.dictionary
    keys = string_chunk_keys(
        StrV(d.offsets, d.chars, jnp.ones(v.dict_size, jnp.bool_)),
        SortOrder(True, True), max(1, v.max_len))
    iota = jnp.arange(v.dict_size, dtype=jnp.int32)
    sorted_ops = lax.sort(list(keys) + [iota], num_keys=len(keys),
                          is_stable=True)
    order = sorted_ops[-1]
    rank = jnp.zeros(v.dict_size, jnp.int32).at[order].set(
        iota, mode="drop")
    from ..expr.values import dict_gather_col

    return order, dict_gather_col(v, ColV(rank, jnp.ones(
        v.dict_size, jnp.bool_)))


def _plain_rank(v: StrV, num_rows, max_len: int) -> Tuple[jax.Array, ColV]:
    """(perm, per-row rank) of a plain string column via one radix-chunk
    sort: ``perm[p]`` is the row holding the p-th smallest string."""
    cap = v.offsets.shape[0] - 1
    perm, _ = sort_with_radix_keys(
        [v], [T.STRING], [SortOrder(True, True)], num_rows, [max_len])
    rank = jnp.zeros(cap, jnp.int32).at[perm].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")
    return perm, ColV(rank, v.validity)


def string_minmax_ranks(
    value_cols: List[Optional[ColV]],
    agg_ops: Sequence[str],
    num_rows: Union[int, jax.Array],
    str_val_max_lens: Sequence[int] = (),
):
    """Replace string-typed min/max inputs with their rank columns.

    Returns ``recover``: agg index -> callable mapping the reduced rank
    column back to the winning strings (a DictV rewrap for dictionary
    columns, a row gather for plain ones). ``str_val_max_lens`` supplies
    the static byte-length bound per string-typed min/max input, in
    order of appearance (dictionary columns ignore theirs — their bound
    is static metadata)."""
    from .filter_gather import gather_string

    recover = {}
    rank_cache = {}  # id(value) -> (order/perm, rank rows): min(s)+max(s)
    si = 0           # over one column share ONE rank sort
    for ai, (op, v) in enumerate(zip(agg_ops, value_cols)):
        if op not in ("min", "max") or not isinstance(v, (StrV, DictV)):
            continue
        ml = str_val_max_lens[si] if si < len(str_val_max_lens) else 64
        si += 1
        cached = rank_cache.get(id(v))
        if cached is None:
            cached = rank_cache[id(v)] = (
                _dict_rank(v) if isinstance(v, DictV)
                else _plain_rank(v, num_rows, ml))
        order_or_perm, rank_rows = cached
        if isinstance(v, DictV):
            def rec(r: ColV, order=order_or_perm, t=v) -> DictV:
                hi = max(t.dict_size - 1, 0)
                codes = jnp.take(order, jnp.clip(r.data, 0, hi), mode="clip")
                return DictV(codes.astype(jnp.int32), t.dictionary,
                             r.validity, t.mat_cap, t.max_len, t.unique)
        else:
            def rec(r: ColV, perm=order_or_perm, src=v) -> StrV:
                cap = src.offsets.shape[0] - 1
                rows = jnp.take(perm, jnp.clip(r.data, 0, cap - 1),
                                mode="clip")
                # winners are distinct source rows, so the source byte
                # pool bounds the output
                return gather_string(src, rows, r.validity,
                                     int(src.chars.shape[0]))
        value_cols[ai] = rank_rows
        recover[ai] = rec
    return recover


def _sorted_segment_aggs(
    agg_ops: Sequence[str],
    sorted_vals: Sequence[Optional[ColV]],
    seg: jax.Array,
    ncap: int,
    live: jax.Array,
) -> List[ColV]:
    """Bandwidth-sized reduction over ALREADY-SORTED segment ids (the SORT
    aggregation strategy): sum/count/count_star batch through ONE
    prefix-difference pass (ops/bucket_reduce.contiguous_segment_reduce —
    the segments are contiguous after the radix sort, so no scatter walk
    per aggregate). Integer sums, counts and count_star are bit-identical
    to :func:`segment_reduce`; FLOAT sums and min/max/first/last keep the
    segment-scatter path — float prefix differences would reorder adds on
    queries that never opted into variableFloatAgg, and cummax has no
    inverse."""
    from .bucket_reduce import contiguous_segment_reduce

    int_specs: List[Tuple[jax.Array, jax.Array]] = []
    cnt_specs: List[jax.Array] = []
    plan: List[tuple] = []
    for op, v in zip(agg_ops, sorted_vals):
        if op == "count_star":
            plan.append(("cnt", len(cnt_specs)))
            cnt_specs.append(live)
        elif op == "count":
            plan.append(("cnt", len(cnt_specs)))
            cnt_specs.append(v.validity & live)
        elif (op == "sum" and v is not None
                and not jnp.issubdtype(v.data.dtype, jnp.floating)):
            ci = len(cnt_specs)
            cnt_specs.append(v.validity & live)
            plan.append(("isum", (len(int_specs), ci, v.data.dtype)))
            int_specs.append((v.data, v.validity & live))
        else:
            plan.append(("seg", (op, v)))
    isums, counts, _ = contiguous_segment_reduce(
        seg, ncap, int_specs, cnt_specs, ())
    out: List[ColV] = []
    for kind, payload in plan:
        if kind == "cnt":
            out.append(ColV(counts[payload], jnp.ones(ncap, jnp.bool_)))
        elif kind == "isum":
            si, ci, dt = payload
            data = isums[si]
            if dt != jnp.int64:
                data = data.astype(dt)  # mod-2^32 of a mod-2^64 sum: exact
            has = counts[ci] > 0
            out.append(ColV(jnp.where(has, data,
                                      jnp.zeros((), data.dtype)), has))
        else:
            op, v = payload
            out.append(segment_reduce(op, v, seg, ncap, live))
    return out


def _jnp_reduce_dtype(dtype) -> T.DataType:
    """Engine DataType standing in for a jnp dtype when only the RADIX
    encoding family matters (float total-order trick vs bool cast vs int
    sign flip — :func:`ops.sort.fixed_radix_keys` reads the VALUE dtype
    from the array itself)."""
    if jnp.issubdtype(dtype, jnp.floating):
        return T.DOUBLE
    if jnp.dtype(dtype) == jnp.bool_:
        return T.BOOLEAN
    return T.LONG


def _radix_groupby(
    key_cols: Sequence[Val],
    value_cols: Sequence[Optional[ColV]],
    agg_ops: Sequence[str],
    perm: jax.Array,
    radix: Sequence[jax.Array],
    live_in: jax.Array,
    cap: int,
) -> Tuple[List[Val], List[ColV], jax.Array]:
    """RADIX strategy: every aggregate family reduces on the tiled
    radix-binned machinery (ops/radix_bin.py) over the sort's binned row
    order — zero scatter instructions, no one-hot, and every per-row
    temporary is tile-sized, so the program's bytes-accessed approaches
    the layout bound instead of amplifying it ~25x (BENCH_r09, ROADMAP
    open item 1). Streams stay in ORIGINAL row order; the loop gathers
    one tile at a time. Integer sums/counts are bit-identical to the
    other lowerings (prefix sums wrap mod 2^64); float sums use the
    NORMAL/BIG/flag stream split (order-insensitive, strictly tighter
    than the matmul hi/lo split — AUTO only picks RADIX for exact float
    sums when variableFloatAgg opted in); min/max/first/last reduce as
    winner-ROW streams via the sort machinery's total-order words, so
    Spark's NaN-largest / -0.0 folding falls out of the encoding."""
    from . import radix_bin as RBX

    # streams are NOT materialized here: every spec carries a builder
    # closure that gathers the RAW column one tile at a time inside the
    # reduction loop and derives its stream in tile-local registers
    # (XLA CSE collapses repeated gathers of the same column), so no
    # cap-sized derived array is ever charged against the byte budget
    adds: List[RBX.AddSpec] = []
    poss: List[RBX.PosSpec] = []
    winners: List[RBX.MinMaxSpec] = []
    plan: List[tuple] = []
    cnt_idx: dict = {}
    nfam = {"u64": 0, "u32": 0, "f64": 0, "or": 0}

    def add_spec(fam, build, is_or=False):
        adds.append(RBX.AddSpec(build, {
            "u64": jnp.uint64, "u32": jnp.uint32, "f64": jnp.float64,
            "or": jnp.uint64}[fam], is_or=is_or))
        nfam[fam] += 1
        return nfam[fam] - 1

    def want_count(valid, key):
        # valid None = live rows only (dead rows zero structurally)
        if key not in cnt_idx:
            if valid is None:
                def build(tk):
                    return jnp.ones(tk.p_t.shape[0], jnp.uint32)
            else:
                def build(tk, v=valid):
                    return tk.take(v).astype(jnp.uint32)
            cnt_idx[key] = add_spec("u32", build)
        return cnt_idx[key]

    # pos stream 0: the group-representative (first live) row, for key
    # output — stability makes first-in-sorted == min original row
    poss.append(RBX.PosSpec(
        lambda tk: jnp.ones(tk.p_t.shape[0], jnp.bool_), "min"))
    for ai, (op, v) in enumerate(zip(agg_ops, value_cols)):
        if op == "count_star":
            plan.append(("cnt", want_count(None, ("star",))))
        elif op == "count":
            plan.append(("cnt", want_count(v.validity, ("c", ai))))
        elif op == "sum" and not jnp.issubdtype(v.data.dtype, jnp.floating):
            ci = want_count(v.validity, ("c", ai))

            def ibuild(tk, d=v.data, vv=v.validity):
                return jnp.where(tk.take(vv),
                                 tk.take(d).astype(jnp.int64),
                                 jnp.int64(0)).astype(jnp.uint64)

            plan.append(("isum", (add_spec("u64", ibuild), ci,
                                  v.data.dtype)))
        elif op == "sum":
            ci = want_count(v.validity, ("c", ai))

            def fpart(tk, i, d=v.data, vv=v.validity):
                return RBX.float_sum_streams(tk.take(d), tk.take(vv))[i]

            fi = add_spec("f64", lambda tk, f=fpart: f(tk, 0))
            add_spec("f64", lambda tk, f=fpart: f(tk, 1))
            oi = add_spec("or", lambda tk, f=fpart: f(tk, 2), is_or=True)
            plan.append(("fsum", (fi, oi, ci, v.data.dtype)))
        elif op in ("min", "max"):
            rdt = _jnp_reduce_dtype(v.data.dtype)

            def wbuild(tk, d=v.data, vv=v.validity, rdt=rdt, op=op):
                return RBX.order_word(tk.take(d), tk.take(vv), rdt, op)

            wi = len(winners)
            winners.append(RBX.MinMaxSpec(
                wbuild, lambda tk, vv=v.validity: tk.take(vv), op))
            plan.append(("winner", (wi, v)))
        elif op in ("first", "last", "first_ignorenulls",
                    "last_ignorenulls"):
            if op.endswith("ignorenulls"):
                def cons(tk, vv=v.validity):
                    return tk.take(vv)
            else:
                def cons(tk):
                    return jnp.ones(tk.p_t.shape[0], jnp.bool_)
            pi = len(poss)
            poss.append(RBX.PosSpec(
                cons, "min" if op.startswith("first") else "max"))
            plan.append(("pos", (pi, v)))
        else:
            raise ValueError(f"unknown aggregation op {op!r}")

    out = RBX.tiled_segment_groupby(
        perm, radix, live_in, adds, poss, winners)
    nseg = out.nseg
    out_live = jnp.arange(cap, dtype=jnp.int32) < nseg

    def row_col(rw, v) -> ColV:
        safe = jnp.clip(rw, 0, cap - 1)
        vals = jnp.take(v.data, safe, mode="clip")
        vv = jnp.take(v.validity, safe, mode="clip") & (rw >= 0)
        return ColV(jnp.where(vv, vals, jnp.zeros((), vals.dtype)), vv)

    out_aggs: List[ColV] = []
    for kind, payload in plan:
        if kind == "cnt":
            out_aggs.append(ColV(out.u32[payload].astype(jnp.int64),
                                 jnp.ones(cap, jnp.bool_)))
        elif kind == "isum":
            si, ci, dt = payload
            data = out.u64[si].astype(jnp.int64)
            if dt != jnp.int64:
                data = data.astype(dt)  # mod-2^32 of a mod-2^64 sum: exact
            has = out.u32[ci] > 0
            out_aggs.append(ColV(jnp.where(has, data,
                                           jnp.zeros((), data.dtype)), has))
        elif kind == "fsum":
            fi, oi, ci, dt = payload
            s = RBX.combine_float_sum(out.f64[fi], out.f64[fi + 1],
                                      out.flags[oi]).astype(dt)
            has = out.u32[ci] > 0
            out_aggs.append(ColV(jnp.where(has, s, jnp.zeros((), dt)), has))
        elif kind == "pos":
            pi, v = payload
            out_aggs.append(row_col(out.pos_rows[pi], v))
        else:
            wi, v = payload
            out_aggs.append(row_col(out.winner_rows[wi], v))

    rep = jnp.clip(out.pos_rows[0], 0, cap - 1)
    out_keys = gather(key_cols, rep, out_live)
    out_aggs = [
        ColV(jnp.where(out_live, a.data, jnp.zeros((), a.data.dtype)),
             a.validity & out_live)
        for a in out_aggs
    ]
    return out_keys, out_aggs, nseg


def sort_groupby(
    key_cols: Sequence[Val],
    key_dtypes: Sequence[T.DataType],
    value_cols: Sequence[Optional[ColV]],
    agg_ops: Sequence[str],
    num_rows: Union[int, jax.Array],
    str_max_lens: Sequence[int] = (),
    prefix_reduce: bool = False,
    radix_reduce: bool = False,
) -> Tuple[List[Val], List[ColV], jax.Array]:
    """Full groupby via sort: sort by keys, segment, reduce.

    ``value_cols[i]`` is the (pre-cast) input for ``agg_ops[i]`` (None for
    count_star). Returns (group key columns, aggregate columns, num_groups);
    outputs are compacted to the front at the input capacity.
    ``prefix_reduce`` (the SORT aggregation strategy) reduces sums/counts
    via prefix differences over the contiguous segments instead of one
    segment scatter per aggregate (see :func:`_sorted_segment_aggs`).
    ``radix_reduce`` (the RADIX strategy) reduces EVERY aggregate family
    — float sums and min/max/first/last included — on the tiled
    radix-binned machinery with zero scatters (:func:`_radix_groupby`).
    """
    cap = (
        key_cols[0].offsets.shape[0] - 1
        if isinstance(key_cols[0], StrV)
        else key_cols[0].validity.shape[0]
    )
    from .filter_gather import live_of

    orders = [SortOrder(True, True) for _ in key_cols]
    perm, radix = sort_with_radix_keys(
        key_cols, key_dtypes, orders, num_rows, str_max_lens
    )
    live_in = live_of(num_rows, cap)
    if radix_reduce:
        return _radix_groupby(key_cols, value_cols, agg_ops, perm, radix,
                              live_in, cap)
    # dead rows sort last (pad_rank is the leading sort key), so liveness in
    # sorted order is the permuted mask — equivalently a prefix of n_live.
    # Using the RAW mask here mislabels rows whenever the mask isn't already
    # a prefix (e.g. after a fused filter) — a real dropped-row bug.
    live = jnp.take(live_in, perm, mode="clip")
    sorted_keys = gather(key_cols, perm, live)
    sorted_vals: List[Optional[ColV]] = []
    for v in value_cols:
        if v is None:
            sorted_vals.append(None)
        else:
            g = gather([v], perm, live)[0]
            assert isinstance(g, ColV)
            sorted_vals.append(g)
    seg, nseg = segment_ids_from_radix_keys(radix, live)

    # representative row (first) of each segment, for key output
    idx = jnp.arange(cap, dtype=jnp.int32)
    first_row = jax.ops.segment_min(
        jnp.where(live, idx, jnp.int32(cap)), seg, num_segments=cap
    )
    out_live = jnp.arange(cap, dtype=jnp.int32) < nseg
    first_row = jnp.clip(first_row, 0, cap - 1)
    out_keys = gather(sorted_keys, first_row, out_live)
    if prefix_reduce:
        out_aggs = _sorted_segment_aggs(agg_ops, sorted_vals, seg, cap, live)
    else:
        out_aggs = [
            segment_reduce(op, v, seg, cap, live)
            for op, v in zip(agg_ops, sorted_vals)
        ]
    # aggregate outputs: zero validity in dead slots
    out_aggs = [
        ColV(jnp.where(out_live, a.data, jnp.zeros((), a.data.dtype)),
             a.validity & out_live)
        for a in out_aggs
    ]
    return out_keys, out_aggs, nseg


def reduce_no_keys(
    value_cols: Sequence[Optional[ColV]],
    agg_ops: Sequence[str],
    num_rows: Union[int, jax.Array],
    str_val_max_lens: Sequence[int] = (),
) -> List[Val]:
    """Grand aggregate (no grouping keys): one output row.

    Reference analog: cudf reduce path in aggregate.scala:806.
    String min/max inputs reduce through their lexicographic ranks (see
    :func:`string_minmax_ranks`).
    """
    if not value_cols:
        return []
    value_cols = list(value_cols)
    recover = string_minmax_ranks(
        value_cols, agg_ops, num_rows, str_val_max_lens)
    cap = next(
        v.validity.shape[0] for v in value_cols if v is not None
    ) if any(v is not None for v in value_cols) else 0
    if cap == 0:
        # only count(*) over an implicit capacity — caller supplies rows
        if isinstance(num_rows, jax.Array) and num_rows.dtype == jnp.bool_:
            cnt = jnp.sum(num_rows.astype(jnp.int64)).reshape(1)
        else:
            cnt = jnp.asarray(num_rows, jnp.int64).reshape(1)
        return [ColV(cnt, jnp.ones(1, jnp.bool_)) for _ in agg_ops]
    from .filter_gather import live_of

    live = live_of(num_rows, cap)
    outs: List[Val] = []
    seg = None  # built lazily for the first/last path only
    for op, v in zip(agg_ops, value_cols):
        outs.append(_reduce_one(op, v, live))
        if outs[-1] is None:
            if seg is None:
                seg = jnp.where(live, 0, 1)
            outs[-1] = segment_reduce(op, v, seg, 1, live)
    for ai, rec in recover.items():
        outs[ai] = rec(outs[ai])
    return outs


def _reduce_one(op: str, col: Optional[ColV], live: jax.Array) -> Optional[ColV]:
    """Grand-aggregate reduction as a PLAIN masked jnp reduce.

    scatter-based segment_* to one segment costs ~60ns/row on TPU
    (emulated-int64 scatter adds); a tree reduce is HBM-bandwidth bound.
    Returns None for ops that still need the segment path (first/last)."""
    if op == "count_star":
        cnt = jnp.sum(live.astype(jnp.int64)).reshape(1)
        return ColV(cnt, jnp.ones(1, jnp.bool_))
    assert col is not None
    valid = col.validity & live
    data = col.data
    if op == "count":
        cnt = jnp.sum(valid.astype(jnp.int64)).reshape(1)
        return ColV(cnt, jnp.ones(1, jnp.bool_))
    has = jnp.any(valid).reshape(1)
    if op == "sum":
        z = jnp.zeros((), data.dtype)
        s = jnp.sum(jnp.where(valid, data, z)).reshape(1)
        return ColV(s, has)
    if op in ("min", "max"):
        if jnp.issubdtype(data.dtype, jnp.floating):
            if op == "max":
                fill = jnp.array(-jnp.inf, data.dtype)
                r = jnp.max(jnp.where(valid, data, fill)).reshape(1)
            else:
                nan_as_inf = jnp.where(jnp.isnan(data), jnp.inf, data)
                d = jnp.where(valid, nan_as_inf, jnp.inf).astype(data.dtype)
                r = jnp.min(d).reshape(1)
                non_nan = jnp.sum(
                    (valid & ~jnp.isnan(data)).astype(jnp.int32)).reshape(1)
                r = jnp.where((non_nan == 0) & has, jnp.nan, r)
        elif data.dtype == jnp.bool_:
            fill = jnp.array(op == "min", jnp.bool_)
            d = jnp.where(valid, data, fill)
            r = (jnp.max(d) if op == "max" else jnp.min(d)).reshape(1)
        else:
            lo, hi = _INT_MIN_MAX.get(jnp.dtype(data.dtype), (0, 1))
            fill = jnp.array(lo if op == "max" else hi, data.dtype)
            d = jnp.where(valid, data, fill)
            r = (jnp.max(d) if op == "max" else jnp.min(d)).reshape(1)
        z = jnp.zeros((), r.dtype)
        return ColV(jnp.where(has, r, z), has)
    return None


# ---------------------------------------------------------------------------
# Hash-bucket groupby (TPU fast path)
# ---------------------------------------------------------------------------
def hash_groupby(
    key_cols: Sequence[ColV],
    key_dtypes: Sequence[T.DataType],
    value_cols: Sequence[Optional[ColV]],
    agg_ops: Sequence[str],
    num_rows: Union[int, jax.Array],
    num_buckets: int,
    approx_float_sum: bool = False,
    reduce_strategy: Optional[str] = None,
) -> Tuple[List[ColV], List[ColV], jax.Array, jax.Array]:
    """O(n) groupby: bucket keys, reduce on the MXU.

    Bucketing tiers:
      1. direct-range: when every key's value range is dense enough that
         the composed (value - min) index fits ``num_buckets`` — the
         TPC-DS dim-key/date case — buckets are injective BY CONSTRUCTION:
         no hash, no collision check, and group keys are reconstructed
         algebraically from the bucket id (zero scatter ops).
      2. murmur3 + exact collision detection (limb-matmul lookups against
         each bucket's representative row); a collision makes
         :func:`groupby_agg` fall back to the sort path.

    Sums/counts run as one-hot limb matmuls (ops/bucket_reduce.py — exact
    for integers); min/max/first/last use scatter segment ops; float sums
    use one scatter op unless ``approx_float_sum`` (order-insensitive
    matmul, the reference's variableFloatAgg tradeoff).

    Returns (out_keys, out_aggs, num_groups, collision_free); outputs are
    bucket-compacted to the front at the input capacity.
    """
    from .bucket_reduce import bucket_equal_check, bucket_reduce
    from .filter_gather import live_of
    from .hashing import murmur3
    from .sort import SortOrder, fixed_radix_keys

    cap = key_cols[0].validity.shape[0]
    B = num_buckets
    live = live_of(num_rows, cap)
    idx = jnp.arange(cap, dtype=jnp.int32)
    any_live = jnp.any(live)

    # --- tier 1: direct-range binning -----------------------------------
    direct_capable = all(not dt.is_floating for dt in key_dtypes)
    mns, spans, strides = [], [], []
    if direct_capable:
        direct_ok = any_live
        stride = jnp.int64(1)
        bucket_direct = jnp.zeros(cap, jnp.int64)
        for c, dt in zip(key_cols, key_dtypes):
            d = c.data.astype(jnp.int64)
            lv = live & c.validity
            has_val = jnp.any(lv)
            mn = jnp.where(has_val, jnp.min(jnp.where(lv, d, jnp.int64(2**62))), 0)
            mx = jnp.where(has_val, jnp.max(jnp.where(lv, d, jnp.int64(-(2**62)))), -1)
            # exact range via u64 (no overflow even at int64 extremes)
            ru = mx.astype(jnp.uint64) - mn.astype(jnp.uint64)
            span = jnp.where(
                ru < jnp.uint64(B), ru.astype(jnp.int64) + 2, jnp.int64(B + 1))
            kidx = jnp.where(
                c.validity,
                (d.astype(jnp.uint64) - mn.astype(jnp.uint64)).astype(jnp.int64) + 1,
                0,
            )
            bucket_direct = bucket_direct + kidx * stride
            mns.append(mn)
            spans.append(span)
            strides.append(stride)
            stride = stride * span
            direct_ok = direct_ok & (stride <= jnp.int64(B))
        bucket_direct = jnp.clip(bucket_direct, 0, B - 1).astype(jnp.int32)
    else:
        direct_ok = jnp.bool_(False)
        bucket_direct = jnp.zeros(cap, jnp.int32)

    # --- tier 2: murmur3 buckets (computed only when tier 1 declines) ----
    def _hash_buckets(_):
        h = murmur3(list(key_cols), list(key_dtypes))
        return (h.astype(jnp.uint32) & jnp.uint32(B - 1)).astype(jnp.int32)

    if direct_capable:
        bucket = lax.cond(
            direct_ok, lambda _: bucket_direct, _hash_buckets, operand=None)
    else:
        bucket = _hash_buckets(None)
    seg = jnp.where(live, bucket, B)  # out-of-range ids drop out everywhere

    # --- reductions (all sums/counts of EVERY column in ONE matmul pass;
    # min/max batched into one scatter family per (op, dtype), their
    # nullability counts riding the same matmul) -------------------------
    int_specs, cnt_specs, flt_specs = [], [], []
    plan = []  # per agg: (path, payload)
    cnt_index: dict = {}
    mm_fam: dict = {}  # (op, dtype) -> [filled (n,) columns]

    def _want_count(valid_arr, key):
        if key not in cnt_index:
            cnt_index[key] = len(cnt_specs)
            cnt_specs.append(valid_arr)
        return cnt_index[key]

    live_count_i = _want_count(live, ("star",))  # also drives `occupied`
    for ai, (op, v) in enumerate(zip(agg_ops, value_cols)):
        if op == "count_star":
            plan.append(("count", live_count_i))
        elif op == "count":
            plan.append(("count", _want_count(v.validity & live, ("c", ai))))
        elif op == "sum" and not jnp.issubdtype(v.data.dtype, jnp.floating):
            ci = _want_count(v.validity & live, ("c", ai))
            int_specs.append((v.data, v.validity & live))
            plan.append(("isum", (len(int_specs) - 1, ci)))
        elif op == "sum" and (approx_float_sum
                              or reduce_strategy == "PALLAS"):
            # PALLAS forces the order-insensitive kernel path even for
            # exact float sums — a forced-strategy tradeoff the conf doc
            # names (the chooser's AUTO never picks it without the
            # variableFloatAgg opt-in)
            ci = _want_count(v.validity & live, ("c", ai))
            flt_specs.append((v.data, v.validity & live))
            plan.append(("fsum", (len(flt_specs) - 1, ci, v.data.dtype)))
        elif op == "sum":
            # exact float sum: one scatter op; nullability via matmul count
            ci = _want_count(v.validity & live, ("c", ai))
            plan.append(("fsum_exact", (v, ci)))
        elif op in ("min", "max"):
            # fill dead/invalid rows with the op's identity so they never
            # win, then batch all columns of one (op, dtype) family into a
            # single segment scatter (ops/bucket_reduce.bucket_min_max);
            # semantics mirror segment_reduce exactly, incl. Spark's
            # NaN-is-largest max and NaN-skipping min
            valid = v.validity & live
            data = v.data
            ci = _want_count(valid, ("c", ai))
            nn_ci = None
            if jnp.issubdtype(data.dtype, jnp.floating):
                if op == "max":
                    d = jnp.where(valid, data,
                                  jnp.array(-jnp.inf, data.dtype))
                else:
                    nn_ci = _want_count(valid & ~jnp.isnan(data), ("nn", ai))
                    nan_as_inf = jnp.where(jnp.isnan(data), jnp.inf, data)
                    d = jnp.where(valid, nan_as_inf,
                                  jnp.inf).astype(data.dtype)
            elif data.dtype == jnp.bool_:
                d = jnp.where(valid, data, jnp.array(op == "min", jnp.bool_))
            else:
                lo, hi = _INT_MIN_MAX.get(jnp.dtype(data.dtype), (0, 1))
                d = jnp.where(valid, data,
                              jnp.array(lo if op == "max" else hi,
                                        data.dtype))
            fam = mm_fam.setdefault((op, jnp.dtype(d.dtype)), [])
            plan.append(("minmax", (op, jnp.dtype(d.dtype), len(fam),
                                    ci, nn_ci)))
            fam.append(d)
        elif reduce_strategy == "PALLAS":
            plan.append(("pallas_pos", (op, v)))  # first/last, kernel
        else:
            plan.append(("scatter", (op, v)))  # first/last

    from .bucket_reduce import bucket_min_max

    isums, counts, fsums = bucket_reduce(
        seg, B, int_specs, cnt_specs, flt_specs,
        strategy=reduce_strategy)
    mm_results = {
        k: bucket_min_max(seg, B, k[0], cols_, strategy=reduce_strategy)
        for k, cols_ in mm_fam.items()
    }
    occupied = counts[live_count_i] > 0
    ngroups = jnp.sum(occupied.astype(jnp.int32)).astype(jnp.int32)

    # --- group keys + collision status (branch on tier) -----------------
    bucket_ids = jnp.arange(B, dtype=jnp.int64)

    def _direct_branch(_):
        keys_out = []
        for (c, dt), mn, span, stride in zip(
            zip(key_cols, key_dtypes), mns, spans, strides
        ):
            kidx = (bucket_ids // stride) % span  # 0 = null slot
            val = (mn + kidx - 1).astype(c.data.dtype)
            valid = (kidx > 0) & occupied
            keys_out.append((jnp.where(valid, val, jnp.zeros((), val.dtype)), valid))
        return tuple(keys_out), jnp.bool_(True)

    def _hash_branch(_):
        if reduce_strategy == "PALLAS":
            from .pallas_groupby import pallas_bucket_position

            rep0, _found = pallas_bucket_position(seg, B, "min", live)
            rep_row = jnp.clip(rep0, 0, cap - 1)
        else:
            first_row = jax.ops.segment_min(
                jnp.where(live, idx, jnp.int32(cap)), seg, num_segments=B)
            rep_row = jnp.clip(first_row, 0, cap - 1)
        order = SortOrder(True, True)
        words: List[jax.Array] = []
        # one nullpack word per 16 keys: 2-bit null ranks must not alias
        nullpacks = [
            jnp.zeros(cap, jnp.uint32)
            for _ in range((len(key_cols) + 15) // 16)
        ]
        for i, (c, dt) in enumerate(zip(key_cols, key_dtypes)):
            null_rank, vk = fixed_radix_keys(c, dt, order)
            nullpacks[i // 16] = nullpacks[i // 16] | (null_rank << (2 * (i % 16)))
            if vk.dtype == jnp.uint64:
                words.append((vk & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
                words.append((vk >> 32).astype(jnp.uint32))
            else:
                words.append(vk.astype(jnp.uint32))
        words.extend(nullpacks)
        ok = jnp.bool_(True)
        for w in words:
            rep_table = jnp.where(
                occupied, jnp.take(w, rep_row, mode="clip"), jnp.uint32(0))
            ok = ok & bucket_equal_check(seg, B, w, rep_table, live)
        keys_out = []
        for c in key_cols:
            kd = jnp.take(c.data, rep_row, mode="clip")
            kv = jnp.take(c.validity, rep_row, mode="clip") & occupied
            keys_out.append((jnp.where(kv, kd, jnp.zeros((), kd.dtype)), kv))
        return tuple(keys_out), ok

    if direct_capable:
        key_tables, collision_free = lax.cond(
            direct_ok, _direct_branch, _hash_branch, operand=None)
    else:
        key_tables, collision_free = _hash_branch(None)

    # --- bucket-compaction: present buckets to the front ----------------
    # All slot work happens at size B (tiny); outputs pad up to the input
    # capacity with plain copies — gathers at cap-size would cost ~100x.
    csum = jnp.cumsum(occupied.astype(jnp.int32))
    if reduce_strategy == "PALLAS":
        # identical slot mapping via one (tiny) B-sized sort, so the
        # PALLAS program carries zero scatter instructions end to end
        _, bucket_of_slot = lax.sort(
            [(~occupied).astype(jnp.uint32),
             jnp.arange(B, dtype=jnp.int32)],
            num_keys=1, is_stable=True)
    else:
        dest = jnp.where(occupied, csum - 1, B)
        bucket_of_slot = (
            jnp.zeros(B, jnp.int32).at[dest].set(
                jnp.arange(B, dtype=jnp.int32), mode="drop")
        )
    slot_live = jnp.arange(B, dtype=jnp.int32) < ngroups
    pad = cap - B

    def to_slots(arr, valid):
        d = jnp.take(arr, bucket_of_slot, mode="clip")
        vv = jnp.take(valid, bucket_of_slot, mode="clip") & slot_live
        d = jnp.where(vv, d, jnp.zeros((), d.dtype))
        if pad > 0:
            d = jnp.concatenate([d, jnp.zeros(pad, d.dtype)])
            vv = jnp.concatenate([vv, jnp.zeros(pad, jnp.bool_)])
        return ColV(d, vv)

    out_keys: List[ColV] = [to_slots(kd, kv) for kd, kv in key_tables]

    out_aggs: List[ColV] = []
    for (kind, payload), (op, v) in zip(plan, zip(agg_ops, value_cols)):
        if kind == "count":
            out_aggs.append(to_slots(counts[payload], jnp.ones(B, jnp.bool_)))
        elif kind == "isum":
            si, ci = payload
            data = isums[si]
            if v.data.dtype != jnp.int64:
                data = data.astype(v.data.dtype)
            out_aggs.append(to_slots(data, counts[ci] > 0))
        elif kind == "fsum":
            si, ci, dt = payload
            out_aggs.append(to_slots(fsums[si].astype(dt), counts[ci] > 0))
        elif kind == "fsum_exact":
            sv, ci = payload
            z = jnp.zeros((), sv.data.dtype)
            sm = jax.ops.segment_sum(
                jnp.where(sv.validity & live, sv.data, z), seg, num_segments=B)
            out_aggs.append(to_slots(sm, counts[ci] > 0))
        elif kind == "minmax":
            mop, mdt, fi, ci, nn_ci = payload
            r = mm_results[(mop, mdt)][fi]
            has = counts[ci] > 0
            if nn_ci is not None:
                # all-NaN group: min skips NaN unless nothing else exists
                r = jnp.where((counts[nn_ci] == 0) & has, jnp.nan, r)
            r = jnp.where(has, r, jnp.zeros((), r.dtype))
            out_aggs.append(to_slots(r, has))
        elif kind == "pallas_pos":
            sop, sv = payload
            from .pallas_groupby import pallas_bucket_position

            consider = (sv.validity & live
                        if sop.endswith("ignorenulls") else live)
            wop = "min" if sop.startswith("first") else "max"
            row, found = pallas_bucket_position(seg, B, wop, consider)
            safe = jnp.clip(row, 0, cap - 1)
            vals = jnp.take(sv.data, safe, mode="clip")
            vv = jnp.take(sv.validity, safe, mode="clip") & found
            out_aggs.append(to_slots(
                jnp.where(vv, vals, jnp.zeros((), vals.dtype)), vv))
        else:
            sop, sv = payload
            r = segment_reduce(sop, sv, seg, B, live)
            out_aggs.append(to_slots(r.data, r.validity))
    return out_keys, out_aggs, ngroups, collision_free


def groupby_agg(
    key_cols: Sequence[Val],
    key_dtypes: Sequence[T.DataType],
    value_cols: Sequence[Optional[ColV]],
    agg_ops: Sequence[str],
    num_rows: Union[int, jax.Array],
    str_max_lens: Sequence[int] = (),
    approx_float_sum: bool = False,
    num_buckets: int = 8192,
    str_val_max_lens: Sequence[int] = (),
    strategy: Optional[str] = None,
) -> Tuple[List[Val], List[Val], jax.Array]:
    """Adaptive groupby: MXU hash-bucket fast path with a traced sort
    fallback.

    Reference analog: cudf's hash groupby with sort-groupby fallback for
    unsupported cases (aggregate.scala:806). Here the choice is a runtime
    ``lax.cond`` on the collision-free check, so low-cardinality aggregates
    (the TPC-DS common case) never pay the bitonic sort.

    ``strategy`` is the plan-level aggregation lowering chosen by the
    exec's strategy chooser (conf spark.rapids.tpu.sql.agg.strategy):
    MATMUL/SCATTER force the hash-bucket tiers' reduction lowering
    (ops/bucket_reduce.py), SORT skips the hash tiers entirely and
    radix-sorts by the grouping keys, reducing each contiguous segment
    via prefix differences — the HBM-bandwidth-sized path. None keeps
    the backend default (identical to round 6).
    Plain string keys always take the sort path; DICT-ENCODED string keys
    whose dictionary is unique group directly on their int32 codes (no
    byte-wise hashing or chunk-key sort at all — the cudf-dictionary32
    trick) and rewrap the output codes, so the group keys stay encoded.
    Non-unique dictionaries (post-transform, where distinct codes may
    hold equal strings) materialize and sort like plain strings.
    """
    key_cols = list(key_cols)
    key_dtypes = list(key_dtypes)
    value_cols = list(value_cols)
    # string min/max reduce over lexicographic RANK columns; winners map
    # back to strings after the (tiered) reduction picked its path
    recover = string_minmax_ranks(
        value_cols, agg_ops, num_rows, str_val_max_lens)
    code_keys = {}  # key index -> DictV template to rewrap from codes
    eff_sml: List[int] = []
    si = 0
    for i, c in enumerate(key_cols):
        if isinstance(c, DictV):
            if si < len(str_max_lens):
                si += 1  # consume this string key's slot either way
            if c.unique:
                key_cols[i] = ColV(c.codes.astype(jnp.int32), c.validity)
                key_dtypes[i] = T.INT
                code_keys[i] = c
            else:
                from ..columnar.column import choose_capacity

                key_cols[i] = materialize_dict(c)
                eff_sml.append(max(4, choose_capacity(max(1, c.max_len), 4)))
        elif isinstance(c, StrV):
            eff_sml.append(str_max_lens[si] if si < len(str_max_lens) else 64)
            si += 1
    str_max_lens = tuple(eff_sml)

    def _rewrap(keys, aggs, n):
        if code_keys:
            from ..columnar.column import choose_capacity

            keys = list(keys)
            for i, t in code_keys.items():
                k = keys[i]
                keys[i] = DictV(
                    k.data, t.dictionary, k.validity,
                    choose_capacity(
                        max(1, int(t.dictionary.chars.shape[0])), 128),
                    t.max_len, True)
        if recover:
            aggs = list(aggs)
            for ai, rec in recover.items():
                aggs[ai] = rec(aggs[ai])
        return keys, aggs, n

    prefix = strategy == "SORT"
    # PALLAS hash tiers cover fixed-width keys; its string/keyless
    # fallback rides the RADIX tiled path so the plan stays scatter-free
    radix = strategy == "RADIX" or strategy == "PALLAS"
    if not key_cols:
        return _rewrap(*sort_groupby(
            key_cols, key_dtypes, value_cols, agg_ops, num_rows,
            str_max_lens, prefix_reduce=prefix, radix_reduce=radix))
    if strategy == "RADIX" or prefix or any(
            isinstance(c, StrV) for c in key_cols):
        return _rewrap(*sort_groupby(
            key_cols, key_dtypes, value_cols, agg_ops, num_rows,
            str_max_lens, prefix_reduce=prefix, radix_reduce=radix))
    cap = key_cols[0].validity.shape[0]

    def pow2_floor(x: int) -> int:
        return 1 << (x.bit_length() - 1) if x & (x - 1) else x

    B2 = pow2_floor(min(cap, num_buckets))
    # the one-hot matmul reduction is K-bound on the MXU at ceil(B/128)
    # output tiles x cap contraction cycles: B=128 costs 1/8th of B=1024.
    # Run narrow tiers first (TPC-DS group-bys are usually <100 groups)
    # and escalate to wider tiers — then the bitonic sort — only when the
    # keys don't fit. lax.cond executes just the taken branch, so the
    # common case never pays the wide tiers.
    B1 = min(1024, B2)
    B0 = min(128, B1)

    def pack(keys, aggs, n):
        return (
            tuple((c.data, c.validity) for c in keys),
            tuple((c.data, c.validity) for c in aggs),
            n,
        )

    def use_sort(_):
        return pack(*sort_groupby(
            key_cols, key_dtypes, value_cols, agg_ops, num_rows,
            str_max_lens, radix_reduce=radix))

    def tier(B, below):
        def run(_):
            hk, ha, hn, ok = hash_groupby(
                list(key_cols), key_dtypes, value_cols, agg_ops, num_rows,
                B, approx_float_sum=approx_float_sum,
                reduce_strategy=strategy)

            def use_hash(_):
                return pack(hk, ha, hn)

            return lax.cond(ok, use_hash, below, operand=None)

        return run

    chain = use_sort
    if B2 > B1:
        chain = tier(B2, chain)
    if B1 > B0:
        chain = tier(B1, chain)
    keys_t, aggs_t, n = tier(B0, chain)(None)
    out_keys = [ColV(d, v) for d, v in keys_t]
    out_aggs = [ColV(d, v) for d, v in aggs_t]
    return _rewrap(out_keys, out_aggs, n)
