"""Sort-based group-by aggregation kernels.

Reference analog: cudf ``table.groupBy(...).aggregate(...)`` as called from
GpuHashAggregateExec (aggregate.scala:806). cudf hash-aggregates; on TPU a
hash table of dynamic size fights XLA, so the design is the classic
sort-compatible alternative the same exec supports: stable-sort rows by the
grouping keys (ops/sort.py), derive segment ids from key-change boundaries,
and reduce each segment with ``jax.ops.segment_*`` — one fused XLA program,
fully static shapes (worst case: every row its own group, so num_segments =
capacity). Null keys form their own group (Spark semantics); aggregate
inputs skip nulls; NaN groups as equal to NaN.

Reductions provided: count_star, count, sum, min, max, first/last (+
ignore-null variants). Average is decomposed by the exec layer into
sum+count partials, mirroring Spark's update/merge model.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from .. import types as T
from ..expr.eval import ColV, StrV, Val
from .filter_gather import gather
from .sort import SortOrder, sort_with_radix_keys


def segment_ids_from_radix_keys(
    sorted_radix_keys: Sequence[jax.Array],
    num_rows: Union[int, jax.Array],
) -> Tuple[jax.Array, jax.Array]:
    """(segment_ids, num_segments) from the co-sorted radix key arrays.

    Two adjacent rows belong to the same group iff every radix key matches
    — the radix encoding already folds Spark's equality rules in
    (null==null via the null-rank key, NaN canonicalized, -0.0 -> 0.0,
    strings as byte chunks). Padding rows get an out-of-range id so every
    segment_* scatter drops them.
    """
    cap = sorted_radix_keys[0].shape[0]
    eq = jnp.ones(cap, jnp.bool_)
    for k in sorted_radix_keys:
        eq = eq & (k == jnp.roll(k, 1))
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    new_seg = live & (~eq | (jnp.arange(cap) == 0))
    seg = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
    num_segments = jnp.max(jnp.where(live, seg, -1)) + 1
    seg = jnp.where(live, seg, cap)  # out-of-range for padding
    return seg, num_segments


_INT_MIN_MAX = {
    jnp.dtype(jnp.int8): (-(2**7), 2**7 - 1),
    jnp.dtype(jnp.int16): (-(2**15), 2**15 - 1),
    jnp.dtype(jnp.int32): (-(2**31), 2**31 - 1),
    jnp.dtype(jnp.int64): (-(2**63), 2**63 - 1),
}


def _segment_count(valid: jax.Array, seg: jax.Array, ncap: int) -> jax.Array:
    return jax.ops.segment_sum(valid.astype(jnp.int64), seg, num_segments=ncap)


def segment_reduce(
    op: str,
    col: Optional[ColV],
    seg: jax.Array,
    ncap: int,
    live: jax.Array,
) -> ColV:
    """One aggregation over segments. Returns (ncap,)-shaped ColV."""
    if op == "count_star":
        cnt = jax.ops.segment_sum(live.astype(jnp.int64), seg, num_segments=ncap)
        return ColV(cnt, jnp.ones(ncap, jnp.bool_))
    assert col is not None
    valid = col.validity & live
    data = col.data
    if op == "count":
        cnt = _segment_count(valid, seg, ncap)
        return ColV(cnt, jnp.ones(ncap, jnp.bool_))
    cnt = _segment_count(valid, seg, ncap)
    has = cnt > 0
    if op == "sum":
        z = jnp.zeros((), data.dtype)
        s = jax.ops.segment_sum(jnp.where(valid, data, z), seg, num_segments=ncap)
        return ColV(s, has)
    if op in ("min", "max"):
        isfloat = jnp.issubdtype(data.dtype, jnp.floating)
        if isfloat:
            if op == "max":
                # Spark: NaN is the largest double; IEEE max propagates NaN,
                # which is exactly the desired result, so plain masking works
                fill = jnp.array(-jnp.inf, data.dtype)
                d = jnp.where(valid, data, fill)
                r = jax.ops.segment_max(d, seg, num_segments=ncap)
            else:
                # min must *skip* NaN unless the group is all-NaN
                nan_as_inf = jnp.where(jnp.isnan(data), jnp.inf, data)
                d = jnp.where(valid, nan_as_inf, jnp.inf).astype(data.dtype)
                r = jax.ops.segment_min(d, seg, num_segments=ncap)
                non_nan = _segment_count(valid & ~jnp.isnan(data), seg, ncap)
                r = jnp.where((non_nan == 0) & has, jnp.nan, r)
        else:
            lo, hi = _INT_MIN_MAX.get(
                jnp.dtype(data.dtype), (0, 1)
            )
            if data.dtype == jnp.bool_:
                fill = jnp.array(op == "min", jnp.bool_)
                d = jnp.where(valid, data, fill)
                r = (
                    jax.ops.segment_max(d, seg, num_segments=ncap)
                    if op == "max"
                    else jax.ops.segment_min(d, seg, num_segments=ncap)
                )
            else:
                fill = jnp.array(lo if op == "max" else hi, data.dtype)
                d = jnp.where(valid, data, fill)
                r = (
                    jax.ops.segment_max(d, seg, num_segments=ncap)
                    if op == "max"
                    else jax.ops.segment_min(d, seg, num_segments=ncap)
                )
        z = jnp.zeros((), r.dtype)
        return ColV(jnp.where(has, r, z), has)
    if op in ("first", "last", "first_ignorenulls", "last_ignorenulls"):
        cap = data.shape[0]
        idx = jnp.arange(cap, dtype=jnp.int32)
        consider = valid if op.endswith("ignorenulls") else live
        big = jnp.int32(cap)
        if op.startswith("first"):
            pos = jax.ops.segment_min(
                jnp.where(consider, idx, big), seg, num_segments=ncap
            )
        else:
            pos = jax.ops.segment_max(
                jnp.where(consider, idx, jnp.int32(-1)), seg, num_segments=ncap
            )
        found = (pos >= 0) & (pos < cap)
        safe = jnp.clip(pos, 0, cap - 1)
        vals = jnp.take(data, safe, mode="clip")
        val_valid = jnp.take(col.validity, safe, mode="clip") & found
        z = jnp.zeros((), vals.dtype)
        return ColV(jnp.where(val_valid, vals, z), val_valid)
    raise ValueError(f"unknown aggregation op {op!r}")


def sort_groupby(
    key_cols: Sequence[Val],
    key_dtypes: Sequence[T.DataType],
    value_cols: Sequence[Optional[ColV]],
    agg_ops: Sequence[str],
    num_rows: Union[int, jax.Array],
    str_max_lens: Sequence[int] = (),
) -> Tuple[List[Val], List[ColV], jax.Array]:
    """Full groupby: sort by keys, segment, reduce.

    ``value_cols[i]`` is the (pre-cast) input for ``agg_ops[i]`` (None for
    count_star). Returns (group key columns, aggregate columns, num_groups);
    outputs are compacted to the front at the input capacity.
    """
    cap = (
        key_cols[0].offsets.shape[0] - 1
        if isinstance(key_cols[0], StrV)
        else key_cols[0].validity.shape[0]
    )
    orders = [SortOrder(True, True) for _ in key_cols]
    perm, radix = sort_with_radix_keys(
        key_cols, key_dtypes, orders, num_rows, str_max_lens
    )
    live_in = jnp.arange(cap, dtype=jnp.int32) < num_rows
    live = jnp.take(live_in, perm, mode="clip")
    sorted_keys = gather(key_cols, perm, live)
    sorted_vals: List[Optional[ColV]] = []
    for v in value_cols:
        if v is None:
            sorted_vals.append(None)
        else:
            g = gather([v], perm, live)[0]
            assert isinstance(g, ColV)
            sorted_vals.append(g)
    seg, nseg = segment_ids_from_radix_keys(radix, num_rows)

    # representative row (first) of each segment, for key output
    idx = jnp.arange(cap, dtype=jnp.int32)
    first_row = jax.ops.segment_min(
        jnp.where(live, idx, jnp.int32(cap)), seg, num_segments=cap
    )
    out_live = jnp.arange(cap, dtype=jnp.int32) < nseg
    first_row = jnp.clip(first_row, 0, cap - 1)
    out_keys = gather(sorted_keys, first_row, out_live)
    out_aggs = [
        segment_reduce(op, v, seg, cap, live)
        for op, v in zip(agg_ops, sorted_vals)
    ]
    # aggregate outputs: zero validity in dead slots
    out_aggs = [
        ColV(jnp.where(out_live, a.data, jnp.zeros((), a.data.dtype)),
             a.validity & out_live)
        for a in out_aggs
    ]
    return out_keys, out_aggs, nseg


def reduce_no_keys(
    value_cols: Sequence[Optional[ColV]],
    agg_ops: Sequence[str],
    num_rows: Union[int, jax.Array],
) -> List[ColV]:
    """Grand aggregate (no grouping keys): one output row.

    Reference analog: cudf reduce path in aggregate.scala:806.
    """
    if not value_cols:
        return []
    cap = next(
        v.validity.shape[0] for v in value_cols if v is not None
    ) if any(v is not None for v in value_cols) else 0
    if cap == 0:
        # only count(*) over an implicit capacity — caller supplies rows
        cnt = jnp.asarray(num_rows, jnp.int64).reshape(1)
        return [ColV(cnt, jnp.ones(1, jnp.bool_)) for _ in agg_ops]
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    seg = jnp.where(live, 0, 1)
    outs = []
    for op, v in zip(agg_ops, value_cols):
        r = segment_reduce(op, v, seg, 1, live)
        outs.append(r)
    return outs
