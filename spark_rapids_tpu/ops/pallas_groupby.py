"""Hand-written Pallas TPU kernels for the hash-groupby update — the
PALLAS aggregation lowering.

Where the cost plane proves XLA fusion won't cooperate (the one-hot
expansion materializing ~25x the logical working set, BENCH_r09 +
hlo.py), these kernels pin the working set explicitly: each grid step
holds one (rows-block x buckets-block) one-hot mask in VMEM, reduces it
there, and accumulates into a buckets-resident output block — the mask
NEVER exists in HBM, so bytes-accessed is the input stream plus the
(tiny) bucket table. The reference's cuDF hash-groupby kernels own
their shared-memory working set the same way; this is that design
retargeted at the TPU memory hierarchy.

Kernels (all dtypes TPU-valid: u32/i32/f32 only — 64-bit values travel
as u32 half/limb planes built outside the kernel):

  * sums/counts: int64 columns split into 16 4-bit limbs (per-block
    one-hot dot is exact in f32 at <= 2^15 per limb; the cross-block
    int32 accumulator stays exact to capacity 2^27 rows), counts as a
    ones limb — reconstruction outside wraps mod 2^64, BIT-identical to
    every other lowering including Java wraparound;
  * float sums: f32 hi/lo split per column, per-block one-hot dots with
    a Kahan-compensated f32 cross-block accumulator (order-insensitive,
    the variableFloatAgg family); |x| beyond f32 range detours through
    the same rare correction the matmul lowering uses;
  * min/max + first/last + representative row: per-bucket lexicographic
    winner over (hi, lo) u32 total-order word planes (the sort
    machinery's radix encoding, so Spark NaN-largest / -0.0 == 0.0 fall
    out), masked VMEM reductions per block, pair-compare across blocks.

``interpret=True`` off-TPU executes the very same kernels under the
Pallas interpreter — the CPU-CI execution path the differential suite
runs (tests/test_radix_agg.py).
"""
from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

#: rows per grid step (the VMEM-resident one-hot's row extent). Kept
#: modest so the interpreter path stays fast in CI.
BLOCK_R = 256
#: buckets per grid step (the one-hot's column extent); B > BLOCK_B
#: tiles the bucket axis through the grid's outer dimension.
BLOCK_B = 256

_U32_MAX = 0xFFFFFFFF


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_rows(arrs: Sequence[jax.Array], n: int, r: int, fill):
    pad = (-n) % r
    if pad == 0:
        return list(arrs)
    return [jnp.concatenate([a, jnp.full((pad,) + a.shape[1:], f, a.dtype)])
            for a, f in zip(arrs, fill)]


def _grid_dims(n: int, B: int) -> Tuple[int, int, int, int]:
    r = min(BLOCK_R, max(8, n))
    bb = min(BLOCK_B, B)
    nbr = -(-max(1, n) // r)
    nbb = -(-B // bb)
    return r, bb, nbr, nbb


# ---------------------------------------------------------------------------
# sums / counts: 4-bit limb accumulation
# ---------------------------------------------------------------------------
def _sum_kernel(seg_ref, limb_ref, out_ref, *, bb):
    from jax.experimental import pallas as pl

    bi = pl.program_id(0)

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    seg = seg_ref[...]
    cols = bi * bb + jax.lax.broadcasted_iota(jnp.int32, (1, bb), 1)
    oh = (seg[:, None] == cols).astype(jnp.float32)  # (r, bb) in VMEM only
    partial = jax.lax.dot_general(
        oh, limb_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # (bb, L)
    out_ref[...] += partial.astype(jnp.int32)


def _limb_plane(seg: jax.Array, limbs: jax.Array, B: int) -> jax.Array:
    """(B, L) int32 per-bucket limb sums via the Pallas sum kernel."""
    from jax.experimental import pallas as pl

    n, L = limbs.shape
    r, bb, nbr, nbb = _grid_dims(n, B)
    seg_p, limbs_p = _pad_rows([seg, limbs], n, r, [B, 0.0])
    out = pl.pallas_call(
        functools.partial(_sum_kernel, bb=bb),
        out_shape=jax.ShapeDtypeStruct((nbb * bb, L), jnp.int32),
        grid=(nbb, nbr),
        in_specs=[
            pl.BlockSpec((r,), lambda bi, ri: (ri,)),
            pl.BlockSpec((r, L), lambda bi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((bb, L), lambda bi, ri: (bi, 0)),
        interpret=_interpret(),
    )(seg_p, limbs_p)
    return out[:B]


def _float_kernel(seg_ref, fl_ref, sum_ref, comp_ref, *, bb):
    from jax.experimental import pallas as pl

    bi = pl.program_id(0)

    @pl.when(pl.program_id(1) == 0)
    def _():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        comp_ref[...] = jnp.zeros_like(comp_ref)

    seg = seg_ref[...]
    cols = bi * bb + jax.lax.broadcasted_iota(jnp.int32, (1, bb), 1)
    oh = (seg[:, None] == cols).astype(jnp.float32)
    partial = jax.lax.dot_general(
        oh, fl_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # Kahan-compensated f32 accumulation across row blocks
    s = sum_ref[...]
    y = partial - comp_ref[...]
    t = s + y
    comp_ref[...] = (t - s) - y
    sum_ref[...] = t


def _float_plane(seg: jax.Array, fl: jax.Array, B: int
                 ) -> Tuple[jax.Array, jax.Array]:
    from jax.experimental import pallas as pl

    n, L = fl.shape
    r, bb, nbr, nbb = _grid_dims(n, B)
    seg_p, fl_p = _pad_rows([seg, fl], n, r, [B, 0.0])
    s, c = pl.pallas_call(
        functools.partial(_float_kernel, bb=bb),
        out_shape=(jax.ShapeDtypeStruct((nbb * bb, L), jnp.float32),
                   jax.ShapeDtypeStruct((nbb * bb, L), jnp.float32)),
        grid=(nbb, nbr),
        in_specs=[
            pl.BlockSpec((r,), lambda bi, ri: (ri,)),
            pl.BlockSpec((r, L), lambda bi, ri: (ri, 0)),
        ],
        out_specs=(pl.BlockSpec((bb, L), lambda bi, ri: (bi, 0)),
                   pl.BlockSpec((bb, L), lambda bi, ri: (bi, 0))),
        interpret=_interpret(),
    )(seg_p, fl_p)
    return s[:B], c[:B]


def pallas_bucket_reduce(
    seg: jax.Array,
    B: int,
    int_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
    count_cols: Sequence[jax.Array] = (),
    float_cols: Sequence[Tuple[jax.Array, jax.Array]] = (),
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """PALLAS lowering of :func:`bucket_reduce`: same contract, same
    bit-exact integer sums/counts (4-bit limbs keep every accumulator
    within exact i32/f32 range to capacity 2^27 rows)."""
    n = seg.shape[0]
    assert n < (1 << 27), "pallas limb accumulators sized for cap < 2^27"
    seg = seg.astype(jnp.int32)
    limbs: List[jax.Array] = []
    for data, valid in int_cols:
        halves = jax.lax.bitcast_convert_type(
            data.astype(jnp.int64), jnp.uint32)  # (n, 2) little-endian
        for half in (halves[..., 0], halves[..., 1]):
            h = jnp.where(valid, half, jnp.uint32(0))
            for i in range(8):
                limbs.append(
                    ((h >> (4 * i)) & jnp.uint32(0xF)).astype(jnp.float32))
    for valid in count_cols:
        limbs.append(valid.astype(jnp.float32))
    out_int: List[jax.Array] = []
    out_cnt: List[jax.Array] = []
    if limbs:
        acc = _limb_plane(seg, jnp.stack(limbs, axis=-1), B)
        k = 0
        for _ in int_cols:
            total = jnp.zeros(B, jnp.uint64)
            for half in range(2):
                for i in range(8):
                    total = total + (acc[:, k].astype(jnp.uint64)
                                     << (32 * half + 4 * i))
                    k += 1
            out_int.append(total.astype(jnp.int64))
        for _ in count_cols:
            out_cnt.append(acc[:, k].astype(jnp.int64))
            k += 1
    out_flt: List[jax.Array] = []
    if float_cols:
        F32_MAX = jnp.float64(3.4028234663852886e38)
        planes: List[jax.Array] = []
        corrections: List[Tuple[jax.Array, jax.Array]] = []
        for data, valid in float_cols:
            d = jnp.where(valid, data, 0.0).astype(jnp.float64)
            # NaN must take the detour too (abs(NaN) > x is False): a
            # NaN left in the matmul stream poisons EVERY bucket through
            # the one-hot dot, not just its own
            ovf = ~(jnp.abs(d) <= F32_MAX)
            d_main = jnp.where(ovf, 0.0, d)
            hi = d_main.astype(jnp.float32)
            lo = (d_main - hi.astype(jnp.float64)).astype(jnp.float32)
            planes.extend([hi, lo])
            corrections.append((jnp.any(ovf), jnp.where(ovf, d, 0.0)))
        s, c = _float_plane(seg, jnp.stack(planes, axis=-1), B)
        for i, (any_ovf, d_ovf) in enumerate(corrections):
            # residual Kahan compensation folds in at f64 width; the
            # rare beyond-f32-range rows take the same cond'd scatter
            # correction as the matmul lowering
            total = (s[:, 2 * i].astype(jnp.float64)
                     - c[:, 2 * i].astype(jnp.float64)
                     + s[:, 2 * i + 1].astype(jnp.float64)
                     - c[:, 2 * i + 1].astype(jnp.float64))
            corr = jax.lax.cond(
                any_ovf,
                lambda d=d_ovf: jax.ops.segment_sum(d, seg, num_segments=B),
                lambda: jnp.zeros(B, jnp.float64),
            )
            out_flt.append(total + corr)
    return out_int, out_cnt, out_flt


# ---------------------------------------------------------------------------
# lexicographic winner over (hi, lo) u32 word planes: min/max, first/last,
# representative row
# ---------------------------------------------------------------------------
def _winner_kernel(seg_ref, hi_ref, lo_ref, whi_ref, wlo_ref, *, bb,
                   is_min):
    from jax.experimental import pallas as pl

    bi = pl.program_id(0)
    ident = jnp.uint32(_U32_MAX if is_min else 0)

    @pl.when(pl.program_id(1) == 0)
    def _():
        whi_ref[...] = jnp.full_like(whi_ref, ident)
        wlo_ref[...] = jnp.full_like(wlo_ref, ident)

    seg = seg_ref[...]
    cols = bi * bb + jax.lax.broadcasted_iota(jnp.int32, (1, bb), 1)
    mask = seg[:, None] == cols  # (r, bb) in VMEM only
    hi = hi_ref[...][:, None]
    lo = lo_ref[...][:, None]
    red = jnp.min if is_min else jnp.max
    cand_hi = red(jnp.where(mask, hi, ident), axis=0)
    tie = mask & (hi == cand_hi[None, :])
    cand_lo = red(jnp.where(tie, lo, ident), axis=0)
    ahi, alo = whi_ref[...], wlo_ref[...]
    if is_min:
        take = (cand_hi < ahi) | ((cand_hi == ahi) & (cand_lo < alo))
    else:
        take = (cand_hi > ahi) | ((cand_hi == ahi) & (cand_lo > alo))
    whi_ref[...] = jnp.where(take, cand_hi, ahi)
    wlo_ref[...] = jnp.where(take, cand_lo, alo)


def pallas_bucket_winner(
    seg: jax.Array, B: int, op: str, hi: jax.Array,
    lo: jax.Array = None,
) -> Tuple[jax.Array, jax.Array]:
    """(winner_hi, winner_lo) u32 per bucket: the lexicographic ``op``
    ('min'/'max') of the (hi, lo) word pair over each bucket's rows.
    Rows excluded from the reduction must carry the op identity
    (u32 max for min, 0 for max) in BOTH planes. Empty buckets return
    the identity; callers mask via their count/found checks."""
    from jax.experimental import pallas as pl

    n = seg.shape[0]
    seg = seg.astype(jnp.int32)
    if lo is None:
        lo = jnp.zeros(n, jnp.uint32)
    r, bb, nbr, nbb = _grid_dims(n, B)
    ident = _U32_MAX if op == "min" else 0
    seg_p, hi_p, lo_p = _pad_rows([seg, hi, lo], n, r, [B, ident, ident])
    whi, wlo = pl.pallas_call(
        functools.partial(_winner_kernel, bb=bb, is_min=op == "min"),
        out_shape=(jax.ShapeDtypeStruct((nbb * bb,), jnp.uint32),
                   jax.ShapeDtypeStruct((nbb * bb,), jnp.uint32)),
        grid=(nbb, nbr),
        in_specs=[
            pl.BlockSpec((r,), lambda bi, ri: (ri,)),
            pl.BlockSpec((r,), lambda bi, ri: (ri,)),
            pl.BlockSpec((r,), lambda bi, ri: (ri,)),
        ],
        out_specs=(pl.BlockSpec((bb,), lambda bi, ri: (bi,)),
                   pl.BlockSpec((bb,), lambda bi, ri: (bi,))),
        interpret=_interpret(),
    )(seg_p, hi_p, lo_p)
    return whi[:B], wlo[:B]


def _order_words(data: jax.Array, fill_excluded: jax.Array, op: str
                 ) -> Tuple[jax.Array, jax.Array]:
    """(hi, lo) u32 order-preserving word planes for one column (the
    sort machinery's radix encoding — NaN canonical-largest, -0.0
    folded), with the op identity at excluded rows."""
    from .sort import _float_radix, _int_radix

    if jnp.issubdtype(data.dtype, jnp.floating):
        w = _float_radix(data)
    elif data.dtype == jnp.bool_:
        w = data.astype(jnp.uint32)
    else:
        w = _int_radix(data)
    if w.dtype == jnp.uint64:
        hi = (w >> 32).astype(jnp.uint32)
        lo = (w & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    else:
        hi = w.astype(jnp.uint32)
        lo = jnp.zeros_like(hi)
    ident = jnp.uint32(_U32_MAX if op == "min" else 0)
    return (jnp.where(fill_excluded, ident, hi),
            jnp.where(fill_excluded, ident, lo))


def _decode_word(whi: jax.Array, wlo: jax.Array, dtype) -> jax.Array:
    """Invert :func:`_order_words` for one winner word pair."""
    from jax import lax

    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        def f32val(k32):
            s = jnp.uint32(1 << 31)
            bits = jnp.where(k32 & s != 0, k32 ^ s, ~k32)
            return lax.bitcast_convert_type(bits, jnp.float32)
        if dtype == jnp.float32:
            return f32val(whi)
        import jax as _jax

        if _jax.default_backend() == "cpu":
            w = (whi.astype(jnp.uint64) << 32) | wlo.astype(jnp.uint64)
            s64 = jnp.uint64(1 << 63)
            bits = jnp.where(w & s64 != 0, w ^ s64, ~w)
            # no 64-bit bitcast under the x64 rewriter: reassemble via
            # the 32-bit halves
            blo = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
            bhi = (bits >> 32).astype(jnp.uint32)
            return _bits64_to_f64(bhi, blo)
        # TPU dialect: the f64 word is the (hi=f32(x), lo=x-hi) pair
        hi = f32val(whi)
        lo = f32val(wlo)
        return hi.astype(jnp.float64) + lo.astype(jnp.float64)
    if dtype == jnp.bool_:
        return whi.astype(jnp.bool_)
    nbits = dtype.itemsize * 8
    if nbits <= 32:
        u = whi ^ jnp.uint32(1 << 31)
        return lax.bitcast_convert_type(u, jnp.int32).astype(dtype)
    w = (whi.astype(jnp.uint64) << 32) | wlo.astype(jnp.uint64)
    u = w ^ jnp.uint64(1 << 63)
    return u.astype(dtype)


def _bits64_to_f64(bhi: jax.Array, blo: jax.Array) -> jax.Array:
    """f64 from raw bit halves via a (n, 2) u32 bitcast (little-endian),
    which the CPU backend supports."""
    from jax import lax

    both = jnp.stack([blo, bhi], axis=-1)
    return lax.bitcast_convert_type(both, jnp.float64)


def pallas_bucket_min_max(
    seg: jax.Array, B: int, op: str, cols: Sequence[jax.Array]
) -> List[jax.Array]:
    """PALLAS lowering of :func:`bucket_reduce.bucket_min_max`: same
    contract (identity-prefilled columns, callers overwrite empty
    buckets via their count mask), per-bucket winners via the
    lexicographic word kernel instead of a segment scatter."""
    out: List[jax.Array] = []
    no = jnp.zeros(seg.shape[0], jnp.bool_)
    for d in cols:
        hi, lo = _order_words(d, no, op)
        whi, wlo = pallas_bucket_winner(seg, B, op, hi, lo)
        out.append(_decode_word(whi, wlo, d.dtype))
    return out


def pallas_bucket_position(
    seg: jax.Array, B: int, op: str, consider: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """(row, found) per bucket: the first ('min') or last ('max')
    considered row — the scatter-free first/last + representative-row
    primitive. Row indices ride +1 so the max identity 0 stays
    distinct."""
    cap = seg.shape[0]
    idx = jnp.arange(cap, dtype=jnp.uint32) + 1
    ident = jnp.uint32(_U32_MAX if op == "min" else 0)
    hi = jnp.where(consider, idx, ident)
    whi, _ = pallas_bucket_winner(seg, B, op, hi)
    found = whi != ident
    row = jnp.where(found, whi.astype(jnp.int32) - 1, -1)
    return row, found
