"""Batch concatenation kernels.

Reference analog: cudf ``Table.concatenate`` as used by GpuCoalesceBatches
(GpuCoalesceBatches.scala:398-571) and GpuShuffleCoalesceExec. Lengths are
host ints at batch boundaries (the reference syncs for row counts there
too), so each part placement is a static ``dynamic_update_slice`` and XLA
fuses the whole stitch into one program.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..expr.eval import ColV, StrV, Val


def concat_fixed(parts: Sequence[ColV], lengths: Sequence[int], out_cap: int) -> ColV:
    dtype = parts[0].data.dtype
    data = jnp.zeros(out_cap, dtype)
    validity = jnp.zeros(out_cap, jnp.bool_)
    off = 0
    for p, n in zip(parts, lengths):
        if n == 0:
            continue
        data = lax.dynamic_update_slice(data, p.data[:n], (off,))
        validity = lax.dynamic_update_slice(validity, p.validity[:n], (off,))
        off += n
    return ColV(data, validity)


def concat_padded_cols(
    col_parts: Sequence[Sequence[ColV]],
    counts: Sequence[jax.Array],
    out_cap: int,
) -> Tuple[List[ColV], jax.Array, jax.Array]:
    """Sync-free concat for FIXED-WIDTH columns: parts stack at their full
    capacities (no compaction) and the returned (out_cap,) live MASK marks
    which rows are real — row counts stay device scalars, so no host
    round-trip. Downstream fused ops consume the mask via live_of
    (reference contrast: the cudf concat path syncs row counts;
    GpuCoalesceBatches.scala:398 — on TPU a sync costs a tunnel RTT, so
    the merge loop avoids it entirely)."""
    caps = [cp[0].validity.shape[0] for cp in col_parts]
    masks = [
        jnp.arange(c, dtype=jnp.int32) < jnp.int32(cnt)
        for c, cnt in zip(caps, counts)
    ]
    mask = jnp.concatenate(masks)
    if mask.shape[0] < out_cap:
        mask = jnp.concatenate(
            [mask, jnp.zeros(out_cap - mask.shape[0], jnp.bool_)])
    else:
        mask = mask[:out_cap]
    ncols = len(col_parts[0])
    out: List[ColV] = []
    for j in range(ncols):
        parts = [cp[j] for cp in col_parts]
        data = jnp.concatenate([p.data for p in parts])
        valid = jnp.concatenate([p.validity for p in parts])
        if data.shape[0] < out_cap:
            pad = out_cap - data.shape[0]
            data = jnp.concatenate([data, jnp.zeros(pad, data.dtype)])
            valid = jnp.concatenate([valid, jnp.zeros(pad, jnp.bool_)])
        else:
            data, valid = data[:out_cap], valid[:out_cap]
        out.append(ColV(data, valid & mask))
    total = sum(jnp.int32(c) for c in counts)
    return out, mask, total


def concat_string(
    parts: Sequence[StrV],
    lengths: Sequence[int],
    byte_lengths: Sequence[int],
    out_cap: int,
    out_char_cap: int,
) -> StrV:
    offsets = jnp.zeros(out_cap + 1, jnp.int32)
    chars = jnp.zeros(out_char_cap, jnp.uint8)
    validity = jnp.zeros(out_cap, jnp.bool_)
    row_off = 0
    byte_off = 0
    for p, n, nb in zip(parts, lengths, byte_lengths):
        if n == 0:
            continue
        shifted = p.offsets[: n + 1] + jnp.int32(byte_off)
        offsets = lax.dynamic_update_slice(offsets, shifted, (row_off,))
        validity = lax.dynamic_update_slice(validity, p.validity[:n], (row_off,))
        if nb > 0:
            chars = lax.dynamic_update_slice(chars, p.chars[:nb], (byte_off,))
        row_off += n
        byte_off += nb
    total_rows, total_bytes = row_off, byte_off
    # keep offsets monotonic through the padded tail
    idx = jnp.arange(out_cap + 1, dtype=jnp.int32)
    offsets = jnp.where(idx <= total_rows, offsets, jnp.int32(total_bytes))
    return StrV(offsets, chars, validity)


def concat_pieces_traced(
    col_parts: Sequence[Sequence[Val]],
    counts: Sequence[jax.Array],
    byte_counts: Sequence[Sequence[jax.Array]],
    out_cap: int,
    out_char_caps: Sequence[int],
) -> Tuple[List[Val], jax.Array]:
    """Concat with TRACED row/byte counts — one XLA program per shape set.

    ``concat_batches_cols`` bakes host lengths into each dispatch, so every
    distinct length combination compiles a fresh executable; the exchange's
    reduce side sees arbitrary piece sizes every query and would compile
    forever. Here counts are operands: placement is masked
    ``dynamic_update_slice`` at traced starts into a sum-of-capacities work
    buffer (pieces applied in order, so each row's OWNING piece writes
    last), then a static head slice. Trace-safe under jit/shard_map.
    """
    k = len(col_parts)
    ncols = len(col_parts[0])
    counts_arr = jnp.stack([jnp.int32(c) for c in counts])
    row_offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts_arr)])
    total = row_offs[k]

    def place(parts: Sequence[jax.Array], lens) -> jax.Array:
        # work buffer >= out_cap so the final head slice never clamps, and
        # >= sum(caps) so no dynamic_update_slice start ever clamps
        caps = [int(p.shape[0]) for p in parts]
        work = jnp.zeros(max(sum(caps), out_cap), parts[0].dtype)
        for i, p in enumerate(parts):
            slot = jnp.arange(caps[i], dtype=jnp.int32)
            masked = jnp.where(slot < lens[i], p, jnp.zeros((), p.dtype))
            work = lax.dynamic_update_slice(work, masked, (row_offs[i],))
        return work

    out: List[Val] = []
    si = 0
    for j in range(ncols):
        parts = [cp[j] for cp in col_parts]
        if isinstance(parts[0], StrV):
            bc = [byte_counts[i][si] for i in range(k)]
            out_char_cap = out_char_caps[si]
            si += 1
            byte_offs = jnp.concatenate([
                jnp.zeros(1, jnp.int32),
                jnp.cumsum(jnp.stack([jnp.int32(b) for b in bc])),
            ])
            # per-row lengths placed like fixed data, then offsets by cumsum
            lens_parts = [p.offsets[1:] - p.offsets[:-1] for p in parts]
            lens_work = place(lens_parts, counts)[:out_cap]
            idx = jnp.arange(out_cap, dtype=jnp.int32)
            lens_work = jnp.where(idx < total, lens_work, 0)
            offsets = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(lens_work).astype(jnp.int32)])
            char_caps = [int(p.chars.shape[0]) for p in parts]
            cwork = jnp.zeros(max(sum(char_caps), out_char_cap), jnp.uint8)
            for i, p in enumerate(parts):
                slot = jnp.arange(char_caps[i], dtype=jnp.int32)
                masked = jnp.where(slot < bc[i], p.chars, jnp.uint8(0))
                cwork = lax.dynamic_update_slice(cwork, masked, (byte_offs[i],))
            chars = cwork[:out_char_cap]
            validity = place(
                [p.validity for p in parts], counts)[:out_cap]
            validity = validity & (idx < total)
            out.append(StrV(offsets, chars, validity))
        else:
            idx = jnp.arange(out_cap, dtype=jnp.int32)
            data = place([p.data for p in parts], counts)[:out_cap]
            validity = place(
                [p.validity for p in parts], counts)[:out_cap]
            validity = validity & (idx < total)
            data = jnp.where(validity, data, jnp.zeros((), data.dtype))
            out.append(ColV(data, validity))
    return out, total


def concat_batches_cols(
    col_parts: Sequence[Sequence[Val]],
    lengths: Sequence[int],
    byte_lengths_per_col: Sequence[Sequence[int]],
    out_cap: int,
    out_char_caps: Sequence[int],
) -> Tuple[List[Val], int]:
    """Concatenate N batches column-wise.

    ``col_parts[i]`` = columns of batch i; ``byte_lengths_per_col[i][j]`` =
    byte length of string column j in batch i (host ints, synced by the
    caller once per batch like cudf's row-count syncs).
    """
    ncols = len(col_parts[0])
    out: List[Val] = []
    si = 0
    for j in range(ncols):
        parts = [cp[j] for cp in col_parts]
        if isinstance(parts[0], StrV):
            bl = [byte_lengths_per_col[i][si] for i in range(len(col_parts))]
            out.append(
                concat_string(parts, lengths, bl, out_cap, out_char_caps[si])
            )
            si += 1
        else:
            out.append(concat_fixed(parts, lengths, out_cap))
    return out, sum(lengths)
