"""Batch concatenation kernels.

Reference analog: cudf ``Table.concatenate`` as used by GpuCoalesceBatches
(GpuCoalesceBatches.scala:398-571) and GpuShuffleCoalesceExec. Lengths are
host ints at batch boundaries (the reference syncs for row counts there
too), so each part placement is a static ``dynamic_update_slice`` and XLA
fuses the whole stitch into one program.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..expr.eval import ColV, StrV, Val


def concat_fixed(parts: Sequence[ColV], lengths: Sequence[int], out_cap: int) -> ColV:
    dtype = parts[0].data.dtype
    data = jnp.zeros(out_cap, dtype)
    validity = jnp.zeros(out_cap, jnp.bool_)
    off = 0
    for p, n in zip(parts, lengths):
        if n == 0:
            continue
        data = lax.dynamic_update_slice(data, p.data[:n], (off,))
        validity = lax.dynamic_update_slice(validity, p.validity[:n], (off,))
        off += n
    return ColV(data, validity)


def concat_string(
    parts: Sequence[StrV],
    lengths: Sequence[int],
    byte_lengths: Sequence[int],
    out_cap: int,
    out_char_cap: int,
) -> StrV:
    offsets = jnp.zeros(out_cap + 1, jnp.int32)
    chars = jnp.zeros(out_char_cap, jnp.uint8)
    validity = jnp.zeros(out_cap, jnp.bool_)
    row_off = 0
    byte_off = 0
    for p, n, nb in zip(parts, lengths, byte_lengths):
        if n == 0:
            continue
        shifted = p.offsets[: n + 1] + jnp.int32(byte_off)
        offsets = lax.dynamic_update_slice(offsets, shifted, (row_off,))
        validity = lax.dynamic_update_slice(validity, p.validity[:n], (row_off,))
        if nb > 0:
            chars = lax.dynamic_update_slice(chars, p.chars[:nb], (byte_off,))
        row_off += n
        byte_off += nb
    total_rows, total_bytes = row_off, byte_off
    # keep offsets monotonic through the padded tail
    idx = jnp.arange(out_cap + 1, dtype=jnp.int32)
    offsets = jnp.where(idx <= total_rows, offsets, jnp.int32(total_bytes))
    return StrV(offsets, chars, validity)


def concat_batches_cols(
    col_parts: Sequence[Sequence[Val]],
    lengths: Sequence[int],
    byte_lengths_per_col: Sequence[Sequence[int]],
    out_cap: int,
    out_char_caps: Sequence[int],
) -> Tuple[List[Val], int]:
    """Concatenate N batches column-wise.

    ``col_parts[i]`` = columns of batch i; ``byte_lengths_per_col[i][j]`` =
    byte length of string column j in batch i (host ints, synced by the
    caller once per batch like cudf's row-count syncs).
    """
    ncols = len(col_parts[0])
    out: List[Val] = []
    si = 0
    for j in range(ncols):
        parts = [cp[j] for cp in col_parts]
        if isinstance(parts[0], StrV):
            bl = [byte_lengths_per_col[i][si] for i in range(len(col_parts))]
            out.append(
                concat_string(parts, lengths, bl, out_cap, out_char_caps[si])
            )
            si += 1
        else:
            out.append(concat_fixed(parts, lengths, out_cap))
    return out, sum(lengths)
