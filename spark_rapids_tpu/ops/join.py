"""Equi-join kernels: sorted build side + vectorized binary search +
static-shape pair expansion.

Reference analog: the cudf join family called from GpuHashJoin.doJoinLeftRight
(execution/GpuHashJoin.scala:265) — innerJoin/leftJoin/leftSemi/leftAnti/
fullOuter hash joins. cudf probes a GPU hash table; on TPU the build side is
radix-sorted once and every probe row finds its match range [lo, hi) with a
vectorized lexicographic binary search (log2(build) steps, pure VPU math, no
scatter/gather in the hot loop). The pair expansion computes, for output
slot j, its (probe row, match ordinal) with a searchsorted over the count
prefix sums — all static shapes; only the total match count syncs to pick
the output capacity bucket (cudf syncs for output sizes at the same spot).

Null join keys never match (SQL equi-join); NaN matches NaN (Spark).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import types as T
from ..expr.eval import ColV, StrV, Val
from .filter_gather import live_of
from .sort import SortOrder, fixed_radix_keys, string_chunk_keys, sort_with_radix_keys


def radix_key_words(
    cols: Sequence[Val],
    dtypes: Sequence[T.DataType],
    str_max_lens: Sequence[int] = (),
) -> Tuple[List[jax.Array], jax.Array]:
    """(key word arrays, any_null) for join-key comparison.

    Words are the same order-preserving u32 radix encoding the sort uses,
    so equality over words == Spark join-key equality (NaN==NaN, -0.0==0.0)
    and the build side can be ordered by them.
    """
    order = SortOrder(True, True)
    words: List[jax.Array] = []
    si = 0
    cap = (
        cols[0].offsets.shape[0] - 1
        if isinstance(cols[0], StrV)
        else cols[0].validity.shape[0]
    )
    any_null = jnp.zeros(cap, jnp.bool_)
    for c, dt in zip(cols, dtypes):
        any_null = any_null | ~c.validity
        if isinstance(c, StrV):
            ml = str_max_lens[si] if si < len(str_max_lens) else 64
            si += 1
            ks = string_chunk_keys(c, order, ml)
        else:
            ks = fixed_radix_keys(c, dt, order)
        for k in ks[1:]:  # skip null_rank: null keys are excluded anyway
            if k.dtype == jnp.uint64:
                words.append((k >> 32).astype(jnp.uint32))
                words.append((k & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
            else:
                words.append(k.astype(jnp.uint32))
    return words, any_null


def _lex_less(a_words, b_words, i, j):
    """a[i] < b[j] lexicographically over word arrays (broadcast-safe)."""
    lt = jnp.zeros(jnp.broadcast_shapes(i.shape, j.shape), jnp.bool_)
    eq = jnp.ones_like(lt)
    for aw, bw in zip(a_words, b_words):
        av = jnp.take(aw, i, mode="clip")
        bv = jnp.take(bw, j, mode="clip")
        lt = lt | (eq & (av < bv))
        eq = eq & (av == bv)
    return lt, eq


def _pack_u64(words: Sequence[jax.Array]) -> jax.Array:
    if len(words) == 1:
        return words[0].astype(jnp.uint64)
    return (words[0].astype(jnp.uint64) << 32) | words[1].astype(jnp.uint64)


def probe_ranges(
    build_words: Sequence[jax.Array],
    build_count: jax.Array,
    probe_words: Sequence[jax.Array],
    probe_live: jax.Array,
    pallas: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """[lo, hi) of build matches per probe row.

    Fast path (single key, i.e. <=2 radix words): a DIRECT-ADDRESS table —
    when the build keys' value range fits a 4x-build-capacity table (the
    TPC-DS dense-dim-key case), per-key (first, count) tables are built
    with two scatters and probing is two gathers. The general path is the
    vectorized binary search, whose log2(build) gather passes are ~20x
    slower on TPU. A lax.cond picks at runtime; only the taken branch
    executes. ``pallas`` (conf sql.join.pallasProbe.enabled, trace-time
    static) lowers single-key probes to the VMEM-tiled Pallas kernel
    instead (ops/pallas_join.py) — no scatter-built table, no gather
    chain."""
    if pallas and len(build_words) <= 2 and len(probe_words) <= 2:
        from .pallas_join import pallas_probe_ranges

        return pallas_probe_ranges(
            build_words, build_count, probe_words, probe_live)
    if len(build_words) <= 2 and len(probe_words) <= 2:
        nb = build_words[0].shape[0]
        tbl = 4 * nb
        bkey = _pack_u64(build_words)
        pkey = _pack_u64(probe_words)
        m = pkey.shape[0]
        bidx = jnp.arange(nb, dtype=jnp.int32)
        live_b = bidx < build_count
        kmin = jnp.min(jnp.where(live_b, bkey, jnp.uint64(2**64 - 1)))
        kmax = jnp.max(jnp.where(live_b, bkey, jnp.uint64(0)))
        has = jnp.any(live_b)
        fits = has & ((kmax - kmin) < jnp.uint64(tbl))

        def direct(_):
            off = (bkey - kmin).astype(jnp.int64)
            tgt = jnp.where(live_b, jnp.clip(off, 0, tbl - 1), tbl)
            first = jnp.full(tbl, nb, jnp.int32).at[tgt].min(
                bidx, mode="drop")
            cnt = jnp.zeros(tbl, jnp.int32).at[tgt].add(1, mode="drop")
            poff = (pkey - kmin).astype(jnp.int64)
            pin = probe_live & (poff >= 0) & (poff < tbl)
            pc = jnp.clip(poff, 0, tbl - 1)
            c = jnp.where(pin, jnp.take(cnt, pc, mode="clip"), 0)
            lo_ = jnp.where(c > 0, jnp.take(first, pc, mode="clip"), 0)
            return lo_, lo_ + c

        def binsearch(_):
            return _probe_binary_search(
                build_words, build_count, probe_words, probe_live)

        return lax.cond(fits, direct, binsearch, operand=None)
    return _probe_binary_search(
        build_words, build_count, probe_words, probe_live)


def _probe_binary_search(
    build_words: Sequence[jax.Array],
    build_count: jax.Array,
    probe_words: Sequence[jax.Array],
    probe_live: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """General path: vectorized lexicographic binary search over the
    radix-sorted build words (build rows sorted live-first)."""
    m = probe_words[0].shape[0]
    nb = build_words[0].shape[0]
    steps = max(1, (nb).bit_length())
    probe_idx = jnp.arange(m, dtype=jnp.int32)

    lo = jnp.zeros(m, jnp.int32)
    hi_l = jnp.broadcast_to(build_count.astype(jnp.int32), (m,))
    for _ in range(steps):
        mid = (lo + hi_l) // 2
        open_ = lo < hi_l  # never move on an empty interval
        # build[mid] < probe ? move lo up : move hi down
        lt, _ = _lex_less(build_words, probe_words, mid, probe_idx)
        lo = jnp.where(open_ & lt, mid + 1, lo)
        hi_l = jnp.where(open_ & ~lt, mid, hi_l)
    first = lo

    lo2 = jnp.zeros(m, jnp.int32)
    hi2 = jnp.broadcast_to(build_count.astype(jnp.int32), (m,))
    for _ in range(steps):
        mid = (lo2 + hi2) // 2
        open_ = lo2 < hi2
        # probe < build[mid] ? move hi down : move lo up
        lt, _ = _lex_less(probe_words, build_words, probe_idx, mid)
        lo2 = jnp.where(open_ & ~lt, mid + 1, lo2)
        hi2 = jnp.where(open_ & lt, mid, hi2)
    last = lo2

    first = jnp.where(probe_live, first, 0)
    last = jnp.where(probe_live, last, 0)
    return first, jnp.maximum(first, last)


def expansion_plan(
    counts: jax.Array, lo: jax.Array, out_cap: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(probe_row, build_row, slot_live) for each output slot j.

    counts/lo are per-probe-row; out_cap is the static output bucket
    (>= total matches, chosen by the caller after syncing the total).

    Built with two jnp.repeat passes (scatter+cumsum under the hood) — the
    obvious searchsorted over the count prefix sums costs log2(out_cap)
    gather passes, ~20x slower on TPU."""
    counts = counts.astype(jnp.int64)
    m = counts.shape[0]
    csum = jnp.cumsum(counts)
    total = csum[-1]
    starts = csum - counts  # output offset of each probe row
    j = jnp.arange(out_cap, dtype=jnp.int64)
    p = jnp.repeat(
        jnp.arange(m, dtype=jnp.int32), counts, total_repeat_length=out_cap)
    # pack (start, lo) so one more repeat recovers both
    packed = (starts << 31) | lo.astype(jnp.int64)
    rep = jnp.repeat(packed, counts, total_repeat_length=out_cap)
    ordinal = j - (rep >> 31)
    build_row = (rep & ((1 << 31) - 1)).astype(jnp.int32) + ordinal.astype(
        jnp.int32)
    slot_live = j < total
    return p, build_row, slot_live


def matched_build_mask(
    lo: jax.Array, hi: jax.Array, probe_live: jax.Array, build_cap: int
) -> jax.Array:
    """Which build rows matched at least one probe row (for full outer).

    Ranges for equal keys are identical, so a +1/-1 difference array over
    range endpoints and a prefix sum marks exactly the covered rows."""
    delta = jnp.zeros(build_cap + 1, jnp.int32)
    lo_m = jnp.where(probe_live & (hi > lo), lo, build_cap)
    hi_m = jnp.where(probe_live & (hi > lo), hi, build_cap)
    delta = delta.at[lo_m].add(1, mode="drop")
    delta = delta.at[hi_m].add(-1, mode="drop")
    return jnp.cumsum(delta[:-1]) > 0
