"""Equi-join kernels: sorted build side + vectorized binary search +
static-shape pair expansion.

Reference analog: the cudf join family called from GpuHashJoin.doJoinLeftRight
(execution/GpuHashJoin.scala:265) — innerJoin/leftJoin/leftSemi/leftAnti/
fullOuter hash joins. cudf probes a GPU hash table; on TPU the build side is
radix-sorted once and every probe row finds its match range [lo, hi) with a
vectorized lexicographic binary search (log2(build) steps, pure VPU math, no
scatter/gather in the hot loop). The pair expansion computes, for output
slot j, its (probe row, match ordinal) with a searchsorted over the count
prefix sums — all static shapes; only the total match count syncs to pick
the output capacity bucket (cudf syncs for output sizes at the same spot).

Null join keys never match (SQL equi-join); NaN matches NaN (Spark).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..expr.eval import ColV, StrV, Val
from .filter_gather import live_of
from .sort import SortOrder, fixed_radix_keys, string_chunk_keys, sort_with_radix_keys


def radix_key_words(
    cols: Sequence[Val],
    dtypes: Sequence[T.DataType],
    str_max_lens: Sequence[int] = (),
) -> Tuple[List[jax.Array], jax.Array]:
    """(key word arrays, any_null) for join-key comparison.

    Words are the same order-preserving u32 radix encoding the sort uses,
    so equality over words == Spark join-key equality (NaN==NaN, -0.0==0.0)
    and the build side can be ordered by them.
    """
    order = SortOrder(True, True)
    words: List[jax.Array] = []
    si = 0
    cap = (
        cols[0].offsets.shape[0] - 1
        if isinstance(cols[0], StrV)
        else cols[0].validity.shape[0]
    )
    any_null = jnp.zeros(cap, jnp.bool_)
    for c, dt in zip(cols, dtypes):
        any_null = any_null | ~c.validity
        if isinstance(c, StrV):
            ml = str_max_lens[si] if si < len(str_max_lens) else 64
            si += 1
            ks = string_chunk_keys(c, order, ml)
        else:
            ks = fixed_radix_keys(c, dt, order)
        for k in ks[1:]:  # skip null_rank: null keys are excluded anyway
            if k.dtype == jnp.uint64:
                words.append((k >> 32).astype(jnp.uint32))
                words.append((k & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
            else:
                words.append(k.astype(jnp.uint32))
    return words, any_null


def _lex_less(a_words, b_words, i, j):
    """a[i] < b[j] lexicographically over word arrays (broadcast-safe)."""
    lt = jnp.zeros(jnp.broadcast_shapes(i.shape, j.shape), jnp.bool_)
    eq = jnp.ones_like(lt)
    for aw, bw in zip(a_words, b_words):
        av = jnp.take(aw, i, mode="clip")
        bv = jnp.take(bw, j, mode="clip")
        lt = lt | (eq & (av < bv))
        eq = eq & (av == bv)
    return lt, eq


def probe_ranges(
    build_words: Sequence[jax.Array],
    build_count: jax.Array,
    probe_words: Sequence[jax.Array],
    probe_live: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """[lo, hi) of build matches per probe row, via vectorized binary
    search over the radix-sorted build words. Build rows are sorted with
    live (non-null-key) rows first; ``build_count`` bounds the search."""
    m = probe_words[0].shape[0]
    nb = build_words[0].shape[0]
    steps = max(1, (nb).bit_length())
    probe_idx = jnp.arange(m, dtype=jnp.int32)

    lo = jnp.zeros(m, jnp.int32)
    hi_l = jnp.broadcast_to(build_count.astype(jnp.int32), (m,))
    for _ in range(steps):
        mid = (lo + hi_l) // 2
        open_ = lo < hi_l  # never move on an empty interval
        # build[mid] < probe ? move lo up : move hi down
        lt, _ = _lex_less(build_words, probe_words, mid, probe_idx)
        lo = jnp.where(open_ & lt, mid + 1, lo)
        hi_l = jnp.where(open_ & ~lt, mid, hi_l)
    first = lo

    lo2 = jnp.zeros(m, jnp.int32)
    hi2 = jnp.broadcast_to(build_count.astype(jnp.int32), (m,))
    for _ in range(steps):
        mid = (lo2 + hi2) // 2
        open_ = lo2 < hi2
        # probe < build[mid] ? move hi down : move lo up
        lt, _ = _lex_less(probe_words, build_words, probe_idx, mid)
        lo2 = jnp.where(open_ & ~lt, mid + 1, lo2)
        hi2 = jnp.where(open_ & lt, mid, hi2)
    last = lo2

    first = jnp.where(probe_live, first, 0)
    last = jnp.where(probe_live, last, 0)
    return first, jnp.maximum(first, last)


def expansion_plan(
    counts: jax.Array, lo: jax.Array, out_cap: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(probe_row, build_row, slot_live) for each output slot j.

    counts/lo are per-probe-row; out_cap is the static output bucket
    (>= total matches, chosen by the caller after syncing the total)."""
    counts = counts.astype(jnp.int64)
    csum = jnp.cumsum(counts)
    total = csum[-1]
    starts = csum - counts  # output offset of each probe row
    j = jnp.arange(out_cap, dtype=counts.dtype)
    p = jnp.searchsorted(csum, j, side="right").astype(jnp.int32)
    m = counts.shape[0]
    p = jnp.clip(p, 0, m - 1)
    ordinal = j - jnp.take(starts, p, mode="clip")
    build_row = jnp.take(lo, p, mode="clip") + ordinal.astype(jnp.int32)
    slot_live = j < total
    return p, build_row, slot_live


def matched_build_mask(
    lo: jax.Array, hi: jax.Array, probe_live: jax.Array, build_cap: int
) -> jax.Array:
    """Which build rows matched at least one probe row (for full outer).

    Ranges for equal keys are identical, so a +1/-1 difference array over
    range endpoints and a prefix sum marks exactly the covered rows."""
    delta = jnp.zeros(build_cap + 1, jnp.int32)
    lo_m = jnp.where(probe_live & (hi > lo), lo, build_cap)
    hi_m = jnp.where(probe_live & (hi > lo), hi, build_cap)
    delta = delta.at[lo_m].add(1, mode="drop")
    delta = delta.at[hi_m].add(-1, mode="drop")
    return jnp.cumsum(delta[:-1]) > 0
