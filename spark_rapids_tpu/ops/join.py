"""Equi-join kernels: the tiered probe lowerings behind
``spark.rapids.tpu.sql.join.strategy`` (exec/join.choose_join_strategy).

Reference analog: the cudf join family called from GpuHashJoin.doJoinLeftRight
(execution/GpuHashJoin.scala:265) — innerJoin/leftJoin/leftSemi/leftAnti/
fullOuter hash joins. cudf probes a GPU hash table; on TPU the build side is
radix-sorted once and every probe batch finds its match range [lo, hi)
through one of four lowerings, all bit-identical:

  * SEARCH — vectorized lexicographic binary search over the sorted build
    words (log2(build) gather passes, the general fallback);
  * DIRECT — scatter-built direct-address (first, count) tables when the
    build keys' value range fits 4x the build capacity (the TPC-DS
    dense-dim-key case); probing is two gathers and the whole join can
    fuse into its consumer chain (exec/join fast path);
  * RADIX — :func:`radix_probe_ranges`: build and probe rows co-sort by
    the SAME order-preserving radix words the build sort already uses
    (the sort IS the binning, exactly as ops/radix_bin.py bins rows for
    the RADIX aggregation tier), and every [lo, hi) falls out of
    segmented prefix sums over the co-sorted order — zero scatter
    instructions, no cap-sized table, no log2(build) gather chain. The
    r10 cost plane showed the join shape touching 29.8x its layout
    bound; the sorted-merge planes are O(build + probe) words, i.e. the
    bound itself;
  * PALLAS — the hand-written VMEM-tiled kernel (ops/pallas_join.py) for
    broadcast-class single-key builds.

The pair expansion computes, for output slot j, its (probe row, match
ordinal); the default lowering is two jnp.repeat passes (scatter+cumsum
under the hood), the RADIX tier uses :func:`radix_expansion_plan`
(prefix-sum searchsorted — scatter-free) instead. All static shapes;
only the total match count syncs to pick the output capacity bucket
(cudf syncs for output sizes at the same spot).

Null join keys never match (SQL equi-join); NaN matches NaN (Spark).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import types as T
from ..expr.eval import ColV, StrV, Val
from .filter_gather import live_of
from .sort import SortOrder, fixed_radix_keys, string_chunk_keys, sort_with_radix_keys


def radix_key_words(
    cols: Sequence[Val],
    dtypes: Sequence[T.DataType],
    str_max_lens: Sequence[int] = (),
) -> Tuple[List[jax.Array], jax.Array]:
    """(key word arrays, any_null) for join-key comparison.

    Words are the same order-preserving u32 radix encoding the sort uses,
    so equality over words == Spark join-key equality (NaN==NaN, -0.0==0.0)
    and the build side can be ordered by them.
    """
    order = SortOrder(True, True)
    words: List[jax.Array] = []
    si = 0
    cap = (
        cols[0].offsets.shape[0] - 1
        if isinstance(cols[0], StrV)
        else cols[0].validity.shape[0]
    )
    any_null = jnp.zeros(cap, jnp.bool_)
    for c, dt in zip(cols, dtypes):
        any_null = any_null | ~c.validity
        if isinstance(c, StrV):
            ml = str_max_lens[si] if si < len(str_max_lens) else 64
            si += 1
            ks = string_chunk_keys(c, order, ml)
        else:
            ks = fixed_radix_keys(c, dt, order)
        for k in ks[1:]:  # skip null_rank: null keys are excluded anyway
            if k.dtype == jnp.uint64:
                words.append((k >> 32).astype(jnp.uint32))
                words.append((k & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
            else:
                words.append(k.astype(jnp.uint32))
    return words, any_null


def pad_key_words(
    build_words: Sequence[jax.Array],
    probe_words: Sequence[jax.Array],
) -> Tuple[List[jax.Array], List[jax.Array]]:
    """Zero-pad the shorter side's word list to the longer count.

    String keys derive their chunk-word count from each SIDE's OWN max
    byte length bucket (exec/join._key_str_lens), so build and probe
    word counts legitimately differ. Every string on the shorter-
    bucketed side fits inside its bucket, so its chunks BEYOND the
    bucket are exactly zero — appending zero words reconstructs the
    true encoding at the longer width (joins compare ascending, so no
    order flip ever touches the padding). Comparing only the common
    prefix instead would falsely match keys that differ past it."""
    bw = list(build_words)
    pw = list(probe_words)
    while len(bw) < len(pw):
        bw.append(jnp.zeros(bw[0].shape[0], jnp.uint32))
    while len(pw) < len(bw):
        pw.append(jnp.zeros(pw[0].shape[0], jnp.uint32))
    return bw, pw


def _lex_less(a_words, b_words, i, j):
    """a[i] < b[j] lexicographically over word arrays (broadcast-safe)."""
    lt = jnp.zeros(jnp.broadcast_shapes(i.shape, j.shape), jnp.bool_)
    eq = jnp.ones_like(lt)
    for aw, bw in zip(a_words, b_words):
        av = jnp.take(aw, i, mode="clip")
        bv = jnp.take(bw, j, mode="clip")
        lt = lt | (eq & (av < bv))
        eq = eq & (av == bv)
    return lt, eq


def _pack_u64(words: Sequence[jax.Array]) -> jax.Array:
    if len(words) == 1:
        return words[0].astype(jnp.uint64)
    return (words[0].astype(jnp.uint64) << 32) | words[1].astype(jnp.uint64)


def probe_ranges(
    build_words: Sequence[jax.Array],
    build_count: jax.Array,
    probe_words: Sequence[jax.Array],
    probe_live: jax.Array,
    pallas: bool = False,
    strategy: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """[lo, hi) of build matches per probe row, per the lowering tier.

    ``strategy`` (trace-time static, from exec/join.resolved_strategy) is
    one of SEARCH / DIRECT / RADIX / PALLAS; ``None`` keeps the legacy
    resolution: the ``pallas`` flag (conf sql.join.pallasProbe.enabled)
    or the DIRECT tier. All tiers return bit-identical ranges.

    DIRECT (single key, i.e. <=2 radix words): a direct-address table —
    when the build keys' value range fits a 4x-build-capacity table (the
    TPC-DS dense-dim-key case), per-key (first, count) tables are built
    with two scatters and probing is two gathers. Its general fallback is
    the vectorized binary search (SEARCH), whose log2(build) gather
    passes are ~20x slower on TPU. A lax.cond picks at runtime; only the
    taken branch executes. PALLAS lowers single-key probes to the
    VMEM-tiled kernel (ops/pallas_join.py) — no scatter-built table, no
    gather chain. RADIX is the co-sorted merge
    (:func:`radix_probe_ranges`) — zero scatters at any key width."""
    if strategy is None:
        strategy = "PALLAS" if pallas else "DIRECT"
    build_words, probe_words = pad_key_words(build_words, probe_words)
    if strategy == "RADIX":
        lo, hi, _ = radix_probe_ranges(
            build_words, build_count, probe_words, probe_live)
        return lo, hi
    if strategy == "SEARCH":
        return _probe_binary_search(
            build_words, build_count, probe_words, probe_live)
    if (strategy == "PALLAS" and len(build_words) <= 2
            and len(probe_words) <= 2):
        from .pallas_join import pallas_probe_ranges

        return pallas_probe_ranges(
            build_words, build_count, probe_words, probe_live)
    if (strategy == "DIRECT" and len(build_words) <= 2
            and len(probe_words) <= 2):
        nb = build_words[0].shape[0]
        tbl = 4 * nb
        bkey = _pack_u64(build_words)
        pkey = _pack_u64(probe_words)
        m = pkey.shape[0]
        bidx = jnp.arange(nb, dtype=jnp.int32)
        live_b = bidx < build_count
        kmin = jnp.min(jnp.where(live_b, bkey, jnp.uint64(2**64 - 1)))
        kmax = jnp.max(jnp.where(live_b, bkey, jnp.uint64(0)))
        has = jnp.any(live_b)
        fits = has & ((kmax - kmin) < jnp.uint64(tbl))

        def direct(_):
            off = (bkey - kmin).astype(jnp.int64)
            tgt = jnp.where(live_b, jnp.clip(off, 0, tbl - 1), tbl)
            first = jnp.full(tbl, nb, jnp.int32).at[tgt].min(
                bidx, mode="drop")
            cnt = jnp.zeros(tbl, jnp.int32).at[tgt].add(1, mode="drop")
            poff = (pkey - kmin).astype(jnp.int64)
            pin = probe_live & (poff >= 0) & (poff < tbl)
            pc = jnp.clip(poff, 0, tbl - 1)
            c = jnp.where(pin, jnp.take(cnt, pc, mode="clip"), 0)
            lo_ = jnp.where(c > 0, jnp.take(first, pc, mode="clip"), 0)
            return lo_, lo_ + c

        def binsearch(_):
            return _probe_binary_search(
                build_words, build_count, probe_words, probe_live)

        return lax.cond(fits, direct, binsearch, operand=None)
    return _probe_binary_search(
        build_words, build_count, probe_words, probe_live)


def _probe_binary_search(
    build_words: Sequence[jax.Array],
    build_count: jax.Array,
    probe_words: Sequence[jax.Array],
    probe_live: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """General path: vectorized lexicographic binary search over the
    radix-sorted build words (build rows sorted live-first)."""
    build_words, probe_words = pad_key_words(build_words, probe_words)
    m = probe_words[0].shape[0]
    nb = build_words[0].shape[0]
    steps = max(1, (nb).bit_length())
    probe_idx = jnp.arange(m, dtype=jnp.int32)

    lo = jnp.zeros(m, jnp.int32)
    hi_l = jnp.broadcast_to(build_count.astype(jnp.int32), (m,))
    for _ in range(steps):
        mid = (lo + hi_l) // 2
        open_ = lo < hi_l  # never move on an empty interval
        # build[mid] < probe ? move lo up : move hi down
        lt, _ = _lex_less(build_words, probe_words, mid, probe_idx)
        lo = jnp.where(open_ & lt, mid + 1, lo)
        hi_l = jnp.where(open_ & ~lt, mid, hi_l)
    first = lo

    lo2 = jnp.zeros(m, jnp.int32)
    hi2 = jnp.broadcast_to(build_count.astype(jnp.int32), (m,))
    for _ in range(steps):
        mid = (lo2 + hi2) // 2
        open_ = lo2 < hi2
        # probe < build[mid] ? move hi down : move lo up
        lt, _ = _lex_less(probe_words, build_words, probe_idx, mid)
        lo2 = jnp.where(open_ & ~lt, mid + 1, lo2)
        hi2 = jnp.where(open_ & lt, mid, hi2)
    last = lo2

    first = jnp.where(probe_live, first, 0)
    last = jnp.where(probe_live, last, 0)
    return first, jnp.maximum(first, last)


# ---------------------------------------------------------------------------
# RADIX tier: co-sorted merge over the radix-binned build+probe order
# ---------------------------------------------------------------------------
def radix_probe_ranges(
    build_words: Sequence[jax.Array],
    build_count: jax.Array,
    probe_words: Sequence[jax.Array],
    probe_live: jax.Array,
    want_matched: bool = False,
    lo_matched_only: bool = False,
) -> Tuple[jax.Array, jax.Array, Optional[jax.Array]]:
    """[lo, hi) per probe row by ONE merge over the co-radix-sorted
    build+probe order — the RADIX join tier.

    The build side arrives already radix-sorted (joinable rows first,
    ``[0, build_count)``); probe rows carry the SAME order-preserving u32
    key words. One stable sort over the union (u64-packed key words;
    build rows concatenated first, so builds precede probes of an equal
    key by stability) makes every equal-key run contiguous, and the
    ranges fall out of segmented prefix sums over that order
    (ops/radix_bin.py's boundary-flag pattern, here over the whole plane
    instead of a tile window):

      * ``hi``  = running count of joinable build rows at the probe's
        position (builds of its run all precede it);
      * ``lo``  = that running count at the probe's RUN START, broadcast
        by a cumulative max over the boundary-flagged exclusive counts
        (:func:`radix_bin.segment_start_broadcast`);
      * ``matched`` (full outer, ``want_matched``) = a reverse segmented
        OR of live-probe presence: a build row matched iff a live probe
        follows it inside its run.

    A second sort by original slot restores row order (the scatter-free
    inverse permutation). No scatter instruction, no direct-address
    table, no log2(build) gather chain — every plane is
    O(build_cap + probe_cap) words, which IS the probe's layout bound.
    Bit-identical to :func:`_probe_binary_search` for every tier of
    torture input (null keys never match upstream via ``probe_live``;
    NaN==NaN and -0.0==0.0 are properties of the shared radix words).
    """
    from .radix_bin import segment_start_broadcast

    build_words, probe_words = pad_key_words(build_words, probe_words)
    nb = build_words[0].shape[0]
    m = probe_words[0].shape[0]
    n = nb + m
    bidx = jnp.arange(nb, dtype=jnp.int32)
    # key words pack in u64 PAIRS (half the key columns the comparator
    # walks). No side key and no park rank: the sort is STABLE and
    # build rows precede probe rows in the concatenation, so within an
    # equal-key run every build row lands before every probe row for
    # free — and dead/null rows may land wherever their garbage words
    # fall (their flags exclude them from every count and the caller
    # masks their outputs)
    packed: List[jax.Array] = []
    for i in range(0, len(build_words), 2):
        hi_w = jnp.concatenate([
            build_words[i].astype(jnp.uint64),
            probe_words[i].astype(jnp.uint64)]) << 32
        if i + 1 < len(build_words):
            hi_w = hi_w | jnp.concatenate([
                build_words[i + 1].astype(jnp.uint64),
                probe_words[i + 1].astype(jnp.uint64)])
        packed.append(hi_w)
    # original slot: build rows keep their build index, probe rows park
    # after them — doubling as the is-build discriminator (joinable
    # build rows are exactly slots < build_count: the build sort puts
    # them first) and as the unsort key
    slot = jnp.concatenate([bidx, nb + jnp.arange(m, dtype=jnp.int32)])
    sorted_all = lax.sort(packed + [slot], num_keys=len(packed),
                          is_stable=True)
    s_words = sorted_all[:len(packed)]
    s_slot = sorted_all[len(packed)]
    is_build = s_slot < build_count.astype(jnp.int32)
    # run boundaries: position 0, or any key word differing from the
    # previous row's
    pos = jnp.arange(n, dtype=jnp.int32)
    f = pos == 0
    for w in s_words:
        prev = jnp.concatenate([w[:1], w[:-1]])
        f = f | (w != prev)
    c_incl = jnp.cumsum(is_build.astype(jnp.int32))
    c_excl = c_incl - is_build.astype(jnp.int32)
    # lo = running build count at the run START (builds in earlier runs
    # = builds with a smaller key = the binary search's 'first');
    # hi = the running count AT the probe's own position — every build
    # of its run already precedes it (stability + concat order), so
    # this is the binary search's 'last' (builds with key <= probe key)
    lo_s = segment_start_broadcast(f, c_excl)
    hi_s = c_incl
    matched_s = None
    if want_matched:
        # reverse segmented suffix-OR of live-probe presence: a build
        # row matched iff a live probe follows it inside its run (all
        # of the run's probes DO follow it — stability again). Pack
        # (run id from the end, probe flag) so one cumulative max over
        # the REVERSED order is that suffix-OR
        seg = jnp.cumsum(f.astype(jnp.int32))
        h = (seg[-1] - seg).astype(jnp.int64)
        is_probe_live = (s_slot >= nb) & jnp.take(
            probe_live, jnp.clip(s_slot - nb, 0, m - 1), mode="clip")
        packed_m = h * 2 + is_probe_live.astype(jnp.int64)
        rmax = jnp.flip(lax.cummax(jnp.flip(packed_m)))
        matched_s = is_build & ((rmax & 1) == 1) & (rmax // 2 == h)
    # unsort: one sort by original slot (builds land at [0, nb), probes
    # at [nb, nb+m)) — the scatter-free inverse permutation; slots are
    # unique, so stability is again irrelevant
    if lo_matched_only:
        # fused-probe variant (exec/join.lower_batch): the caller only
        # consumes (lo, matched) for its single-build-row gather, so lo
        # and the matched bit pack into ONE unsort payload — a third of
        # the payload bytes
        # NOTE: the returned hi is lo + the MATCH BIT (not the true run
        # end) — callers on this path either need only membership
        # (semi/anti) or have a uniqueness guarantee (inner/left)
        packed_lm = (lo_s << 1) | (hi_s > lo_s).astype(jnp.int32)
        back = lax.sort([s_slot, packed_lm], num_keys=1, is_stable=False)
        plm = back[1][nb:]
        lo = jnp.where(probe_live, plm >> 1, 0)
        matched = probe_live & ((plm & 1) == 1)
        return lo, jnp.where(matched, lo + 1, lo), None
    outs = [s_slot, lo_s, hi_s]
    if want_matched:
        outs.append(matched_s.astype(jnp.int32))
    back = lax.sort(outs, num_keys=1, is_stable=False)
    lo = back[1][nb:]
    hi = back[2][nb:]
    # unmatched live rows report their insertion point (lo == hi), the
    # exact value the binary search returns — bit-identity holds on the
    # whole (lo, hi) surface, not just matched rows
    lo = jnp.where(probe_live, lo, 0)
    hi = jnp.where(probe_live, hi, 0)
    matched = (back[3][:nb] > 0) if want_matched else None
    return lo, jnp.maximum(lo, hi), matched


def radix_expansion_plan(
    counts: jax.Array, lo: jax.Array, out_cap: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scatter-free :func:`expansion_plan` for the RADIX tier: the probe
    row of output slot j is a searchsorted over the count prefix sums
    (log2(probe) compare/gather passes — vs jnp.repeat's scatter+cumsum,
    which would put the one scatter family right back into the zero-
    scatter tier). Same (probe_row, build_row, slot_live) contract and
    the same output order: probe rows ascending, match ordinals ascending
    within a probe row."""
    counts = counts.astype(jnp.int32)
    csum = jnp.cumsum(counts)
    total = csum[-1]
    starts = csum - counts
    j = jnp.arange(out_cap, dtype=jnp.int32)
    p = jnp.searchsorted(csum, j, side="right").astype(jnp.int32)
    pc = jnp.clip(p, 0, counts.shape[0] - 1)
    ordinal = j - jnp.take(starts, pc, mode="clip")
    build_row = jnp.take(lo, pc, mode="clip").astype(jnp.int32) + ordinal
    slot_live = j < total
    return pc, jnp.where(slot_live, build_row, 0), slot_live


def expansion_plan(
    counts: jax.Array, lo: jax.Array, out_cap: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(probe_row, build_row, slot_live) for each output slot j.

    counts/lo are per-probe-row; out_cap is the static output bucket
    (>= total matches, chosen by the caller after syncing the total).

    Built with two jnp.repeat passes (scatter+cumsum under the hood) — the
    obvious searchsorted over the count prefix sums costs log2(out_cap)
    gather passes, ~20x slower on TPU."""
    counts = counts.astype(jnp.int64)
    m = counts.shape[0]
    csum = jnp.cumsum(counts)
    total = csum[-1]
    starts = csum - counts  # output offset of each probe row
    j = jnp.arange(out_cap, dtype=jnp.int64)
    p = jnp.repeat(
        jnp.arange(m, dtype=jnp.int32), counts, total_repeat_length=out_cap)
    # pack (start, lo) so one more repeat recovers both
    packed = (starts << 31) | lo.astype(jnp.int64)
    rep = jnp.repeat(packed, counts, total_repeat_length=out_cap)
    ordinal = j - (rep >> 31)
    build_row = (rep & ((1 << 31) - 1)).astype(jnp.int32) + ordinal.astype(
        jnp.int32)
    slot_live = j < total
    return p, build_row, slot_live


def matched_build_mask(
    lo: jax.Array, hi: jax.Array, probe_live: jax.Array, build_cap: int
) -> jax.Array:
    """Which build rows matched at least one probe row (for full outer).

    Ranges for equal keys are identical, so a +1/-1 difference array over
    range endpoints and a prefix sum marks exactly the covered rows."""
    delta = jnp.zeros(build_cap + 1, jnp.int32)
    lo_m = jnp.where(probe_live & (hi > lo), lo, build_cap)
    hi_m = jnp.where(probe_live & (hi > lo), hi, build_cap)
    delta = delta.at[lo_m].add(1, mode="drop")
    delta = delta.at[hi_m].add(-1, mode="drop")
    return jnp.cumsum(delta[:-1]) > 0
