"""Radix-binned tiled groupby reduction — the RADIX aggregation lowering.

The cost plane (BENCH_r09 + hlo.py) proved the aggregation hot path
touches ~25x its logical working set: the one-hot expansion prices the
reduce in materialized (rows x buckets) bytes, the scatter lowering in
near-serial per-row updates (and, on the CPU dialect, a while-loop whose
full-width accumulator XLA charges per instruction). This module is the
rewrite: rows are ordered once by their radix key digits (the same
order-preserving u32/u64 words the sort machinery builds —
ops/sort.sort_with_radix_keys IS the multi-pass radix binning), then ONE
``lax.fori_loop`` walks the binned order in HBM-resident tiles sized
from the static layout. EVERYTHING per-row beyond the sort happens
inside that loop on tile-sized temporaries: the raw value columns are
gathered one tile at a time and the reduction streams (limb-free sums,
the float stream split, winner words) are BUILT IN THE TILE — no
cap-sized derived array ever materializes, which is precisely where the
first cut of this lowering still paid ~3x the layout bound. Boundary
flags likewise derive per tile from the sliced sorted key words (plus
one carried word per key), and the per-segment results are written
exactly once into the output buffer through a sliding window whose
boundary segment rides the loop carry. No one-hot is ever built and no
scatter instruction is ever emitted.

Reduction families, all scatter-free:

  * sums/counts (AddSpec): per-tile prefix-sum differences at the
    segment boundaries (integer sums wrap mod 2^64 exactly like native
    adds — BIT-identical to the scatter/matmul lowerings);
  * float sums: split per row (IN the tile) into a NORMAL stream (f64
    accumulated by a SEGMENTED scan that resets at every segment
    boundary, so one group's magnitude can never absorb a neighbouring
    group's sum), a BIG stream (|x| > 2^500 scaled down by
    2^-600 — exact power-of-two scaling — so giant magnitudes cannot
    annihilate the prefix's low bits, rescaled after the reduce), and
    per-segment +inf/-inf/NaN presence FLAGS (an OR stream whose
    21-bit-lane tile sums saturate to 3 presence bits in a ONE-BYTE
    output buffer), recombined with IEEE semantics (any NaN or mixed
    infinities -> NaN, else the surviving infinity, else
    normal + big * 2^600). Order-insensitive like the matmul hi/lo
    split, but in native f64 — strictly tighter;
  * min/max (MinMaxSpec): WINNER-ROW streams — the tile-built order
    word is the sort machinery's total-order radix encoding (so Spark's
    NaN-largest / -0.0 == 0.0 rules fall out and all-NaN groups
    naturally win a NaN row), the per-tile winner comes from one
    tile-local secondary sort, and only the winning ROW index is
    materialized — the value is gathered once at the end;
  * first/last and the group-representative row (PosSpec): SORT-FREE.
    The radix sort is stable with dead rows last, so within a segment
    rows appear in ascending ORIGINAL order — first/last considered is
    a per-segment min/max POSITION, computed as one cumulative-max over
    a (segment, position) packing, no order word and no in-tile sort.

The flush tile: the loop runs ceil(cap/tile)+1 trips; the final trip
carries no live rows and exists solely to write the last open segment's
partial through the normal window path, so the body has no conditionals.

Zero new dependencies; everything lowers to sort/slice/cumsum/gather.
"""
from __future__ import annotations

from typing import Callable, List, NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax

#: test hook: force the tile row count (0 = derive from the layout).
#: Lets tests drive multi-tile paths (incl. the flush tile and non-
#: divisible caps) on small inputs. Must stay <= 2^20 so the saturating
#: flag fields below cannot overflow their 21-bit lanes (and the
#: PosSpec position packing its u64).
FORCE_TILE_ROWS = 0

#: |x| above this routes a float row through the scaled BIG stream
F64_BIG = 2.0 ** 500
#: exact power-of-two scaling for the BIG stream (scaling is lossless;
#: the rescale may overflow to inf, which is the mathematically correct
#: sum in that case)
BIG_SCALE_DOWN = 2.0 ** -600
BIG_SCALE_UP = 2.0 ** 600

#: OR-stream field layout: three 21-bit per-tile count lanes (+inf,
#: -inf, NaN). A tile holds < 2^20 rows, so a lane can never carry into
#: its neighbor before the per-tile saturation back to presence bits.
#: Plain python ints (not jnp scalars): the module is lazily imported,
#: possibly inside a jit trace, where a module-scope jnp constant would
#: be born a tracer and leak into every later trace.
_FLAG_LANE = 21
_FLAG_MASK = (1 << _FLAG_LANE) - 1

_U64_MAX = 0xFFFFFFFFFFFFFFFF


def default_tile_rows(cap: int, n_streams: int) -> int:
    """Tile rows sized from the static layout: the loop body's working
    set (streams + the winner sorts' key copies) should sit in fast
    memory (~1 MiB target — VMEM-scale on TPU, L2-scale on the CPU
    fallback), clamped to [2^12, 2^16] and never above the capacity
    bucket."""
    if FORCE_TILE_ROWS:
        return min(FORCE_TILE_ROWS, 1 << 20)
    per_row = max(16, 8 * max(1, n_streams))
    t = max(2, (1 << 20) // per_row)
    t = 1 << max(12, min(16, t.bit_length() - 1))
    while t > cap and t > 8:
        t >>= 1
    return max(8, t)


class TileCtx:
    """Per-tile gather context handed to every stream builder: ``take``
    gathers an ORIGINAL-row-order array at this tile's sorted rows.
    Builders that share a raw column produce syntactically identical
    gathers, which XLA CSE collapses to one — the reason builders close
    over raw columns instead of pre-materializing cap-sized streams."""

    __slots__ = ("p_t",)

    def __init__(self, p_t: jax.Array):
        self.p_t = p_t

    def take(self, arr: jax.Array) -> jax.Array:
        return jnp.take(arr, self.p_t, mode="clip")


class AddSpec(NamedTuple):
    """One additive stream: ``build(ctx)`` returns the (tile,) values
    (already zeroed at rows that must not contribute), ``dtype`` the
    accumulation family (uint64 / uint32 / float64). ``is_or`` marks a
    21-bit-lane flag stream (uint64 build dtype) that combines by
    per-tile saturation + bitwise OR and outputs 3 presence bits."""

    build: Callable[[TileCtx], jax.Array]
    dtype: object
    is_or: bool = False


class MinMaxSpec(NamedTuple):
    """One winner-row reduction ordered by a total-order word:
    ``word(ctx)`` is the (tile,) uint64 key (identity — u64 max for
    min, 0 for max — at non-considered rows), ``cons(ctx)`` the
    considered mask — carried explicitly because a considered value's
    word can legitimately EQUAL the identity (int64.max under min), so
    identity-matching alone cannot distinguish "no considered row" from
    "the extreme value won"."""

    word: Callable[[TileCtx], jax.Array]
    cons: Callable[[TileCtx], jax.Array]
    op: str


class PosSpec(NamedTuple):
    """First ('min') / last ('max') considered row per segment. The
    stable sort makes sorted position order == original row order
    within a segment, so the winner is a positional extremum — no order
    word, no in-tile sort."""

    cons: Callable[[TileCtx], jax.Array]
    op: str


class SegmentedOutputs(NamedTuple):
    u64: List[jax.Array]        # per non-or uint64 AddSpec, (cap,) u64
    u32: List[jax.Array]        # per uint32 AddSpec, (cap,) uint32
    f64: List[jax.Array]        # per float64 AddSpec, (cap,) float64
    flags: List[jax.Array]      # per OR AddSpec, (cap,) uint8 presence
    pos_rows: List[jax.Array]   # per PosSpec, (cap,) i32 (-1 = empty)
    winner_rows: List[jax.Array]  # per MinMaxSpec, (cap,) i32 (-1 = empty)
    nseg: jax.Array             # int32 device scalar


def _tile_diffs(stacked: jax.Array, bounds: jax.Array) -> jax.Array:
    """Per-local-segment sums of a (tile, K) stack over NONDECREASING
    local segment ids, as prefix differences at ``bounds`` (B_local+1,).
    EXACT for the modular integer families (differences of wrapped
    prefixes equal the wrapped segment sum); floats use
    :func:`_tile_segment_sums` instead — a cross-segment float prefix
    lets one segment's magnitude absorb its neighbours' sums."""
    c = jnp.cumsum(stacked, axis=0)
    padded = jnp.concatenate(
        [jnp.zeros((1, stacked.shape[1]), stacked.dtype), c])
    lo, hi = bounds[:-1], bounds[1:]
    return (jnp.take(padded, hi, axis=0, mode="clip")
            - jnp.take(padded, lo, axis=0, mode="clip"))


def _tile_segment_sums(stacked: jax.Array, starts: jax.Array,
                       bounds: jax.Array) -> jax.Array:
    """Per-local-segment FLOAT sums of a (tile, K) stack: a segmented
    associative scan whose running sum RESETS at every segment start
    (``starts``, the per-row boundary flags), read at each segment's
    last row. Accumulation therefore never crosses a segment boundary —
    group A's 1e30 cannot cancel group B's 6.0 the way a tile-wide
    prefix difference would (the rounding class is a per-group tree
    sum, the variableFloatAgg contract)."""
    flags = jnp.broadcast_to(starts[:, None], stacked.shape)

    def comb(a, b):
        af, av = a
        bf, bv = b
        return af | bf, bv + jnp.where(bf, jnp.zeros((), stacked.dtype),
                                       av)

    _, pref = lax.associative_scan(comb, (flags, stacked), axis=0)
    lo, hi = bounds[:-1], bounds[1:]
    out = jnp.take(pref, jnp.maximum(hi - 1, 0), axis=0, mode="clip")
    return jnp.where((hi > lo)[:, None], out,
                     jnp.zeros((), stacked.dtype))


def _saturate_flags(x: jax.Array) -> jax.Array:
    """Collapse the three 21-bit per-tile count lanes of an OR stream
    back to presence bits 0/1/2 (a uint8)."""
    p = (x & _FLAG_MASK) > 0
    m = ((x >> _FLAG_LANE) & _FLAG_MASK) > 0
    q = (x >> (2 * _FLAG_LANE)) > 0
    return (p.astype(jnp.uint8) | (m.astype(jnp.uint8) << 1)
            | (q.astype(jnp.uint8) << 2))


def tiled_segment_groupby(
    perm: jax.Array,
    sorted_words: Sequence[jax.Array],
    live_in: jax.Array,
    adds: Sequence[AddSpec] = (),
    pos: Sequence[PosSpec] = (),
    winners: Sequence[MinMaxSpec] = (),
    tile_rows: int = 0,
) -> SegmentedOutputs:
    """Reduce every stream per segment of the radix-sorted order, one
    HBM-resident tile at a time.

    ``perm``/``sorted_words``: the radix sort's permutation and
    co-sorted key words (dead rows sort LAST — the pad_rank leading key
    contract of ops/sort.sort_with_radix_keys). ``live_in`` is the
    liveness mask in ORIGINAL row order. Stream builders receive a
    :class:`TileCtx` and return tile-local values; additive builders
    must already hold their identity (0) at rows that must not
    contribute — dead rows are dropped structurally.

    Outputs are segment-compacted to the front at the input capacity;
    segment order is the sorted key order (ascending radix words).
    """
    cap = perm.shape[0]
    u64_specs = [s for s in adds if s.dtype == jnp.uint64 and not s.is_or]
    u32_specs = [s for s in adds if s.dtype == jnp.uint32]
    f64_specs = [s for s in adds if s.dtype == jnp.float64]
    or_specs = [s for s in adds if s.is_or]
    n_streams = (len(adds) + len(pos) + 2 * len(winners))
    tile = min(tile_rows or default_tile_rows(cap, n_streams), max(8, cap))
    BL = tile + 1
    trips = -(-cap // tile) + 1  # +1 flush trip writes the final open seg
    w_is_min = [w.op == "min" for w in winners]
    p_is_min = [p.op == "min" for p in pos]

    iota_bl = jnp.arange(BL + 1, dtype=jnp.int32)
    row_ids = jnp.arange(tile, dtype=jnp.int32)
    # PosSpec packing: seg * PACK + payload, payload in [0, BL] — u64 so
    # tile <= 2^20 can never overflow (BL^2 < 2^42)
    PACK = jnp.uint64(BL + 1)

    def body(t, carry):
        (S, prev_ok, prev_w, cu64, cu32, cf64, cflag, cpr, cww, cwr,
         b_u64, b_u32, b_f64, b_flag, b_pos, b_wrow) = carry
        start = t * tile
        pos_ok = (start + row_ids) < cap
        p_t = lax.dynamic_slice(perm, (start,), (tile,))
        ctx = TileCtx(p_t)
        lv_t = jnp.where(pos_ok, ctx.take(live_in), False)
        # boundary flags IN the tile: a live row starts a segment when
        # any sorted key word differs from the previous row's (the
        # previous tile's last word rides the carry; prev_ok is False
        # only on trip 0, where the first live row always starts one)
        w_ts = [jnp.where(
            pos_ok, lax.dynamic_slice(w, (start,), (tile,)),
            jnp.zeros((), w.dtype)) for w in sorted_words]
        diff = jnp.zeros(tile, jnp.bool_)
        for i, w_t in enumerate(w_ts):
            prev_col = jnp.concatenate(
                [prev_w[i][None].astype(w_t.dtype), w_t[:-1]])
            diff = diff | (w_t != prev_col)
        at0 = row_ids == 0
        diff = jnp.where(at0 & ~prev_ok, True, diff)
        f_t = lv_t & diff
        csum = jnp.cumsum(f_t.astype(jnp.int32))
        s_open = (S > 0).astype(jnp.int32)
        seg_local = jnp.where(lv_t, csum - 1 + s_open, BL)
        n_new = csum[-1]
        last_local = jnp.max(jnp.where(lv_t, seg_local, 0))
        w_base = jnp.maximum(S - 1, 0)
        bounds = jnp.searchsorted(seg_local, iota_bl, side="left")
        lo, hi = bounds[:-1], bounds[1:]
        present = hi > lo
        # the last local segment stays open (it may continue into the
        # next tile) and rides the carry instead of being written —
        # except on the flush trip, which exists precisely to write it
        is_flush = t == trips - 1
        keep1 = (jnp.arange(BL, dtype=jnp.int32) != last_local) | is_flush
        keep = keep1[:, None]

        def family(specs, dtype, cprev, buf, saturate):
            if not specs:
                return cprev, buf
            cols = [jnp.where(lv_t, s.build(ctx), jnp.zeros((), dtype))
                    for s in specs]
            stacked = jnp.stack(cols, axis=-1)
            if dtype == jnp.float64:
                # floats must not share a prefix across segments (one
                # group's magnitude would absorb its neighbours');
                # integers wrap mod 2^n, where prefix differences ARE
                # the segment sums
                part = _tile_segment_sums(stacked, f_t, bounds)
            else:
                part = _tile_diffs(stacked, bounds)
            if saturate:
                # flag streams summed per tile in 21-bit lanes: saturate
                # to presence bits, then OR across tile boundaries
                part = _saturate_flags(part)
                comb = cprev | part[0]
            else:
                comb = cprev + part[0]
            row0 = jnp.arange(part.shape[0],
                              dtype=jnp.int32)[:, None] == 0
            part = jnp.where(row0, comb[None, :], part)
            c_out = part[last_local]
            part = jnp.where(keep, part, jnp.zeros((), part.dtype))
            buf = lax.dynamic_update_slice(buf, part,
                                           (w_base, jnp.int32(0)))
            return c_out, buf

        cu64, b_u64 = family(u64_specs, jnp.uint64, cu64, b_u64, False)
        cu32, b_u32 = family(u32_specs, jnp.uint32, cu32, b_u32, False)
        cf64, b_f64 = family(f64_specs, jnp.float64, cf64, b_f64, False)
        cflag, b_flag = family(or_specs, jnp.uint64, cflag, b_flag, True)

        if pos:
            npr = []
            for i, spec in enumerate(pos):
                cons_t = spec.cons(ctx) & lv_t
                if p_is_min[i]:
                    # first considered = smallest position: pack as
                    # BL - position so one cumulative MAX finds it (the
                    # nondecreasing seg prefix makes later segments
                    # dominate earlier ones)
                    pay = jnp.where(cons_t,
                                    jnp.uint64(BL) - row_ids.astype(
                                        jnp.uint64),
                                    jnp.uint64(0))
                else:
                    pay = jnp.where(cons_t,
                                    row_ids.astype(jnp.uint64) + 1,
                                    jnp.uint64(0))
                enc = (jnp.minimum(seg_local, BL).astype(jnp.uint64)
                       * PACK + pay)
                cmax = lax.cummax(enc)
                at_end = jnp.take(cmax, jnp.maximum(hi - 1, 0),
                                  mode="clip")
                pay_end = at_end % PACK
                found = present & (pay_end > 0)
                ppos = jnp.where(
                    p_is_min[i],
                    jnp.uint64(BL) - jnp.maximum(pay_end, 1),
                    jnp.maximum(pay_end, 1) - 1).astype(jnp.int32)
                rw = jnp.where(
                    found,
                    jnp.take(p_t, jnp.clip(ppos, 0, tile - 1),
                             mode="clip"),
                    -1)
                # open-segment carry: for 'first' an earlier tile's hit
                # is earlier in sorted (== original) order and always
                # wins; for 'last' the current tile's hit wins. Masked
                # select on local segment 0, never .at[0].set (a
                # single-element DUS in the body reads as scatter)
                cr = cpr[i]
                take_c = (cr >= 0) & (p_is_min[i] | (rw[0] < 0))
                bl0 = jnp.arange(BL, dtype=jnp.int32) == 0
                rw = jnp.where(bl0 & take_c, cr, rw)
                npr.append(rw[last_local])
                rw = jnp.where(keep1, rw, -1)
                b_pos = lax.dynamic_update_slice(
                    b_pos, rw[:, None], (w_base, jnp.int32(i)))
            cpr = jnp.stack(npr)

        if winners:
            nww, nwr = [], []
            for i, spec in enumerate(winners):
                cons_t = spec.cons(ctx) & lv_t
                ident = jnp.uint64(_U64_MAX if w_is_min[i] else 0)
                word_t = jnp.where(cons_t, spec.word(ctx), ident)
                # one tile-local secondary sort: within each segment the
                # winner sits at the first (min) / last (max) position.
                # Considered rows sort toward the winner position (the
                # crank key) so an identity-word collision — int64.max
                # under min radix-encodes to the identity — can never
                # let a non-considered row shadow a real winner.
                crank = (~cons_t if w_is_min[i] else cons_t).astype(
                    jnp.uint32)
                _, _, sword, sperm, scons = lax.sort(
                    [seg_local, crank, word_t, p_t,
                     cons_t.astype(jnp.uint32)],
                    num_keys=3, is_stable=True)
                wpos = lo if w_is_min[i] else jnp.maximum(hi - 1, 0)
                wd = jnp.where(present,
                               jnp.take(sword, wpos, mode="clip"), ident)
                won = present & (jnp.take(scons, wpos, mode="clip") > 0)
                rw = jnp.where(won, jnp.take(sperm, wpos, mode="clip"),
                               -1)
                # combine the open segment (local 0) with the carry
                # pair; cr < 0 marks "no considered row yet" and never
                # wins, and an empty current winner yields to a carry
                cw, cr = cww[i], cwr[i]
                better = (cw <= wd[0]) if w_is_min[i] else (cw >= wd[0])
                take_c = (cr >= 0) & (better | (rw[0] < 0))
                # masked selects, not .at[0].set — a single-element
                # dynamic-update-slice inside the while body is exactly
                # the CPU scatter-emulation signature the hlo.py
                # classifier hunts, and this loop must never read as one
                bl0 = jnp.arange(BL, dtype=jnp.int32) == 0
                wd = jnp.where(bl0 & take_c, cw, wd)
                rw = jnp.where(bl0 & take_c, cr, rw)
                nww.append(wd[last_local])
                nwr.append(rw[last_local])
                rw = jnp.where(keep1, rw, -1)
                b_wrow = lax.dynamic_update_slice(
                    b_wrow, rw[:, None], (w_base, jnp.int32(i)))
            cww, cwr = jnp.stack(nww), jnp.stack(nwr)

        new_prev_w = tuple(w_t[-1] for w_t in w_ts)
        return (S + n_new, jnp.bool_(True), new_prev_w,
                cu64, cu32, cf64, cflag, cpr, cww, cwr,
                b_u64, b_u32, b_f64, b_flag, b_pos, b_wrow)

    init = (
        jnp.int32(0),
        jnp.bool_(False),
        tuple(jnp.zeros((), w.dtype) for w in sorted_words),
        jnp.zeros(max(1, len(u64_specs)), jnp.uint64),
        jnp.zeros(max(1, len(u32_specs)), jnp.uint32),
        jnp.zeros(max(1, len(f64_specs)), jnp.float64),
        jnp.zeros(max(1, len(or_specs)), jnp.uint8),
        jnp.full(max(1, len(pos)), -1, jnp.int32),
        (jnp.asarray([_U64_MAX if m else 0 for m in w_is_min],
                     jnp.uint64)
         if winners else jnp.zeros(1, jnp.uint64)),
        jnp.full(max(1, len(winners)), -1, jnp.int32),
        jnp.zeros((cap + BL, max(1, len(u64_specs))), jnp.uint64),
        jnp.zeros((cap + BL, max(1, len(u32_specs))), jnp.uint32),
        jnp.zeros((cap + BL, max(1, len(f64_specs))), jnp.float64),
        jnp.zeros((cap + BL, max(1, len(or_specs))), jnp.uint8),
        jnp.full((cap + BL, max(1, len(pos))), -1, jnp.int32),
        jnp.full((cap + BL, max(1, len(winners))), -1, jnp.int32),
    )
    (S, _, _, _, _, _, _, _, _, _,
     b_u64, b_u32, b_f64, b_flag, b_pos, b_wrow) = lax.fori_loop(
        0, trips, body, init)
    return SegmentedOutputs(
        u64=[b_u64[:cap, i] for i in range(len(u64_specs))],
        u32=[b_u32[:cap, i] for i in range(len(u32_specs))],
        f64=[b_f64[:cap, i] for i in range(len(f64_specs))],
        flags=[b_flag[:cap, i] for i in range(len(or_specs))],
        pos_rows=[b_pos[:cap, i] for i in range(len(pos))],
        winner_rows=[b_wrow[:cap, i] for i in range(len(winners))],
        nseg=S,
    )


# ---------------------------------------------------------------------------
# shared segmented-scan helpers (the RADIX join tier's co-sorted merge in
# ops/join.py reuses the same boundary-flag machinery this module's tile
# loop is built from)
# ---------------------------------------------------------------------------
def segment_start_broadcast(flags: jax.Array,
                            values: jax.Array) -> jax.Array:
    """Broadcast ``values`` at segment-start positions (``flags``) to
    every later row of the segment, via one cumulative max — valid
    whenever the flagged values are NONDECREASING across segment starts
    (true for any prefix-sum-derived stream over a sorted order, e.g.
    the join merge's running build counts). Rows before the first flag
    report -1."""
    marked = jnp.where(flags, values.astype(jnp.int32), -1)
    return lax.cummax(marked)


# ---------------------------------------------------------------------------
# tile-local stream pieces (used by the groupby plan builder's closures)
# ---------------------------------------------------------------------------
def float_sum_streams(data, consider):
    """(normal, big, flag_fields) streams for one float column — tile-
    local when called from an AddSpec builder (the intended use), but
    shape-polymorphic.

    normal: plain finite values (|x| <= 2^500), identity elsewhere;
    big: huge finite values scaled by 2^-600 (exact), identity elsewhere;
    flag_fields: 21-bit-lane counts (+inf at bit 0, -inf at bit 21, NaN
    at bit 42) — an OR stream for :func:`tiled_segment_groupby`.
    """
    d = jnp.where(consider, data, 0.0).astype(jnp.float64)
    isnan = d != d
    ispinf = d == jnp.inf
    isninf = d == -jnp.inf
    finite = jnp.isfinite(d)
    big = finite & (jnp.abs(d) > F64_BIG)
    normal = jnp.where(finite & ~big, d, 0.0)
    bigs = jnp.where(big, d * BIG_SCALE_DOWN, 0.0)
    fields = (ispinf.astype(jnp.uint64)
              | (isninf.astype(jnp.uint64) << _FLAG_LANE)
              | (isnan.astype(jnp.uint64) << (2 * _FLAG_LANE)))
    return normal, bigs, fields


def combine_float_sum(normal: jax.Array, big: jax.Array,
                      flags: jax.Array) -> jax.Array:
    """Recombine one float column's per-segment streams with IEEE
    semantics: NaN (or mixed infinities) dominates, then the surviving
    infinity, else normal + big * 2^600 (which may itself overflow to
    the mathematically correct infinity). ``flags`` is the (cap,) uint8
    presence output of the OR stream."""
    p = (flags & jnp.uint8(1)) != 0
    m = (flags & jnp.uint8(2)) != 0
    q = (flags & jnp.uint8(4)) != 0
    s = normal + big * BIG_SCALE_UP
    r = jnp.where(p, jnp.inf, jnp.where(m, -jnp.inf, s))
    return jnp.where(q | (p & m), jnp.nan, r)


def order_word(col_data: jax.Array, consider: jax.Array, dtype,
               op: str) -> jax.Array:
    """Total-order uint64 word for a min/max winner stream: the sort
    machinery's order-preserving radix encoding (Spark NaN-largest,
    -0.0 == 0.0), with the op's identity at non-considered rows.
    Elementwise, so MinMaxSpec builders call it on tile slices."""
    from ..expr.eval import ColV
    from .sort import SortOrder, fixed_radix_keys

    _, vk = fixed_radix_keys(
        ColV(col_data, consider), dtype, SortOrder(True, True))
    w = vk.astype(jnp.uint64)
    ident = jnp.uint64(_U64_MAX if op == "min" else 0)
    return jnp.where(consider, w, ident)
