"""String kernels over Arrow offsets+chars columns, XLA-native.

Reference analog: the cudf string kernels consumed by
sql-plugin/.../sql/rapids/stringFunctions.scala (substring, locate, concat,
pad, replace, LIKE, trim, case mapping) and GpuCast.scala's string casts.
cudf implements these as per-row CUDA kernels over the same Arrow layout
(offsets int32 + chars uint8). There is no cudf on TPU, so this module
re-designs each operation as a *static-shape, whole-column* XLA program:

  * per-byte row ids via vectorized searchsorted over the offsets array;
  * per-row reductions (first mismatch, first non-space, match counts) via
    segment_min/segment_sum with sorted segment ids;
  * pattern search as a shifted-compare over the whole chars buffer with a
    static unroll over the (literal) pattern bytes;
  * ragged outputs built by one gather pass over the output byte space
    (out position -> source position), never per-row Python.

Everything here traces inside the engine's single fused projection jit
(expr/eval.py), so XLA fuses string predicates with the surrounding
arithmetic — there is no kernel-per-op dispatch like the CUDA path.

UTF-8: Spark compares strings as unsigned bytes (UTF8String.compareTo) and
indexes by *character*; both are honored — byte-wise compares, and char
indexing via a cumsum over non-continuation bytes. Case mapping covers
code points < 0x250 (ASCII + Latin supplements, the byte-length-preserving
range); beyond that bytes pass through unchanged (documented incompat, like
the reference's GpuInitCap incompatibility notes).
"""
from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..expr.values import StrV as Str  # (offsets, chars, validity)
from .filter_gather import (  # noqa: F401  (re-exports)
    piecewise_by_row,
    rows_of_positions,
)

BIG = np.int32(2**31 - 1)


# ---------------------------------------------------------------------------
# layout primitives
# ---------------------------------------------------------------------------
def byte_lens(offsets: jax.Array) -> jax.Array:
    return offsets[1:] - offsets[:-1]


def row_ids(offsets: jax.Array, nbytes: int) -> jax.Array:
    """Row id per byte position of the chars buffer (padding bytes clamp to
    the last row; callers mask with ``in_data``). One scatter + cumsum
    (see filter_gather.rows_of_positions for why not searchsorted)."""
    from .filter_gather import rows_of_positions

    return rows_of_positions(offsets, nbytes)


def char_starts(chars: jax.Array, total: jax.Array) -> jax.Array:
    """True at bytes that begin a UTF-8 code point, False past ``total``."""
    n = chars.shape[0]
    in_data = jnp.arange(n, dtype=jnp.int32) < total
    return ((chars & 0xC0) != 0x80) & in_data


def char_prefix(chars: jax.Array, total: jax.Array) -> jax.Array:
    """(nbytes+1,) exclusive prefix count of char-start bytes: the number of
    characters strictly before byte p is ``char_prefix[p]``."""
    starts = char_starts(chars, total)
    return jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(starts.astype(jnp.int32))]
    )


def char_positions(chars: jax.Array, total: jax.Array) -> jax.Array:
    """(nbytes,) map char ordinal -> byte position of that char's first byte.

    Built with a scatter: start byte p has ordinal char_prefix[p]; unused
    slots hold ``total`` so out-of-range ordinals land at the data end.
    """
    n = chars.shape[0]
    starts = char_starts(chars, total)
    cp = char_prefix(chars, total)
    pos = jnp.arange(n, dtype=jnp.int32)
    tgt = jnp.where(starts, cp[:-1], n)  # out-of-bounds -> dropped
    return (
        jnp.full(n, total, dtype=jnp.int32).at[tgt].set(pos, mode="drop")
    )


def all_ascii(chars: jax.Array, total) -> jax.Array:
    """True when no byte in [0, total) has the high bit set. Gates the
    lax.cond ASCII fast paths: char==byte turns the UTF-8 cumsum/scatter
    machinery into pure arithmetic, and XLA executes only the taken
    branch."""
    n = chars.shape[0]
    hi = (chars >= 0x80) & (jnp.arange(n, dtype=jnp.int32) < total)
    return ~jnp.any(hi)


def char_counts(s: Str) -> jax.Array:
    """Per-row character counts (Spark length())."""
    total = s.offsets[-1]
    lens = byte_lens(s.offsets)

    def fast(_):
        return lens

    def full(_):
        cp = char_prefix(s.chars, total)
        return cp[s.offsets[1:]] - cp[s.offsets[:-1]]

    return jax.lax.cond(all_ascii(s.chars, total), fast, full, operand=None)


def char_to_byte(s: Str, char_idx: jax.Array) -> jax.Array:
    """Per-row: byte position of character ``char_idx`` (0-based within the
    row), clamped to the row end for out-of-range ordinals."""
    total = s.offsets[-1]
    lens = byte_lens(s.offsets)

    def fast(_):
        k = jnp.clip(char_idx, 0, lens)
        return (s.offsets[:-1] + k).astype(jnp.int32)

    def full(_):
        cp = char_prefix(s.chars, total)
        pos = char_positions(s.chars, total)
        nchars = cp[s.offsets[1:]] - cp[s.offsets[:-1]]
        first = cp[s.offsets[:-1]]
        k = jnp.clip(char_idx, 0, nchars)
        n = s.chars.shape[0]
        raw = pos[jnp.clip(first + k, 0, n - 1)]
        return jnp.where(k >= nchars, s.offsets[1:], raw).astype(jnp.int32)

    return jax.lax.cond(all_ascii(s.chars, total), fast, full, operand=None)


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------
def compare(a: Str, b: Str) -> Tuple[jax.Array, jax.Array]:
    """(lt, eq) per row — unsigned byte-wise, Spark UTF8String.compareTo.

    One aligned-gather pass over a's chars buffer: byte j of a is matched
    with the byte at the same within-row position of b, the first mismatch
    per row found with segment_min, then a single byte compare decides.
    """
    cap = a.offsets.shape[0] - 1
    na, nb = a.chars.shape[0], b.chars.shape[0]
    la, lb = byte_lens(a.offsets), byte_lens(b.offsets)
    rid = row_ids(a.offsets, na)
    pos = jnp.arange(na, dtype=jnp.int32)
    within = pos - a.offsets[rid]
    common = jnp.minimum(la, lb)[rid]
    in_cmp = (within < common) & (pos < a.offsets[-1])
    bb = b.chars[jnp.clip(b.offsets[rid] + within, 0, nb - 1)]
    mism = in_cmp & (a.chars != bb)
    first = jax.ops.segment_min(
        jnp.where(mism, within, BIG), rid, num_segments=cap,
        indices_are_sorted=True,
    )
    has = first < BIG
    av = a.chars[jnp.clip(a.offsets[:-1] + first, 0, na - 1)]
    bv = b.chars[jnp.clip(b.offsets[:-1] + first, 0, nb - 1)]
    lt = jnp.where(has, av < bv, la < lb)
    eq = ~has & (la == lb)
    return lt, eq


def equals_literal(s: Str, lit: bytes) -> jax.Array:
    """Per-row equality against a host-side literal (string IN lists)."""
    lens = byte_lens(s.offsets)
    if len(lit) == 0:
        return lens == 0
    m = find_matches(s.chars, lit)
    n = s.chars.shape[0]
    at = m[jnp.clip(s.offsets[:-1], 0, n - 1)]
    return (lens == len(lit)) & at


# ---------------------------------------------------------------------------
# literal pattern search
# ---------------------------------------------------------------------------
def find_matches(chars: jax.Array, pat: bytes) -> jax.Array:
    """match[p] = chars[p:p+len(pat)] == pat. Static unroll over the pattern
    bytes (a shifted compare per byte); positions whose window runs past the
    buffer are False."""
    n = chars.shape[0]
    m = len(pat)
    assert m >= 1
    padded = jnp.concatenate([chars, jnp.zeros(m, jnp.uint8)])
    out = jnp.ones(n, jnp.bool_)
    for k, byte in enumerate(pat):
        out = out & (jax.lax.dynamic_slice_in_dim(padded, k, n) == np.uint8(byte))
    limit = n - m
    return out & (jnp.arange(n, dtype=jnp.int32) <= limit)


def prefix_counts(mask: jax.Array) -> jax.Array:
    """(n+1,) exclusive prefix sums of a bool mask."""
    return jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(mask.astype(jnp.int32))]
    )


def next_match(match: jax.Array) -> jax.Array:
    """(n+1,) nm[p] = smallest q >= p with match[q], else BIG (reverse
    running minimum)."""
    n = match.shape[0]
    idx = jnp.where(match, jnp.arange(n, dtype=jnp.int32), BIG)
    rm = jax.lax.cummin(idx, reverse=True)
    return jnp.concatenate([rm, jnp.full(1, BIG, jnp.int32)])


def has_border(pat: bytes) -> bool:
    """True if the pattern has a proper border (can self-overlap), in which
    case greedy non-overlapping replace is order-dependent and the planner
    falls back (reference falls back for regex-special patterns similarly)."""
    m = len(pat)
    return any(pat[: m - d] == pat[d:] for d in range(1, m))


# ---------------------------------------------------------------------------
# ragged output builders
# ---------------------------------------------------------------------------
def _out_rows(new_offsets: jax.Array, out_cap: int) -> Tuple[jax.Array, jax.Array]:
    from .filter_gather import rows_of_positions

    pos = jnp.arange(out_cap, dtype=jnp.int32)
    rid = rows_of_positions(new_offsets, out_cap)
    return rid, pos - new_offsets[rid]


def offsets_of_lens(new_lens: jax.Array) -> jax.Array:
    return jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(new_lens.astype(jnp.int32))]
    )


def take_slices(s: Str, start_bytes: jax.Array, new_lens: jax.Array,
                out_cap: int) -> Tuple[jax.Array, jax.Array]:
    """Build (new_offsets, out_chars) where each output row is the
    contiguous byte slice [start_bytes, start_bytes + new_lens) of the
    source buffer. Serves substring / trim / substring_index / split-part.

    src[pos] = start_bytes[row] + (pos - new_offsets[row]) — the bracketed
    delta is piecewise-constant per row, so it expands with one
    scatter+cumsum instead of a row-id gather."""
    new_offsets = offsets_of_lens(new_lens)
    delta = start_bytes.astype(jnp.int32) - new_offsets[:-1]
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    src = pos + piecewise_by_row(delta, new_offsets, out_cap)
    src = jnp.clip(src, 0, s.chars.shape[0] - 1)
    out = jnp.where(
        pos < new_offsets[-1], s.chars[src], jnp.uint8(0),
    )
    return new_offsets, out


def concat(pieces: Sequence[Str], out_cap: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Spark concat(): per-row byte concatenation; null if ANY input null.
    Returns (new_offsets, out_chars, validity)."""
    valid = functools.reduce(jnp.logical_and, [p.validity for p in pieces])
    lens = [byte_lens(p.offsets) for p in pieces]
    total = functools.reduce(jnp.add, lens)
    total = jnp.where(valid, total, 0)
    new_offsets = offsets_of_lens(total)
    rid, within = _out_rows(new_offsets, out_cap)
    out = jnp.zeros(out_cap, jnp.uint8)
    cum = jnp.zeros_like(rid)
    for p, ln in zip(pieces, lens):
        w = within - cum
        sel = (w >= 0) & (w < ln[rid])
        src = jnp.clip(p.offsets[:-1][rid] + w, 0, p.chars.shape[0] - 1)
        out = jnp.where(sel, p.chars[src], out)
        cum = cum + ln[rid]
    out = jnp.where(
        jnp.arange(out_cap, dtype=jnp.int32) < new_offsets[-1], out, jnp.uint8(0)
    )
    return new_offsets, out, valid


# ---------------------------------------------------------------------------
# case mapping (code points < 0x250 — byte-length preserving range)
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=4)
def _case_luts(upper: bool) -> np.ndarray:
    lut = np.arange(0x250, dtype=np.int32)
    for cp in range(0x250):
        c = chr(cp)
        m = c.upper() if upper else c.lower()
        if len(m) == 1 and ord(m) < 0x250 and (
            len(m.encode("utf-8")) == len(c.encode("utf-8"))
        ):
            lut[cp] = ord(m)
    return lut


def _ascii_case(chars: jax.Array, upper: bool) -> jax.Array:
    """Pure-arithmetic ASCII case map (no table gathers)."""
    lo, hi = (ord("a"), ord("z")) if upper else (ord("A"), ord("Z"))
    delta = jnp.uint8(32)
    in_rng = (chars >= lo) & (chars <= hi)
    return jnp.where(in_rng, chars - delta if upper else chars + delta, chars)


def map_case(chars: jax.Array, total: jax.Array, upper: bool) -> jax.Array:
    """Byte-length-preserving simple case mapping. ASCII and 2-byte
    sequences below U+0250 are mapped; everything else passes through.
    All-ASCII buffers (checked at runtime, lax.cond) take a gather-free
    arithmetic path."""
    n = chars.shape[0]

    def fast(_):
        mapped = _ascii_case(chars, upper)
        return jnp.where(jnp.arange(n, dtype=jnp.int32) < total, mapped, chars)

    def full(_):
        lut = jnp.asarray(_case_luts(upper))
        is_ascii = chars < 0x80
        is2 = (chars & 0xE0) == 0xC0
        nxt = jnp.concatenate([chars[1:], jnp.zeros(1, jnp.uint8)])
        prv = jnp.concatenate([jnp.zeros(1, jnp.uint8), chars[:-1]])
        cp2 = ((chars & 0x1F).astype(jnp.int32) << 6) | (nxt & 0x3F).astype(jnp.int32)
        mapped2 = lut[jnp.clip(cp2, 0, 0x24F)]
        in_range2 = is2 & (cp2 < 0x250)
        # continuation byte of a mapped 2-byte char: recompute from prev
        prev_cp2 = ((prv & 0x1F).astype(jnp.int32) << 6) | (chars & 0x3F).astype(jnp.int32)
        prev_is2 = (prv & 0xE0) == 0xC0
        prev_mapped = lut[jnp.clip(prev_cp2, 0, 0x24F)]
        prev_in = prev_is2 & (prev_cp2 < 0x250) & ((chars & 0xC0) == 0x80)
        out = _ascii_case(chars, upper)
        out = jnp.where(~is_ascii, chars, out)
        out = jnp.where(in_range2, (0xC0 | (mapped2 >> 6)).astype(jnp.uint8), out)
        out = jnp.where(prev_in, (0x80 | (prev_mapped & 0x3F)).astype(jnp.uint8), out)
        return jnp.where(jnp.arange(n, dtype=jnp.int32) < total, out, chars)

    return jax.lax.cond(all_ascii(chars, total), fast, full, operand=None)
