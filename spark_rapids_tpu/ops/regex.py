"""Regular expressions on TPU: host-compiled byte-DFA, device execution
as a segmented associative scan of packed transition functions.

Reference analog: at the reference's version, regex support is the
"treated like a regular string" guard (GpuOverrides.scala:414
``canRegexpBeTreatedLikeARegularString``) — RegExpReplace/StringSplit run
only for literal-equivalent patterns and everything else falls back. This
module keeps that guard (:func:`regex_as_literal`) AND adds a real RLike:

  * host: a regex SUBSET (literals, ``.``, classes, ``* + ? {m,n}``,
    alternation, grouping, ``^ $`` anchors; UTF-8 aware — multi-byte
    characters become byte-sequence alternations so ``.``/negated classes
    count CODEPOINTS, not bytes) parses to a Thompson NFA, then subset-
    constructs a byte DFA capped at 16 states.
  * device: each byte maps to its 256-entry transition row (a small-table
    gather — the fast kind); rows pack 16 states x 4 bits into two u32
    words; a SEGMENTED ``lax.associative_scan`` composes transition
    functions along the byte pool, resetting at row starts, so every
    row's final DFA state appears in O(log n) depth with elementwise-only
    composition. No per-row loops, no big-table gathers.

Unsupported constructs raise :class:`RegexUnsupported` and the planner
falls back to CPU for that expression (same contract as the reference).
Semantics follow Java's Pattern for the supported subset (which agrees
with Python ``re`` there — the CPU oracle uses ``re``).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

MAX_DFA_STATES = 16


class RegexUnsupported(Exception):
    """Pattern outside the supported subset — caller falls back."""


# ---------------------------------------------------------------------------
# the "treated like a regular string" guard (GpuOverrides.scala:414)
# ---------------------------------------------------------------------------
_META = set(".^$*+?()[]{}|\\")


def regex_as_literal(pattern: str) -> Optional[str]:
    """The literal string this regex matches verbatim, or None.

    Mirrors ``canRegexpBeTreatedLikeARegularString``: no active
    metacharacters; ``\\x`` escapes of metacharacters unescape."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\":
            if i + 1 >= len(pattern):
                return None
            n = pattern[i + 1]
            # escaped punctuation is that literal char in Java (and
            # Python); escaped letters/digits are regex classes
            if not n.isalnum():
                out.append(n)
                i += 2
                continue
            return None
        if c in _META:
            return None
        out.append(c)
        i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# parser -> NFA (byte-based, UTF-8 aware)
# ---------------------------------------------------------------------------
ByteSet = FrozenSet[int]

_ASCII_D = frozenset(range(0x30, 0x3A))
_ASCII_W = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
    + list(range(0x61, 0x7B)) + [0x5F]
)
_ASCII_S = frozenset([0x20, 0x09, 0x0A, 0x0B, 0x0C, 0x0D])
_ALL_ASCII = frozenset(range(0x80))


@dataclasses.dataclass
class _Nfa:
    """Thompson NFA: states 0..n-1; edges (src, byteset|None=eps, dst)."""

    n: int = 0
    eps: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    edges: List[Tuple[int, ByteSet, int]] = dataclasses.field(
        default_factory=list)

    def state(self) -> int:
        self.n += 1
        return self.n - 1


@dataclasses.dataclass(frozen=True)
class _Frag:
    start: int
    end: int


def _byte_edge(nfa: _Nfa, bs: ByteSet) -> _Frag:
    s, e = nfa.state(), nfa.state()
    nfa.edges.append((s, bs, e))
    return _Frag(s, e)


_CONT = frozenset(range(0x80, 0xC0))


def _nonascii_char(nfa: _Nfa) -> _Frag:
    """Fragment matching ONE non-ASCII codepoint (its UTF-8 bytes)."""
    s, e = nfa.state(), nfa.state()
    # 2-byte
    m1 = nfa.state()
    nfa.edges.append((s, frozenset(range(0xC2, 0xE0)), m1))
    nfa.edges.append((m1, _CONT, e))
    # 3-byte
    m2a, m2b = nfa.state(), nfa.state()
    nfa.edges.append((s, frozenset(range(0xE0, 0xF0)), m2a))
    nfa.edges.append((m2a, _CONT, m2b))
    nfa.edges.append((m2b, _CONT, e))
    # 4-byte
    m3a, m3b, m3c = nfa.state(), nfa.state(), nfa.state()
    nfa.edges.append((s, frozenset(range(0xF0, 0xF5)), m3a))
    nfa.edges.append((m3a, _CONT, m3b))
    nfa.edges.append((m3b, _CONT, m3c))
    nfa.edges.append((m3c, _CONT, e))
    return _Frag(s, e)


def _char_frag(nfa: _Nfa, ascii_set: ByteSet, include_nonascii: bool) -> _Frag:
    """Fragment matching one CHARACTER from an ASCII set, optionally also
    any non-ASCII character."""
    if not include_nonascii:
        return _byte_edge(nfa, ascii_set)
    s, e = nfa.state(), nfa.state()
    if ascii_set:
        a = _byte_edge(nfa, ascii_set)
        nfa.eps.append((s, a.start))
        nfa.eps.append((a.end, e))
    na = _nonascii_char(nfa)
    nfa.eps.append((s, na.start))
    nfa.eps.append((na.end, e))
    return _Frag(s, e)


def _literal_char(nfa: _Nfa, ch: str) -> _Frag:
    b = ch.encode("utf-8")
    if len(b) == 1:
        return _byte_edge(nfa, frozenset([b[0]]))
    frag = None
    for byte in b:
        f = _byte_edge(nfa, frozenset([byte]))
        if frag is None:
            frag = f
        else:
            nfa.eps.append((frag.end, f.start))
            frag = _Frag(frag.start, f.end)
    return frag


class _Parser:
    """Recursive-descent parser for the supported subset."""

    def __init__(self, pattern: str, nfa: _Nfa):
        self.p = pattern
        self.i = 0
        self.nfa = nfa
        self.anchored_start = False
        self.anchored_end = False

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self) -> _Frag:
        if self.peek() == "^":
            self.anchored_start = True
            self.next()
        frag = self.alternation(top=True)
        if self.i < len(self.p):
            raise RegexUnsupported(f"trailing input at {self.i}")
        return frag

    def alternation(self, top: bool = False) -> _Frag:
        frags = [self.concat(top)]
        while self.peek() == "|":
            self.next()
            frags.append(self.concat(top))
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.state(), self.nfa.state()
        for f in frags:
            self.nfa.eps.append((s, f.start))
            self.nfa.eps.append((f.end, e))
        return _Frag(s, e)

    def concat(self, top: bool = False) -> _Frag:
        frags: List[_Frag] = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                break
            if c == "$":
                if not top or self.i + 1 != len(self.p):
                    raise RegexUnsupported("'$' not at end")
                self.anchored_end = True
                self.next()
                break
            if c == "^":
                raise RegexUnsupported("'^' not at start")
            frags.append(self.repeat())
        if not frags:
            s = self.nfa.state()
            return _Frag(s, s)
        out = frags[0]
        for f in frags[1:]:
            self.nfa.eps.append((out.end, f.start))
            out = _Frag(out.start, f.end)
        return out

    def repeat(self) -> _Frag:
        atom_start = self.i
        frag = self.atom()
        c = self.peek()
        if c not in ("*", "+", "?", "{"):
            return frag
        if c == "{":
            m, n = self._bounds()
        else:
            self.next()
            m, n = {"*": (0, None), "+": (1, None), "?": (0, 1)}[c]
        if self.peek() == "?":
            raise RegexUnsupported("lazy quantifier")
        atom_src = self.p[atom_start : self.i]
        # expand {m,n} by atom repetition (DFA doesn't count)
        if m > 8 or (n is not None and n > 16):
            raise RegexUnsupported("large bounded repetition")

        def clone() -> _Frag:
            sub = _Parser(atom_src, self.nfa)
            f = sub.repeat_cloned()
            return f

        return self._repeat_frag(frag, m, n, clone)

    def repeat_cloned(self) -> _Frag:
        # atom_src includes the quantifier-free atom only
        return self.atom()

    def _repeat_frag(self, frag, m, n, clone) -> _Frag:
        nfa = self.nfa
        if (m, n) == (0, None):  # *
            s = nfa.state()
            nfa.eps.append((s, frag.start))
            nfa.eps.append((frag.end, s))
            return _Frag(s, s)
        if (m, n) == (1, None):  # +
            nfa.eps.append((frag.end, frag.start))
            s, e = nfa.state(), nfa.state()
            nfa.eps.append((s, frag.start))
            nfa.eps.append((frag.end, e))
            return _Frag(s, e)
        if (m, n) == (0, 1):  # ?
            s, e = nfa.state(), nfa.state()
            nfa.eps.append((s, frag.start))
            nfa.eps.append((frag.end, e))
            nfa.eps.append((s, e))
            return _Frag(s, e)
        # {m,n} / {m,}: m required copies then (n-m) optional (or a star)
        parts: List[_Frag] = [frag]
        for _ in range(m - 1 if m > 0 else 0):
            parts.append(clone())
        out: Optional[_Frag] = None
        for f in parts if m > 0 else []:
            if out is None:
                out = f
            else:
                nfa.eps.append((out.end, f.start))
                out = _Frag(out.start, f.end)
        if n is None:  # {m,}: trailing star of a clone
            f = clone()
            s = nfa.state()
            nfa.eps.append((s, f.start))
            nfa.eps.append((f.end, s))
            star = _Frag(s, s)
            if out is None:
                return star
            nfa.eps.append((out.end, star.start))
            return _Frag(out.start, star.end)
        for _ in range(n - m):
            f = clone()
            s, e = nfa.state(), nfa.state()
            nfa.eps.append((s, f.start))
            nfa.eps.append((f.end, e))
            nfa.eps.append((s, e))
            opt = _Frag(s, e)
            if out is None:
                out = opt
            else:
                nfa.eps.append((out.end, opt.start))
                out = _Frag(out.start, opt.end)
        assert out is not None
        return out

    def _bounds(self) -> Tuple[int, Optional[int]]:
        assert self.next() == "{"
        j = self.p.find("}", self.i)
        if j < 0:
            raise RegexUnsupported("unclosed {")
        body = self.p[self.i : j]
        self.i = j + 1
        if "," in body:
            lo, hi = body.split(",", 1)
            if not lo.isdigit() or (hi and not hi.isdigit()):
                raise RegexUnsupported(f"bounds {{{body}}}")
            return int(lo), (int(hi) if hi else None)
        if not body.isdigit():
            raise RegexUnsupported(f"bounds {{{body}}}")
        return int(body), int(body)

    def atom(self) -> _Frag:
        c = self.next()
        if c == "(":
            if self.p[self.i : self.i + 2] == "?:":
                self.i += 2
            elif self.peek() == "?":
                raise RegexUnsupported("special group")
            f = self.alternation()
            if self.peek() != ")":
                raise RegexUnsupported("unclosed group")
            self.next()
            return f
        if c == ".":
            # Java dot: any char except line terminators (\n \r; the
            # non-ASCII terminators U+0085/U+2028/U+2029 still match —
            # documented incompat, they are vanishingly rare in data)
            return _char_frag(
                self.nfa, _ALL_ASCII - frozenset([0x0A, 0x0D]), True)
        if c == "[":
            return self._char_class()
        if c == "\\":
            return self._escape()
        if c in "*+?{":
            raise RegexUnsupported(f"dangling quantifier {c!r}")
        return _literal_char(self.nfa, c)

    def _escape(self) -> _Frag:
        if self.peek() is None:
            raise RegexUnsupported("dangling backslash")
        c = self.next()
        table = {
            "d": (_ASCII_D, False), "D": (_ALL_ASCII - _ASCII_D, True),
            "w": (_ASCII_W, False), "W": (_ALL_ASCII - _ASCII_W, True),
            "s": (_ASCII_S, False), "S": (_ALL_ASCII - _ASCII_S, True),
        }
        if c in table:
            bs, nonascii = table[c]
            return _char_frag(self.nfa, bs, nonascii)
        simple = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "0": "\0"}
        if c in simple:
            return _literal_char(self.nfa, simple[c])
        if not c.isalnum():  # escaped punctuation = literal (Java)
            return _literal_char(self.nfa, c)
        raise RegexUnsupported(f"escape \\{c}")

    def _char_class(self) -> _Frag:
        neg = False
        if self.peek() == "^":
            neg = True
            self.next()
        members: Set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexUnsupported("unclosed [")
            if c == "]" and not first:
                self.next()
                break
            first = False
            c = self.next()
            if c == "\\":
                e = self.next() if self.peek() is not None else None
                if e is None:
                    raise RegexUnsupported("dangling backslash in class")
                cls = {"d": _ASCII_D, "w": _ASCII_W, "s": _ASCII_S}.get(e)
                if cls is not None:
                    members |= set(cls)
                    continue
                simple = {"n": "\n", "t": "\t", "r": "\r"}.get(e, e)
                if len(simple.encode("utf-8")) != 1:
                    raise RegexUnsupported("non-ASCII class member")
                members.add(simple.encode("utf-8")[0])
                continue
            if ord(c) > 0x7F:
                raise RegexUnsupported("non-ASCII class member")
            if self.peek() == "-" and self.i + 1 < len(self.p) and \
                    self.p[self.i + 1] != "]":
                self.next()
                hi = self.next()
                if ord(hi) > 0x7F:
                    raise RegexUnsupported("non-ASCII class range")
                if ord(hi) < ord(c):
                    raise RegexUnsupported("reversed class range")
                members |= set(range(ord(c), ord(hi) + 1))
            else:
                members.add(ord(c))
        if neg:
            return _char_frag(self.nfa, _ALL_ASCII - frozenset(members), True)
        return _char_frag(self.nfa, frozenset(members), False)


# ---------------------------------------------------------------------------
# NFA -> DFA (subset construction)
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Dfa:
    """Byte DFA packed for device execution.

    lut_lo/lut_hi: (256,) uint32 — per input byte, the packed transition
    vector next[s] for s in 0..15 (4 bits each; states >= 8 in hi).
    State 0 is the start; ``dead`` marks the absorbing reject state."""

    nstates: int
    lut_lo: np.ndarray
    lut_hi: np.ndarray
    accept_mask: int
    start_accept: bool
    absorbing: bool = True  # no '$': accept sticks once reached


def compile_search_dfa(pattern: str) -> Dfa:
    """DFA for Java ``Matcher.find`` semantics (unanchored unless ^/$)."""
    nfa = _Nfa()
    parser = _Parser(pattern, nfa)
    frag = parser.parse()
    start = nfa.state()
    accept = frag.end
    nfa.eps.append((start, frag.start))
    if not parser.anchored_start:
        # leading any-byte loop (bytes, not chars: prefix skipping does
        # not need codepoint alignment — match starts are byte positions
        # and multi-byte atoms re-align)
        loop = _byte_edge(nfa, frozenset(range(256)))
        nfa.eps.append((start, loop.start))
        nfa.eps.append((loop.end, start))
    absorbing = not parser.anchored_end
    return _build_dfa(nfa, start, accept, absorbing)


def _build_dfa(nfa: _Nfa, start: int, accept: int, absorbing: bool) -> Dfa:
    eps_adj: Dict[int, List[int]] = {}
    for a, b in nfa.eps:
        eps_adj.setdefault(a, []).append(b)
    by_src: Dict[int, List[Tuple[ByteSet, int]]] = {}
    for s, bs, d in nfa.edges:
        by_src.setdefault(s, []).append((bs, d))

    def closure(states: FrozenSet[int]) -> FrozenSet[int]:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in eps_adj.get(s, ()):
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_set = closure(frozenset([start]))
    dfa_ids: Dict[FrozenSet[int], int] = {start_set: 0}
    order: List[FrozenSet[int]] = [start_set]
    trans: List[List[int]] = []
    i = 0
    ACCEPT_SENTINEL = frozenset([-1])
    while i < len(order):
        cur = order[i]
        i += 1
        row = [None] * 256
        if cur is ACCEPT_SENTINEL or (absorbing and accept in cur):
            # absorbing accept: all bytes stay accepted
            aid = dfa_ids.get(ACCEPT_SENTINEL)
            if aid is None:
                aid = len(order)
                if aid >= MAX_DFA_STATES:
                    raise RegexUnsupported(
                        f"DFA exceeds {MAX_DFA_STATES} states")
                dfa_ids[ACCEPT_SENTINEL] = aid
                order.append(ACCEPT_SENTINEL)
            trans.append([aid] * 256)
            continue
        # group target sets per byte
        for b in range(256):
            tgt = set()
            for s in cur:
                for bs, d in by_src.get(s, ()):
                    if b in bs:
                        tgt.add(d)
            t = closure(frozenset(tgt)) if tgt else frozenset()
            tid = dfa_ids.get(t)
            if tid is None:
                tid = len(order)
                if tid >= MAX_DFA_STATES:
                    raise RegexUnsupported(
                        f"DFA exceeds {MAX_DFA_STATES} states")
                dfa_ids[t] = tid
                order.append(t)
            row[b] = tid
        trans.append(row)

    n = len(order)
    accept_mask = 0
    for st, sid in dfa_ids.items():
        if st is ACCEPT_SENTINEL or (st is not None and accept in st):
            accept_mask |= 1 << sid
    lut_lo = np.zeros(256, np.uint32)
    lut_hi = np.zeros(256, np.uint32)
    for b in range(256):
        lo = 0
        hi = 0
        for s in range(min(n, 16)):
            nxt = trans[s][b]
            if s < 8:
                lo |= nxt << (4 * s)
            else:
                hi |= nxt << (4 * (s - 8))
        lut_lo[b] = lo
        lut_hi[b] = hi
    return Dfa(
        nstates=n, lut_lo=lut_lo, lut_hi=lut_hi,
        accept_mask=accept_mask,
        start_accept=bool(accept_mask & 1),
        absorbing=absorbing,
    )


# ---------------------------------------------------------------------------
# device execution
# ---------------------------------------------------------------------------
def _extract4(lo, hi, s):
    """4-bit field s (0..15) of a packed (lo, hi) transition vector;
    s may be a traced array (variable shift — elementwise)."""
    import jax.numpy as jnp

    s32 = s.astype(jnp.uint32)
    lo_f = (lo >> (4 * s32)) & jnp.uint32(15)
    hi_f = (hi >> (4 * (s32 - 8))) & jnp.uint32(15)
    return jnp.where(s32 < 8, lo_f, hi_f)


def dfa_accept_rows(offsets, chars, validity, dfa: Dfa):
    """(cap,) bool: does each row contain a match (DFA accept at row end).

    Segmented transition-composition scan; all heavy steps are elementwise
    or small-table gathers."""
    import jax.numpy as jnp
    from jax import lax

    cap = offsets.shape[0] - 1
    ncap = chars.shape[0]
    lut_lo = jnp.asarray(dfa.lut_lo)
    lut_hi = jnp.asarray(dfa.lut_hi)
    ci = chars.astype(jnp.int32)
    lo = jnp.take(lut_lo, ci, mode="clip")
    hi = jnp.take(lut_hi, ci, mode="clip")
    # segment resets at row starts; out-of-range starts (empty/padding
    # rows at the end of a FULL char pool) must DROP, not clip — a clip
    # would plant a bogus reset on the last real byte
    reset = (
        jnp.zeros(ncap, jnp.bool_)
        .at[offsets[:cap]]
        .set(True, mode="drop")
    )

    def combine(a, b):
        areset, alo, ahi = a
        breset, blo, bhi = b
        # compose: out[s] = b[a[s]] — unrolled over the 16 fields
        out_lo = jnp.zeros_like(alo)
        out_hi = jnp.zeros_like(ahi)
        for s in range(8):
            a_s = (alo >> jnp.uint32(4 * s)) & jnp.uint32(15)
            out_lo = out_lo | (_extract4(blo, bhi, a_s) << jnp.uint32(4 * s))
        for s in range(8):
            a_s = (ahi >> jnp.uint32(4 * s)) & jnp.uint32(15)
            out_hi = out_hi | (_extract4(blo, bhi, a_s) << jnp.uint32(4 * s))
        lo_ = jnp.where(breset, blo, out_lo)
        hi_ = jnp.where(breset, bhi, out_hi)
        return areset | breset, lo_, hi_

    _, slo, shi = lax.associative_scan(combine, (reset, lo, hi))
    # state after byte j, starting from state 0 at its row start
    st = _extract4(slo, shi, jnp.zeros(ncap, jnp.uint32))
    acc_tbl = jnp.asarray(
        np.array([(dfa.accept_mask >> s) & 1 for s in range(16)], np.int32))
    acc_at = jnp.take(acc_tbl, st.astype(jnp.int32), mode="clip") == 1
    lens = offsets[1:] - offsets[:cap]
    last = jnp.clip(offsets[1:] - 1, 0, max(ncap - 1, 0))
    row_acc = jnp.take(acc_at, last, mode="clip")
    if not dfa.absorbing:
        # Java '$' also matches just before a FINAL line terminator:
        # accept when the row ends in '\n' and the state before it accepts
        prev = jnp.clip(offsets[1:] - 2, 0, max(ncap - 1, 0))
        last_is_nl = jnp.take(chars, last, mode="clip") == np.uint8(0x0A)
        acc_prev = jnp.take(acc_at, prev, mode="clip")
        acc_prev = jnp.where(
            lens > 1, acc_prev, dfa.start_accept)  # row == "\n"
        row_acc = row_acc | (last_is_nl & acc_prev)
    row_acc = jnp.where(lens > 0, row_acc, dfa.start_accept)
    return row_acc & validity
