"""Pallas hash-join probe kernel — the PALLAS tier of
``spark.rapids.tpu.sql.join.strategy`` (the legacy
``sql.join.pallasProbe.enabled`` toggle still forces it under AUTO).

The general probe is a vectorized binary search over the sorted build
words — log2(build) gather passes, each at HBM-random-access speed, and
the r09 cost plane shows the probe programs touching XLA bytes at
`hbm_frac_xla` 0.0055. This kernel is the fast-memory alternative for
the broadcast-class case (small build side, one fixed-width key <= 2
u32 words): each grid step holds one (probe-block x build-tile) equality
mask in VMEM, reduces it to per-probe (first match, match count) there,
and accumulates across build tiles — the mask never exists in HBM and
no gather chain is emitted. Work is O(probe x build) compares, which
beats the search only while the build side is VMEM-tile small; the
strategy conf keeps it forced-only and :func:`ops.join.probe_ranges`
falls back to the search for multi-word keys.

Build rows [0, build_count) are the sorted JOINABLE rows (exec/join
sorts null-key and dead rows past the count), so equal keys are
contiguous and (first, count) is exactly the [lo, hi) contract of the
binary search. ``interpret=True`` runs the same kernel off-TPU (CPU CI).
"""
from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

#: probe rows / build rows per grid step (the VMEM equality-mask extent)
BLOCK_P = 256
BLOCK_BUILD = 256


def _probe_kernel(phi_ref, plo_ref, plive_ref, bhi_ref, blo_ref,
                  blive_ref, first_ref, cnt_ref, *, rb, sentinel):
    from jax.experimental import pallas as pl

    bj = pl.program_id(1)

    @pl.when(bj == 0)
    def _():
        first_ref[...] = jnp.full_like(first_ref, sentinel)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)

    eq = ((phi_ref[...][:, None] == bhi_ref[...][None, :])
          & (plo_ref[...][:, None] == blo_ref[...][None, :])
          & (blive_ref[...][None, :] != 0)
          & (plive_ref[...][:, None] != 0))  # (rp, rb) in VMEM only
    gidx = bj * rb + jax.lax.broadcasted_iota(jnp.int32, (1, rb), 1)
    cand = jnp.min(jnp.where(eq, gidx, sentinel), axis=1)
    first_ref[...] = jnp.minimum(first_ref[...], cand)
    cnt_ref[...] += jnp.sum(eq, axis=1, dtype=jnp.int32)


def pallas_probe_ranges(
    build_words: Sequence[jax.Array],
    build_count: jax.Array,
    probe_words: Sequence[jax.Array],
    probe_live: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """[lo, hi) of build matches per probe row — the Pallas lowering of
    :func:`ops.join.probe_ranges` for <= 2 u32 key words per side."""
    from jax.experimental import pallas as pl

    nb = build_words[0].shape[0]
    m = probe_words[0].shape[0]
    zero_b = jnp.zeros(nb, jnp.uint32)
    zero_p = jnp.zeros(m, jnp.uint32)
    bhi = build_words[0].astype(jnp.uint32)
    blo = (build_words[1].astype(jnp.uint32) if len(build_words) > 1
           else zero_b)
    phi = probe_words[0].astype(jnp.uint32)
    plo = (probe_words[1].astype(jnp.uint32) if len(probe_words) > 1
           else zero_p)
    blive = (jnp.arange(nb, dtype=jnp.int32)
             < build_count.astype(jnp.int32)).astype(jnp.int32)
    plive = probe_live.astype(jnp.int32)

    rp = min(BLOCK_P, max(8, m))
    rb = min(BLOCK_BUILD, max(8, nb))
    nbp = -(-m // rp)
    nbb = -(-nb // rb)
    sentinel = nbb * rb

    from .pallas_groupby import _pad_rows

    phi_p, plo_p, plive_p = _pad_rows([phi, plo, plive], m, rp, [0, 0, 0])
    bhi_p, blo_p, blive_p = _pad_rows([bhi, blo, blive], nb, rb, [0, 0, 0])

    first, cnt = pl.pallas_call(
        functools.partial(_probe_kernel, rb=rb, sentinel=sentinel),
        out_shape=(jax.ShapeDtypeStruct((nbp * rp,), jnp.int32),
                   jax.ShapeDtypeStruct((nbp * rp,), jnp.int32)),
        grid=(nbp, nbb),
        in_specs=[
            pl.BlockSpec((rp,), lambda pi, bi: (pi,)),
            pl.BlockSpec((rp,), lambda pi, bi: (pi,)),
            pl.BlockSpec((rp,), lambda pi, bi: (pi,)),
            pl.BlockSpec((rb,), lambda pi, bi: (bi,)),
            pl.BlockSpec((rb,), lambda pi, bi: (bi,)),
            pl.BlockSpec((rb,), lambda pi, bi: (bi,)),
        ],
        out_specs=(pl.BlockSpec((rp,), lambda pi, bi: (pi,)),
                   pl.BlockSpec((rp,), lambda pi, bi: (pi,))),
        interpret=jax.default_backend() != "tpu",
    )(phi_p, plo_p, plive_p, bhi_p, blo_p, blive_p)
    first, cnt = first[:m], cnt[:m]
    lo = jnp.where(cnt > 0, first, 0)
    lo = jnp.where(probe_live, lo, 0)
    cnt = jnp.where(probe_live, cnt, 0)
    return lo, lo + cnt
