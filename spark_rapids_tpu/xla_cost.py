"""Compiled-program cost plane: what XLA says a program costs.

Every byte/flop figure the engine reported before this module was a
LAYOUT-DERIVED estimate (``bytesTouched`` = rows x row-bytes); nothing
measured what XLA actually compiled. The compiler itself closes that
loop: ``jax.stages.Compiled.cost_analysis()`` and ``memory_analysis()``
report per-program FLOPs, bytes accessed, and temp/argument/output
allocation straight from the optimized HLO — exactly the evidence
needed to decide whether a slow program is MXU-bound or wasting HBM
bandwidth before anyone rewrites it (reference contrast: the JVM plugin
leans on cudf's kernel-level buildTime/GPU metrics for the same call).

Mechanism: :func:`exec.base.cached_pipeline` — the single chokepoint
every jit pipeline cache in the engine goes through (incl. the mesh
path's ``_cached_program``) — wraps each freshly-built jit callable in a
:class:`CostProbe` at compile-miss time. The probe's FIRST call runs the
trace (``lower``) and compile phases explicitly, timed separately,
harvests the cost/memory analyses from the compiled executable, emits
ONE typed ``program_cost`` event (+ live obs twins), and keeps the
compiled executable for every later call — so a warm rerun emits
nothing and pays nothing (the recompile-guard contract).

Zero-overhead contract (the events.py/obs pattern): with event logging
AND the live obs plane off — and :data:`FORCE_HARVEST` unset — wrapping
is skipped entirely at miss time and ``cost_analysis`` is never called
(tests/test_program_cost.py pins this with a spy). ``FORCE_HARVEST`` is
the bench/harness opt-in: harvesting without any event sink still
records into the in-process table below, which bench.py reads to emit
``hbm_frac_xla`` per shape.

Graceful degradation: the CPU fallback backend reports different (or
missing) cost keys than a real TPU — every harvested field is therefore
Optional and every consumer (profiler roofline, explain_metrics
columns, bench) guards on key presence instead of erroring.
"""
from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from . import events as _events
from .conf import conf

ROOFLINE_PEAK_HBM_GBPS = conf(
    "spark.rapids.tpu.roofline.peakHbmGBps", 0.0,
    "Peak HBM bandwidth (GB/s) the roofline report measures achieved "
    "bandwidth against (tpu_profile '== roofline ==', explain_metrics, "
    "bench hbm_frac_xla). 0.0 (the default) picks a per-backend peak: "
    "819 GB/s on TPU (v5e public spec), a nominal 100 GB/s on the CPU "
    "fallback backend. Calibrate per deployment: run a saturating "
    "memcpy-shaped query and set this to the best achieved figure so "
    "the limiter classification reflects YOUR part, not the spec sheet.",
    conf_type=float,
    check=lambda v: None if v >= 0 else "must be >= 0")
ROOFLINE_PEAK_TFLOPS = conf(
    "spark.rapids.tpu.roofline.peakTflops", 0.0,
    "Peak compute throughput (TFLOP/s) for the roofline report's "
    "compute-bound classification. 0.0 (the default) picks a per-backend "
    "peak: 197 TFLOP/s on TPU (v5e bf16 spec; the one-hot bucket_reduce "
    "matmuls run on the MXU), a nominal 1 TFLOP/s on the CPU fallback "
    "backend.", conf_type=float,
    check=lambda v: None if v >= 0 else "must be >= 0")

#: per-backend (peak HBM GB/s, peak TFLOP/s) defaults when the roofline
#: confs are 0.0 — the TPU row is the v5e public spec (bench.py's
#: HBM_GBPS constant is the same 819), the CPU row a nominal DDR-class
#: figure so the fallback backend still classifies limiters
BACKEND_PEAKS: Dict[str, Tuple[float, float]] = {
    "tpu": (819.0, 197.0),
    "gpu": (900.0, 19.5),
    "cpu": (100.0, 1.0),
}


#: explicitly conf-set roofline peaks, recorded by the session at
#: execute time (set_conf_peaks) so harvested program_cost events carry
#: them to the OFFLINE profiler, which has no RapidsConf to read —
#: that is the only channel through which the conf can reach it. None
#: while both confs are 0.0 (per-backend defaults apply everywhere).
#: Process-global last-writer-wins, like the conf-derived engine
#: singletons: concurrent sessions disagreeing on declared hardware
#: peaks is a misconfiguration, not a supported state.
_CONF_PEAKS: Optional[Tuple[float, float]] = None


def set_conf_peaks(conf_) -> None:
    global _CONF_PEAKS
    g = conf_.get(ROOFLINE_PEAK_HBM_GBPS)
    t = conf_.get(ROOFLINE_PEAK_TFLOPS)
    _CONF_PEAKS = (g, t) if (g or t) else None


# ---------------------------------------------------------------------------
# Harvest gating + the in-process record table
# ---------------------------------------------------------------------------
#: bench/harness opt-in: harvest even with events+obs off (records land
#: only in the in-process table below). NOT a user conf — the user-facing
#: switches are the event log / obs plane themselves.
FORCE_HARVEST = False

_LOCK = threading.Lock()
#: bounded: a long-lived serving process must not grow without bound;
#: consumers needing durability use the event log
_RECORDS: deque = deque(maxlen=8192)
_SEQ = 0

#: the program_cost event's REQUIRED fields (None when the backend
#: didn't report them — consumers .get() and guard)
COST_FIELDS = ("flops", "bytes_accessed", "temp_bytes", "argument_bytes",
               "output_bytes", "alias_bytes")


#: lazily-bound obs module (circular import: obs imports events); bound
#: once so the disabled hot path below never hits sys.modules
_OBS_MOD = None


def harvesting() -> bool:
    """True when compile misses should harvest XLA cost analyses.
    Consulted at every compile miss (cached_pipeline) AND by op_timed on
    every hot-section entry (attribution scope rides the same gate so a
    harvest can never lose its op silently) — the disabled path is two
    module-bool reads, no allocation."""
    global _OBS_MOD
    if FORCE_HARVEST:
        return True
    if _events.enabled():
        return True
    mod = _OBS_MOD
    if mod is None:
        # double-checked under the record lock: the bind is idempotent
        # but the sanctioned shape costs nothing off the first call
        with _LOCK:
            if _OBS_MOD is None:
                from . import obs

                _OBS_MOD = obs
            mod = _OBS_MOD
    return mod.enabled()


def snapshot() -> int:
    """Monotonic record sequence — snapshot before a run, pass to
    :func:`records_since` after, and you have THAT run's programs (the
    compile_snapshot() pattern)."""
    with _LOCK:
        return _SEQ


def records_since(seq: int = 0) -> List[dict]:
    with _LOCK:
        return [dict(r) for r in _RECORDS if r["seq"] > seq]


def digest_of(key: Any) -> str:
    """Stable short digest of a pipeline-cache key — the program's
    signature identity across the event log, obs, and reports."""
    return hashlib.sha1(repr(key).encode()).hexdigest()[:12]


# ---------------------------------------------------------------------------
# Current-op attribution: exec/base.op_timed pushes the executing exec's
# name here (only while a cost consumer is on), so a program compiled
# inside TpuHashAggregateExec.op_timed() records op=TpuHashAggregateExec
# and the roofline report can join XLA bytes against that op's measured
# device lane. Compiles outside any op scope (scan staging helpers)
# record op=None; consumers guard.
# ---------------------------------------------------------------------------
_OP = threading.local()


@contextlib.contextmanager
def op_scope(name: str):
    stack = getattr(_OP, "stack", None)
    if stack is None:
        stack = _OP.stack = []
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()


def current_op() -> Optional[str]:
    stack = getattr(_OP, "stack", None)
    return stack[-1] if stack else None


# ---------------------------------------------------------------------------
# Harvesting a compiled executable
# ---------------------------------------------------------------------------
def harvest_compiled(compiled) -> Dict[str, Any]:
    """Cost/memory fields from a ``jax.stages.Compiled``, every key
    guarded: backends disagree on the cost_analysis payload (a list of
    dicts on CPU, a dict on newer jax; key spellings vary) and
    memory_analysis may be absent entirely — missing values surface as
    None, never as an exception."""
    out: Dict[str, Any] = {k: None for k in COST_FIELDS}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        if ca.get("flops") is not None:
            out["flops"] = float(ca["flops"])
        if ca.get("bytes accessed") is not None:
            out["bytes_accessed"] = float(ca["bytes accessed"])
        # optional per-output breakdown (spelling varies by backend)
        for k in ("bytes accessedout{}", "bytes accessed output"):
            if ca.get(k) is not None:
                out["out_bytes"] = float(ca[k])
                break
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for field, attr in (
            ("temp_bytes", "temp_size_in_bytes"),
            ("argument_bytes", "argument_size_in_bytes"),
            ("output_bytes", "output_size_in_bytes"),
            ("generated_code_bytes", "generated_code_size_in_bytes"),
            ("alias_bytes", "alias_size_in_bytes"),
        ):
            v = getattr(ma, attr, None)
            if v is not None:
                out[field] = int(v)
        # donation correction: XLA folds input buffers aliased to
        # outputs (donate_argnums) INTO temp_size_in_bytes and reports
        # them separately as alias_size_in_bytes. Raw temp therefore
        # RISES under donation even though no new HBM is allocated —
        # the aliased bytes are the donated inputs being reused.
        # Subtracting restores temp_bytes' meaning ("scratch allocated
        # beyond the arguments"); a non-donating program has alias 0,
        # so every existing consumer sees unchanged numbers.
        if out.get("temp_bytes") is not None and out.get("alias_bytes"):
            out["temp_bytes"] = max(
                0, out["temp_bytes"] - out["alias_bytes"])
    return out


def note_program_cost(site: str, digest: str, trace_ns: int,
                      compile_ns: int, cost: Dict[str, Any],
                      op: Optional[str] = None) -> dict:
    """Record one compiled program's cost: in-process table always,
    typed ``program_cost`` event + live obs twins when those planes are
    on. Exactly one call per compile miss (CostProbe guarantees it)."""
    global _SEQ
    rec: Dict[str, Any] = {
        "site": site, "digest": digest,
        "backend": jax.default_backend(),
        "trace_ms": round(trace_ns / 1e6, 3),
        "compile_ms": round(compile_ns / 1e6, 3),
        "op": op,
    }
    if _CONF_PEAKS is not None:
        g, t = _CONF_PEAKS
        if g:
            rec["peak_hbm_gbps"] = g
        if t:
            rec["peak_tflops"] = t
    rec.update(cost)
    with _LOCK:
        _SEQ += 1
        rec["seq"] = _SEQ
        _RECORDS.append(rec)
    if _events.enabled():
        ev = {k: rec.get(k) for k in
              ("site", "digest", "backend", "trace_ms", "compile_ms")
              + COST_FIELDS}
        for k in ("op", "out_bytes", "generated_code_bytes",
                  "peak_hbm_gbps", "peak_tflops", "from_cache",
                  "saved_ms"):
            if rec.get(k) is not None:
                ev[k] = rec[k]
        _events.emit("program_cost", **ev)
    from . import obs as _obs

    if _obs.enabled():
        _obs.note_program_cost(site, trace_ns / 1e9, compile_ns / 1e9,
                               rec.get("temp_bytes"))
    return rec


# ---------------------------------------------------------------------------
# The probe
# ---------------------------------------------------------------------------
class CostProbe:
    """First-call shim around a cached jit callable: run trace+compile
    explicitly (timed separately), harvest the executable's analyses,
    then serve every call from the kept ``Compiled``. Total first-call
    work is the same trace+compile+run jit would have done lazily.

    Defensive by design — a probe must never fail a query: if the
    callable can't ``lower`` with these args, or the AOT executable
    rejects them (signature drift the cache key didn't capture), the
    probe falls back to the plain jit path permanently."""

    __slots__ = ("_fn", "_site", "_digest", "_compiled", "_done", "_lock")

    def __init__(self, fn: Callable, site: str, digest: str):
        self._fn = fn
        self._site = site
        self._digest = digest
        self._compiled = None
        self._done = False
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        if not self._done:
            with self._lock:
                if not self._done:
                    self._harvest(args, kwargs)
                    self._done = True
        c = self._compiled
        if c is not None:
            try:
                return c(*args, **kwargs)
            except (TypeError, ValueError):
                # args the AOT executable won't take (the cache key
                # under-captured the signature): jit handles them.
                # ONLY signature errors fall back — a genuine runtime
                # failure (device OOM, XlaRuntimeError) must propagate,
                # not silently retrace+recompile and fail twice
                self._compiled = None
        return self._fn(*args, **kwargs)

    def _harvest(self, args, kwargs) -> None:
        if not harvesting():
            return
        try:
            t0 = time.perf_counter_ns()
            lowered = self._fn.lower(*args, **kwargs)
            t1 = time.perf_counter_ns()
            compiled = lowered.compile()
            t2 = time.perf_counter_ns()
        except Exception:
            return
        rec = note_program_cost(self._site, self._digest, t1 - t0, t2 - t1,
                                harvest_compiled(compiled), op=current_op())
        # per-fusion HLO attribution (hlo.py): same gate — this runs
        # only inside the harvesting() window, so with events+obs off
        # as_text() is never fetched (the zero-overhead contract); a
        # parse failure records nothing and never fails the query
        from . import hlo as _hlo

        _hlo.harvest_hlo(compiled, self._site, self._digest,
                         op=rec.get("op"),
                         xla_bytes=rec.get("bytes_accessed"))
        self._compiled = compiled


def wrap(built, site: Optional[str], key) -> Any:
    """Pipeline-cache hook (exec/base.cached_pipeline): wrap a freshly
    built value in a CostProbe when harvesting is on. Handles the mesh
    path's ``(jit_fn, aux)`` tuples; values without a ``lower`` hook
    (plain callables) pass through untouched, as does everything when no
    cost consumer is active (the zero-overhead contract)."""
    if site is None or not harvesting():
        return built
    if (isinstance(built, tuple) and built
            and callable(built[0]) and hasattr(built[0], "lower")):
        return (CostProbe(built[0], site, digest_of(key)),) + built[1:]
    if callable(built) and hasattr(built, "lower"):
        return CostProbe(built, site, digest_of(key))
    return built
