"""TpuSession + DataFrame: the Catalyst stand-in.

A DataFrame is an immutable logical node tree; ``collect()`` lowers it to a
CPU physical plan (the 'what Spark would hand us' plan), runs the override
pass, and executes the result. ``last_executed_plan`` and
``last_explain`` expose what happened for the differential-test harness
(reference: ExecutionPlanCaptureCallback, Plugin.scala:216-305).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from .. import events as _events  # registers the eventLog.* conf entries
from .. import faults as _faults  # registers the test.faults.* entries
from .. import obs as _obs
from ..conf import (DONATION_WITNESS_ENABLED, RACECHECK_WITNESS_ENABLED,
                    RapidsConf)
from ..cpu import plan as C
from ..memory import catalog as _catalog  # noqa: F401 — registers the
# memory.* conf entries (hbm.budgetBytes) BEFORE RapidsConf validates a
# user's settings dict; the plan analyzer's OOM check reads them
from ..serve import scheduler as _serve  # noqa: F401 — registers the
# serve.* conf entries (serve.enabled picks the submit path below)
from ..exec.transitions import ColumnarToRowExec
from ..expr import aggregates as A
from ..expr import expressions as E
from ..plugin.overrides import TpuOverrides
from ..types import StructType
from ..utils import locks as _locks


@dataclasses.dataclass(frozen=True)
class LNode:
    """Logical node; lowered 1:1 to a CPU physical exec."""

    kind: str
    args: tuple  # hashable payload
    children: Tuple["LNode", ...] = ()


_SCANNER_CACHE: Dict[tuple, Any] = {}
_SCANNER_CACHE_LOCK = threading.Lock()


def _make_scanner(fmt: str, path: str, opts: tuple, conf: RapidsConf,
                  pushed: tuple = ()):
    """Build (and cache) a file scanner; the cache avoids re-parsing
    footers on every schema access (conf identity is part of the key).
    Guarded: concurrent serving sessions plan in parallel, and the
    check-then-act would otherwise build (and race-install) duplicate
    scanners for one file."""
    # the key holds the conf VALUES planning depends on, not id(conf): an
    # id can be reused after GC and silently serve a scanner planned under
    # different settings (advisor finding r2)
    from ..conf import (
        CLOUD_SCHEMES,
        MAX_READER_BATCH_SIZE_BYTES,
        PARQUET_READER_TYPE,
    )

    key = (fmt, path, opts, pushed, conf.get(PARQUET_READER_TYPE),
           conf.get(MAX_READER_BATCH_SIZE_BYTES), conf.get(CLOUD_SCHEMES))
    sc = _SCANNER_CACHE.get(key)
    if sc is not None:
        return sc
    with _SCANNER_CACHE_LOCK:
        sc = _SCANNER_CACHE.get(key)
        if sc is not None:
            return sc
        od = dict(opts)
        if fmt == "parquet":
            from ..io.parquet import ParquetScanner

            sc = ParquetScanner(
                path, conf, columns=od.get("columns"),
                filters=list(pushed))
        elif fmt == "csv":
            from ..io.csv import CsvScanner

            sc = CsvScanner(
                path, conf, schema=od.get("schema"),
                header=od.get("header", True), sep=od.get("sep", ","))
        elif fmt == "orc":
            from ..io.orc import OrcScanner

            sc = OrcScanner(path, conf, columns=od.get("columns"),
                            filters=list(pushed))
        else:
            raise ValueError(f"unknown file format {fmt}")
        if len(_SCANNER_CACHE) > 256:
            _SCANNER_CACHE.clear()
        _SCANNER_CACHE[key] = sc
    return sc


def _extract_pushed_filters(cond: E.Expression) -> tuple:
    """col-vs-literal conjuncts for row-group pruning (reference: the
    parquet pushdown assembled in GpuParquetScan's filterBlocks). Unknown
    shapes are simply not pushed — pruning is advisory, the filter exec
    still runs."""
    from ..io.parquet import PushedFilter

    out: List[PushedFilter] = []

    def visit(e: E.Expression):
        if isinstance(e, E.And):
            visit(e.left)
            visit(e.right)
            return
        ops = {
            E.EqualTo: "=", E.LessThan: "<", E.LessThanOrEqual: "<=",
            E.GreaterThan: ">", E.GreaterThanOrEqual: ">=",
        }
        t = type(e)
        if t in ops:
            l, r = e.left, e.right
            if isinstance(l, E.UnresolvedAttribute) and isinstance(r, E.Literal):
                out.append(PushedFilter(l.name, ops[t], r.value))
            elif isinstance(r, E.UnresolvedAttribute) and isinstance(l, E.Literal):
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
                out.append(PushedFilter(r.name, flip[ops[t]], l.value))
        elif isinstance(e, E.IsNull) and isinstance(
                e.child, E.UnresolvedAttribute):
            out.append(PushedFilter(e.child.name, "isnull"))
        elif isinstance(e, E.IsNotNull) and isinstance(
                e.child, E.UnresolvedAttribute):
            out.append(PushedFilter(e.child.name, "notnull"))

    visit(cond)
    return tuple(out)


def _resolve_udfs(e: E.Expression, conf: RapidsConf) -> E.Expression:
    """Resolution pass: PythonUDF -> bytecode-compiled expression tree when
    spark.rapids.tpu.sql.udfCompiler.enabled (reference: the udf-compiler's
    injectResolutionRule rewriting ScalaUDF bodies, Plugin.scala:31-64).
    Uncompilable UDFs stay as PythonUDF nodes and run row-by-row on CPU."""
    from ..conf import UDF_COMPILER_ENABLED

    if not conf.get(UDF_COMPILER_ENABLED):
        return e

    def rw(node):
        if isinstance(node, E.PythonUDF):
            from ..udf import try_compile

            compiled = try_compile(node)
            if compiled is not None:
                return compiled
        return node

    return e.transform(rw)


def _lower(node: LNode, conf: RapidsConf) -> C.CpuExec:
    k = node.kind
    rx = lambda ex: _resolve_udfs(ex, conf)  # noqa: E731
    if k == "filter" and node.children[0].kind == "file_scan":
        # push col-vs-literal conjuncts into the scan for row-group pruning
        (cond,) = node.args
        cond = rx(cond)
        fmt, path, opts = node.children[0].args
        pushed = (
            _extract_pushed_filters(cond) if fmt in ("parquet", "orc") else ())
        sc = _make_scanner(fmt, path, opts, conf, pushed)
        return C.CpuFilterExec(conf, cond, C.CpuFileScanExec(conf, sc, fmt))
    kids = [_lower(c, conf) for c in node.children]
    if k == "file_scan":
        fmt, path, opts = node.args
        return C.CpuFileScanExec(
            conf, _make_scanner(fmt, path, opts, conf), fmt)
    if k == "scan":
        rows, schema, nparts = node.args
        per = (len(rows) + nparts - 1) // nparts if rows else 0
        parts = [
            list(rows[i * per: (i + 1) * per]) if per else []
            for i in range(nparts)
        ] if nparts > 1 else [list(rows)]
        return C.CpuScanExec(conf, parts, schema)
    if k == "range":
        start, end, step, slices, name = node.args
        return C.CpuRangeExec(conf, start, end, step, slices, name)
    if k == "project":
        (exprs,) = node.args
        return C.CpuProjectExec(conf, [rx(e) for e in exprs], kids[0])
    if k == "filter":
        (cond,) = node.args
        return C.CpuFilterExec(conf, rx(cond), kids[0])
    if k == "aggregate":
        keys, aggs = node.args
        return C.CpuHashAggregateExec(
            conf, [rx(e) for e in keys], [rx(a) for a in aggs], kids[0])
    if k == "sort":
        exprs, orders = node.args
        return C.CpuSortExec(
            conf, [rx(e) for e in exprs], list(orders), kids[0])
    if k == "limit":
        (n,) = node.args
        return C.CpuLocalLimitExec(conf, n, kids[0])
    if k == "collect_limit":
        (n,) = node.args
        return C.CpuCollectLimitExec(conf, n, kids[0])
    if k == "generate":
        gens, name, with_pos = node.args
        return C.CpuGenerateExec(
            conf, [rx(g) for g in gens], name, with_pos, kids[0])
    if k == "union":
        return C.CpuUnionExec(conf, kids)
    if k == "expand":
        projections, names = node.args
        return C.CpuExpandExec(conf, [list(p) for p in projections], list(names), kids[0])
    if k == "join":
        lkeys, rkeys, how, cond = node.args
        return C.CpuJoinExec(conf, kids[0], kids[1], list(lkeys), list(rkeys), how, cond)
    if k == "window":
        (wexprs,) = node.args
        return C.CpuWindowExec(conf, list(wexprs), kids[0])
    raise ValueError(f"unknown logical node {k}")


def _as_expr(e: Union[str, E.Expression]) -> E.Expression:
    return E.col(e) if isinstance(e, str) else e


_SESSION_SEQ = [0]
_SESSION_SEQ_LOCK = threading.Lock()


def _next_session_id() -> str:
    with _SESSION_SEQ_LOCK:
        _SESSION_SEQ[0] += 1
        return f"session-{_SESSION_SEQ[0]}"


# Query ids are PROCESS-global, not per-session: concurrent serving
# sessions share the live progress tracker (keyed by query id) and merge
# their event logs for offline profiling — per-session numbering would
# collide entries across sessions (two live "query 3"s overwrite each
# other's progress attribution).
_QUERY_SEQ = [0]
_QUERY_SEQ_LOCK = threading.Lock()


def _next_query_id() -> int:
    with _QUERY_SEQ_LOCK:
        _QUERY_SEQ[0] += 1
        return _QUERY_SEQ[0]


class TpuSession:
    """reference analog: SparkSession with the plugin installed."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self.conf = RapidsConf(settings)
        self.overrides = TpuOverrides(self.conf)
        self.last_executed_plan = None
        self.last_cpu_plan = None
        self.last_analysis = None
        #: stable name in serving queues / event lanes ("session-N")
        self.serve_id = _next_session_id()
        # planning is session-state-mutating (last_* fields, the pending
        # obs slot): the serving path lets N threads share one session,
        # so plan+claim runs under this lock (the drain itself is
        # arbitrated by the scheduler + semaphore, not this lock)
        self._plan_lock = _locks.ordered_lock("sql.plan", reentrant=True)
        self._serve_analysis = None
        self._serve_plan_key = None
        self._last_digest: Optional[str] = None
        # the structured event log (events.py): a ring buffer always backs
        # export_trace(); a JSONL sink appears when eventLog.dir is set.
        # Disabled (the default) costs one boolean per emit site.
        self.events = _events.EventLogger(self.conf)
        self._active_query: Optional[int] = None
        self._pending_obs: Optional[tuple] = None
        # the live observability plane (obs/): registry + conf-gated
        # /metrics + /status exporter thread + watchdog. ensure_started
        # is a no-op returning None with the confs off (the default) —
        # no registry, no threads, one boolean per emit site.
        self._obs_plane = _obs.ensure_started(self.conf)
        # deterministic fault injector (faults.py, chaos testing): a
        # no-op returning None with the test.faults.* confs off (the
        # default) — nothing installed, injection sites stay one
        # module-global boolean read. Never uninstalled implicitly;
        # tests pair install with faults.uninstall().
        _faults.install(self.conf)
        # persistent AOT program cache (serve/program_cache.py): a no-op
        # returning None with the aotCache.* confs off (the default) —
        # no directory touched, no jax config change, the pipeline-cache
        # fast path unchanged. Same lifecycle as the fault injector:
        # process-global, tests pair install with uninstall().
        from ..serve import program_cache as _progcache

        _progcache.install(self.conf)
        # runtime lock-order witness (utils/locks.py): validates every
        # ordered_lock acquire against the declared LOCK_ORDER and
        # records observed acquisition pairs. Off (the default) keeps an
        # acquire at one module-global read; process-global once on,
        # tests pair install_witness with uninstall_witness().
        if self.conf.get(RACECHECK_WITNESS_ENABLED):
            _locks.install_witness()
        # runtime donation witness (plugin/donation.py): asserts donated
        # planes really were deleted post-dispatch and types use-after-
        # donation errors. Same lifecycle as the lock witness (process-
        # global once on; SRTPU_DONATION_WITNESS=1 is the env hook).
        if self.conf.get(DONATION_WITNESS_ENABLED):
            from ..plugin import donation as _donation

            _donation.install_witness()

    def close(self) -> None:
        """Flush/close the session's event sink (atexit also covers a
        forgotten close) and detach it from the process-global emit
        path. The obs plane is process-wide and stays up for other
        sessions; stop it explicitly with obs.shutdown()."""
        if _events._ACTIVE is self.events:
            _events.uninstall()
        self.events.close()

    @property
    def obs_address(self) -> Optional[str]:
        """Base URL of the live metrics exporter (None unless
        spark.rapids.tpu.metrics.http.enabled): <url>/metrics is the
        Prometheus scrape target, <url>/status feeds tools/tpu_top.py."""
        return (self._obs_plane.address
                if self._obs_plane is not None else None)

    @property
    def last_explain(self) -> str:
        return self.overrides.last_explain

    def create_dataframe(
        self, data: Dict[str, Sequence[Any]], schema: StructType,
        num_partitions: int = 1,
    ) -> "DataFrame":
        names = schema.names
        n = len(data[names[0]]) if names else 0
        rows = tuple(
            tuple(data[name][i] for name in names) for i in range(n)
        )
        return DataFrame(self, LNode("scan", (rows, schema, num_partitions)))

    def from_rows(self, rows: Sequence[tuple], schema: StructType,
                  num_partitions: int = 1) -> "DataFrame":
        return DataFrame(
            self, LNode("scan", (tuple(tuple(r) for r in rows), schema, num_partitions))
        )

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_slices: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(self, LNode("range", (start, end, step, num_slices, "id")))

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    # -- execution ---------------------------------------------------------
    def _execute(self, node: LNode) -> C.CpuExec:
        from ..exec.base import compile_snapshot

        cpu = _lower(node, self.conf)
        self.last_cpu_plan = cpu
        from ..conf import ANALYSIS_CROSS_CHECK, ANALYSIS_ENABLED, SQL_ENABLED

        obs_on = _obs.enabled()
        serve_on = self._serve_enabled()
        run_analysis = self.conf.get(SQL_ENABLED) and (
            self.conf.get(ANALYSIS_CROSS_CHECK)
            # with event logging on, the analyzer's forecasts ride in the
            # log so tpu_profile's forecast-vs-actual report has its
            # bounds without a separate explain() run; the live plane
            # needs them too — /status progress denominators; the serving
            # scheduler needs the peak-HBM forecast for admission
            or ((self.events.enabled or obs_on or serve_on)
                and self.conf.get(ANALYSIS_ENABLED)))
        digest: Optional[str] = None
        if self.events.enabled or obs_on or serve_on:
            import hashlib

            digest = hashlib.sha1(
                cpu.tree_string().encode()).hexdigest()[:12]
        self._last_digest = digest
        analysis = None
        self._serve_analysis = None
        self._serve_plan_key = None
        if run_analysis:
            # the static analyzer runs BEFORE conversion/execution — it
            # must never touch the device (plugin/plananalysis.py)
            from ..plugin.plananalysis import analyze_plan

            if serve_on and digest is not None:
                # one analysis per plan digest across ALL sessions: the
                # admission forecast of a repeated plan shape is served
                # from the shared cache instead of recomputed
                from ..serve import SharedPlanCache, conf_fingerprint

                key = (digest, conf_fingerprint(self.conf))
                self._serve_plan_key = key
                analysis, _hit = SharedPlanCache.get().analysis_for(
                    key, lambda: analyze_plan(cpu, self.conf))
                self.last_analysis = analysis
            else:
                analysis = self.last_analysis = analyze_plan(
                    cpu, self.conf)
            self._serve_analysis = analysis
        final, is_tpu = self.overrides.apply(cpu)
        if is_tpu:
            final = ColumnarToRowExec(self.conf, final)
        self.last_executed_plan = final
        # snapshot BEFORE execution so explain_metrics reports only the
        # misses THIS plan's run compiled (the counter is process-global)
        self._compile_baseline = compile_snapshot()
        from .. import xla_cost as _xla_cost

        # same pattern for harvested program costs: the report shows the
        # XLA cost columns for programs THIS run compiled (a warm rerun
        # compiles nothing, so its report carries none — steady state);
        # conf-declared roofline peaks ride in the harvested events so
        # the offline profiler (which has no conf) honors calibration
        self._cost_baseline = _xla_cost.snapshot()
        _xla_cost.set_conf_peaks(self.conf)
        from .. import hlo as _hlo

        _hlo.set_conf_top_k(self.conf)
        if self.events.enabled or obs_on:
            qid = self._active_query = _next_query_id()
            if self.events.enabled:
                self._emit_query_events(node, qid, digest, is_tpu)
            if obs_on:
                # progress registration is DEFERRED to the drain paths
                # (_run_collect / the writer generator) whose finally
                # guarantees a matching note_query_end — a direct
                # _execute consumer (ml/columnar_rdd, bench device
                # timing) must not strand a forever-"running" query in
                # /status. THIS query's analysis only — last_analysis
                # may hold a previous query's when the analyzer was
                # skipped here.
                self._pending_obs = (
                    qid, digest,
                    analysis.rows_by_op if analysis is not None else None,
                    analysis.batches_by_op
                    if analysis is not None else None)
        return final

    def _obs_take_pending(self) -> Optional[tuple]:
        """Claim the deferred progress registration for one drain path.
        Callers take it EAGERLY (right after _execute) — the slot is
        shared per session, so a later query must not be able to
        overwrite a writer's registration before its sink drains."""
        pending = self._pending_obs
        self._pending_obs = None
        return pending

    @staticmethod
    def _obs_begin(pending: Optional[tuple]) -> Optional[int]:
        """Activate a claimed registration on the DRAINING thread
        (attribution is by thread); returns the qid to close."""
        if pending is None or not _obs.enabled():
            return None
        qid, digest, rows_by_op, batches_by_op = pending
        _obs.note_query_start(qid, digest, rows_by_op, batches_by_op)
        return qid

    # -- event log ---------------------------------------------------------
    def _emit_query_events(self, node: LNode, qid: int, plan_digest: str,
                           is_tpu: bool) -> None:
        """query_start + plan_tagged + plan_analysis for one execution.
        The session's logger becomes the process-wide active sink, so
        engine-level emitters (catalog, caches, transports) attribute to
        this session's log."""
        import hashlib

        from .. import envinfo as _envinfo

        _events.install(self.events)
        # env provenance rides on every query_start so a merged/archived
        # log records WHAT hardware produced it (tpu_profile --diff
        # warns when two logs' environments differ)
        _events.emit("query_start", query_id=qid, plan_digest=plan_digest,
                     sql_hash=hashlib.sha1(
                         repr(node).encode()).hexdigest()[:12],
                     env=_envinfo.environment_info())
        meta = self.overrides.last_meta
        if meta is not None:
            fallbacks = []

            def walk(m):
                if m.reasons:
                    name = m.rule.name if m.rule else m.wrapped.node_name
                    fallbacks.append({"op": name,
                                      "reasons": list(m.reasons)})
                for c in m.child_metas:
                    walk(c)

            walk(meta)
            _events.emit("plan_tagged", query_id=qid, on_tpu=is_tpu,
                         fallbacks=fallbacks)
        if self.last_analysis is not None:
            _events.emit("plan_analysis", query_id=qid,
                         **self.last_analysis.event_fields())

    _PENDING_UNSET = object()

    def _run_collect(self, final: C.CpuExec, qid: Optional[int] = None,
                     pending: Any = _PENDING_UNSET,
                     digest: Optional[str] = None) -> List[tuple]:
        """Driver-side collect with the query_end event (duration + row
        count) paired to _execute's query_start. Emitted in a finally so a
        failing query still CLOSES its window — an unterminated
        query_start would make the offline profiler attribute every later
        event to the dead query. The serving path passes ``qid`` and the
        ``pending`` obs registration it claimed under the plan lock
        (concurrent submits on one session would otherwise race the
        shared slots)."""
        import time as _time

        t0 = _time.perf_counter_ns()
        if pending is TpuSession._PENDING_UNSET:
            pending = self._obs_take_pending()
        if qid is None:
            qid = self._active_query
        obs_qid = self._obs_begin(pending)
        # the HBM ledger's ownership window: buffers registered by this
        # drain belong to this query; the sweep at close folds the
        # observed peak into the per-digest admission feed and runs the
        # leak sentinel. qid is None exactly when events+obs are off, so
        # the off path never touches the ledger (zero-overhead contract).
        from ..memory import ledger as _ledger

        scope = _ledger.query_scope(qid) if qid is not None else None
        rows: Optional[List[tuple]] = None
        try:
            if scope is not None:
                with scope:
                    rows = final.collect()
            else:
                rows = final.collect()
            return rows
        finally:
            if self.events.enabled:
                _events.emit(
                    "query_end", query_id=qid,
                    dur=_time.perf_counter_ns() - t0,
                    rows=len(rows) if rows is not None else None,
                    error=rows is None)
            if obs_qid is not None:
                _obs.note_query_end(
                    obs_qid,
                    rows=len(rows) if rows is not None else None,
                    error=rows is None)
            if qid is not None:
                _catalog.BufferCatalog.get().ledger.sweep_query(
                    qid, digest=digest or self._last_digest)

    # -- serving path (serve/scheduler.py) ---------------------------------
    def _serve_enabled(self) -> bool:
        return self.conf.get(_serve.SERVE_ENABLED)

    def _collect(self, node: LNode) -> List[tuple]:
        """Plan + drain one query, through the serving scheduler when
        spark.rapids.tpu.serve.enabled is set."""
        if not self._serve_enabled():
            return self._run_collect(self._execute(node))
        return self._collect_serve(node)

    def _collect_serve(self, node: LNode) -> List[tuple]:
        """Serve-path drain with the OOM requeue contract (ROADMAP item
        4's failure mode): an admitted query whose runtime peak busts
        its static forecast — and whose spill/retry/split recovery
        (memory/retry.py) still couldn't complete it at the CURRENT
        occupancy — releases its reservation (the finally below) and is
        resubmitted exactly ONCE with its forecast inflated to the
        observed peak watermark, so the scheduler queues it until that
        much headroom is real. A second typed OOM propagates: forecast
        misses degrade to queueing, genuine can't-fit degrades to a
        named error, never a crash loop."""
        from ..memory.retry import TpuOOMError
        from ..serve import QueryScheduler

        try:
            return self._collect_serve_once(node)
        except TpuOOMError as e:
            from ..memory.catalog import BufferCatalog

            # THIS query's observed need: the ledger's per-query peak
            # when it tracked the failed attempt (the attributed figure
            # — catalog-registered buffers this query actually owned),
            # else the catalog watermark the typed error captured at its
            # failure. NEVER the process-lifetime peak_device_bytes,
            # which an earlier heavy query pins forever and would
            # inflate every later small query's requeue. Capped at the
            # total budget so a transient OOM can never convert into a
            # permanent ServeAdmissionRejected (acquire rejects
            # forecasts above the budget outright).
            cat = BufferCatalog.get()
            led_peak = cat.observed_query_peak(self._active_query)
            observed = led_peak or getattr(e, "watermark", None) or 0
            budget, _, _ = cat.admission_state()
            if budget is not None:
                observed = min(observed, budget)
            QueryScheduler.get(self.conf).note_oom_requeue(
                self.serve_id, self._last_digest or "", observed or None,
                forecast_source="ledger" if led_peak else "watermark")
            return self._collect_serve_once(
                node, forecast_floor=observed or None)

    def _collect_serve_once(self, node: LNode,
                            forecast_floor: Optional[int] = None
                            ) -> List[tuple]:
        """Submit-through-scheduler: plan on the calling thread (host
        work of a queued query overlaps the running query's device
        compute), admit against the peak-HBM forecast, host-prefetch
        scans after admission but BEFORE the device semaphore, then
        drain. The reservation releases in a finally so a failed query
        frees its headroom. ``forecast_floor``: the OOM-requeue path's
        inflated forecast (the observed peak watermark of the failed
        attempt) — admission then waits for headroom reality showed the
        query needs, not what the analyzer guessed."""
        from ..serve import QueryScheduler, SharedPlanCache
        from ..serve.scheduler import SERVE_PRIORITY

        sched = QueryScheduler.get(self.conf)
        with self._plan_lock:
            final = self._execute(node)
            digest = self._last_digest or ""
            plan_key = self._serve_plan_key
            analysis = self._serve_analysis
            pending = self._obs_take_pending()
            qid = self._active_query
        # the analyzer's peak-HBM forecast whenever it produced one —
        # "bounded" (forecasts ASSERTED) is a stronger property than the
        # admission check needs: parquet plans forecast a peak (footer-
        # derived residency) without being fully bounded
        forecast = analysis.peak_hbm if analysis is not None else None
        forecast_source = "analyzer"
        # the measured-stats loop (ROADMAP 5a): once the HBM ledger has
        # observed a completed run of this plan digest, its per-query
        # peak replaces the static bound — admission charges what the
        # plan was MEASURED to hold, not what the analyzer guessed
        from ..memory.catalog import BufferCatalog as _BC

        observed = _BC.get().ledger.observed_peak(digest)
        if observed:
            forecast = observed
            forecast_source = "ledger"
        if forecast_floor is not None:
            forecast = max(forecast or 0, forecast_floor)
        try:
            # priority/timeout/depth are THIS session's settings — the
            # scheduler singleton may have been created by another one
            ticket = sched.acquire(
                self.serve_id, self.conf.get(SERVE_PRIORITY), forecast,
                digest, conf_=self.conf,
                forecast_source=forecast_source)
        except Exception:
            # a reject/timeout must still CLOSE the query_start window
            # _execute opened, or the offline profiler attributes every
            # later event to the dead query
            if self.events.enabled and qid is not None:
                _events.emit("query_end", query_id=qid, dur=0, rows=None,
                             error=True)
            raise
        try:
            if isinstance(final, ColumnarToRowExec):
                # pipelined phase split: host-side decode starts now, on
                # the shared pools, while whoever holds the semaphore
                # keeps the device busy
                final.tpu_child.host_prefetch()
            rows = self._run_collect(final, qid=qid, pending=pending,
                                     digest=digest)
            if plan_key is not None:
                SharedPlanCache.get().mark_warm(plan_key)
            return rows
        finally:
            sched.release(ticket)

    def export_trace(self, path: str) -> str:
        """Write the session's event ring buffer as Chrome/Perfetto
        trace-event JSON — open it directly in ui.perfetto.dev. Works with
        or without eventLog.dir (the ring buffer always backs it); raises
        when event logging is off entirely."""
        if not self.events.enabled:
            raise RuntimeError(
                "event logging is off: set spark.rapids.tpu.eventLog."
                "enabled (ring buffer only) or eventLog.dir (JSONL file) "
                "to record a trace")
        return _events.export_chrome_trace(self.events.records(), path)

    def explain_metrics(self) -> str:
        """Per-operator metrics report for the LAST executed plan — the
        profiler's user-facing output (reference analog: the SQL-UI metric
        table each GpuExec publishes). Every exec line shows wall-clock
        totalTime, output rows/batches, and bytesTouched; runs under
        spark.rapids.tpu.metrics.deviceSync.enabled add device-accurate
        opTimeDevice and a derived per-op HBM GB/s labeled by the lane
        that fed it (hbm_gbps[device] preferred; hbm_gbps[host]
        otherwise — the host lane understates async device work, so its
        figure overstates bandwidth and says so in its label). When the
        cost plane harvested programs during the run (event log / obs
        on), per-op xla_bytes/xla_flops/xla_gbps columns report what XLA
        actually compiled. The footer counts XLA pipeline compile-cache
        misses by site for THIS plan's run (a recompile-storm detector)
        plus the harvested trace/compile time split. How to read it:
        docs/tuning.md."""
        from ..exec.base import TpuExec, format_metrics

        plan = self.last_executed_plan
        if plan is None:
            return "<no plan executed yet>"
        node = plan.tpu_child if isinstance(plan, ColumnarToRowExec) else plan
        if not isinstance(node, TpuExec):
            return "<last plan ran on CPU; no device metrics>"
        return format_metrics(node, getattr(self, "_compile_baseline", None),
                              cost_since=getattr(self, "_cost_baseline",
                                                 None))


class GroupedData:
    def __init__(self, df: "DataFrame", keys: Sequence[E.Expression]):
        self._df = df
        self._keys = list(keys)

    def agg(self, *aggs: A.AggregateExpression) -> "DataFrame":
        return DataFrame(
            self._df.session,
            LNode("aggregate", (tuple(self._keys), tuple(aggs)), (self._df.node,)),
        )

    def count(self) -> "DataFrame":
        return self.agg(A.agg(A.Count(), "count"))


class DataFrameReader:
    """reference analog: spark.read with the plugin's scan rules."""

    def __init__(self, session: "TpuSession"):
        self._session = session

    def parquet(self, path: str,
                columns: Optional[Sequence[str]] = None) -> "DataFrame":
        opts = (("columns", tuple(columns) if columns else None),)
        return DataFrame(
            self._session, LNode("file_scan", ("parquet", path, opts)))

    def csv(self, path: str, schema: Optional[StructType] = None,
            header: bool = True, sep: str = ",") -> "DataFrame":
        opts = (("schema", schema), ("header", header), ("sep", sep))
        return DataFrame(
            self._session, LNode("file_scan", ("csv", path, opts)))

    def orc(self, path: str,
            columns: Optional[Sequence[str]] = None) -> "DataFrame":
        opts = (("columns", tuple(columns) if columns else None),)
        return DataFrame(
            self._session, LNode("file_scan", ("orc", path, opts)))


class DataFrameWriter:
    """reference analog: df.write through GpuParquetFileFormat +
    GpuFileFormatWriter's commit protocol."""

    def __init__(self, df: "DataFrame"):
        self._df = df

    def _batches(self):
        df = self._df
        sess = df.session
        final = sess._execute(df.node)
        schema = final.output_schema
        # capture NOW: by the time the generator drains, another query on
        # this session may have replaced _active_query (and, same race,
        # overwritten the shared _pending_obs slot)
        qid = sess._active_query
        obs_pending = sess._obs_take_pending()

        def gen():
            import time as _time

            t0 = _time.perf_counter_ns()
            # activated here, on the draining thread, so note_batch
            # attribution lands on this query (and the finally below
            # guarantees the matching end)
            obs_qid = sess._obs_begin(obs_pending)
            from ..memory import ledger as _ledger

            scope = _ledger.query_scope(qid) if qid is not None else None
            ok = False
            try:
                if scope is not None:
                    scope.__enter__()
                if isinstance(final, ColumnarToRowExec):
                    # columnar fast path: hand device batches to the writer
                    yield from final.tpu_child.execute_columnar()
                else:
                    from ..columnar.batch import batch_from_rows

                    buf: List[tuple] = []
                    for row in (
                        r for p in range(final.num_partitions)
                        for r in final.execute_rows_partition(p)
                    ):
                        buf.append(row)
                        if len(buf) >= 65536:
                            yield batch_from_rows(buf, schema)
                            buf = []
                    if buf:
                        yield batch_from_rows(buf, schema)
                ok = True
            finally:
                if scope is not None:
                    scope.__exit__(None, None, None)
                if sess.events.enabled:
                    # writer path: duration only (a row count would force
                    # a device sync per batch just for logging); the
                    # finally closes the window even on error/abandonment
                    _events.emit("query_end", query_id=qid,
                                 dur=_time.perf_counter_ns() - t0,
                                 rows=None, error=not ok)
                if obs_qid is not None:
                    _obs.note_query_end(obs_qid, rows=None, error=not ok)
                if qid is not None:
                    _catalog.BufferCatalog.get().ledger.sweep_query(
                        qid, digest=sess._last_digest)

        return gen(), schema

    def parquet(self, path: str, compression: str = "snappy") -> Dict[str, int]:
        from ..io.parquet import write_parquet

        batches, schema = self._batches()
        return write_parquet(batches, path, schema, compression)

    def orc(self, path: str, compression: str = "zstd") -> Dict[str, int]:
        from ..io.orc import write_orc

        batches, schema = self._batches()
        return write_orc(batches, path, schema, compression)

    def csv(self, path: str) -> Dict[str, int]:
        from ..io.csv import write_csv

        batches, schema = self._batches()
        return write_csv(batches, path, schema)


class DataFrame:
    def __init__(self, session: TpuSession, node: LNode):
        self.session = session
        self.node = node

    @property
    def write(self) -> DataFrameWriter:
        return DataFrameWriter(self)

    # -- transformations ---------------------------------------------------
    def select(self, *exprs: Union[str, E.Expression]) -> "DataFrame":
        return DataFrame(
            self.session,
            LNode("project", (tuple(_as_expr(e) for e in exprs),), (self.node,)),
        )

    def where(self, cond: E.Expression) -> "DataFrame":
        return DataFrame(self.session, LNode("filter", (cond,), (self.node,)))

    filter = where

    def with_column(self, name: str, expr: E.Expression) -> "DataFrame":
        schema = self.schema
        exprs: List[E.Expression] = []
        replaced = False
        for f in schema.fields:
            if f.name == name:
                exprs.append(E.Alias(expr, name))
                replaced = True
            else:
                exprs.append(E.col(f.name))
        if not replaced:
            exprs.append(E.Alias(expr, name))
        return self.select(*exprs)

    def group_by(self, *keys: Union[str, E.Expression]) -> GroupedData:
        return GroupedData(self, [_as_expr(k) for k in keys])

    def agg(self, *aggs: A.AggregateExpression) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def order_by(self, *exprs: Union[str, E.Expression],
                 ascending: Union[bool, Sequence[bool]] = True,
                 nulls_first: Union[None, bool, Sequence[Optional[bool]]] = None,
                 ) -> "DataFrame":
        es = [_as_expr(e) for e in exprs]
        if isinstance(ascending, bool):
            ascending = [ascending] * len(es)
        if nulls_first is None or isinstance(nulls_first, bool):
            nulls_first = [nulls_first] * len(es)
        orders = tuple(zip(ascending, nulls_first))
        return DataFrame(self.session, LNode("sort", (tuple(es), orders), (self.node,)))

    sort = order_by

    def limit(self, n: int) -> "DataFrame":
        """Global limit (Spark CollectLimit semantics: at most n rows total,
        taken from partitions in order)."""
        return DataFrame(
            self.session, LNode("collect_limit", (n,), (self.node,)))

    def local_limit(self, n: int) -> "DataFrame":
        """Per-partition limit (Spark LocalLimit)."""
        return DataFrame(self.session, LNode("limit", (n,), (self.node,)))

    def explode(self, values: Sequence[E.Expression], name: str = "col",
                pos: bool = False) -> "DataFrame":
        """explode(array(e1..eN)): one output row per element, keeping the
        input columns (posexplode with ``pos=True``)."""
        return DataFrame(
            self.session,
            LNode("generate", (tuple(values), name, pos), (self.node,)))

    def cross_join(self, other: "DataFrame",
                   condition: Optional[E.Expression] = None) -> "DataFrame":
        """Cartesian product, optionally with a residual condition."""
        return DataFrame(
            self.session,
            LNode("join", ((), (), "inner", condition),
                  (self.node, other.node)),
        )

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(
            self.session, LNode("union", (), (self.node, other.node))
        )

    def join(self, other: "DataFrame", on: Union[str, Sequence[str], Sequence[Tuple[str, str]]],
             how: str = "inner", condition: Optional[E.Expression] = None) -> "DataFrame":
        if isinstance(on, str):
            on = [on]
        pairs = [
            (k, k) if isinstance(k, str) else k for k in on
        ]
        lkeys = tuple(E.col(a) for a, _ in pairs)
        rkeys = tuple(E.col(b) for _, b in pairs)
        return DataFrame(
            self.session,
            LNode("join", (lkeys, rkeys, how, condition), (self.node, other.node)),
        )

    def with_windows(self, *wexprs) -> "DataFrame":
        """Append window columns (function OVER partition/order spec)."""
        return DataFrame(
            self.session, LNode("window", (tuple(wexprs),), (self.node,))
        )

    def distinct(self) -> "DataFrame":
        keys = tuple(E.col(f.name) for f in self.schema.fields)
        return DataFrame(
            self.session, LNode("aggregate", (keys, ()), (self.node,))
        )

    # -- actions -----------------------------------------------------------
    @property
    def schema(self) -> StructType:
        return _lower(self.node, self.session.conf).output_schema

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    def collect(self) -> List[tuple]:
        return self.session._collect(self.node)

    def count(self) -> int:
        return len(self.collect())

    def to_pydict(self) -> Dict[str, List[Any]]:
        rows = self.collect()
        names = self.columns
        return {n: [r[i] for r in rows] for i, n in enumerate(names)}

    def explain(self) -> str:
        """Tagging report (which operators run on TPU and why not) plus —
        when sql.analysis.enabled — the static plan analysis: per-operator
        batch layouts, nullability, the compile-signature forecast
        (recompile-storm detection), and the predicted peak HBM footprint
        checked against the memory budget. Nothing is lowered or executed
        and no device allocation happens (see docs/tuning.md)."""
        conf = self.session.conf
        cpu = _lower(self.node, conf)
        from ..plugin.overrides import PlanMeta

        meta = PlanMeta(cpu, conf)
        meta.tag_for_tpu()
        lines = meta.explain_lines()
        from ..conf import ANALYSIS_ENABLED, SQL_ENABLED

        if conf.get(SQL_ENABLED) and conf.get(ANALYSIS_ENABLED):
            from ..plugin.plananalysis import analyze_plan

            analysis = analyze_plan(cpu, conf, meta=meta)
            self.session.last_analysis = analysis
            lines.extend(analysis.render_lines())
        return "\n".join(lines)
