"""User-facing SQL layer: session + DataFrame building CPU physical plans
that the plugin (plugin/overrides.py) then rewrites onto the TPU.

In the reference the 'user layer' is Spark itself; here a small DataFrame
API stands in for Catalyst, producing the CPU physical plans the override
pass consumes — the same seam the reference plugs into
(Plugin.scala:40-47 ColumnarRule hooks).
"""
from .session import DataFrame, TpuSession  # noqa: F401
