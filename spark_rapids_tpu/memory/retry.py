"""OOM retry + split-and-retry harness: the runtime recovery plane.

Reference analog: ``RmmRapidsRetryIterator.scala`` — the reference wraps
every operator's batch work in ``withRetry``/``withRetryNoSplit``: a
``RetryOOM`` spills spillable buffers and re-attempts, a
``SplitAndRetryOOM`` halves the input and recurses, and only exhaustion
surfaces to the task. Our static half (the serve scheduler admitting on
the analyzer's peak-HBM forecast) queues work that predictably fits; this
module is the dynamic half for when the forecast is WRONG — a mis-sized
join, fragmentation, an un-modeled shape. A wrong forecast must degrade
to spill -> retry -> half-capacity batches, never to a raw XLA
``RESOURCE_EXHAUSTED`` killing the query.

The harness is wired at the exec per-batch dispatch boundaries
(exec/base.run_fused_chain, sort, aggregate update, join probe): the
attempt runs, a classified device-OOM releases what the process can give
back — spillable catalog buffers (``BufferCatalog.ensure_headroom``),
device scan-cache residency, the caller's staged prefetch via
``on_pressure`` — and re-attempts with bounded backoff. When retries
exhaust, the input ``ColumnarBatch`` splits row-wise in half
(columnar/split.py, preserving validity planes, dict aux planes, and
capacity buckets) and both halves recurse with bounded depth; outputs
re-join through the engine's existing multi-batch concat path, so
aggregates/sorts/joins/projects complete on half-capacity programs.
Final exhaustion raises a named :class:`TpuSplitAndRetryOOM` carrying op,
watermark, budget, attempts, and split depth.

Fault injection (faults.py) fires at the top of each attempt — the only
way to drive these paths on a CPU-fallback box that never really OOMs.
Zero-overhead-off: ``memory.oomRetry.enabled`` off short-circuits to a
plain call; on (the default), the happy path costs one try/except frame.
"""
from __future__ import annotations

import contextlib
import logging
import time
from typing import Callable, List, Optional, Sequence, Union

from .. import events as _events
from .. import faults as _faults
from .. import obs as _obs
from ..conf import RapidsConf, conf

log = logging.getLogger("spark_rapids_tpu.memory")

OOM_RETRY_ENABLED = conf(
    "spark.rapids.tpu.memory.oomRetry.enabled", True,
    "Wrap per-batch exec dispatches in the OOM retry + split-and-retry "
    "harness (memory/retry.py): a device allocation failure spills "
    "spillable buffers, drops scan-cache residency, and re-attempts "
    "with backoff; exhausted retries split the input batch in half and "
    "recurse (bounded depth), so operators complete on half-capacity "
    "programs instead of dying. Off restores the raw-failure behavior.")
OOM_RETRY_MAX_ATTEMPTS = conf(
    "spark.rapids.tpu.memory.oomRetry.maxAttempts", 2,
    "Attempts per split level before the harness escalates to "
    "split-and-retry (each failed attempt spills + backs off first).",
    check=lambda v: None if v > 0 else "must be positive")
OOM_RETRY_BACKOFF_MS = conf(
    "spark.rapids.tpu.memory.oomRetry.backoffMs", 5,
    "Base backoff before re-attempting after an OOM (doubles per "
    "attempt; gives concurrent queries a window to release memory). "
    "0 disables the sleep.", conf_type=int,
    check=lambda v: None if v >= 0 else "must be >= 0")
OOM_RETRY_MAX_SPLIT_DEPTH = conf(
    "spark.rapids.tpu.memory.oomRetry.maxSplitDepth", 4,
    "Split-and-retry recursion bound: each level halves the batch, so "
    "depth 4 reaches 1/16 capacity before TpuSplitAndRetryOOM surfaces.",
    check=lambda v: None if v >= 0 else "must be >= 0")


# ---------------------------------------------------------------------------
# Typed errors
# ---------------------------------------------------------------------------
class TpuOOMError(RuntimeError):
    """Base of the typed device-memory failures. Carries the recovery
    context so the error ALONE tells the story: the op, the catalog
    watermark and derived budget at failure, how many attempts ran, and
    how deep the split recursion went."""

    def __init__(self, message: str, op: str = "",
                 watermark: Optional[int] = None,
                 budget: Optional[int] = None, attempts: int = 0,
                 split_depth: int = 0):
        super().__init__(message)
        self.op = op
        self.watermark = watermark
        self.budget = budget
        self.attempts = attempts
        self.split_depth = split_depth


class TpuRetryOOM(TpuOOMError):
    """A classified device allocation failure on a non-splittable path
    whose bounded retries exhausted (the reference's RetryOOM verdict)."""


class TpuSplitAndRetryOOM(TpuOOMError):
    """Retries AND split-and-retry exhausted — the operator cannot
    complete even at 1/2^maxSplitDepth capacity."""


class TpuOutOfDeviceMemory(TpuOOMError):
    """A raw device allocation failure OUTSIDE the retry harness (scan
    staging, exchange, mesh staging) wrapped with op, live watermark,
    derived budget, and the largest spillable buffer — instead of a bare
    XLA traceback."""


#: substrings that identify a backend device-memory failure; XLA surfaces
#: RESOURCE_EXHAUSTED on TPU/GPU, the CPU backend "Out of memory", and
#: the injector (faults.py) deliberately carries the first pattern
_OOM_PATTERNS = (
    "RESOURCE_EXHAUSTED",
    "Resource exhausted",
    "resource exhausted",
    "Out of memory",
    "out of memory",
    "OutOfMemory",
    "Failed to allocate",
    "failed to allocate",
    "Allocation failure",
)


def is_device_oom(exc: BaseException) -> bool:
    """True when ``exc`` looks like a device allocation failure worth
    recovering from. Typed TpuOOMError verdicts return False — they are
    FINAL (a nested harness or named wrapper already recovered as far as
    recovery goes), except TpuOutOfDeviceMemory, which names a raw
    failure a surrounding harness may still fix by spilling."""
    if isinstance(exc, TpuOOMError):
        return isinstance(exc, TpuOutOfDeviceMemory)
    msg = str(exc)
    if any(p in msg for p in _OOM_PATTERNS):
        return True
    # XlaRuntimeError without a message match: only the explicit
    # RESOURCE_EXHAUSTED code counts (other runtime errors are bugs)
    return False


def _hbm_state() -> tuple:
    from .catalog import BufferCatalog

    cat = BufferCatalog.get()
    return cat.device_bytes, cat.budget, cat.largest_spillable()


def classify_oom(exc: BaseException, op: str) -> Optional[TpuRetryOOM]:
    """Wrap a raw backend failure into the typed TpuRetryOOM (None when
    ``exc`` is not a device OOM)."""
    if not is_device_oom(exc):
        return None
    watermark, budget, _ = _hbm_state()
    return TpuRetryOOM(
        f"device OOM in {op}: {exc}", op=op, watermark=watermark,
        budget=budget)


def _emit_retry(op: str, kind: str, attempt: int, depth: int) -> None:
    if _events.enabled() or _obs.enabled():
        watermark, budget, _ = _hbm_state()
        _events.emit("oom_retry", op=op, kind=kind, attempt=attempt,
                     depth=depth, watermark=watermark, budget=budget)
        if _obs.enabled():
            _obs.note_oom_retry(op, kind)


def _release_pressure(op: str,
                      on_pressure: Optional[Callable[[], None]]) -> int:
    """Give back what the process can: spill every spillable catalog
    buffer, drop scan-cache residency, and run the caller's hook
    (staged-prefetch invalidation). Returns bytes known released."""
    from .catalog import BufferCatalog

    freed = BufferCatalog.get().ensure_headroom()
    from ..io.scan_cache import DeviceScanCache

    cache = DeviceScanCache._instance
    if cache is not None:
        freed += cache.drop_under_pressure()
    if on_pressure is not None:
        try:
            on_pressure()
        except Exception:  # pragma: no cover - a hook must not mask OOM
            log.exception("on_pressure hook failed during OOM recovery")
    return freed


def concat_batches(conf_: RapidsConf, batches: Sequence) -> "object":
    """THE engine-wide multi-batch row stitch (the GpuCoalesceBatches
    concat): dict columns materialize at the boundary, char pools
    re-bucket, zero-column batches carry their summed row count.
    Re-joins split-and-retry piece outputs here, and
    exec/basic.TpuCoalesceBatchesExec._flush delegates to the same body
    — one implementation, no drift. Schema taken from the pieces."""
    batches = [b for b in batches if b is not None]
    if len(batches) == 1:
        return batches[0]
    from ..columnar import ColumnarBatch
    from ..columnar.column import choose_capacity

    schema = batches[0].schema
    if not batches[0].columns:
        total = sum(b.num_rows for b in batches)
        # same bucket rule as the columned branch below — a zero-column
        # count(*) stitch must land on the bucket the planner forecasts
        return ColumnarBatch(
            [], schema, total,
            capacity=choose_capacity(max(1, total),
                                     conf_.shape_bucket_min))
    from .. import types as T
    from ..exec.base import batch_from_vals, materialized_batch, \
        vals_of_batch
    from ..ops import concat as concat_ops

    pending = [materialized_batch(b) for b in batches]
    lengths = [b.num_rows for b in pending]
    total = sum(lengths)
    out_cap = choose_capacity(max(1, total), conf_.shape_bucket_min)
    str_cols = [
        j for j, f in enumerate(schema.fields)
        if isinstance(f.dataType, (T.StringType, T.BinaryType))
    ]
    byte_lengths = []
    for b in pending:
        bl = [int(b.columns[j].offsets[b.num_rows]) for j in str_cols]
        byte_lengths.append(bl)
    out_char_caps = [
        choose_capacity(
            max(1, sum(bl[k] for bl in byte_lengths)), 128)
        for k in range(len(str_cols))
    ]
    cols, n = concat_ops.concat_batches_cols(
        [vals_of_batch(b) for b in pending], lengths, byte_lengths,
        out_cap, out_char_caps)
    return batch_from_vals(cols, schema, n)


def _raise_if_donation_uaf(e: BaseException, op: str) -> None:
    """A deleted-array error surfacing inside the retry harness means a
    donated plane leaked into a re-attempt — the donation guard's
    snapshot/restore contract was violated upstream. Re-type it with
    the operator attribution so the failure reads as the soundness bug
    it is, not a mystery backend error."""
    from ..plugin import donation as _donation

    if (not isinstance(e, _donation.TpuDonationViolation)
            and _donation._use_after_donation(e)):
        raise _donation.TpuDonationViolation(
            "retry", op,
            f"donated plane re-read by a retry attempt: {e}") from e


def with_oom_retry(op: str, attempt_fn: Callable, batch,
                   conf_: RapidsConf,
                   combine: Union[str, Callable, None] = "concat",
                   on_pressure: Optional[Callable[[], None]] = None):
    """Run ``attempt_fn(batch)`` under the retry + split-and-retry
    harness.

    ``combine`` shapes the return value when a split happened:

      * ``"concat"`` (default) — pieces re-join row-wise through the
        multi-batch concat path; returns ONE batch (exact for row-local
        operators: project/filter chains);
      * ``"list"`` — returns the list of per-piece outputs in row order
        (aggregate updates hand the pieces to their merge path, the join
        probe streams them out as separate batches);
      * a callable — custom re-join (the sort re-sorts the stitched
        pieces); a device OOM inside it escalates to
        TpuSplitAndRetryOOM like any other exhaustion.
    """
    if not conf_.get(OOM_RETRY_ENABLED):
        out = attempt_fn(batch)
        return [out] if combine == "list" else out
    max_attempts = conf_.get(OOM_RETRY_MAX_ATTEMPTS)
    backoff_ms = conf_.get(OOM_RETRY_BACKOFF_MS)
    max_depth = conf_.get(OOM_RETRY_MAX_SPLIT_DEPTH)
    total_attempts = [0]

    def run(b, depth: int) -> List:
        last: Optional[BaseException] = None
        for attempt in range(1, max_attempts + 1):
            total_attempts[0] += 1
            try:
                if _faults.enabled():
                    _faults.check("oom", op, cap=b.capacity)
                return [attempt_fn(b)]
            except Exception as e:  # noqa: BLE001 - filtered below
                if not is_device_oom(e):
                    _raise_if_donation_uaf(e, op)
                    raise
                last = e
                _emit_retry(op, "retry", attempt, depth)
                freed = _release_pressure(op, on_pressure)
                log.warning(
                    "device OOM in %s (attempt %d/%d, split depth %d): "
                    "released %d B, retrying", op, attempt, max_attempts,
                    depth, freed)
                if backoff_ms:
                    time.sleep(backoff_ms / 1e3 * (1 << (attempt - 1)))
        # retries exhausted at this level: split and recurse
        n = b.num_rows
        if depth >= max_depth or n < 2:
            watermark, budget, _ = _hbm_state()
            raise TpuSplitAndRetryOOM(
                f"device OOM in {op}: {total_attempts[0]} attempt(s) "
                f"exhausted at split depth {depth} "
                f"({n} row(s); watermark {watermark} B, budget "
                f"{budget if budget is not None else 'unlimited'}) — "
                f"last failure: {last}", op=op, watermark=watermark,
                budget=budget, attempts=total_attempts[0],
                split_depth=depth) from last
        from ..columnar import split_batch
        from ..plugin import donation as _donation

        lo, hi = split_batch(b)
        # the halves are fresh dynamic-slice outputs private to this
        # retry recursion — no cache/exchange/spill ever holds them —
        # so the smaller re-dispatches may donate their planes even
        # when the parent batch was shared (plugin/donation.py)
        _donation.mark_exclusive(lo)
        _donation.mark_exclusive(hi)
        _emit_retry(op, "split", total_attempts[0], depth + 1)
        if _events.enabled():
            _events.emit("batch_split", op=op, depth=depth + 1, rows=n,
                         rows_left=lo.num_rows, rows_right=hi.num_rows)
        if _obs.enabled():
            _obs.note_batch_split(op)
        log.warning(
            "split-and-retry in %s: %d rows -> %d + %d (depth %d)",
            op, n, lo.num_rows, hi.num_rows, depth + 1)
        return run(lo, depth + 1) + run(hi, depth + 1)

    outs = run(batch, 0)
    if combine == "list":
        return outs
    if len(outs) == 1:
        return outs[0]
    joiner = (combine if callable(combine)
              else (lambda pieces: concat_batches(conf_, pieces)))
    try:
        return joiner(outs)
    except Exception as e:  # noqa: BLE001 - filtered below
        if not is_device_oom(e):
            raise
        watermark, budget, _ = _hbm_state()
        raise TpuSplitAndRetryOOM(
            f"device OOM in {op} while re-joining {len(outs)} split "
            f"piece(s): {e}", op=op, watermark=watermark, budget=budget,
            attempts=total_attempts[0]) from e


def with_oom_retry_nosplit(op: str, fn: Callable, conf_: RapidsConf):
    """Retry-only harness for non-splittable work (the aggregate's merge,
    broadcast builds): spill + backoff between attempts, TpuRetryOOM on
    exhaustion (the reference's withRetryNoSplit)."""
    if not conf_.get(OOM_RETRY_ENABLED):
        return fn()
    max_attempts = conf_.get(OOM_RETRY_MAX_ATTEMPTS)
    backoff_ms = conf_.get(OOM_RETRY_BACKOFF_MS)
    last: Optional[BaseException] = None
    for attempt in range(1, max_attempts + 1):
        try:
            if _faults.enabled():
                _faults.check("oom", op)
            return fn()
        except Exception as e:  # noqa: BLE001 - filtered below
            if not is_device_oom(e):
                _raise_if_donation_uaf(e, op)
                raise
            last = e
            _emit_retry(op, "retry", attempt, 0)
            _release_pressure(op, None)
            if backoff_ms:
                time.sleep(backoff_ms / 1e3 * (1 << (attempt - 1)))
    watermark, budget, _ = _hbm_state()
    raise TpuRetryOOM(
        f"device OOM in {op}: {max_attempts} attempt(s) exhausted on a "
        f"non-splittable path (watermark {watermark} B) — last failure: "
        f"{last}", op=op, watermark=watermark, budget=budget,
        attempts=max_attempts) from last


@contextlib.contextmanager
def named_oom(op: str):
    """Wrap raw device allocation failures OUTSIDE the retry harness
    (scan staging, exchange staging, mesh staging) into a named
    :class:`TpuOutOfDeviceMemory` reporting op, live watermark, derived
    budget, and the largest spillable buffer — no more bare XLA
    tracebacks."""
    try:
        yield
    except Exception as e:  # noqa: BLE001 - filtered below
        if isinstance(e, TpuOOMError) or not is_device_oom(e):
            raise
        watermark, budget, largest = _hbm_state()
        raise TpuOutOfDeviceMemory(
            f"device allocation failed in {op}: {e} "
            f"(catalog watermark {watermark} B, budget "
            f"{budget if budget is not None else 'unlimited'}, largest "
            f"spillable {largest} B)", op=op, watermark=watermark,
            budget=budget) from e
