"""SpillableColumnarBatch: a batch handle that survives device pressure.

Reference analog: SpillableColumnarBatch.scala:28-118 — wraps a batch in a
catalog-registered buffer; `get_batch()` re-materializes from whatever tier
it currently lives on. Used for join build sides, broadcast batches, and
cached shuffle pieces (the reference registers the same three)."""
from __future__ import annotations

from typing import List, Optional

from ..columnar import ColumnarBatch, DeviceColumn
from .catalog import ACTIVE_BATCHING_PRIORITY, BufferCatalog, SpillableHandle


class SpillableVals:
    """Spillable handle over a raw Val list (ColV/StrV) — the working-set
    form used by shuffle pieces and join build sides, where no schema is
    attached yet."""

    def __init__(self, vals, priority: int = ACTIVE_BATCHING_PRIORITY,
                 catalog: Optional[BufferCatalog] = None,
                 ledger_kind: str = "spillable"):
        from ..expr.values import StrV

        arrays = {}
        self._layout: List[str] = []
        for i, v in enumerate(vals):
            if isinstance(v, StrV):
                arrays[f"c{i}_offsets"] = v.offsets
                arrays[f"c{i}_chars"] = v.chars
                arrays[f"c{i}_validity"] = v.validity
                self._layout.append("s")
            else:
                arrays[f"c{i}_data"] = v.data
                arrays[f"c{i}_validity"] = v.validity
                self._layout.append("f")
        self._handle = SpillableHandle(arrays, priority, catalog,
                                       ledger_kind=ledger_kind)

    @property
    def size_bytes(self) -> int:
        return self._handle.size

    @property
    def tier(self) -> int:
        return self._handle.tier

    def get_vals(self):
        from ..expr.values import ColV, StrV

        arrs = self._handle.materialize()
        out = []
        for i, kind in enumerate(self._layout):
            if kind == "s":
                out.append(StrV(arrs[f"c{i}_offsets"], arrs[f"c{i}_chars"],
                                arrs[f"c{i}_validity"]))
            else:
                out.append(ColV(arrs[f"c{i}_data"], arrs[f"c{i}_validity"]))
        return out

    def close(self, reason: str = "close") -> None:
        self._handle.close(reason=reason)


class SpillableColumnarBatch:
    def __init__(self, batch: ColumnarBatch,
                 priority: int = ACTIVE_BATCHING_PRIORITY,
                 catalog: Optional[BufferCatalog] = None,
                 ledger_kind: str = "spillable"):
        self.schema = batch.schema
        self.num_rows = batch.num_rows
        arrays = {}
        self._layout: List[str] = []
        for i, c in enumerate(batch.columns):
            if c.is_string:
                arrays[f"c{i}_offsets"] = c.offsets
                arrays[f"c{i}_chars"] = c.chars
                arrays[f"c{i}_validity"] = c.validity
                self._layout.append("s")
            else:
                arrays[f"c{i}_data"] = c.data
                arrays[f"c{i}_validity"] = c.validity
                self._layout.append("f")
        self._handle = SpillableHandle(arrays, priority, catalog,
                                       ledger_kind=ledger_kind)

    @property
    def size_bytes(self) -> int:
        return self._handle.size

    def get_batch(self) -> ColumnarBatch:
        arrs = self._handle.materialize()
        cols = []
        for i, (kind, f) in enumerate(zip(self._layout, self.schema.fields)):
            if kind == "s":
                cols.append(DeviceColumn(
                    f.dataType, self.num_rows, None,
                    arrs[f"c{i}_validity"],
                    offsets=arrs[f"c{i}_offsets"],
                    chars=arrs[f"c{i}_chars"]))
            else:
                cols.append(DeviceColumn(
                    f.dataType, self.num_rows,
                    arrs[f"c{i}_data"], arrs[f"c{i}_validity"]))
        return ColumnarBatch(cols, self.schema, self.num_rows)

    @property
    def tier(self) -> int:
        return self._handle.tier

    def close(self, reason: str = "close") -> None:
        self._handle.close(reason=reason)
