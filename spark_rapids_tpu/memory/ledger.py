"""Per-buffer HBM ledger: lifecycle attribution + leak sentinel.

Reference analog: RapidsBufferCatalog tracks every GPU buffer as an
individually-identified RapidsBuffer with an owner and a storage tier;
our catalog (memory/catalog.py) kept only aggregate byte counters. This
module adds the per-buffer book: every registered SpillableHandle,
scan-cache entry, and admission reservation gets a ledger record with an
owner tag — (query id, op, creation site, creation-path digest) — and a
full lifecycle (alloc -> spill/unspill -> free-with-reason), emitted as
typed ``buffer_alloc``/``buffer_free``/``heap_snapshot`` events with
live obs twins (``tpu_hbm_bytes{op=...}`` gauge family, leak counter).

Zero-overhead-off contract (the PR 5/6 pattern): every hot entry point
checks :meth:`Ledger.armed` FIRST — with events+obs off and no force
arm, no record dict is built, no labels are touched, no lock beyond the
armed read is taken. ``force_arm()`` is the bench/test hook (the
xla_cost.FORCE_HARVEST pattern) so per-shape attribution works without
standing up the whole obs plane.

The **leak sentinel** rides the query window: execution paths enter
``query_scope(qid)`` so allocations are owned by their query, and
``sweep_query(qid)`` at query end flags still-live spillable buffers
whose owning query is gone — surfaced as a watchdog alert, a ``/status``
heap block, and a harness teardown assertion. Scan-cache entries are
exempt by design (they outlive queries on purpose); reservations are
released by the scheduler after the query closes and are exempt too.

The ledger also feeds ROADMAP 5a's measured-stats loop: per-query peaks
are folded into a bounded per-plan-digest history, and the serve
scheduler's admission forecast + the PR 13 requeue consume the observed
peak instead of the raw global watermark.
"""
from __future__ import annotations

import hashlib
import logging
import os
import sys
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from .. import events as _events
from .. import obs as _obs
from ..conf import RapidsConf, conf
from ..utils.locks import ordered_lock

log = logging.getLogger("spark_rapids_tpu.memory.ledger")

LEDGER_ENABLED = conf(
    "spark.rapids.tpu.memory.ledger.enabled", True,
    "Per-buffer HBM ledger (owner attribution, lifecycle events, leak "
    "sentinel). Only ever active while the event log or live metrics "
    "are on (or a bench/test force-arms it) — with both off the ledger "
    "costs one boolean read per buffer registration.")

LEAK_SENTINEL_ENABLED = conf(
    "spark.rapids.tpu.memory.ledger.leakSentinel.enabled", True,
    "Flag spillable buffers that outlive their owning query at "
    "query end (watchdog alert + /status heap block + "
    "tpu_hbm_leaked_buffers counter). Requires the ledger.")

#: record kinds
KIND_SPILLABLE = "spillable"
KIND_SCAN_CACHE = "scan_cache"
KIND_RESERVATION = "reservation"
#: deliberately-retained exec state (join build sides, broadcast
#: batches): reused across re-executions of the cached plan, so
#: outliving one query is the point — the creating site DECLARES it
#: (SpillableHandle ledger_kind) instead of the sentinel guessing
KIND_PLAN_STATE = "plan_state"

#: kinds the leak sentinel never flags: scan-cache entries and declared
#: plan state outlive queries by design, reservations are released by
#: the scheduler AFTER the query window closes (session finally ->
#: sched.release ordering)
SWEEP_EXEMPT_KINDS = frozenset(
    {KIND_SCAN_CACHE, KIND_RESERVATION, KIND_PLAN_STATE})

#: bounded history sizes (per-digest observed peaks / per-query peaks)
_DIGEST_HISTORY = 256
_QUERY_HISTORY = 256

# -- force arm (bench/tests): attribution without events/obs ---------------
_FORCE = False


def force_arm(on: bool = True) -> None:
    """Arm the ledger regardless of events/obs state (bench per-shape
    attribution, tests). NOT a public conf — the production arm signal
    is the event log / obs plane being on."""
    global _FORCE
    _FORCE = on


def force_armed() -> bool:
    return _FORCE


# -- threadlocal query ownership scope -------------------------------------
_QUERY = threading.local()


@contextmanager
def query_scope(query_id: Optional[str]):
    """Every buffer registered on this thread while the scope is open is
    owned by ``query_id`` (the execution paths in sql/session.py enter
    it around collect/write drains). Nests: an inner scope shadows."""
    stack = getattr(_QUERY, "stack", None)
    if stack is None:
        stack = _QUERY.stack = []
    stack.append(query_id)
    try:
        yield
    finally:
        # defensive pop: a generator-held scope can be finalized on a
        # DIFFERENT thread (GC of an abandoned writer drain) whose
        # threadlocal stack never saw the push — pop only what we pushed
        cur = getattr(_QUERY, "stack", None)
        if cur and cur[-1] == query_id:
            cur.pop()
        elif stack and stack[-1] == query_id:
            stack.pop()


def current_query() -> Optional[str]:
    stack = getattr(_QUERY, "stack", None)
    return stack[-1] if stack else None


# -- creation-site capture -------------------------------------------------
#: memoized (filename, lineno) -> (site, origin digest); unbounded in
#: principle but keyed by static call sites, so bounded by the code.
#: The memo lock is a raw leaf below the whole hierarchy: nothing is
#: acquired under it (pure relpath/sha1 compute on a miss).
_SITE_CACHE: Dict[tuple, Tuple[str, str]] = {}
_SITE_LOCK = threading.Lock()
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SKIP_FILES = (os.path.join(_PKG_DIR, "memory"),)


def _call_site() -> Tuple[str, str]:
    """(site, origin) of the nearest caller outside memory/: site is
    ``file.py:lineno`` (human), origin is a stable 12-hex digest of the
    full path form (machine — survives basename collisions)."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_SKIP_FILES[0]):
            key = (fn, f.f_lineno)
            with _SITE_LOCK:
                hit = _SITE_CACHE.get(key)
                if hit is None:
                    rel = os.path.relpath(fn, _PKG_DIR) \
                        if fn.startswith(_PKG_DIR) \
                        else os.path.basename(fn)
                    site = f"{rel}:{f.f_lineno}"
                    origin = hashlib.sha1(
                        f"{fn}:{f.f_lineno}".encode()).hexdigest()[:12]
                    hit = _SITE_CACHE[key] = (site, origin)
            return hit
        f = f.f_back
    return ("<unknown>", "000000000000")


def _current_op() -> Optional[str]:
    # lazy: xla_cost is import-light but keep the cycle surface minimal
    from .. import xla_cost as _xla_cost

    return _xla_cost.current_op()


class Ledger:
    """The per-buffer book. One instance per BufferCatalog; all state
    under its own ordered lock ("memory.ledger", below the catalog so
    catalog paths may call in while holding theirs, above the event/obs
    leaf sinks the note_* methods emit into)."""

    def __init__(self, conf_: Optional[RapidsConf] = None):
        self.conf = conf_ or RapidsConf({})
        self._enabled = bool(self.conf.get(LEDGER_ENABLED))
        self._sentinel = bool(self.conf.get(LEAK_SENTINEL_ENABLED))
        self._lock = ordered_lock("memory.ledger", reentrant=True)
        self._records: Dict[int, dict] = {}
        self._next_lid = 0
        #: device-live bytes per op (scan cache included: those arrays
        #: occupy HBM even though the catalog watermark never saw them)
        self._by_op: Dict[str, int] = {}
        self._op_peak: Dict[str, int] = {}
        self._live_bytes = 0
        self._churn_by_op: Dict[str, int] = {}
        #: per-query device-live/peak (bounded; keyed by query id, which
        #: is process-globally unique — the owner tag also carries tid)
        self._query_live: Dict[str, int] = {}
        self._query_peak: "OrderedDict[str, int]" = OrderedDict()
        #: per-plan-digest observed peaks (the admission feed)
        self._digest_peak: "OrderedDict[str, int]" = OrderedDict()
        self._alloc_count = 0
        self._free_count = 0
        self._leaked_total = 0
        self._leaked_live = 0

    # -- arming ------------------------------------------------------------
    def armed(self) -> bool:
        """True when lifecycle recording is on. The ONE hot-path guard:
        callers check it before building any record (zero-overhead-off
        contract)."""
        if not self._enabled:
            return False
        return _FORCE or _events.enabled() or _obs.enabled()

    def sentinel_enabled(self) -> bool:
        return self._enabled and self._sentinel

    # -- lifecycle ---------------------------------------------------------
    def note_alloc(self, nbytes: int, kind: str = KIND_SPILLABLE,
                   op: Optional[str] = None, site: Optional[str] = None,
                   query_id: Optional[str] = None) -> Optional[int]:
        """Record an allocation; returns the ledger id (lid) the caller
        must hand back to note_free/note_spill/note_unspill, or None
        when the ledger is not armed. ``op``/``site``/``query_id``
        default to the ambient attribution context (xla_cost op scope,
        caller's code site, threadlocal query scope)."""
        if not self.armed():
            return None
        if site is None:
            site, origin = _call_site()
        else:
            origin = hashlib.sha1(site.encode()).hexdigest()[:12]
        if op is None:
            op = _current_op()
        if query_id is None:
            query_id = current_query()
        opkey = op or "(unattributed)"
        device = kind != KIND_RESERVATION
        with self._lock:
            lid = self._next_lid
            self._next_lid += 1
            self._records[lid] = {
                "lid": lid, "kind": kind, "bytes": int(nbytes),
                "op": op, "query_id": query_id,
                "tid": threading.get_ident(), "site": site,
                "origin": origin,
                "alloc_ns": time.perf_counter_ns(),
                "device": device, "leaked": False,
            }
            self._alloc_count += 1
            if device:
                self._live_bytes += nbytes
                nb = self._by_op.get(opkey, 0) + nbytes
                self._by_op[opkey] = nb
                if nb > self._op_peak.get(opkey, 0):
                    self._op_peak[opkey] = nb
                if query_id is not None:
                    ql = self._query_live.get(query_id, 0) + nbytes
                    self._query_live[query_id] = ql
                    if ql > self._query_peak.get(query_id, 0):
                        self._note_query_peak(query_id, ql)
            if _obs.enabled() and device:
                _obs.set_gauge("tpu_hbm_bytes", self._by_op[opkey],
                               op=opkey)
        if _events.enabled():
            _events.emit("buffer_alloc", bid=lid, kind=kind,
                         bytes=int(nbytes), op=op, query_id=query_id,
                         site=site, origin=origin)
        return lid

    def note_free(self, lid: Optional[int], reason: str = "close") -> None:
        if lid is None or not self._enabled:
            return
        with self._lock:
            r = self._records.pop(lid, None)
            if r is None:
                return  # already freed — close() is idempotent upstream
            self._free_count += 1
            if r["leaked"]:
                self._leaked_live -= 1
            opkey = r["op"] or "(unattributed)"
            if r["device"]:
                self._live_bytes -= r["bytes"]
                self._by_op[opkey] = self._by_op.get(opkey, 0) - r["bytes"]
                qid = r["query_id"]
                if qid is not None and qid in self._query_live:
                    self._query_live[qid] -= r["bytes"]
            if _obs.enabled() and r["device"]:
                _obs.set_gauge("tpu_hbm_bytes", self._by_op[opkey],
                               op=opkey)
        if _events.enabled():
            _events.emit("buffer_free", bid=lid, kind=r["kind"],
                         bytes=r["bytes"], reason=reason, op=r["op"],
                         query_id=r["query_id"])

    def note_spill(self, lid: Optional[int]) -> None:
        """Buffer left the device tier (device->host): its bytes stop
        counting as device-live for its op/query, and count as churn."""
        if lid is None or not self._enabled:
            return
        with self._lock:
            r = self._records.get(lid)
            if r is None or not r["device"]:
                return
            r["device"] = False
            opkey = r["op"] or "(unattributed)"
            self._live_bytes -= r["bytes"]
            self._by_op[opkey] = self._by_op.get(opkey, 0) - r["bytes"]
            self._churn_by_op[opkey] = \
                self._churn_by_op.get(opkey, 0) + r["bytes"]
            qid = r["query_id"]
            if qid is not None and qid in self._query_live:
                self._query_live[qid] -= r["bytes"]
            if _obs.enabled():
                _obs.set_gauge("tpu_hbm_bytes", self._by_op[opkey],
                               op=opkey)

    def note_unspill(self, lid: Optional[int]) -> None:
        if lid is None or not self._enabled:
            return
        with self._lock:
            r = self._records.get(lid)
            if r is None or r["device"]:
                return
            r["device"] = True
            opkey = r["op"] or "(unattributed)"
            self._live_bytes += r["bytes"]
            nb = self._by_op.get(opkey, 0) + r["bytes"]
            self._by_op[opkey] = nb
            if nb > self._op_peak.get(opkey, 0):
                self._op_peak[opkey] = nb
            qid = r["query_id"]
            if qid is not None:
                ql = self._query_live.get(qid, 0) + r["bytes"]
                self._query_live[qid] = ql
                if ql > self._query_peak.get(qid, 0):
                    self._note_query_peak(qid, ql)
            if _obs.enabled():
                _obs.set_gauge("tpu_hbm_bytes", nb, op=opkey)

    def _note_query_peak(self, qid: str, peak: int) -> None:
        # under self._lock
        self._query_peak[qid] = peak
        self._query_peak.move_to_end(qid)
        while len(self._query_peak) > _QUERY_HISTORY:
            self._query_peak.popitem(last=False)

    # -- query end: peak fold + leak sentinel ------------------------------
    def sweep_query(self, query_id: Optional[str],
                    digest: Optional[str] = None) -> List[dict]:
        """Close a query's ownership window: fold its observed peak into
        the per-digest history (the admission feed) and — when the
        sentinel is on — flag every still-live spillable buffer it owns
        as leaked. Returns the leak records (copies) for the caller
        (watchdog alert detail, harness assertion)."""
        if query_id is None or not self._enabled:
            return []
        leaks: List[dict] = []
        with self._lock:
            peak = self._query_peak.get(query_id)
            self._query_live.pop(query_id, None)
            if digest and peak:
                old = self._digest_peak.get(digest, 0)
                self._digest_peak[digest] = max(old, peak)
                self._digest_peak.move_to_end(digest)
                while len(self._digest_peak) > _DIGEST_HISTORY:
                    self._digest_peak.popitem(last=False)
            if self._sentinel:
                for r in self._records.values():
                    if (r["query_id"] == query_id and not r["leaked"]
                            and r["kind"] not in SWEEP_EXEMPT_KINDS):
                        r["leaked"] = True
                        self._leaked_total += 1
                        self._leaked_live += 1
                        leaks.append(dict(r))
        if leaks:
            for r in leaks:
                log.warning(
                    "leaked buffer %d: %d B from %s (op %s) outlives "
                    "query %s", r["lid"], r["bytes"], r["site"],
                    r["op"], query_id)
            if _obs.enabled():
                _obs.inc("tpu_hbm_leaked_buffers", len(leaks))
        if _events.enabled():
            snap = self.snapshot()
            _events.emit("heap_snapshot", query_id=query_id,
                         live_bytes=snap["live_bytes"],
                         by_op=snap["by_op"], top=snap["top"],
                         leaked=snap["leaked"])
        return leaks

    # -- admission feed ----------------------------------------------------
    def observed_peak(self, digest: Optional[str]) -> Optional[int]:
        """Largest device-byte peak any completed run of this plan
        digest reached — the measured replacement for the analyzer's
        static bound in the serve admission path."""
        if not digest or not self._enabled:
            return None
        with self._lock:
            return self._digest_peak.get(digest)

    def query_peak(self, query_id: Optional[str]) -> Optional[int]:
        """Observed device-byte peak of one query (survives its sweep in
        the bounded history) — the PR 13 requeue's inflated forecast."""
        if query_id is None or not self._enabled:
            return None
        with self._lock:
            return self._query_peak.get(query_id)

    # -- views -------------------------------------------------------------
    def top_owners(self, n: int = 3) -> List[Tuple[str, int]]:
        """Top ops by device-live bytes (watchdog pressure detail)."""
        with self._lock:
            rows = [(op, b) for op, b in self._by_op.items() if b > 0]
        rows.sort(key=lambda kv: kv[1], reverse=True)
        return rows[:n]

    def snapshot(self, top: int = 3) -> dict:
        """JSON-able live-heap view (heap_snapshot event payload and the
        /status heap block's core)."""
        with self._lock:
            by_op = {op: b for op, b in self._by_op.items() if b > 0}
            live = self._live_bytes
            leaked = self._leaked_live
        rows = sorted(by_op.items(), key=lambda kv: kv[1], reverse=True)
        return {"live_bytes": live, "by_op": by_op,
                "top": [[op, b] for op, b in rows[:top]],
                "leaked": leaked}

    def live_leaks(self) -> List[dict]:
        """Still-live records the sentinel has flagged (copies)."""
        with self._lock:
            return [dict(r) for r in self._records.values()
                    if r["leaked"]]

    def op_peaks(self) -> Dict[str, int]:
        """Per-op peak device-live bytes since construction (or the last
        rebase) — explain_metrics' memory footer + bench hbm_peak_by_op."""
        with self._lock:
            return dict(self._op_peak)

    def status_block(self) -> dict:
        """The /status ``heap`` block."""
        snap = self.snapshot(top=3)
        with self._lock:
            snap["leaked_total"] = self._leaked_total
            snap["allocs"] = self._alloc_count
            snap["frees"] = self._free_count
            snap["tracked"] = len(self._records)
            snap["spill_churn_bytes"] = sum(self._churn_by_op.values())
        return snap

    def rebase_peaks(self) -> None:
        """Reset per-op peaks (and churn) to the current live values —
        the bench per-shape window pattern (mirrors the catalog's
        peak_device_bytes rebase in bench._mem_snapshot)."""
        with self._lock:
            self._op_peak = {op: b for op, b in self._by_op.items()
                             if b > 0}
            self._churn_by_op = {}

    def stats(self) -> dict:
        with self._lock:
            return {
                "allocs": self._alloc_count,
                "frees": self._free_count,
                "tracked": len(self._records),
                "live_bytes": self._live_bytes,
                "leaked_live": self._leaked_live,
                "leaked_total": self._leaked_total,
            }
