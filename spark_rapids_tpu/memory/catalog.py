"""Buffer catalog with tiered DEVICE -> HOST -> DISK spill.

Reference analog: RapidsBufferCatalog.scala:34-109 (central registry of
spillable buffers keyed by id), RapidsBufferStore.scala:40 (store chain with
synchronous spill on allocation pressure), SpillPriorities.scala:26, and
DeviceMemoryEventHandler.scala:33 (allocation-failure callback draining the
stores). There is no RMM on TPU — XLA owns the allocator — so pressure is
tracked by *accounting*: every registered buffer adds its byte size to the
device-tier total, and `request()` (called before large materializations)
drains lowest-priority buffers to host/disk until the configured budget
holds. jax arrays whose last reference drops are freed by XLA, so "spill"
here means: copy to host numpy (or an .npz on disk), drop the device
reference, and rematerialize on demand.
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
from typing import Dict, List, Optional

from .. import events as _events
from .. import obs as _obs
from ..conf import (
    HBM_POOL_FRACTION,
    HBM_RESERVE,
    HOST_SPILL_STORAGE_SIZE,
    MEMORY_DEBUG,
    RapidsConf,
    SPILL_ENABLED,
    conf,
)
from ..utils.locks import ordered_lock
from .ledger import KIND_RESERVATION, Ledger

log = logging.getLogger("spark_rapids_tpu.memory")

HBM_BUDGET_BYTES = conf(
    "spark.rapids.tpu.memory.hbm.budgetBytes", 0,
    "Explicit spill budget for catalog-tracked device buffers; 0 derives "
    "it from allocFraction * device memory (or unlimited when the backend "
    "reports no memory stats).")

# tier ordering (reference: RapidsBuffer.scala:54-61 StorageTier)
TIER_DEVICE = 0
TIER_HOST = 1
TIER_DISK = 2

# spill priorities (reference: SpillPriorities.scala:26)
HOST_MEMORY_BUFFER_SPILL_PRIORITY = -100
INPUT_FROM_SHUFFLE_PRIORITY = -50
ACTIVE_BATCHING_PRIORITY = 0


def derive_hbm_budget(conf_: RapidsConf) -> Optional[int]:
    """The device-tier spill budget: explicit hbm.budgetBytes, else
    allocFraction * device memory, else None (unlimited / accounting
    only). ONE derivation shared by the catalog and the static plan
    analyzer (plugin/plananalysis.py), so the plan-time OOM warning and
    the runtime spill trigger can never disagree on the budget."""
    explicit = conf_.get(HBM_BUDGET_BYTES)
    if explicit:
        return int(explicit)
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        limit = stats.get("bytes_limit") if stats else None
    except Exception:  # pragma: no cover - backend-dependent
        limit = None
    if not limit:
        return None
    frac = conf_.get(HBM_POOL_FRACTION)
    reserve = conf_.get(HBM_RESERVE)
    return max(int(limit * frac) - reserve, 1 << 20)


class SpillMetrics:
    def __init__(self):
        self.device_to_host = 0
        self.host_to_disk = 0
        self.spilled_bytes = 0
        #: buffers re-materialized on device after a spill (each one paid
        #: a host->device upload the plan didn't ask for)
        self.unspills = 0
        #: high-water mark of catalog-tracked device bytes — the figure
        #: to compare against the HBM budget when sizing a deployment
        self.peak_device_bytes = 0


class BufferCatalog:
    """Process-wide registry of spillable buffers.

    Buffers register with a byte size and spill priority; `request(bytes)`
    synchronously spills lowest-priority device buffers until the budget
    accommodates the new allocation (reference:
    RapidsBufferStore.synchronousSpill)."""

    _instance: Optional["BufferCatalog"] = None
    _instance_lock = threading.Lock()

    def __init__(self, conf_: Optional[RapidsConf] = None):
        self.conf = conf_ or RapidsConf({})
        self._lock = ordered_lock("memory.catalog", reentrant=True)
        self._buffers: Dict[int, "SpillableHandle"] = {}
        self._next_id = 0
        self._device_bytes = 0
        self._host_bytes = 0
        self.metrics = SpillMetrics()
        self._spill_dir: Optional[str] = None
        self._budget = self._derive_budget()
        # admission reservations (serve/scheduler.py): rid -> (bytes,
        # label). An admitted query's forecast counts against the budget
        # from admission until release, so the scheduler's admit decision
        # and the spiller can never over-commit the same headroom.
        self._reservations: Dict[int, tuple] = {}
        self._reserved_bytes = 0
        self._next_rid = 0
        #: per-buffer lifecycle book (owner attribution, leak sentinel,
        #: observed-peak admission feed) — armed only while events/obs
        #: are on (or force-armed by bench/tests)
        self.ledger = Ledger(self.conf)

    # -- singleton (reference: RapidsBufferCatalog.singleton) --------------
    @classmethod
    def get(cls) -> "BufferCatalog":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = BufferCatalog()
            return cls._instance

    @classmethod
    def reset(cls, conf_: Optional[RapidsConf] = None) -> "BufferCatalog":
        """Re-initialize (tests / executor restart)."""
        with cls._instance_lock:
            cls._instance = BufferCatalog(conf_)
            return cls._instance

    def _derive_budget(self) -> Optional[int]:
        return derive_hbm_budget(self.conf)

    @property
    def budget(self) -> Optional[int]:
        """The live spill budget (None = unlimited) — read by the
        watchdog's pressure rule and the /status HBM block so they can
        never disagree with the spiller."""
        return self._budget

    def _obs_watermark(self) -> None:
        """Mirror the device-byte watermark into the live registry (a
        leaf-lock callee: safe under self._lock)."""
        _obs.set_gauge("tpu_hbm_device_bytes", self._device_bytes)
        _obs.set_gauge("tpu_hbm_peak_device_bytes",
                       self.metrics.peak_device_bytes)
        if self._budget is not None:
            # keep the budget gauge tracking the LIVE catalog (a reset
            # with new memory confs would otherwise leave the plane
            # advertising the first session's stale derivation)
            _obs.set_gauge("tpu_hbm_budget_bytes", self._budget)

    # -- registration ------------------------------------------------------
    def register(self, handle: "SpillableHandle") -> int:
        with self._lock:
            bid = self._next_id
            self._next_id += 1
            self._buffers[bid] = handle
            self._device_bytes += handle.size
            if self._device_bytes > self.metrics.peak_device_bytes:
                self.metrics.peak_device_bytes = self._device_bytes
            if self.conf.get(MEMORY_DEBUG):
                log.info("register buffer %d (%d B, prio %d): device=%d B",
                         bid, handle.size, handle.priority, self._device_bytes)
            if _obs.enabled():
                self._obs_watermark()
            if self.ledger.armed():
                handle._lid = self.ledger.note_alloc(
                    handle.size,
                    kind=getattr(handle, "ledger_kind", "spillable"))
        self.request(0)
        return bid

    def unregister(self, bid: int, reason: str = "close") -> None:
        with self._lock:
            h = self._buffers.pop(bid, None)
            if h is None:
                return
            if h.tier == TIER_DEVICE:
                self._device_bytes -= h.size
            elif h.tier == TIER_HOST:
                self._host_bytes -= h.size
            if _obs.enabled():
                self._obs_watermark()
            self.ledger.note_free(getattr(h, "_lid", None), reason)

    def on_unspill(self, h: "SpillableHandle", from_host: bool) -> None:
        with self._lock:
            if from_host:
                self._host_bytes -= h.size
            self._device_bytes += h.size
            self.metrics.unspills += 1
            if self._device_bytes > self.metrics.peak_device_bytes:
                self.metrics.peak_device_bytes = self._device_bytes
            if _events.enabled():
                _events.emit("spill", kind="unspill", bytes=h.size,
                             device_bytes=self._device_bytes,
                             bid=getattr(h, "_lid", None))
            if _obs.enabled():
                _obs.inc("tpu_spills", 1, kind="unspill")
                _obs.inc("tpu_spill_bytes", h.size, kind="unspill")
                self._obs_watermark()
            self.ledger.note_unspill(getattr(h, "_lid", None))
        # the just-materialized buffer is the one in use: spill OTHERS to
        # make room (the reference pins via addReference during access)
        self.request(0, exclude=h)

    # -- pressure ----------------------------------------------------------
    def _account_device_spill(self, freed: int, emergency: bool,
                              handle: Optional["SpillableHandle"] = None
                              ) -> None:
        """THE device->host spill bookkeeping (byte counters, metrics,
        spill event, obs twins, debug log) — one body shared by the
        proactive path (:meth:`request`) and the OOM-recovery path
        (:meth:`ensure_headroom`) so the two sets of books can never
        diverge. Called after a successful ``spill_to_host``."""
        lid = getattr(handle, "_lid", None)
        with self._lock:
            self._device_bytes -= freed
            self._host_bytes += freed
            self.metrics.device_to_host += 1
            self.metrics.spilled_bytes += freed
            if _events.enabled():
                _events.emit("spill", kind="device_to_host",
                             bytes=freed,
                             device_bytes=self._device_bytes,
                             bid=lid)
            if _obs.enabled():
                _obs.inc("tpu_spills", 1, kind="device_to_host")
                _obs.inc("tpu_spill_bytes", freed,
                         kind="device_to_host")
                self._obs_watermark()
            self.ledger.note_spill(lid)
        if self.conf.get(MEMORY_DEBUG):
            log.info("%sspilled %d B to host (device=%d B)",
                     "emergency " if emergency else "", freed,
                     self._device_bytes)

    def _drain_host_overage(self) -> None:
        """Push host-tier buffers to disk while the tier exceeds
        host.spillStorageSize. The victim list is snapshotted under the
        lock, but the loop re-reads the LIVE byte count under the lock
        each iteration so concurrent spillers stop as soon as the tier
        is under cap instead of each pushing the full overage to disk.
        Deliberately budget-independent: a budget-less catalog's
        emergency spills must still respect the HOST cap."""
        host_cap = self.conf.get(HOST_SPILL_STORAGE_SIZE)
        with self._lock:
            hosts = sorted(
                (h for h in self._buffers.values()
                 if h.tier == TIER_HOST),
                key=lambda h: h.priority,
            ) if self._host_bytes > host_cap else []
        for h in hosts:
            with self._lock:
                if self._host_bytes <= host_cap:
                    break
            freed = h.spill_to_disk(self._disk_dir())
            if freed:
                with self._lock:
                    self._host_bytes -= freed
                    self.metrics.host_to_disk += 1
                    if _events.enabled():
                        _events.emit("spill", kind="host_to_disk",
                                     bytes=freed,
                                     device_bytes=self._device_bytes)
                    if _obs.enabled():
                        _obs.inc("tpu_spills", 1, kind="host_to_disk")
                        _obs.inc("tpu_spill_bytes", freed,
                                 kind="host_to_disk")

    def request(self, nbytes: int, exclude: Optional["SpillableHandle"] = None
                ) -> None:
        """Make room for an upcoming allocation of ``nbytes`` (the
        DeviceMemoryEventHandler analog, invoked proactively)."""
        if self._budget is None or not self.conf.get(SPILL_ENABLED):
            return
        # victims are picked under the catalog lock but spilled OUTSIDE it:
        # each spill takes the handle's own lock, and materialize() takes
        # handle-then-catalog — never holding one while acquiring the other
        # in the opposite order avoids a lock-order inversion
        with self._lock:
            need = self._device_bytes + nbytes - self._budget
            victims = sorted(
                (h for h in self._buffers.values()
                 if h.tier == TIER_DEVICE and not h.pinned
                 and h is not exclude),
                key=lambda h: h.priority,
            ) if need > 0 else []
        for h in victims:
            if need <= 0:
                break
            freed = h.spill_to_host()
            if freed:
                self._account_device_spill(freed, emergency=False,
                                           handle=h)
                need -= freed
        self._drain_host_overage()

    def ensure_headroom(self, nbytes: Optional[int] = None,
                        exclude: Optional["SpillableHandle"] = None) -> int:
        """EMERGENCY spill for OOM recovery (memory/retry.py): drain
        unpinned device-tier buffers to host until ``nbytes`` have been
        freed — or ALL of them when ``nbytes`` is None (a real backend
        OOM means XLA's allocator is full regardless of what the
        accounting thinks, so the recovery path empties what it can).
        Unlike :meth:`request` this ignores the device budget (a
        budget-less catalog still frees memory) but keeps the same
        victim order, lock discipline, and spill accounting — and the
        HOST-tier cap still applies (the overage drain below runs
        unconditionally, not behind the budget guard). Returns bytes
        freed."""
        if not self.conf.get(SPILL_ENABLED):
            return 0
        with self._lock:
            victims = sorted(
                (h for h in self._buffers.values()
                 if h.tier == TIER_DEVICE and not h.pinned
                 and h is not exclude),
                key=lambda h: h.priority,
            )
        total = 0
        for h in victims:
            if nbytes is not None and total >= nbytes:
                break
            freed = h.spill_to_host()
            if not freed:
                continue
            total += freed
            self._account_device_spill(freed, emergency=True, handle=h)
        # unconditional (not gated on total): a recovery pass that freed
        # nothing itself must still drain an overage a concurrent
        # spiller left — the host cap holds on every exit path
        self._drain_host_overage()
        return total

    def largest_spillable(self) -> int:
        """Size of the largest unpinned device-tier buffer (0 when none)
        — reported by TpuOutOfDeviceMemory so an OOM error names what a
        spill could still have freed."""
        with self._lock:
            return max(
                (h.size for h in self._buffers.values()
                 if h.tier == TIER_DEVICE and not h.pinned), default=0)

    def _disk_dir(self) -> str:
        # under the catalog lock: concurrent host-overage drains
        # otherwise both see None and mkdtemp twice, scattering spill
        # files across two directories (one leaked on cleanup)
        with self._lock:
            if self._spill_dir is None:
                self._spill_dir = tempfile.mkdtemp(prefix="srtpu_spill_")
            return self._spill_dir

    @property
    def device_bytes(self) -> int:
        return self._device_bytes

    # -- admission reservations (serve/scheduler.py) -----------------------
    def observed_query_peak(self, query_id: Optional[str]
                            ) -> Optional[int]:
        """Ledger-observed device-byte peak of one query — the figure
        the PR 13 requeue inflates its forecast to (replacing the raw
        global watermark the typed OOM carries)."""
        return self.ledger.query_peak(query_id)

    def reserve(self, nbytes: int, label: str = "") -> int:
        """Charge an admitted query's peak-HBM forecast against the
        budget until :meth:`release_reservation`. Accounting only — no
        allocation happens; the reservation narrows what the scheduler
        will admit next. Deliberately conservative: a running query's
        ACTUAL buffers also register in ``device_bytes``, so headroom is
        double-counted toward safety (queueing, never OOM)."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            lid = self.ledger.note_alloc(
                int(nbytes), kind=KIND_RESERVATION,
                site=f"reservation:{label}" if label else "reservation",
            ) if self.ledger.armed() else None
            self._reservations[rid] = (int(nbytes), label, lid)
            self._reserved_bytes += int(nbytes)
            if _obs.enabled():
                _obs.set_gauge("tpu_hbm_reserved_bytes",
                               self._reserved_bytes)
            if self.conf.get(MEMORY_DEBUG):
                log.info("reserve %d B (%s): reserved=%d B", nbytes, label,
                         self._reserved_bytes)
            return rid

    def release_reservation(self, rid: int) -> None:
        with self._lock:
            entry = self._reservations.pop(rid, None)
            if entry is None:
                return
            self._reserved_bytes -= entry[0]
            self.ledger.note_free(entry[2], reason="release")
            if _obs.enabled():
                _obs.set_gauge("tpu_hbm_reserved_bytes",
                               self._reserved_bytes)

    @property
    def reserved_bytes(self) -> int:
        return self._reserved_bytes

    def admission_state(self) -> tuple:
        """(budget, device_bytes, reserved_bytes) read atomically under
        the catalog lock — the scheduler derives its admission headroom
        from one consistent snapshot, never from separate property reads
        that could interleave with a concurrent register/reserve."""
        with self._lock:
            return self._budget, self._device_bytes, self._reserved_bytes


class SpillableHandle:
    """One spillable buffer set: named jax arrays that can round-trip
    DEVICE -> HOST (numpy) -> DISK (.npz) and back (reference:
    RapidsBuffer.scala:63-140 acquire/addReference/free + the per-tier
    RapidsBuffer implementations)."""

    def __init__(self, arrays: Dict[str, "object"], priority: int = 0,
                 catalog: Optional[BufferCatalog] = None,
                 ledger_kind: str = "spillable"):
        self._catalog = catalog or BufferCatalog.get()
        #: HBM-ledger record kind. Sites whose buffers DELIBERATELY
        #: outlive the creating query (join build sides, broadcast
        #: batches — reused with the cached plan) declare "plan_state"
        #: so the leak sentinel doesn't flag designed retention.
        self.ledger_kind = ledger_kind
        self._device: Optional[Dict[str, object]] = dict(arrays)
        self._host: Optional[Dict[str, object]] = None
        self._disk_path: Optional[str] = None
        self.tier = TIER_DEVICE
        self.priority = priority
        self.pinned = False
        self.size = sum(a.size * a.dtype.itemsize for a in arrays.values())
        self._closed = False
        #: ledger record id — assigned by register() when the ledger is
        #: armed, None otherwise (the zero-overhead-off path)
        self._lid: Optional[int] = None
        # guards tier transitions; "memory.spillable" ranks just above
        # the catalog — close() unregisters while holding it
        self._tlock = ordered_lock("memory.spillable", reentrant=True)
        self._id = self._catalog.register(self)

    # -- tier transitions (each holds the handle lock; the catalog never
    # holds ITS lock while calling in here — see BufferCatalog.request) ----
    def spill_to_host(self) -> int:
        with self._tlock:
            if self.tier != TIER_DEVICE or self._closed:
                return 0
            import jax
            import numpy as np

            self._host = {
                k: np.asarray(jax.device_get(v))
                for k, v in self._device.items()
            }
            self._device = None
            self.tier = TIER_HOST
            return self.size

    def spill_to_disk(self, dirpath: str) -> int:
        with self._tlock:
            if self.tier != TIER_HOST or self._closed:
                return 0
            import numpy as np

            self._disk_path = os.path.join(dirpath, f"buf{self._id}.npz")
            np.savez(self._disk_path, **self._host)
            self._host = None
            self.tier = TIER_DISK
            return self.size

    def materialize(self) -> Dict[str, object]:
        """Bring the arrays back on device (re-registering the device
        bytes); the reference analog is SpillableColumnarBatch
        .getColumnarBatch re-materializing from whatever tier."""
        with self._tlock:
            if self._closed:
                raise ValueError("buffer already closed")
            if self.tier == TIER_DEVICE:
                return self._device
            import jax.numpy as jnp
            import numpy as np

            from_disk = self.tier == TIER_DISK
            if from_disk:
                with np.load(self._disk_path) as z:
                    self._host = {k: z[k] for k in z.files}
                os.unlink(self._disk_path)
                self._disk_path = None
            dev = {k: jnp.asarray(v) for k, v in self._host.items()}
            self._device = dev
            self._host = None
            self.tier = TIER_DEVICE
        self._catalog.on_unspill(self, from_host=not from_disk)
        return dev

    # -- lifecycle (Arm idiom: with_resource(SpillableHandle(...))) --------
    def close(self, reason: str = "close") -> None:
        # taken under the tier lock so a close can't interleave with an
        # in-flight spill: unregister() reads self.tier to pick which byte
        # counter to decrement, and the spill loop decrements the same
        # counter when spill_to_* returns nonzero — serializing the two
        # keeps the accounting single-entry either way
        with self._tlock:
            if self._closed:
                return
            self._closed = True
            self._catalog.unregister(self._id, reason=reason)
            self._device = None
            self._host = None
            if self._disk_path and os.path.exists(self._disk_path):
                os.unlink(self._disk_path)
