"""TPU concurrency semaphore.

Reference analog: GpuSemaphore.scala:27-106 — caps how many tasks hold the
device at once (spark.rapids.sql.concurrentGpuTasks); acquired before the
first device allocation of a task, re-entrant per task, released at I/O
waits and task end. Here "task" = thread: each driver/executor thread
executing partitions acquires once; nested execs piggyback on the
thread-local count."""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..conf import (
    CONCURRENT_TPU_TASKS,
    SEMAPHORE_ACQUIRE_TIMEOUT_MS,
    RapidsConf,
)
from ..utils.locks import ordered_lock


class TpuSemaphoreTimeout(RuntimeError):
    """Raised when sql.semaphore.acquireTimeoutMs elapses before a permit
    frees. Names the threads currently holding permits so a wedged holder
    (the watchdog's 'deadlocked semaphore' scenario) is identifiable from
    the error alone, without a thread dump."""


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, permits: int, timeout_ms: int = 0):
        self.permits = permits
        self.timeout_ms = timeout_ms
        self._sem = threading.BoundedSemaphore(permits)
        self._local = threading.local()
        # thread ident -> thread name for every current permit holder —
        # read (under the holders lock) to name the culprits when an
        # acquire times out
        self._holders: Dict[int, str] = {}
        self._holders_lock = ordered_lock("memory.semaphore_holders")

    @classmethod
    def initialize(cls, conf: Optional[RapidsConf] = None) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                c = conf or RapidsConf({})
                cls._instance = TpuSemaphore(
                    c.get(CONCURRENT_TPU_TASKS),
                    c.get(SEMAPHORE_ACQUIRE_TIMEOUT_MS))
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        return cls.initialize()

    @classmethod
    def reset(cls, conf: Optional[RapidsConf] = None) -> "TpuSemaphore":
        with cls._lock:
            cls._instance = None
        return cls.initialize(conf)

    def holder_names(self) -> list:
        with self._holders_lock:
            return sorted(self._holders.values())

    # -- reference API: acquireIfNecessary / releaseIfNecessary ------------
    def acquire_if_necessary(self) -> None:
        depth = getattr(self._local, "depth", 0)
        if depth == 0:
            if self.timeout_ms > 0:
                t0 = time.monotonic()
                if not self._sem.acquire(timeout=self.timeout_ms / 1e3):
                    waited_ms = (time.monotonic() - t0) * 1e3
                    held = ", ".join(self.holder_names()) \
                        or "<released during wait>"
                    raise TpuSemaphoreTimeout(
                        f"thread {threading.current_thread().name!r} gave "
                        f"up acquiring the TPU semaphore after "
                        f"{waited_ms:.0f}ms "
                        f"(spark.rapids.tpu.sql.semaphore.acquireTimeoutMs"
                        f"={self.timeout_ms}); {self.permits} permit(s), "
                        f"held by: {held}")
            else:
                self._sem.acquire()
            with self._holders_lock:
                self._holders[threading.get_ident()] = \
                    threading.current_thread().name
        self._local.depth = depth + 1

    def release_if_necessary(self) -> None:
        depth = getattr(self._local, "depth", 0)
        if depth <= 0:
            return
        depth -= 1
        self._local.depth = depth
        if depth == 0:
            with self._holders_lock:
                self._holders.pop(threading.get_ident(), None)
            self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
        return False
