"""TPU concurrency semaphore.

Reference analog: GpuSemaphore.scala:27-106 — caps how many tasks hold the
device at once (spark.rapids.sql.concurrentGpuTasks); acquired before the
first device allocation of a task, re-entrant per task, released at I/O
waits and task end. Here "task" = thread: each driver/executor thread
executing partitions acquires once; nested execs piggyback on the
thread-local count."""
from __future__ import annotations

import threading
from typing import Optional

from ..conf import CONCURRENT_TPU_TASKS, RapidsConf


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, permits: int):
        self.permits = permits
        self._sem = threading.BoundedSemaphore(permits)
        self._local = threading.local()

    @classmethod
    def initialize(cls, conf: Optional[RapidsConf] = None) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                c = conf or RapidsConf({})
                cls._instance = TpuSemaphore(c.get(CONCURRENT_TPU_TASKS))
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        return cls.initialize()

    @classmethod
    def reset(cls, conf: Optional[RapidsConf] = None) -> "TpuSemaphore":
        with cls._lock:
            cls._instance = None
        return cls.initialize(conf)

    # -- reference API: acquireIfNecessary / releaseIfNecessary ------------
    def acquire_if_necessary(self) -> None:
        depth = getattr(self._local, "depth", 0)
        if depth == 0:
            self._sem.acquire()
        self._local.depth = depth + 1

    def release_if_necessary(self) -> None:
        depth = getattr(self._local, "depth", 0)
        if depth <= 0:
            return
        depth -= 1
        self._local.depth = depth
        if depth == 0:
            self._sem.release()

    def __enter__(self):
        self.acquire_if_necessary()
        return self

    def __exit__(self, *exc):
        self.release_if_necessary()
        return False
