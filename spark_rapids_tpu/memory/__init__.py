"""Device & memory runtime (SURVEY.md L1 / §2.7).

Reference analog: the RapidsBufferCatalog / SpillableColumnarBatch /
GpuSemaphore subsystem. XLA owns the physical allocator on TPU, so this
layer does what RMM's pool + event handler did by *accounting*: registered
buffers count toward a budget, and pressure drains them host/disk-ward.
"""
from .catalog import (
    ACTIVE_BATCHING_PRIORITY,
    BufferCatalog,
    HOST_MEMORY_BUFFER_SPILL_PRIORITY,
    INPUT_FROM_SHUFFLE_PRIORITY,
    SpillableHandle,
    TIER_DEVICE,
    TIER_DISK,
    TIER_HOST,
)
from .ledger import Ledger, current_query, force_arm, query_scope
from .retry import (
    TpuOOMError,
    TpuOutOfDeviceMemory,
    TpuRetryOOM,
    TpuSplitAndRetryOOM,
    classify_oom,
    is_device_oom,
    named_oom,
    with_oom_retry,
    with_oom_retry_nosplit,
)
from .semaphore import TpuSemaphore, TpuSemaphoreTimeout
from .spillable import SpillableColumnarBatch, SpillableVals

__all__ = [
    "ACTIVE_BATCHING_PRIORITY",
    "BufferCatalog",
    "HOST_MEMORY_BUFFER_SPILL_PRIORITY",
    "INPUT_FROM_SHUFFLE_PRIORITY",
    "Ledger",
    "SpillableHandle",
    "SpillableColumnarBatch",
    "SpillableVals",
    "TIER_DEVICE",
    "TIER_DISK",
    "TIER_HOST",
    "TpuOOMError",
    "TpuOutOfDeviceMemory",
    "TpuRetryOOM",
    "TpuSemaphore",
    "TpuSemaphoreTimeout",
    "TpuSplitAndRetryOOM",
    "classify_oom",
    "current_query",
    "force_arm",
    "is_device_oom",
    "named_oom",
    "query_scope",
    "with_oom_retry",
    "with_oom_retry_nosplit",
]
