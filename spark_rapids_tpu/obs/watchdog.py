"""Stall / pressure / recompile-storm watchdog over the live registry.

Reference analog: the driver-side monitoring operators bolt onto the
Spark UI (stuck-task speculation signals, memory alerts) — here a small
sampler raises TYPED alerts from the observability plane:

  * **stall** — an operator span has been OPEN longer than
    ``watchdog.stallThresholdMs`` (a hung device dispatch, a wedged
    host decode, a deadlocked semaphore — for the latter,
    ``sql.semaphore.acquireTimeoutMs`` is the matching escape hatch:
    the blocked acquirer raises a named TpuSemaphoreTimeout listing
    the holder threads instead of waiting forever);
  * **hbm_pressure** — the BufferCatalog device-byte watermark is above
    ``watchdog.hbmPressureFraction`` of the shared budget
    (derive_hbm_budget — the SAME derivation the spiller and the plan
    analyzer use, so all three agree on what "full" means);
  * **recompile_storm** — at least ``sql.analysis.recompileStorm
    .threshold`` compile misses hit ONE site within
    ``watchdog.recompileStorm.windowMs`` (the LIVE twin of the
    analyzer's static storm forecast and the profiler's post-hoc
    footer).

Every alert is surfaced three ways: a ``log.warning``, an ``alert``
event in the PR-5 event log (so offline traces show when the watchdog
fired), and the ``alerts`` list in ``/status``. An alert key stays
ACTIVE while its condition holds — one alert per episode, not one per
sample tick.

:func:`replay_alerts` runs the same rules over a recorded event log
(``tools/tpu_profile.py --alerts``) so thresholds can be tuned from
production recordings without re-running anything.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from .. import events as _events
from .registry import MetricsRegistry

log = logging.getLogger("spark_rapids_tpu.obs")

STALL = "stall"
HBM_PRESSURE = "hbm_pressure"
RECOMPILE_STORM = "recompile_storm"
RETRY_STORM = "retry_storm"
BUFFER_LEAK = "buffer_leak"


def _default_storm_threshold() -> int:
    # ONE home for the storm count: the conf entry's declared default
    # (tests pin tools/tpu_profile.py's CLI default to the same value)
    from ..conf import ANALYSIS_STORM_THRESHOLD

    return ANALYSIS_STORM_THRESHOLD.default


@dataclasses.dataclass(frozen=True)
class WatchdogRules:
    """Thresholds shared by the live sampler and the offline replay."""

    stall_ns: int = 30_000 * 1_000_000
    pressure_fraction: float = 0.85
    storm_threshold: int = dataclasses.field(
        default_factory=_default_storm_threshold)
    storm_window_ns: int = 10_000 * 1_000_000
    #: OOM-retry burst threshold (per op, inside storm_window_ns): a
    #: storm means forecasts are systematically wrong or the budget is
    #: too tight for the traffic — the query completes, but every batch
    #: pays spill + backoff (+ half-capacity recompiles)
    retry_storm_threshold: int = 8

    @classmethod
    def from_conf(cls, conf_) -> "WatchdogRules":
        from ..conf import (
            ANALYSIS_STORM_THRESHOLD,
            WATCHDOG_PRESSURE_FRACTION,
            WATCHDOG_RETRY_STORM_THRESHOLD,
            WATCHDOG_STALL_MS,
            WATCHDOG_STORM_WINDOW_MS,
        )

        return cls(
            stall_ns=int(conf_.get(WATCHDOG_STALL_MS)) * 1_000_000,
            pressure_fraction=conf_.get(WATCHDOG_PRESSURE_FRACTION),
            # ONE storm definition engine-wide: the live window reuses the
            # static analyzer's per-site signature threshold
            storm_threshold=conf_.get(ANALYSIS_STORM_THRESHOLD),
            storm_window_ns=int(
                conf_.get(WATCHDOG_STORM_WINDOW_MS)) * 1_000_000,
            retry_storm_threshold=conf_.get(
                WATCHDOG_RETRY_STORM_THRESHOLD),
        )


@dataclasses.dataclass
class Alert:
    kind: str        # stall | hbm_pressure | recompile_storm |
                     # retry_storm | buffer_leak
    detail: str      # what tripped (op name, site, watermark source)
    value: float     # the measured quantity (ns, bytes, miss count)
    threshold: float  # the rule it crossed
    ts: int          # perf_counter_ns at detection

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def describe(self) -> str:
        if self.kind == STALL:
            return (f"stall: {self.detail} span open "
                    f"{self.value / 1e9:.1f}s "
                    f"(threshold {self.threshold / 1e9:.1f}s)")
        if self.kind == HBM_PRESSURE:
            return (f"hbm_pressure: {self.detail} at "
                    f"{self.value / 1e6:.1f}MB, over "
                    f"{self.threshold / 1e6:.1f}MB")
        if self.kind == RETRY_STORM:
            return (f"retry_storm: op {self.detail} hit {self.value:g} "
                    f"OOM recovery actions in window "
                    f"(threshold {self.threshold:g}) — forecasts or the "
                    "HBM budget need attention")
        if self.kind == BUFFER_LEAK:
            return (f"buffer_leak: {self.value:g} buffer"
                    f"{'' if self.value == 1 else 's'} outlived the "
                    f"owning query — {self.detail}")
        return (f"recompile_storm: site {self.detail} compiled "
                f"{self.value:g} times in window "
                f"(threshold {self.threshold:g})")


class Watchdog:
    """Samples the registry (and the BufferCatalog) on an interval.

    ``check_now()`` is the deterministic single-tick entry point the
    tests (and the optional background thread) drive; it returns only
    NEWLY raised alerts. The same condition re-alerts only after it
    clears — a 60s stall is one alert, not sixty."""

    def __init__(self, registry: MetricsRegistry, rules: WatchdogRules,
                 interval_s: float = 1.0,
                 budget: Optional[int] = None,
                 conf_budget: Optional[int] = None, history: int = 64):
        self.registry = registry
        self.rules = rules
        self.interval_s = interval_s
        self._budget = budget  # hard override (tests / tooling)
        # fallback when the LIVE catalog has no budget (e.g. it was
        # lazily created under a default conf while the session that
        # enabled the watchdog set memory.hbm.budgetBytes) — without it
        # the pressure rule would silently never fire in that setup
        self._conf_budget = conf_budget
        self._alerts: deque = deque(maxlen=history)
        self._active: Set[tuple] = set()
        self._episode = 0  # flight-recorder dump counter (one per batch)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one sample tick ---------------------------------------------------
    def check_now(self, now_ns: Optional[int] = None) -> List[Alert]:
        now = now_ns if now_ns is not None else time.perf_counter_ns()
        found: Dict[tuple, Alert] = {}

        # stalls: operator spans still open past the threshold
        for op, section, start in self.registry.open_spans():
            age = now - start
            if age >= self.rules.stall_ns:
                name = op + ("." + section if section else "")
                found[(STALL, op, start)] = Alert(
                    STALL, name, age, self.rules.stall_ns, now)

        # HBM pressure: live watermark vs the shared budget
        from ..memory.catalog import BufferCatalog

        cat = BufferCatalog.get()
        # precedence: explicit override, then the budget the SPILLER
        # actually enforces (the catalog's), then the watchdog conf's
        # own derivation as a last resort
        budget = self._budget
        if budget is None:
            budget = cat.budget if cat.budget else self._conf_budget
        if budget:
            limit = self.rules.pressure_fraction * budget
            dev = cat.device_bytes
            if dev >= limit:
                detail = "BufferCatalog device watermark"
                # the HBM ledger (when armed) knows WHO holds the bytes —
                # an actionable alert names the owners, not just the level
                owners = cat.ledger.top_owners(3)
                if owners:
                    detail += " — top owners: " + ", ".join(
                        f"{op} {b / 1e6:.1f}MB" for op, b in owners)
                found[(HBM_PRESSURE,)] = Alert(
                    HBM_PRESSURE, detail, dev, limit, now)

        # buffer leaks: the ledger's query-end sentinel flagged live
        # buffers that outlived their owning query — the alert stays
        # active until the leaked buffers are actually freed
        leaks = cat.ledger.live_leaks()
        if leaks:
            top = sorted(
                leaks, key=lambda r: -(r.get("bytes") or 0))[:3]
            detail = ", ".join(
                f"{r.get('op') or '(unattributed)'} "
                f"{(r.get('bytes') or 0) / 1e6:.1f}MB "
                f"(query {r.get('query_id')})" for r in top)
            found[(BUFFER_LEAK,)] = Alert(
                BUFFER_LEAK, detail, len(leaks), 1, now)

        # live recompile storm: misses per site inside the window
        lo = now - self.rules.storm_window_ns
        per_site: Dict[str, int] = {}
        for ts, site in self.registry.recent_compile_misses():
            if ts >= lo:
                per_site[site] = per_site.get(site, 0) + 1
        for site, n in per_site.items():
            if n >= self.rules.storm_threshold:
                found[(RECOMPILE_STORM, site)] = Alert(
                    RECOMPILE_STORM, site, n,
                    self.rules.storm_threshold, now)

        # live retry storm: OOM recovery actions per op inside the window
        per_op: Dict[str, int] = {}
        for ts, op in self.registry.recent_oom_retries():
            if ts >= lo:
                per_op[op] = per_op.get(op, 0) + 1
        for op, n in per_op.items():
            if n >= self.rules.retry_storm_threshold:
                found[(RETRY_STORM, op)] = Alert(
                    RETRY_STORM, op, n,
                    self.rules.retry_storm_threshold, now)

        new: List[Alert] = []
        with self._lock:
            for key, alert in found.items():
                if key not in self._active:
                    self._active.add(key)
                    self._alerts.append(alert)
                    new.append(alert)
            # conditions that cleared may fire again as a fresh episode
            self._active &= set(found)
        for alert in new:
            log.warning("watchdog %s", alert.describe())
            self.registry.inc("tpu_watchdog_alerts", 1, kind=alert.kind)
            if _events.enabled():
                _events.emit("alert", kind=alert.kind, detail=alert.detail,
                             value=alert.value, threshold=alert.threshold)
        if new and _events.enabled():
            # flight recorder: in ring-only mode (eventLog.flightRecorder
            # .enabled) each alert episode dumps the ring — including the
            # alert events just emitted — to eventLog.dir for post-hoc
            # diagnosis; a streaming logger returns None (already durable)
            with self._lock:
                self._episode += 1
                episode = self._episode
            path = _events.flight_dump(episode)
            if path:
                log.warning("watchdog flight record: %s", path)
        return new

    def alerts(self) -> List[Alert]:
        with self._lock:
            return list(self._alerts)

    # -- background thread -------------------------------------------------
    def start(self) -> None:
        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.check_now()
                except Exception:  # pragma: no cover - never kill the host
                    log.exception("watchdog tick failed")

        # the thread-slot transition runs under the lock: two unserialized
        # start() calls otherwise both see None and spawn two tick threads
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=run, name="srtpu-watchdog", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        # claim the thread under the lock, join OUTSIDE it: the tick
        # thread takes the same lock in check_now, so joining while
        # holding it would stall stop() behind an in-flight tick
        with self._lock:
            t = self._thread
            self._thread = None
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5)


# ---------------------------------------------------------------------------
# Offline replay: the same rules over a PR-5 event log, so thresholds are
# tuned against recordings (tools/tpu_profile.py --alerts).
# ---------------------------------------------------------------------------
def replay_alerts(events: List[dict], rules: WatchdogRules,
                  budget: Optional[int] = None) -> List[Alert]:
    """Alerts the watchdog WOULD have raised over a recorded run.

    Mapping from the live sampler (which sees open spans / live
    watermarks) to the log (which records closed spans / spill events):

      * stall            — any ``op_span`` whose dur >= stall_ns (the
                           span was necessarily open that long);
      * hbm_pressure     — any ``spill``/``unspill`` whose live
                           ``device_bytes`` watermark crossed the
                           pressure line (budget from the log's
                           ``plan_analysis`` events unless overridden);
      * recompile_storm  — per-site sliding window over
                           ``compile_miss`` events; one alert per
                           episode (the count must drop below the
                           threshold before the same site alerts again);
      * retry_storm      — the same sliding-window/episode rule over
                           ``oom_retry`` events per op (the live rule
                           samples the registry's retry ring);
      * buffer_leak      — any ``heap_snapshot`` with ``leaked > 0``
                           (the ledger's query-end sentinel fired); one
                           alert per episode, cleared by a clean
                           snapshot.

    When the log carries ledger events (``buffer_alloc``/``buffer_free``
    plus bid-stamped spills), the pressure alert reconstructs per-op
    device residency and names the top-3 owning ops at the moment the
    watermark crossed the line — the replay twin of the live alert's
    ``top_owners`` detail.
    """
    out: List[Alert] = []
    site_win: Dict[str, deque] = {}
    site_storming: Dict[str, bool] = {}
    retry_win: Dict[str, deque] = {}
    retry_storming: Dict[str, bool] = {}
    pressure_active = False
    leak_active = False
    # bid -> (op, bytes) for device-resident ledger buffers, so the
    # pressure alert can name owners from the recording alone
    heap: Dict[object, tuple] = {}
    off_device: Set[object] = set()
    for r in events:
        ev = r.get("event")
        ts = r.get("ts", 0)
        if ev == "plan_analysis" and budget is None:
            budget = r.get("budget")
        elif ev == "op_span":
            # host lane only, matching the live sampler (which watches
            # op_timed's open-span table): a deviceSync log carries a
            # device-wait twin of the same episode — counting both would
            # replay one live stall as two alerts
            if r.get("lane", "host") != "host":
                continue
            dur = r.get("dur") or 0
            if dur >= rules.stall_ns:
                name = r.get("op", "?") + (
                    "." + r["section"] if r.get("section") else "")
                out.append(Alert(STALL, name, dur, rules.stall_ns, ts))
        elif ev == "spill":
            bid = r.get("bid")
            if bid is not None and bid in heap:
                if r.get("kind") == "device_to_host":
                    off_device.add(bid)
                elif r.get("kind") == "unspill":
                    off_device.discard(bid)
            if not budget:
                continue
            limit = rules.pressure_fraction * budget
            dev = r.get("device_bytes") or 0
            if dev >= limit and not pressure_active:
                detail = "BufferCatalog device watermark"
                by_op: Dict[str, int] = {}
                for hbid, (hop, hbytes) in heap.items():
                    if hbid not in off_device:
                        by_op[hop] = by_op.get(hop, 0) + hbytes
                owners = sorted(
                    by_op.items(), key=lambda kv: -kv[1])[:3]
                if owners:
                    detail += " — top owners: " + ", ".join(
                        f"{op} {b / 1e6:.1f}MB" for op, b in owners)
                out.append(Alert(HBM_PRESSURE, detail, dev, limit, ts))
            pressure_active = dev >= limit
        elif ev == "buffer_alloc":
            if r.get("kind") != "reservation":
                heap[r.get("bid")] = (
                    r.get("op") or "(unattributed)", r.get("bytes") or 0)
        elif ev == "buffer_free":
            heap.pop(r.get("bid"), None)
            off_device.discard(r.get("bid"))
        elif ev == "heap_snapshot":
            leaked = r.get("leaked") or 0
            if leaked and not leak_active:
                out.append(Alert(
                    BUFFER_LEAK, f"query {r.get('query_id')}",
                    leaked, 1, ts))
            leak_active = leaked > 0
        elif ev == "compile_miss":
            site = r.get("site", "?")
            win = site_win.setdefault(site, deque())
            win.append(ts)
            lo = ts - rules.storm_window_ns
            while win and win[0] < lo:
                win.popleft()
            if len(win) >= rules.storm_threshold:
                if not site_storming.get(site):
                    out.append(Alert(
                        RECOMPILE_STORM, site, len(win),
                        rules.storm_threshold, ts))
                site_storming[site] = True
            else:
                site_storming[site] = False
        elif ev == "oom_retry":
            op = r.get("op", "?")
            win = retry_win.setdefault(op, deque())
            win.append(ts)
            lo = ts - rules.storm_window_ns
            while win and win[0] < lo:
                win.popleft()
            if len(win) >= rules.retry_storm_threshold:
                if not retry_storming.get(op):
                    out.append(Alert(
                        RETRY_STORM, op, len(win),
                        rules.retry_storm_threshold, ts))
                retry_storming[op] = True
            else:
                retry_storming[op] = False
    return out
