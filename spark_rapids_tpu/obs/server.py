"""Conf-gated stdlib-HTTP exporter: /metrics (Prometheus) + /status (JSON).

Reference analog: the Spark UI's live SQL tab + the JVM's standard
Prometheus servlet — but stdlib-only (http.server), bound to localhost
by default, and started as a daemon thread so a dying driver never hangs
on it. ``/metrics`` serves Prometheus text exposition 0.0.4 of the whole
metric catalog (every family renders its HELP/TYPE header even before
the first sample — scrape targets are stable from process start);
``/status`` serves the operator view ``tools/tpu_top.py`` renders: live
queries with per-op forecast-derived progress, the HBM watermark vs the
shared budget, and the watchdog's alert history.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .progress import ProgressTracker
from .registry import MetricsRegistry
from .watchdog import Watchdog

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def build_status(registry: MetricsRegistry, progress: ProgressTracker,
                 watchdog: Optional[Watchdog]) -> dict:
    """The /status payload (also called directly by tests: everything in
    it must be plain-JSON serializable)."""
    from ..memory.catalog import BufferCatalog

    cat = BufferCatalog.get()
    m = cat.metrics
    budget = cat.budget
    hbm = {
        "device_bytes": cat.device_bytes,
        "peak_device_bytes": m.peak_device_bytes,
        "spilled_bytes": m.spilled_bytes,
        "reserved_bytes": cat.reserved_bytes,
        "budget_bytes": budget,
        "pressure": (cat.device_bytes / budget) if budget else None,
    }
    # serving queue (serve/scheduler.py) — peek only: /status must not
    # conjure a scheduler in a process that never served
    from ..serve.scheduler import QueryScheduler

    sched = QueryScheduler.instance()
    serve = None
    if sched is not None:
        serve = {
            "stats": sched.stats(),
            "queue": sched.queue_status(),
            "active": sched.active_status(),
        }
    # persistent AOT program cache (serve/program_cache.py) — peek only:
    # stats() is None in a process that never installed one
    from ..serve import program_cache

    # environment provenance (envinfo — the same helper bench.py stamps
    # into BENCH_*.json): a live operator must be able to tell at a
    # glance whether the numbers on screen are device-backed or the CPU
    # fallback's
    from .. import envinfo

    return {
        "queries": progress.status(),
        "queries_live": progress.live_count(),
        "env": envinfo.environment_info(),
        "hbm": hbm,
        # per-buffer HBM ledger (memory/ledger.py): live bytes broken
        # down by owning op, top owners, and the leak sentinel's tally —
        # all zeros while the ledger is unarmed
        "heap": cat.ledger.status_block(),
        "serve": serve,
        "program_cache": program_cache.stats(),
        "alerts": [a.to_json() for a in watchdog.alerts()]
        if watchdog is not None else [],
        "metrics": registry.snapshot(),
    }


class MetricsServer:
    """Daemon-thread HTTP server over one registry/progress/watchdog."""

    def __init__(self, registry: MetricsRegistry,
                 progress: ProgressTracker,
                 watchdog: Optional[Watchdog] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        self.progress = progress
        self.watchdog = watchdog
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr chatter per scrape
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(200, PROM_CONTENT_TYPE,
                                   outer.registry.render_prometheus()
                                   .encode())
                    elif path == "/status":
                        body = json.dumps(build_status(
                            outer.registry, outer.progress,
                            outer.watchdog)).encode()
                        self._send(200, "application/json", body)
                    elif path == "/healthz":
                        self._send(200, "text/plain", b"ok\n")
                    else:
                        self._send(404, "text/plain",
                                   b"try /metrics or /status\n")
                except Exception as e:  # pragma: no cover - scrape races
                    try:
                        self._send(500, "text/plain",
                                   f"error: {e}\n".encode())
                    except OSError:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="srtpu-metrics-http", daemon=True)

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
