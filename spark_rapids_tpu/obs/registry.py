"""Live metrics registry: the ONLINE half of observability.

Reference analog: the SQLMetrics every GpuExec publishes into the live
Spark UI while a query runs (GpuExec.scala gpuLongMetric + the
SQLAppStatusListener aggregation) — where PR 5's event log is the
*offline* record, this registry is what an operator (or the admission
controller of ROADMAP item 3) watches in real time: per-op host/device
time and bytes, compile misses by site, the BufferCatalog device-byte
watermark, shuffle transport traffic, scan-cache effectiveness.

Design mirrors events.py exactly so the two planes share one mental
model:

  * a process-global ``install()``-ed registry behind a module-global
    ``_ENABLED`` boolean — with nothing installed (the default) every
    hot-path call site pays ONE boolean read and builds nothing
    (tests/test_obs.py pins this, the same zero-overhead contract the
    event log carries);
  * every metric is DECLARED up front in :data:`METRICS` (name, kind,
    help, label names) — the single source of truth for the emit sites,
    the Prometheus renderer, and the CI completeness check that every
    EVENT_TYPES-backed counter has a live twin
    (:data:`EVENT_BACKED_METRICS`);
  * the registry lock is a LEAF lock: no registry method ever calls
    into another engine subsystem, so emitters may call in while
    holding their own locks (the BufferCatalog does) with no
    lock-ordering hazard.

Label dimensions keep cardinality bounded: operator class names, lanes,
spill kinds, codec names — and a ``device`` label on the mesh-staging
counter so the multichip SPMD path reports per-chip.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..utils.locks import ordered_lock

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

#: per-batch host-time histogram buckets (seconds)
_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0)

# ---------------------------------------------------------------------------
# The metric catalog. Counters are cumulative since install; gauges are
# last-write; the histogram buckets per-batch operator wall time.
# Prometheus exposition appends ``_total`` to counters.
# ---------------------------------------------------------------------------
METRICS: Dict[str, Tuple[str, str, Tuple[str, ...]]] = {
    "tpu_op_time_seconds": (
        COUNTER, "Cumulative operator time by lane (host wall-clock from "
        "op_timed; device wait under metrics.deviceSync.enabled)",
        ("op", "lane")),
    "tpu_op_rows": (
        COUNTER, "Output rows recorded per operator", ("op",)),
    "tpu_op_batches": (
        COUNTER, "Output batches recorded per operator", ("op",)),
    "tpu_op_bytes": (
        COUNTER, "Output bytesTouched recorded per operator", ("op",)),
    "tpu_op_batch_seconds": (
        HISTOGRAM, "Per-batch operator host time distribution", ("op",)),
    "tpu_compile_misses": (
        COUNTER, "XLA pipeline-cache compile misses by site", ("site",)),
    "tpu_compile_seconds": (
        COUNTER, "Harvested XLA program build time by site and phase "
        "(trace = jit lower, compile = XLA backend compile — the "
        "program_cost event's live twin, xla_cost.py)", ("site", "phase")),
    "tpu_program_temp_bytes": (
        GAUGE, "Largest XLA temp allocation harvested per compile site "
        "(memory_analysis temp_size_in_bytes high-water mark; a jump "
        "means a kernel started materializing intermediates the layout "
        "model doesn't know about)", ("site",)),
    "tpu_hlo_scatter_programs": (
        COUNTER, "Harvested programs whose optimized HLO contains at "
        "least one scatter-classified instruction, by compile site "
        "(hlo.py per-fusion attribution — the hlo_summary event's live "
        "twin; scatters are the byte-amplification idiom the roofline "
        "push hunts)", ("site",)),
    "tpu_hlo_top_fusion_bytes": (
        GAUGE, "Largest single-fusion byte attribution harvested per "
        "compile site (high-water mark; a jump means one fusion started "
        "owning more of the program's traffic — the per-instruction "
        "refinement of tpu_program_temp_bytes)", ("site",)),
    "tpu_transfers": (
        COUNTER, "Host-link transfers by direction (h2d/d2h/fence)",
        ("direction",)),
    "tpu_transfer_bytes": (
        COUNTER, "Host-link bytes by direction", ("direction",)),
    "tpu_spills": (
        COUNTER, "Buffer-catalog spill events by kind "
        "(device_to_host/host_to_disk/unspill)", ("kind",)),
    "tpu_spill_bytes": (
        COUNTER, "Bytes moved by spill events, by kind", ("kind",)),
    "tpu_hbm_device_bytes": (
        GAUGE, "Live catalog-tracked device bytes (the BufferCatalog "
        "watermark)", ()),
    "tpu_hbm_peak_device_bytes": (
        GAUGE, "High-water mark of catalog-tracked device bytes", ()),
    "tpu_hbm_budget_bytes": (
        GAUGE, "Derived HBM spill budget (0 = unlimited/unknown)", ()),
    "tpu_shuffle_pieces": (
        COUNTER, "Shuffle pieces through the transport SPI",
        ("direction", "codec")),
    "tpu_shuffle_bytes": (
        COUNTER, "Shuffle transport bytes", ("direction", "codec")),
    "tpu_shuffle_codec_seconds": (
        COUNTER, "Shuffle codec time (encode/decode)", ("op",)),
    "tpu_scan_cache_ops": (
        COUNTER, "Device scan-cache operations (hit/miss/put/evict)",
        ("op",)),
    "tpu_program_cache": (
        COUNTER, "Persistent AOT program-cache operations "
        "(hit/miss/put/deserialize/evict/corrupt/write_error — "
        "serve/program_cache.py; the program_cache event's live twin). "
        "A warm process shows hits ~= deserializes and zero compile "
        "misses; corrupt entries are deleted and fall through to plain "
        "compiles.", ("op",)),
    "tpu_program_cache_resident_bytes": (
        GAUGE, "Bytes resident in the AOT program-cache directory "
        "(updated after each store's size-capped LRU sweep)", ()),
    "tpu_program_cache_saved_seconds": (
        COUNTER, "Original trace+compile seconds the persisted cost "
        "payloads say deserialize hits avoided (the compile-seconds-"
        "avoided estimate tpu_profile's program-cache section reports)",
        ()),
    "tpu_scan_cache_hit_ratio": (
        GAUGE, "hits / (hits + misses) of the device scan cache", ()),
    "tpu_scan_cache_resident_bytes": (
        GAUGE, "Bytes resident in the device scan cache", ()),
    "tpu_queries": (
        COUNTER, "Queries by lifecycle state (started/finished/failed)",
        ("state",)),
    "tpu_queries_live": (
        GAUGE, "Queries currently executing", ()),
    "tpu_mesh_staged_rows": (
        COUNTER, "Rows staged onto each mesh shard (per-chip lane of the "
        "multichip SPMD path)", ("device",)),
    "tpu_mesh_shard_seconds": (
        COUNTER, "Per-chip completion time of mesh SPMD programs "
        "(dispatch to that shard's outputs ready — upper bound, polled "
        "in shard order; the live twin of the per-chip op_span lanes)",
        ("device",)),
    "tpu_watchdog_alerts": (
        COUNTER, "Watchdog alerts raised, by kind "
        "(stall/hbm_pressure/recompile_storm)", ("kind",)),
    "tpu_agg_strategy": (
        COUNTER, "Aggregation lowering choices by resolved strategy "
        "(MATMUL/SCATTER/SORT — conf sql.agg.strategy)", ("strategy",)),
    "tpu_join_strategy": (
        COUNTER, "Join probe lowering choices by resolved strategy "
        "(SEARCH/DIRECT/RADIX/PALLAS — conf sql.join.strategy; the "
        "join_strategy event's live twin)", ("strategy",)),
    "tpu_pq_pipeline_stages": (
        COUNTER, "Pipelined parquet decode stages completed "
        "(decode/upload/unpack)", ("stage",)),
    "tpu_pq_pipeline_bytes": (
        COUNTER, "Bytes through the pipelined parquet decode stages",
        ("stage",)),
    "tpu_serve_admissions": (
        COUNTER, "Serving-layer admission decisions by verdict "
        "(admit/queue/reject — serve/scheduler.py)", ("verdict",)),
    "tpu_serve_queue": (
        COUNTER, "Fair-queue lifecycle ops (enqueue/dequeue/timeout)",
        ("op",)),
    "tpu_serve_queue_depth": (
        GAUGE, "Queries currently waiting in the serving queue (all "
        "sessions)", ()),
    "tpu_serve_queue_wait_seconds": (
        HISTOGRAM, "Queued duration per admitted query", ()),
    "tpu_serve_plan_cache": (
        COUNTER, "Shared plan-cache lookups by outcome (hit/miss) — one "
        "analysis/compile-prep per plan digest across sessions", ("op",)),
    "tpu_hbm_reserved_bytes": (
        GAUGE, "Outstanding admission reservations (admitted peak-HBM "
        "forecasts not yet released)", ()),
    "tpu_oom_retries": (
        COUNTER, "OOM recovery actions by op and kind (retry = spill + "
        "re-attempt, split = escalation to half capacity, requeue = the "
        "serve scheduler re-admitting with an inflated forecast — "
        "memory/retry.py; the oom_retry event's live twin). A nonzero "
        "rate means forecasts are wrong or the budget is tight; the "
        "watchdog's retry-storm rule alerts on a burst.", ("op", "kind")),
    "tpu_batch_splits": (
        COUNTER, "Split-and-retry halvings by op (the batch_split "
        "event's live twin): each one means the op completed on "
        "half-capacity programs instead of dying", ("op",)),
    "tpu_shuffle_fetch_retries": (
        COUNTER, "Network shuffle fetch transient-failure outcomes "
        "(retry = backed off and re-fetched, failure = retries "
        "exhausted, FetchFailedError raised — shuffle/network.py)",
        ("outcome",)),
    "tpu_donated_bytes": (
        COUNTER, "Input-plane bytes donated to XLA per certified "
        "compile site (plugin/donation.py; the donation event's live "
        "twin). Donated planes' HBM is reused for program outputs/"
        "temps — zero here with donation enabled means no dispatch "
        "qualified (batches not exclusive, dict columns, or the site "
        "is uncertified).", ("site",)),
    "tpu_hbm_bytes": (
        GAUGE, "Device-live HBM bytes attributed per owning op by the "
        "per-buffer ledger (memory/ledger.py; the buffer_alloc/"
        "buffer_free events' live twin). Covers spillable handles AND "
        "scan-cache entries — the attributed decomposition of "
        "tpu_hbm_device_bytes plus cache residency; '(unattributed)' "
        "rows are buffers created outside any op scope.", ("op",)),
    "tpu_hbm_leaked_buffers": (
        COUNTER, "Buffers the leak sentinel flagged as outliving their "
        "owning query (memory/ledger.py sweep at query end; the "
        "heap_snapshot event's live twin). Any nonzero value is a "
        "lifecycle bug — the /status heap block names the owners.", ()),
}

#: event type -> the live metric family that carries the same signal, so
#: the offline (events.EVENT_TYPES) and online planes can never drift: a
#: new event type without a live twin fails tests/test_obs.py and the CI
#: obs job's /metrics completeness check.
EVENT_BACKED_METRICS: Dict[str, str] = {
    "query_start": "tpu_queries",
    "query_end": "tpu_queries",
    "plan_tagged": "tpu_queries",
    "plan_analysis": "tpu_queries",
    "op_span": "tpu_op_time_seconds",
    "op_batch": "tpu_op_rows",
    "compile_miss": "tpu_compile_misses",
    "program_cost": "tpu_compile_seconds",
    "hlo_summary": "tpu_hlo_scatter_programs",
    "transfer": "tpu_transfer_bytes",
    "spill": "tpu_spill_bytes",
    "shuffle_write": "tpu_shuffle_bytes",
    "shuffle_fetch": "tpu_shuffle_bytes",
    "scan_cache": "tpu_scan_cache_ops",
    "program_cache": "tpu_program_cache",
    "alert": "tpu_watchdog_alerts",
    "agg_strategy": "tpu_agg_strategy",
    "join_strategy": "tpu_join_strategy",
    "pq_pipeline": "tpu_pq_pipeline_stages",
    "admission": "tpu_serve_admissions",
    "queue": "tpu_serve_queue",
    "oom_retry": "tpu_oom_retries",
    "batch_split": "tpu_batch_splits",
    "donation": "tpu_donated_bytes",
    "buffer_alloc": "tpu_hbm_bytes",
    "buffer_free": "tpu_hbm_bytes",
    "heap_snapshot": "tpu_hbm_leaked_buffers",
}


def _label_values(name: str, labels: Dict[str, str]) -> tuple:
    """Order **labels by the metric's declared label names (missing
    labels render empty, unknown labels raise — a typo at an emit site
    must fail loudly in tests, not mint a new series silently)."""
    declared = METRICS[name][2]
    unknown = set(labels) - set(declared)
    if unknown:
        raise ValueError(f"{name}: undeclared label(s) {sorted(unknown)}")
    return tuple(str(labels.get(k, "")) for k in declared)


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms + open-span table.

    One lock guards everything; every method is O(1)-ish and NEVER calls
    out of this module (leaf-lock discipline — see module docstring)."""

    def __init__(self):
        self._lock = ordered_lock("obs.registry")
        # name -> label-values tuple -> float
        self._vals: Dict[str, Dict[tuple, float]] = {
            name: {} for name in METRICS
        }
        # histograms: name -> labels -> [bucket counts..., +inf, sum]
        self._hist: Dict[str, Dict[tuple, List[float]]] = {
            name: {} for name, (kind, _, _) in METRICS.items()
            if kind == HISTOGRAM
        }
        # open operator spans (the stall watchdog's sample set):
        # token -> (op, section, start_ns)
        self._spans: Dict[int, Tuple[str, str, int]] = {}
        self._span_seq = 0
        # recent compile misses (ts_ns, site) for live storm detection
        self._miss_ring: deque = deque(maxlen=4096)
        # recent OOM recovery actions (ts_ns, op) — the retry-storm
        # watchdog rule's window (same shape as the miss ring)
        self._retry_ring: deque = deque(maxlen=4096)

    # -- writes ------------------------------------------------------------
    def inc(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = _label_values(name, labels)
        with self._lock:
            d = self._vals[name]
            d[key] = d.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        key = _label_values(name, labels)
        with self._lock:
            self._vals[name][key] = float(value)

    def set_gauge_max(self, name: str, value: float, **labels: str) -> None:
        """High-water-mark gauge write: keeps the larger of the current
        and new value under ONE lock acquisition (a read-then-set pair
        would race concurrent emitters)."""
        key = _label_values(name, labels)
        with self._lock:
            d = self._vals[name]
            cur = d.get(key)
            if cur is None or value > cur:
                d[key] = float(value)

    def rebase_gauge(self, name: str) -> None:
        """Drop every labeled row of a high-water gauge so the next
        ``set_gauge_max`` writes record a fresh window's peak — the
        bench's per-shape rebase (the BufferCatalog peak-watermark
        pattern: the gauge is a monotonic process-wide max, and a
        window owner resetting it between windows is the only way a
        later window's reading is its OWN peak, not an earlier,
        hungrier one's)."""
        with self._lock:
            self._vals[name].clear()

    def observe(self, name: str, value: float, **labels: str) -> None:
        key = _label_values(name, labels)
        with self._lock:
            h = self._hist[name].get(key)
            if h is None:
                h = self._hist[name][key] = [0.0] * (len(_BUCKETS) + 2)
            for i, ub in enumerate(_BUCKETS):
                if value <= ub:
                    h[i] += 1
            h[len(_BUCKETS)] += 1          # +Inf / count
            h[len(_BUCKETS) + 1] += value  # sum

    # -- open spans (stall detection) --------------------------------------
    def span_open(self, op: str, section: str = "",
                  start_ns: Optional[int] = None) -> int:
        with self._lock:
            self._span_seq += 1
            token = self._span_seq
            self._spans[token] = (
                op, section, start_ns or time.perf_counter_ns())
            return token

    def span_close(self, token: int) -> None:
        with self._lock:
            self._spans.pop(token, None)

    def open_spans(self) -> List[Tuple[str, str, int]]:
        with self._lock:
            return list(self._spans.values())

    # -- compile-miss ring (live storm detection) --------------------------
    def note_compile_miss(self, site: str,
                          ts_ns: Optional[int] = None) -> None:
        self.inc("tpu_compile_misses", 1, site=site)
        with self._lock:
            self._miss_ring.append((ts_ns or time.perf_counter_ns(), site))

    def recent_compile_misses(self) -> List[Tuple[int, str]]:
        with self._lock:
            return list(self._miss_ring)

    # -- OOM-retry ring (live retry-storm detection) -----------------------
    def note_oom_retry(self, op: str, kind: str = "retry",
                       ts_ns: Optional[int] = None) -> None:
        self.inc("tpu_oom_retries", 1, op=op, kind=kind)
        with self._lock:
            self._retry_ring.append(
                (ts_ns or time.perf_counter_ns(), op))

    def recent_oom_retries(self) -> List[Tuple[int, str]]:
        with self._lock:
            return list(self._retry_ring)

    # -- reads -------------------------------------------------------------
    def value(self, name: str, **labels: str) -> float:
        key = _label_values(name, labels)
        with self._lock:
            return self._vals[name].get(key, 0.0)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{name: {"k=v,k=v": value}} — the JSON-friendly view /status
        embeds (histograms report their count and sum)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, series in self._vals.items():
                if METRICS[name][0] == HISTOGRAM:
                    continue
                if series:
                    declared = METRICS[name][2]
                    out[name] = {
                        ",".join(f"{k}={v}" for k, v in zip(declared, key)):
                        val for key, val in series.items()
                    }
            for name, series in self._hist.items():
                if series:
                    declared = METRICS[name][2]
                    out[name] = {}
                    for key, h in series.items():
                        lbl = ",".join(
                            f"{k}={v}" for k, v in zip(declared, key))
                        out[name][lbl + ("|count" if lbl else "count")] = \
                            h[len(_BUCKETS)]
                        out[name][lbl + ("|sum" if lbl else "sum")] = \
                            h[len(_BUCKETS) + 1]
        return out

    # -- Prometheus text exposition (version 0.0.4) ------------------------
    def render_prometheus(self) -> str:
        """Every declared family renders its # HELP / # TYPE header even
        with zero samples (so scrapers — and the CI completeness check —
        see the full catalog from the first scrape)."""
        def esc(v: str) -> str:
            return v.replace("\\", "\\\\").replace('"', '\\"') \
                    .replace("\n", "\\n")

        def num(value: float) -> str:
            # FULL precision: %g's 6 significant digits would quantize a
            # byte counter past ~1e6 and make small scrape-to-scrape
            # deltas vanish under Prometheus rate(); repr is the
            # shortest exact round-trip (integers render bare)
            if float(value).is_integer() and abs(value) < 1e15:
                return str(int(value))
            return repr(float(value))

        def fmt(name: str, key: tuple, declared: tuple, value: float,
                extra: str = "") -> str:
            pairs = [f'{k}="{esc(v)}"'
                     for k, v in zip(declared, key) if v != ""]
            if extra:
                pairs.append(extra)
            lbl = "{" + ",".join(pairs) + "}" if pairs else ""
            return f"{name}{lbl} {num(value)}"

        lines: List[str] = []
        with self._lock:
            for name in sorted(METRICS):
                kind, help_, declared = METRICS[name]
                ename = name + ("_total" if kind == COUNTER else "")
                lines.append(f"# HELP {ename} {help_}")
                lines.append(f"# TYPE {ename} {kind}")
                if kind == HISTOGRAM:
                    for key, h in sorted(self._hist[name].items()):
                        for i, ub in enumerate(_BUCKETS):
                            lines.append(fmt(
                                name + "_bucket", key, declared, h[i],
                                extra=f'le="{ub:g}"'))
                        lines.append(fmt(
                            name + "_bucket", key, declared,
                            h[len(_BUCKETS)], extra='le="+Inf"'))
                        lines.append(fmt(name + "_count", key, declared,
                                         h[len(_BUCKETS)]))
                        lines.append(fmt(name + "_sum", key, declared,
                                         h[len(_BUCKETS) + 1]))
                    continue
                for key, value in sorted(self._vals[name].items()):
                    lines.append(fmt(ename, key, declared, value))
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Process-global active registry — the events.py install pattern: emit
# sites live deep in the engine where no session handle exists, so the
# observability plane INSTALLS the registry; with nothing installed the
# fast path is one module-global boolean read.
# ---------------------------------------------------------------------------
_ENABLED = False
_ACTIVE: Optional[MetricsRegistry] = None


def enabled() -> bool:
    """The hot-path guard: True only while a registry is installed. Call
    sites that would build labels/compute values check this FIRST."""
    return _ENABLED


def active() -> Optional[MetricsRegistry]:
    return _ACTIVE


def install(registry: MetricsRegistry) -> None:
    global _ENABLED, _ACTIVE
    _ACTIVE = registry
    _ENABLED = True


def uninstall() -> None:
    global _ENABLED, _ACTIVE
    _ACTIVE = None
    _ENABLED = False


# -- module-level emit helpers (no-ops when nothing is installed) -----------
def inc(name: str, value: float = 1.0, **labels: str) -> None:
    if not _ENABLED:
        return
    reg = _ACTIVE
    if reg is not None:
        reg.inc(name, value, **labels)


def set_gauge(name: str, value: float, **labels: str) -> None:
    if not _ENABLED:
        return
    reg = _ACTIVE
    if reg is not None:
        reg.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels: str) -> None:
    if not _ENABLED:
        return
    reg = _ACTIVE
    if reg is not None:
        reg.observe(name, value, **labels)
