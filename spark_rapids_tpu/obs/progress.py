"""Per-query live progress: rows done vs the plan analyzer's forecast.

Reference analog: the Spark UI's per-stage task progress bars — but the
denominator here is STATIC: the plan analyzer (plugin/plananalysis.py)
forecasts each operator's output rows and batch count from the bound
plan, and record_batch's live numerators divide into them. A bounded
plan therefore shows true fractional progress before the first batch
lands; an unbounded op (file scans, joins) shows its numerators with a
null denominator instead of a fake percentage.

Attribution is BY THREAD: a session begins its query on the thread that
will drain the plan (collect/writer both consume on the caller's
thread), so concurrent sessions in different threads each feed their own
query's numerators — the same model Spark uses (task thread -> stage).
Operators that hop threads (none today) would simply not attribute;
numerators are best-effort progress, never accounting of record.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional


class OpProgress:
    __slots__ = ("rows", "batches", "bytes")

    def __init__(self):
        self.rows = 0
        self.batches = 0
        self.bytes = 0


class QueryState:
    __slots__ = ("query_id", "plan_digest", "start_ns", "end_ns",
                 "thread_ident", "rows_forecast", "batches_forecast",
                 "ops", "done", "error", "rows_out")

    def __init__(self, query_id, plan_digest: str,
                 rows_forecast: Dict[str, int],
                 batches_forecast: Dict[str, int], thread_ident: int):
        self.query_id = query_id
        self.plan_digest = plan_digest
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.thread_ident = thread_ident
        self.rows_forecast = dict(rows_forecast or {})
        self.batches_forecast = dict(batches_forecast or {})
        self.ops: Dict[str, OpProgress] = {}
        self.done = False
        self.error = False
        self.rows_out: Optional[int] = None

    def to_status(self) -> dict:
        end = self.end_ns or time.perf_counter_ns()
        ops: List[dict] = []
        for op in sorted(set(self.ops) | set(self.rows_forecast)
                         | set(self.batches_forecast)):
            p = self.ops.get(op)
            rows = p.rows if p else 0
            batches = p.batches if p else 0
            rf = self.rows_forecast.get(op)
            bf = self.batches_forecast.get(op)
            # rows when both sides have them; else batches (a lazy row
            # count — still a device scalar — records batches only);
            # no denominator at all -> None, never a fake percentage
            if rf and rows:
                progress: Optional[float] = min(1.0, rows / rf)
            elif bf and batches:
                progress = min(1.0, batches / bf)
            elif rf or bf:
                progress = 0.0
            else:
                progress = None
            ops.append({
                "op": op, "rows": rows, "rows_forecast": rf,
                "batches": batches, "batches_forecast": bf,
                "bytes": p.bytes if p else 0, "progress": progress,
            })
        state = ("failed" if self.error
                 else "finished" if self.done else "running")
        return {
            "query_id": self.query_id, "plan_digest": self.plan_digest,
            "state": state, "elapsed_ms": (end - self.start_ns) / 1e6,
            "rows_out": self.rows_out, "ops": ops,
        }


class ProgressTracker:
    """Thread-safe live-query table + a short finished-query history.

    The lock is a LEAF lock (same discipline as the metrics registry):
    no method calls out of this module."""

    def __init__(self, history: int = 16):
        self._lock = threading.Lock()
        self._live: Dict[object, QueryState] = {}
        self._by_thread: Dict[int, object] = {}
        self._recent: deque = deque(maxlen=history)

    def begin(self, query_id, plan_digest: str = "",
              rows_forecast: Optional[Dict[str, int]] = None,
              batches_forecast: Optional[Dict[str, int]] = None) -> None:
        ident = threading.get_ident()
        st = QueryState(query_id, plan_digest, rows_forecast or {},
                        batches_forecast or {}, ident)
        with self._lock:
            self._live[query_id] = st
            self._by_thread[ident] = query_id

    def note_batch(self, op: str, rows: Optional[int],
                   nbytes: int) -> None:
        """Called from record_batch on the draining thread; silently a
        no-op when the thread has no live query (direct exec tests)."""
        with self._lock:
            qid = self._by_thread.get(threading.get_ident())
            st = self._live.get(qid) if qid is not None else None
            if st is None:
                return
            p = st.ops.get(op)
            if p is None:
                p = st.ops[op] = OpProgress()
            p.batches += 1
            if rows:
                p.rows += rows
            p.bytes += nbytes

    def end(self, query_id, rows: Optional[int] = None,
            error: bool = False) -> None:
        with self._lock:
            st = self._live.pop(query_id, None)
            if st is None:
                return
            if self._by_thread.get(st.thread_ident) == query_id:
                del self._by_thread[st.thread_ident]
            st.done = True
            st.error = error
            st.rows_out = rows
            st.end_ns = time.perf_counter_ns()
            self._recent.append(st)

    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    def status(self) -> List[dict]:
        """Live queries first (oldest first), then recent history.
        Payloads are built UNDER the lock: note_batch inserts into
        st.ops concurrently, and iterating that dict unlocked could
        raise mid-scrape — /status must stay parseable mid-run."""
        with self._lock:
            live = sorted(self._live.values(), key=lambda s: s.start_ns)
            return [s.to_status() for s in live] + \
                   [s.to_status() for s in reversed(self._recent)]

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._by_thread.clear()
            self._recent.clear()


#: process-global tracker (always present — emit sites are gated on
#: registry.enabled(), so an idle tracker costs nothing)
_TRACKER = ProgressTracker()


def tracker() -> ProgressTracker:
    return _TRACKER
