"""Live observability plane: metrics registry, HTTP exporter, watchdog.

The ONLINE half of observability (PR 5's event log is the offline half):
``ensure_started(conf)`` idempotently installs the process-global
:class:`MetricsRegistry`, and — per conf — starts the ``/metrics`` +
``/status`` HTTP exporter thread and the stall/pressure/storm watchdog.
Everything here follows the events.py zero-overhead contract: with the
plane off (the default) every engine emit site pays one module-global
boolean read (``enabled()``) and nothing else — no locks, no dicts, no
threads.

This module is also the facade the engine emits through: the helpers
below (``note_op_batch``, ``add_op_time``, ``note_compile_miss``,
``note_query_start``/``end``, span open/close) update the registry AND
the per-query progress tracker in one call so call sites stay
one-liners. It is the signal bus ROADMAP item 3's admission controller
reads: live HBM watermark, compile-miss rate, and queue depth all come
from here.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from .progress import ProgressTracker, tracker
from .registry import (
    EVENT_BACKED_METRICS,
    METRICS,
    MetricsRegistry,
    active,
    enabled,
    inc,
    install,
    observe,
    set_gauge,
    uninstall,
)
from .watchdog import Alert, Watchdog, WatchdogRules, replay_alerts
from ..utils.locks import ordered_lock as _ordered_lock

__all__ = [
    "Alert", "EVENT_BACKED_METRICS", "METRICS", "MetricsRegistry",
    "ObsPlane", "ProgressTracker", "Watchdog", "WatchdogRules",
    "active", "add_op_time", "enabled", "ensure_started", "inc",
    "install", "note_batch_split", "note_compile_miss",
    "note_hlo_summary", "note_oom_retry",
    "note_op_batch", "note_program_cost",
    "note_query_end", "note_query_start", "observe", "plane",
    "replay_alerts",
    "set_gauge", "shutdown", "span_close", "span_open", "tracker",
    "uninstall",
]


# ---------------------------------------------------------------------------
# Engine-facing emit helpers (all no-ops while the plane is off; callers
# still guard on enabled() before computing anything expensive)
# ---------------------------------------------------------------------------
def add_op_time(op: str, lane: str, dur_ns: int) -> None:
    reg = active()
    if reg is None:
        return
    reg.inc("tpu_op_time_seconds", dur_ns / 1e9, op=op, lane=lane)
    if lane == "host":
        reg.observe("tpu_op_batch_seconds", dur_ns / 1e9, op=op)


def span_open(op: str, section: str = "") -> Optional[int]:
    reg = active()
    return reg.span_open(op, section) if reg is not None else None


def span_close(token: Optional[int]) -> None:
    reg = active()
    if reg is not None and token is not None:
        reg.span_close(token)


def note_op_batch(op: str, rows: Optional[int], nbytes: int) -> None:
    reg = active()
    if reg is None:
        return
    reg.inc("tpu_op_batches", 1, op=op)
    if rows:
        reg.inc("tpu_op_rows", rows, op=op)
    reg.inc("tpu_op_bytes", nbytes, op=op)
    tracker().note_batch(op, rows, nbytes)


def note_compile_miss(site: str) -> None:
    reg = active()
    if reg is not None:
        reg.note_compile_miss(site)


def note_oom_retry(op: str, kind: str = "retry") -> None:
    """Live twin of the oom_retry event (memory/retry.py): counter plus
    the ring the watchdog's retry-storm window samples."""
    reg = active()
    if reg is not None:
        reg.note_oom_retry(op, kind)


def note_batch_split(op: str) -> None:
    """Live twin of the batch_split event."""
    reg = active()
    if reg is not None:
        reg.inc("tpu_batch_splits", 1, op=op)


def note_program_cost(site: str, trace_s: float, compile_s: float,
                      temp_bytes: Optional[int] = None) -> None:
    """Live twins of the program_cost event (xla_cost.py): compile
    seconds by site+phase, and the largest-temp-allocation high-water
    gauge (None when the backend's memory_analysis reported nothing)."""
    reg = active()
    if reg is None:
        return
    reg.inc("tpu_compile_seconds", trace_s, site=site, phase="trace")
    reg.inc("tpu_compile_seconds", compile_s, site=site, phase="compile")
    if temp_bytes is not None:
        reg.set_gauge_max("tpu_program_temp_bytes", temp_bytes, site=site)


def note_hlo_summary(site: str, scatter_count: int,
                     top_fusion_bytes: int) -> None:
    """Live twins of the hlo_summary event (hlo.py): scatter-program
    counter per site (incremented once per program containing any
    scatter-classified instruction) and the largest-single-fusion byte
    high-water gauge."""
    reg = active()
    if reg is None:
        return
    if scatter_count:
        reg.inc("tpu_hlo_scatter_programs", 1, site=site)
    if top_fusion_bytes:
        reg.set_gauge_max("tpu_hlo_top_fusion_bytes", top_fusion_bytes,
                          site=site)


def note_query_start(query_id, plan_digest: str = "",
                     rows_forecast: Optional[Dict[str, int]] = None,
                     batches_forecast: Optional[Dict[str, int]] = None
                     ) -> None:
    reg = active()
    if reg is None:
        return
    reg.inc("tpu_queries", 1, state="started")
    tracker().begin(query_id, plan_digest, rows_forecast,
                    batches_forecast)
    reg.set_gauge("tpu_queries_live", tracker().live_count())


def note_query_end(query_id, rows: Optional[int] = None,
                   error: bool = False) -> None:
    reg = active()
    if reg is None:
        return
    reg.inc("tpu_queries", 1, state="failed" if error else "finished")
    tracker().end(query_id, rows, error)
    reg.set_gauge("tpu_queries_live", tracker().live_count())


# ---------------------------------------------------------------------------
# The process-global plane: registry (+ exporter thread, + watchdog) —
# ONE per process no matter how many sessions ask (like BufferCatalog).
# ---------------------------------------------------------------------------
class ObsPlane:
    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.server = None     # MetricsServer when http.enabled
        self.watchdog: Optional[Watchdog] = None

    @property
    def address(self) -> Optional[str]:
        return self.server.address if self.server is not None else None


_PLANE: Optional[ObsPlane] = None
_PLANE_LOCK = _ordered_lock("obs.plane")


def plane() -> Optional[ObsPlane]:
    return _PLANE


def ensure_started(conf_) -> Optional[ObsPlane]:
    """Install the registry and start the conf'd threads (idempotent).

    Returns None — and starts NOTHING, installs NOTHING — unless one of
    metrics.live.enabled / metrics.http.enabled / watchdog.enabled is
    set: the off path must not even construct a registry (the CI obs
    job asserts no exporter thread and no registry with defaults)."""
    from ..conf import (
        LIVE_METRICS_ENABLED,
        METRICS_HTTP_ENABLED,
        METRICS_HTTP_HOST,
        METRICS_HTTP_PORT,
        WATCHDOG_ENABLED,
        WATCHDOG_INTERVAL_MS,
    )

    want_http = conf_.get(METRICS_HTTP_ENABLED)
    want_dog = conf_.get(WATCHDOG_ENABLED)
    if not (conf_.get(LIVE_METRICS_ENABLED) or want_http or want_dog):
        return None
    from ..memory.catalog import derive_hbm_budget

    global _PLANE
    with _PLANE_LOCK:
        if _PLANE is None:
            reg = MetricsRegistry()
            install(reg)
            _PLANE = ObsPlane(reg)
            reg.set_gauge("tpu_hbm_budget_bytes",
                          derive_hbm_budget(conf_) or 0)
        p = _PLANE
        if want_dog and p.watchdog is None:
            p.watchdog = Watchdog(
                p.registry, WatchdogRules.from_conf(conf_),
                interval_s=conf_.get(WATCHDOG_INTERVAL_MS) / 1e3,
                # pressure fallback when the live catalog carries no
                # budget of its own (lazily created under default conf)
                conf_budget=derive_hbm_budget(conf_))
            p.watchdog.start()
            if p.server is not None:  # late watchdog joins a live server
                p.server.watchdog = p.watchdog
        if want_http and p.server is None:
            from .server import MetricsServer

            p.server = MetricsServer(
                p.registry, tracker(), p.watchdog,
                host=conf_.get(METRICS_HTTP_HOST),
                port=int(conf_.get(METRICS_HTTP_PORT))).start()
    return _PLANE


def shutdown() -> None:
    """Stop threads, uninstall the registry, clear progress (tests /
    clean driver exit). The WHOLE teardown holds _PLANE_LOCK so a
    concurrent ensure_started() cannot install a fresh plane halfway
    through and have it silently uninstalled underneath it (the server/
    watchdog threads never call ensure_started, so joining them under
    the lock cannot deadlock)."""
    global _PLANE
    with _PLANE_LOCK:
        p = _PLANE
        _PLANE = None
        if p is not None:
            if p.server is not None:
                p.server.stop()
            if p.watchdog is not None:
                p.watchdog.stop()
            uninstall()
            tracker().reset()
