"""Deterministic, conf-gated fault injector (`spark.rapids.tpu.test.faults.*`).

Reference analog: the RMM retry-OOM injection the reference's integration
tests drive (`RmmSpark.forceRetryOOM` / `forceSplitAndRetryOOM` plus the
spillable-store fault hooks) — the only honest way to exercise the OOM
retry / split-and-retry plane (memory/retry.py) on a CPU-fallback box
whose XLA backend never actually exhausts device memory.

Five channels, each with its own conf of comma-separated site specs:

  * ``oom``      — synthetic device-OOM raised at the top of a retry-
                   harness attempt (the site is the exec's node name);
  * ``transfer`` — host-link upload failure in ``packed_upload``;
  * ``fetch``    — network shuffle fetch failure (shuffle/network.py);
  * ``compile``  — pipeline-cache build failure (exec/base.py);
  * ``aotcache`` — persistent AOT program-cache I/O failure
                   (serve/program_cache.py; sites ``read:<site>`` /
                   ``write:<site>``).

Spec grammar (per entry, comma-separated; site matching is fnmatch so
``*`` and prefixes work)::

    site        fire on EVERY arrival at the site
    site@N      fire on exactly the Nth arrival (1-based, once)
    site%K      fire on every Kth arrival
    site>C      fire while the attempt's batch capacity exceeds C rows
                (the honest memory-exhaustion model: full batches fail,
                split halves fit)
    site?K      fire on ONE arrival in [1, K], chosen deterministically
                from test.faults.seed (seeded chaos schedules)

Zero-overhead-off contract (the events.py pattern): with the confs off —
the default — ``enabled()`` is one module-global boolean read and
``check()`` is never consulted; tests/test_retry.py pins this with a
registry-style spy.
"""
from __future__ import annotations

import fnmatch
import threading
from typing import Dict, List, Optional, Tuple

from .conf import RapidsConf, conf

FAULTS_ENABLED = conf(
    "spark.rapids.tpu.test.faults.enabled", False,
    "Install the deterministic fault injector (chaos testing; see the "
    "channel confs test.faults.oom/transfer/fetch/compile). Off — the "
    "default — keeps every injection site a single module-global boolean "
    "read. Setting any channel spec implies this key.", internal=True)
FAULTS_SEED = conf(
    "spark.rapids.tpu.test.faults.seed", 0,
    "Seed for the '?K' spec form: the firing arrival is derived "
    "deterministically from (seed, channel, site), so a chaos schedule "
    "replays exactly.", internal=True)
FAULTS_OOM = conf(
    "spark.rapids.tpu.test.faults.oom", "",
    "Synthetic device-OOM specs for the retry-harness channel: "
    "'site[@N|%K|>C|?K]' entries, comma-separated; sites are exec node "
    "names (fnmatch patterns allowed). Injected errors carry the XLA "
    "RESOURCE_EXHAUSTED pattern so the real classifier handles them.",
    internal=True)
FAULTS_TRANSFER = conf(
    "spark.rapids.tpu.test.faults.transfer", "",
    "Host-link transfer failure specs (site 'packed_upload').",
    internal=True)
FAULTS_FETCH = conf(
    "spark.rapids.tpu.test.faults.fetch", "",
    "Shuffle network fetch failure specs (site 'network_fetch'); the "
    "injected error is a ConnectionError, so the client's backoff retry "
    "path handles it like a real peer reset.", internal=True)
FAULTS_COMPILE = conf(
    "spark.rapids.tpu.test.faults.compile", "",
    "Pipeline-cache build failure specs (sites are compile-cache site "
    "names, e.g. 'fused_chain', 'agg_plan').", internal=True)
FAULTS_AOTCACHE = conf(
    "spark.rapids.tpu.test.faults.aotcache", "",
    "Persistent AOT program-cache I/O failure specs "
    "(serve/program_cache.py): sites are 'read:<compile-site>' / "
    "'write:<compile-site>' (fnmatch, so 'read:*' corrupts every "
    "lookup). A read fault is handled as a corrupt entry (deleted, "
    "plain compile fallback); a write fault skips the store — either "
    "way the query must succeed, which is exactly what the chaos CI "
    "job asserts.", internal=True)

_CHANNEL_CONFS = {
    "oom": FAULTS_OOM,
    "transfer": FAULTS_TRANSFER,
    "fetch": FAULTS_FETCH,
    "compile": FAULTS_COMPILE,
    "aotcache": FAULTS_AOTCACHE,
}


class InjectedFault(RuntimeError):
    """Base of every injector-raised error (tests discriminate on it)."""


class InjectedOOM(InjectedFault):
    """Synthetic device OOM. The message deliberately carries the XLA
    RESOURCE_EXHAUSTED pattern so memory/retry.py's classifier treats it
    exactly like a real backend allocation failure."""


class InjectedTransferError(InjectedFault, ConnectionError):
    """Synthetic host-link transfer failure."""


class InjectedFetchError(InjectedFault, ConnectionError):
    """Synthetic shuffle fetch failure (a ConnectionError, so the
    transport's retry loop treats it like a real peer reset)."""


class InjectedCompileError(InjectedFault):
    """Synthetic XLA compile failure."""


class InjectedCacheError(InjectedFault, OSError):
    """Synthetic AOT program-cache I/O failure (an OSError, so the
    cache's defensive read/write paths treat it exactly like a real
    disk fault: corrupt-entry deletion on read, skipped store on
    write)."""


_ERROR_OF = {
    "oom": InjectedOOM,
    "transfer": InjectedTransferError,
    "fetch": InjectedFetchError,
    "compile": InjectedCompileError,
    "aotcache": InjectedCacheError,
}


class _Spec:
    """One parsed site spec."""

    __slots__ = ("pattern", "mode", "arg")

    def __init__(self, pattern: str, mode: str, arg: int):
        self.pattern = pattern
        self.mode = mode  # "always" | "nth" | "every" | "cap_gt" | "seeded"
        self.arg = arg

    def fires(self, arrival: int, cap: Optional[int], seed_at: int) -> bool:
        if self.mode == "always":
            return True
        if self.mode == "nth":
            return arrival == self.arg
        if self.mode == "every":
            return arrival % self.arg == 0
        if self.mode == "cap_gt":
            return cap is not None and cap > self.arg
        # seeded: one deterministic arrival in [1, arg]
        return arrival == seed_at


def _parse_specs(raw: str) -> List[_Spec]:
    """Parse a channel's spec list, VALIDATING at construction (session
    init) so a typo'd schedule is a clear conf error, never a
    mid-query crash from inside the recovery plane (e.g. 'site%0'
    would otherwise divide by zero at the injection site). Separators
    split on their LAST occurrence, so fnmatch '?' inside a pattern
    survives when a real separator follows; a bare trailing '?<K>' is
    always the seeded spec — '?' as a trailing fnmatch wildcard is not
    expressible (use '*')."""
    out: List[_Spec] = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        for sep, mode in (("@", "nth"), ("%", "every"), (">", "cap_gt"),
                          ("?", "seeded")):
            if sep in entry:
                pat, _, arg = entry.rpartition(sep)
                try:
                    n = int(arg)
                except ValueError:
                    raise ValueError(
                        f"bad fault spec {entry!r}: expected an integer "
                        f"after {sep!r}")
                if mode == "cap_gt":
                    if n < 0:
                        raise ValueError(
                            f"bad fault spec {entry!r}: capacity "
                            "threshold must be >= 0")
                elif n <= 0:
                    raise ValueError(
                        f"bad fault spec {entry!r}: argument must be "
                        "positive")
                out.append(_Spec(pat.strip(), mode, n))
                break
        else:
            out.append(_Spec(entry, "always", 0))
    return out


class FaultInjector:
    """Per-(channel, site) arrival counters driving the parsed specs —
    deterministic by construction (counts, not clocks)."""

    def __init__(self, conf_: RapidsConf):
        self.seed = int(conf_.get(FAULTS_SEED))
        self._specs: Dict[str, List[_Spec]] = {
            ch: _parse_specs(conf_.get(centry))
            for ch, centry in _CHANNEL_CONFS.items()
        }
        self._counts: Dict[Tuple[str, str], int] = {}
        self._fired: List[Tuple[str, str, int]] = []
        self._lock = threading.Lock()

    def _seed_at(self, channel: str, site: str, k: int) -> int:
        # xorshift-style mix of (seed, channel, site) -> [1, k]
        h = (self.seed * 1_000_003) & 0xFFFFFFFF
        for c in channel + ":" + site:
            h = ((h ^ ord(c)) * 16_777_619) & 0xFFFFFFFF
        return (h % max(1, k)) + 1

    def check(self, channel: str, site: str,
              cap: Optional[int] = None) -> None:
        """Raise the channel's typed injected error if any spec fires on
        this arrival at ``site``."""
        specs = self._specs.get(channel)
        if not specs:
            return
        with self._lock:
            key = (channel, site)
            arrival = self._counts.get(key, 0) + 1
            self._counts[key] = arrival
            hit = None
            for s in specs:
                if not fnmatch.fnmatch(site, s.pattern):
                    continue
                seed_at = (self._seed_at(channel, site, s.arg)
                           if s.mode == "seeded" else 0)
                if s.fires(arrival, cap, seed_at):
                    hit = s
                    break
            if hit is None:
                return
            self._fired.append((channel, site, arrival))
        if channel == "oom":
            raise InjectedOOM(
                f"RESOURCE_EXHAUSTED: injected synthetic device OOM at "
                f"{site} (arrival {arrival}"
                + (f", cap {cap}" if cap is not None else "") + ")")
        raise _ERROR_OF[channel](
            f"injected {channel} fault at {site} (arrival {arrival})")

    def fired(self) -> List[Tuple[str, str, int]]:
        """(channel, site, arrival) for every fault raised so far."""
        with self._lock:
            return list(self._fired)


# ---------------------------------------------------------------------------
# Process-global active injector (the events.py install pattern: injection
# sites live deep in the engine where no session handle exists).
# ---------------------------------------------------------------------------
_ENABLED = False
_ACTIVE: Optional[FaultInjector] = None
_INSTALL_LOCK = threading.Lock()


def enabled() -> bool:
    """The hot-path guard — one module-global boolean read."""
    return _ENABLED


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def install(conf_: RapidsConf) -> Optional[FaultInjector]:
    """Install the injector when the confs ask for one (idempotent per
    conf; any nonempty channel spec implies faults.enabled). Returns
    None — and installs NOTHING — with the confs off."""
    want = conf_.get(FAULTS_ENABLED) or any(
        conf_.get(c) for c in _CHANNEL_CONFS.values())
    if not want:
        return None
    global _ENABLED, _ACTIVE
    with _INSTALL_LOCK:
        _ACTIVE = FaultInjector(conf_)
        _ENABLED = True
        return _ACTIVE


def uninstall() -> None:
    global _ENABLED, _ACTIVE
    with _INSTALL_LOCK:
        _ACTIVE = None
        _ENABLED = False


def check(channel: str, site: str, cap: Optional[int] = None) -> None:
    """Consult the active injector; a no-op when injection is off. Call
    sites guard on :func:`enabled` first so the off path stays one
    boolean read."""
    if not _ENABLED:
        return
    inj = _ACTIVE
    if inj is not None:
        inj.check(channel, site, cap)
