"""Window expressions.

Reference analog: GpuWindowExpression.scala:784 — window function + spec
(partition/order/frame) with frame validation; GpuRowNumber (:712),
GpuLead/GpuLag (:758,:772), and aggregate-over-window lowering (:709).

Frames supported (same initial set the reference validates for):
  * ROWS/RANGE UNBOUNDED PRECEDING .. CURRENT ROW  ("running"; RANGE
    includes the full peer group, Spark's default when ORDER BY is set)
  * UNBOUNDED PRECEDING .. UNBOUNDED FOLLOWING     (whole partition,
    Spark's default without ORDER BY)
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from .. import types as T
from ..types import DataType
from . import expressions as E
from .aggregates import AggregateFunction

ROWS = "rows"
RANGE = "range"

UNBOUNDED_PRECEDING = "unbounded_preceding"
CURRENT_ROW = "current_row"
UNBOUNDED_FOLLOWING = "unbounded_following"


@dataclasses.dataclass(frozen=True)
class WindowFrame:
    """Frame bounds. ``lower``/``upper`` are the sentinels above, or — for
    ROWS frames — int offsets from the current row (negative = preceding,
    e.g. ROWS BETWEEN 2 PRECEDING AND CURRENT ROW -> lower=-2, upper=0),
    matching the reference's literal row-frame bounds requirement
    (GpuWindowExpression.scala:451)."""

    frame_type: str = RANGE
    lower: object = UNBOUNDED_PRECEDING
    upper: object = CURRENT_ROW

    @property
    def is_running(self) -> bool:
        return (
            self.lower == UNBOUNDED_PRECEDING and self.upper == CURRENT_ROW
        )

    @property
    def is_whole_partition(self) -> bool:
        return (
            self.lower == UNBOUNDED_PRECEDING
            and self.upper == UNBOUNDED_FOLLOWING
        )

    @property
    def is_bounded_rows(self) -> bool:
        """Literal ROWS frame (current row = offset 0)."""
        lo = 0 if self.lower == CURRENT_ROW else self.lower
        hi = 0 if self.upper == CURRENT_ROW else self.upper
        return (
            self.frame_type == ROWS
            and isinstance(lo, int) and isinstance(hi, int) and lo <= hi
        )

    def row_bounds(self):
        lo = 0 if self.lower == CURRENT_ROW else self.lower
        hi = 0 if self.upper == CURRENT_ROW else self.upper
        return int(lo), int(hi)

    @property
    def is_bounded_range(self) -> bool:
        """Literal RANGE frame over the ORDER BY key VALUE: lower/upper
        are numeric offsets (preceding = negative, like row_bounds) or
        one-sided sentinels. Reference: RangeFrame handling in
        GpuWindowExpression.scala:88,168."""
        if self.frame_type != RANGE:
            return False
        if self.is_running or self.is_whole_partition:
            return False  # cheaper dedicated kernels handle these
        lo_ok = self.lower in (UNBOUNDED_PRECEDING, CURRENT_ROW) or \
            isinstance(self.lower, (int, float))
        hi_ok = self.upper in (UNBOUNDED_FOLLOWING, CURRENT_ROW) or \
            isinstance(self.upper, (int, float))
        return lo_ok and hi_ok

    def range_bounds(self):
        """(lo, hi) numeric key-value offsets; None = unbounded side;
        CURRENT ROW = offset 0 (the frame then starts/ends at the peer
        boundary, which the value search finds naturally)."""
        lo = (None if self.lower == UNBOUNDED_PRECEDING
              else 0 if self.lower == CURRENT_ROW else self.lower)
        hi = (None if self.upper == UNBOUNDED_FOLLOWING
              else 0 if self.upper == CURRENT_ROW else self.upper)
        return lo, hi


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """PARTITION BY / ORDER BY / frame."""

    partition_by: Tuple[E.Expression, ...] = ()
    order_by: Tuple[E.Expression, ...] = ()
    #: (ascending, nulls_first|None) per order key
    orders: Tuple[Tuple[bool, Optional[bool]], ...] = ()
    frame: Optional[WindowFrame] = None

    def resolved_frame(self) -> WindowFrame:
        if self.frame is not None:
            return self.frame
        # Spark defaults: with ORDER BY -> RANGE UNBOUNDED..CURRENT;
        # without -> whole partition
        if self.order_by:
            return WindowFrame(RANGE, UNBOUNDED_PRECEDING, CURRENT_ROW)
        return WindowFrame(RANGE, UNBOUNDED_PRECEDING, UNBOUNDED_FOLLOWING)


class WindowFunction(E.Expression):
    """Marker base for ranking/offset window functions."""


@dataclasses.dataclass(frozen=True)
class RowNumber(WindowFunction):
    @property
    def dtype(self):
        return T.INT

    @property
    def nullable(self):
        return False


@dataclasses.dataclass(frozen=True)
class Rank(WindowFunction):
    @property
    def dtype(self):
        return T.INT

    @property
    def nullable(self):
        return False


@dataclasses.dataclass(frozen=True)
class DenseRank(WindowFunction):
    @property
    def dtype(self):
        return T.INT

    @property
    def nullable(self):
        return False


@dataclasses.dataclass(frozen=True)
class Lead(WindowFunction):
    child: E.Expression = None  # type: ignore[assignment]
    offset: int = 1
    default: Optional[E.Expression] = None

    @property
    def dtype(self):
        return self.child.dtype


@dataclasses.dataclass(frozen=True)
class Lag(WindowFunction):
    child: E.Expression = None  # type: ignore[assignment]
    offset: int = 1
    default: Optional[E.Expression] = None

    @property
    def dtype(self):
        return self.child.dtype


@dataclasses.dataclass(frozen=True)
class WindowExpression(E.Expression):
    """function OVER spec (reference: GpuWindowExpression)."""

    func: E.Expression = None  # type: ignore[assignment]  # WindowFunction | AggregateFunction
    spec: WindowSpec = WindowSpec()
    name: str = ""

    @property
    def dtype(self):
        return self.func.dtype

    def resolved_name(self) -> str:
        return self.name or f"{type(self.func).__name__.lower()}_over"
